"""Query lifecycle management.

Analog of execution/SqlQueryManager.java:92,304 (createQuery + enforcement
loops), QueryTracker.java (registry + expiry), and QueryStateMachine.java
(the state lattice QUEUED → PLANNING → RUNNING → FINISHING → FINISHED /
FAILED / CANCELED with listeners). Execution itself is pluggable — the
LocalRunner for single-process, the distributed scheduler for a cluster —
via the `execute_fn` the manager is constructed with.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from presto_tpu.obs import events as _obs_events
from presto_tpu.obs import lifecycle as _lifecycle
from presto_tpu.server.resource_groups import ResourceGroupManager
from presto_tpu.server.session import Session

# state lattice (QueryState.java) — terminal states are absorbing;
# EXPIRED is the enforcement loop's terminal (query_max_run_time_s),
# distinct from FAILED so clients and the event stream can attribute it
QUEUED = "QUEUED"
PLANNING = "PLANNING"
RUNNING = "RUNNING"
FINISHING = "FINISHING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"
EXPIRED = "EXPIRED"
TERMINAL = {FINISHED, FAILED, CANCELED, EXPIRED}


@dataclasses.dataclass
class QueryResult:
    columns: List[str]
    types: List[str]
    rows: List[tuple]


@dataclasses.dataclass
class QueryInfo:
    query_id: str
    sql: str
    state: str
    user: str
    resource_group: Optional[str]
    create_time: float
    end_time: Optional[float] = None
    error: Optional[str] = None
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)


class QueryExecution:
    """One query's state machine + worker thread
    (SqlQueryExecution.java:97 — start():335 runs analyze/plan/schedule)."""

    def __init__(self, session: Session, sql: str,
                 execute_fn: Callable[[Session, str], QueryResult]):
        self.session = session
        self.sql = sql
        self.query_id = session.query_id
        self._execute_fn = execute_fn
        self._lock = threading.Lock()
        self._done = threading.Event()
        self.state = QUEUED
        self.error: Optional[str] = None
        self.error_type: Optional[str] = None
        self.result: Optional[QueryResult] = None
        self.create_time = time.time()
        self.end_time: Optional[float] = None
        self.resource_group: Optional[str] = None
        self._cancel_requested = False
        self._listeners: List[Callable[[str], None]] = []
        # lifecycle plane (obs/lifecycle.py): the registry entry's
        # Timeline when the session runs with lifecycle=on, else None —
        # a None timeline keeps every serving-path hook a no-op
        self.timeline = None
        self.expired_limit_s: Optional[float] = None
        self.expired_elapsed_s: Optional[float] = None

    # -- state machine -----------------------------------------------------

    def _transition(self, new: str) -> bool:
        with self._lock:
            if self.state in TERMINAL:
                return False
            self.state = new
            if new in TERMINAL:
                self.end_time = time.time()
        if self.timeline is not None:
            attrs = {}
            if new == EXPIRED and self.expired_limit_s is not None:
                attrs = {"limitS": self.expired_limit_s,
                         "elapsedS": self.expired_elapsed_s}
            _lifecycle.transition(self.query_id, new, **attrs)
        for fn in list(self._listeners):
            fn(new)
        if new in TERMINAL:
            self._done.set()
        return True

    def add_state_listener(self, fn: Callable[[str], None]):
        # registration races with _transition's snapshot iteration; the
        # lock keeps the list itself consistent (a listener added during
        # a transition may or may not see that event — callers register
        # before submitting work)
        with self._lock:
            self._listeners.append(fn)

    def fail(self, message: str, error_type: str = "INTERNAL_ERROR"):
        self.error = message
        self.error_type = error_type
        self._transition(FAILED)

    def cancel(self):
        self._cancel_requested = True
        self._transition(CANCELED)

    def expire(self, limit_s: float):
        """Enforcement-loop kill: terminal EXPIRED with the limit and
        elapsed wall in the error payload."""
        elapsed = time.time() - self.create_time
        self.expired_limit_s = float(limit_s)
        self.expired_elapsed_s = round(elapsed, 6)
        self.error = (f"Query exceeded maximum run time of {limit_s}s "
                      f"(elapsed {elapsed:.3f}s)")
        self.error_type = "EXCEEDED_TIME_LIMIT"
        self._transition(EXPIRED)

    @property
    def done(self) -> bool:
        return self.state in TERMINAL

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    # -- execution ---------------------------------------------------------

    def start(self):
        threading.Thread(target=self._run, daemon=True,
                         name=f"query-{self.query_id}").start()

    def _run(self):
        if not self._transition(PLANNING):
            return
        try:
            self._transition(RUNNING)
            result = self._execute_fn(self.session, self.sql)
            if self._cancel_requested:
                return
            self._transition(FINISHING)
            self.result = result
            self._transition(FINISHED)
        except Exception as e:  # noqa: BLE001 — query failure is data, not a crash
            self.fail(f"{type(e).__name__}: {e}", error_type=type(e).__name__)
            self._traceback = traceback.format_exc()

    def info(self) -> QueryInfo:
        stats: Dict[str, Any] = {"elapsed_s": round(
            (self.end_time or time.time()) - self.create_time, 6)}
        if self.timeline is not None:
            stats["lifecycle"] = self.timeline.doc()
        if self.expired_limit_s is not None:
            stats["expired"] = {"limitS": self.expired_limit_s,
                                "elapsedS": self.expired_elapsed_s}
        return QueryInfo(
            query_id=self.query_id,
            sql=self.sql,
            state=self.state,
            user=self.session.user,
            resource_group=self.resource_group,
            create_time=self.create_time,
            end_time=self.end_time,
            error=self.error,
            stats=stats,
        )


class QueryManager:
    """Registry + admission + enforcement (SqlQueryManager: createQuery:304,
    the limit-enforcement loop, QueryTracker expiry)."""

    def __init__(
        self,
        execute_fn: Callable[[Session, str], QueryResult],
        resource_groups: Optional[ResourceGroupManager] = None,
        max_query_history: int = 100,
        min_query_expire_age_s: float = 600.0,
    ):
        self._execute_fn = execute_fn
        self._queries: Dict[str, QueryExecution] = {}
        self._lock = threading.Lock()
        self.resource_groups = resource_groups or ResourceGroupManager()
        self.max_query_history = max_query_history
        self.min_query_expire_age_s = min_query_expire_age_s
        self._enforcer = threading.Thread(
            target=self._enforcement_loop, daemon=True, name="query-enforcer"
        )
        self._enforcer_stop = threading.Event()
        self._enforcer.start()
        self.listeners: List[Callable[[str, QueryInfo], None]] = []
        # queue-wait speculative precompile hook (coordinator wires this
        # to exec.farm.speculate); called with the QueryExecution when it
        # enters a resource-group queue. None = no speculation.
        self.speculate_fn: Optional[Callable] = None

    def close(self):
        self._enforcer_stop.set()

    # -- lifecycle ---------------------------------------------------------

    def create_query(self, session: Session, sql: str,
                     execute_fn: Optional[Callable] = None) -> QueryExecution:
        """execute_fn override supports coordinator-side statements
        (SHOW/SET/EXPLAIN — DataDefinitionExecution analog)."""
        qe = QueryExecution(session, sql, execute_fn or self._execute_fn)
        # slot accounting: a group slot is held only once the group actually
        # starts the query (a query canceled while still queued never held
        # one); release exactly once whichever of {terminal transition,
        # deferred start of an already-canceled query} observes it first
        qe._rg_slot_held = False
        qe._rg_released = False
        qe._rg_lock = threading.Lock()
        try:
            lifecycle_on = str(session.get("lifecycle")).lower() == "on"
        except KeyError:
            lifecycle_on = False
        if lifecycle_on:
            try:
                objectives = _lifecycle.parse_objectives(
                    session.get("slo_objectives"))
            except (KeyError, ValueError):
                objectives = {}
            try:
                factor = float(session.get("latency_regression_factor"))
            except (KeyError, TypeError, ValueError):
                factor = 0.0
            qe.timeline = _lifecycle.register(
                qe.query_id, objectives=objectives,
                regression_factor=factor).timeline
        try:
            inflight_on = str(session.get("inflight")).lower() == "on"
        except KeyError:
            inflight_on = False
        if inflight_on:
            # inflight plane (obs/inflight.py): operator heartbeats, the
            # stall/straggler watcher, and the query doctor; registering
            # arms the plane — off sessions never reach this
            from presto_tpu.obs import inflight as _inflight

            try:
                thr = float(session.get("stall_threshold_s"))
            except (KeyError, TypeError, ValueError):
                thr = 2.0
            try:
                sf = float(session.get("straggler_factor"))
            except (KeyError, TypeError, ValueError):
                sf = 4.0
            _inflight.register(qe.query_id, stall_threshold_s=thr,
                               straggler_factor=sf)
        with self._lock:
            self._queries[qe.query_id] = qe
        self._emit("queryCreated", qe)
        qe.add_state_listener(
            lambda state, qe=qe: self._on_state(qe, state)
        )

        def on_group(gid, qe=qe):
            qe.resource_group = gid
            entry = _lifecycle.get(qe.query_id)
            if entry is not None:
                entry.group = gid
            try:
                from presto_tpu.obs import inflight as _inflight

                inf = _inflight.get(qe.query_id)
                if inf is not None:
                    inf.group = gid
            except Exception:
                pass

        def start_from_group(qe=qe):
            qe._rg_slot_held = True
            try:
                # compile-budget accounting baseline: the process-wide
                # compile counter as of this query's start; the delta at
                # completion is charged to its resource group
                from presto_tpu.exec import programs as _programs

                qe._rg_compiles0 = _programs.snapshot()["compiles"]
            except Exception:
                qe._rg_compiles0 = None
            try:
                # farm-attributed compiles (boot arming, queue-wait
                # speculation) are charged by the farm itself — net them
                # out of this query's terminal delta
                from presto_tpu.exec import farm as _farm

                qe._rg_farm0 = _farm.farm_compiles()
            except Exception:
                qe._rg_farm0 = None
            _lifecycle.mark(qe.query_id, "admitted")
            if qe.done:
                # canceled/failed while queued: the group just granted a slot
                # to a dead query — give it straight back
                self._release_slot(qe)
                return
            qe.start()

        try:
            self.resource_groups.submit(
                session.user, session.source,
                session.get("query_priority"), start_from_group,
                on_group=on_group,
                on_queued=lambda qe=qe: self._on_queued(qe),
            )
        except Exception as e:  # admission rejection
            if qe.timeline is not None:
                _obs_events.EVENTS.emit(
                    "admission_rejected", query_id=qe.query_id,
                    group=getattr(e, "group", None) or qe.resource_group,
                    reason=str(e))
            qe.fail(str(e), error_type="QUERY_QUEUE_FULL")
        self._expire_old()
        return qe

    def _on_queued(self, qe: QueryExecution):
        _lifecycle.mark(qe.query_id, "queued")
        if self.speculate_fn is not None:
            try:
                # queue wait is the farm's window: compile the query's
                # HBO-predicted programs while it waits for admission
                self.speculate_fn(qe)
            except Exception:
                pass

    def _release_slot(self, qe: QueryExecution):
        with qe._rg_lock:
            if not qe._rg_slot_held or qe._rg_released:
                return
            qe._rg_released = True
        self.resource_groups.query_finished(qe.resource_group, qe.session.user)

    def _on_state(self, qe: QueryExecution, state: str):
        if state in TERMINAL:
            self._charge_compiles(qe)
            self._release_slot(qe)
            try:
                # inflight plane: close any open stall episode and stop
                # the watcher from flagging the finished query
                from presto_tpu.obs import inflight as _inflight

                _inflight.finish(qe.query_id)
            except Exception:
                pass
            self._emit("queryCompleted", qe)

    def _charge_compiles(self, qe: QueryExecution):
        """Charge the query's compile-event delta to its resource group
        BEFORE the slot release, so the release-triggered drain evaluates
        budgets that already include this query's consumption. The
        process-wide counter over-attributes under concurrency (a
        neighbor's compiles land in the delta) — acceptable for a budget
        whose job is throttling storms, not exact billing."""
        base = getattr(qe, "_rg_compiles0", None)
        if base is None or not qe.resource_group:
            return
        try:
            from presto_tpu.exec import programs as _programs

            delta = _programs.snapshot()["compiles"] - base
            farm0 = getattr(qe, "_rg_farm0", None)
            if farm0 is not None:
                try:
                    from presto_tpu.exec import farm as _farm

                    # farm work charges its own deltas (speculation) or is
                    # deliberately un-charged (boot) — don't bill it twice
                    delta -= max(0, _farm.farm_compiles() - farm0)
                except Exception:
                    pass
            if delta > 0:
                self.resource_groups.charge_compiles(
                    qe.resource_group, delta, qe.session.user)
        except Exception:
            pass

    def _emit(self, event: str, qe: QueryExecution):
        for fn in list(self.listeners):
            try:
                fn(event, qe.info())
            except Exception:
                pass

    def get(self, query_id: str) -> QueryExecution:
        with self._lock:
            if query_id not in self._queries:
                raise KeyError(f"unknown query {query_id}")
            return self._queries[query_id]

    def cancel(self, query_id: str):
        self.get(query_id).cancel()

    def queries(self) -> List[QueryInfo]:
        with self._lock:
            return [qe.info() for qe in self._queries.values()]

    # -- enforcement (SqlQueryManager.enforceMemoryLimits/TimeLimits) --------

    def _enforcement_loop(self):
        while not self._enforcer_stop.wait(1.0):
            now = time.time()
            with self._lock:
                running = [q for q in self._queries.values() if not q.done]
            for q in running:
                limit = q.session.get("query_max_run_time_s")
                if limit and now - q.create_time > limit:
                    q.expire(limit)

    def _expire_old(self):
        with self._lock:
            done = [q for q in self._queries.values() if q.done]
            if len(self._queries) <= self.max_query_history:
                return
            done.sort(key=lambda q: q.end_time or 0)
            now = time.time()
            for q in done:
                if len(self._queries) <= self.max_query_history:
                    break
                if now - (q.end_time or now) >= self.min_query_expire_age_s or len(
                    self._queries
                ) > 2 * self.max_query_history:
                    del self._queries[q.query_id]


def batch_to_result(batch) -> QueryResult:
    """Materialize an engine Batch into the wire-facing QueryResult."""
    d = batch.to_pydict()
    cols = list(d.keys())
    n = len(next(iter(d.values()))) if cols else 0
    rows = [tuple(d[c][i] for c in cols) for i in range(n)]
    return QueryResult(
        columns=cols,
        types=[str(t) for t in batch.types],
        rows=rows,
    )
