"""Standalone cluster launchers:

    python -m presto_tpu.server --coordinator --port 8080 \
        --catalog tpch:sf=1 [--min-workers 2] [--secret S]
    python -m presto_tpu.server --worker --coordinator-url http://host:8080 \
        --catalog tpch:sf=1 [--node-id w1] [--secret S]

Reference: server/PrestoServer.java:69-119 — one binary, role decided by
config (coordinator=true/false); here by flag. Workers announce to the
coordinator (airlift discovery analog) and serve the /v1/task data plane;
the coordinator serves /v1/statement + introspection and schedules
fragments. Both sides must be configured with the same catalogs (the
reference distributes etc/catalog/*.properties the same way).

Catalog specs (repeatable --catalog):
    tpch:sf=<N>           deterministic TPC-H generator connector
    tpcds:sf=<N>          deterministic TPC-DS generator connector
    parquet:dir=<path>    directory of <table>.parquet files
    orc:dir=<path>        directory of <table>.orc files
    memory:               empty in-memory connector
Optionally prefix with a name: `--catalog warehouse=parquet:dir=/data`.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def build_catalog(specs):
    from presto_tpu.connector import Catalog

    cat = Catalog()
    if not specs:
        specs = ["tpch:sf=0.01"]
    for i, spec in enumerate(specs):
        name = None
        if "=" in spec.split(":", 1)[0]:
            name, spec = spec.split("=", 1)
        kind, _, argstr = spec.partition(":")
        args = {}
        for kv in filter(None, argstr.split(",")):
            k, _, v = kv.partition("=")
            args[k] = v
        if kind == "tpch":
            from presto_tpu.catalog.tpch import TpchConnector

            conn = TpchConnector(float(args.get("sf", 1.0)))
        elif kind == "tpcds":
            from presto_tpu.catalog.tpcds import TpcdsConnector

            conn = TpcdsConnector(float(args.get("sf", 1.0)))
        elif kind == "parquet":
            from presto_tpu.catalog.parquet import ParquetConnector

            conn = ParquetConnector(args["dir"])
        elif kind == "orc":
            from presto_tpu.catalog.orc import OrcConnector

            conn = OrcConnector(args["dir"])
        elif kind == "memory":
            from presto_tpu.catalog.memory import MemoryConnector

            conn = MemoryConnector()
        else:
            # plugin connectors: any importable module exposing
            # create_connector(**args) -> Connector (the PluginManager /
            # ConnectorFactory SPI analog — discovery by module path
            # instead of a plugin directory scan)
            import importlib

            try:
                mod = importlib.import_module(kind)
            except ImportError:
                raise SystemExit(f"unknown catalog kind: {kind}")
            factory = getattr(mod, "create_connector", None)
            if factory is None:
                raise SystemExit(
                    f"plugin module {kind} has no create_connector()")
            conn = factory(**args)
        cat.register(name or kind, conn, default=(i == 0))
    return cat


def main(argv=None):
    p = argparse.ArgumentParser(prog="python -m presto_tpu.server")
    role = p.add_mutually_exclusive_group(required=True)
    role.add_argument("--coordinator", action="store_true")
    role.add_argument("--worker", action="store_true")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral, printed on start)")
    p.add_argument("--catalog", action="append", default=[],
                   help="catalog spec, repeatable (see module docstring)")
    p.add_argument("--coordinator-url", default=None,
                   help="(worker) coordinator to announce to")
    p.add_argument("--node-id", default=None, help="(worker) node id")
    p.add_argument("--min-workers", type=int, default=1)
    p.add_argument("--secret", default=None,
                   help="shared cluster secret for task endpoints")
    p.add_argument("--batch-rows", type=int, default=1 << 17)
    p.add_argument("--run-slots", type=int, default=4,
                   help="(worker) fair-executor run slots per worker")
    p.add_argument("--memory-pool-bytes", type=int, default=None)
    p.add_argument("--spill-dir", default=None)
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu, tpu) — the site "
                        "config may ignore the JAX_PLATFORMS env var")
    p.add_argument("--password-file", default=None,
                   help="(coordinator) enable BASIC auth from this file "
                        "(lines: user:salt:sha256(salt||password))")
    p.add_argument("--session-properties", default=None,
                   help="(coordinator) JSON rules file of session property "
                        "defaults matched by user/source regex")
    p.add_argument("--query-event-log", default=None,
                   help="(coordinator) append query-completion events as "
                        "JSON lines to this file (EventListener analog)")
    p.add_argument("--function-plugin", action="append", default=[],
                   help="module[:attr] exposing register_functions(registry)"
                        " — loads user scalar/aggregate functions "
                        "(Plugin.getFunctions analog), repeatable")
    p.add_argument("--cluster-memory-limit-bytes", type=int, default=None,
                   help="(coordinator) cluster-wide memory ceiling for the "
                        "low-memory killer")
    p.add_argument("--tls-dir", default=None,
                   help="serve HTTPS: directory holding (or receiving a "
                        "generated self-signed) cluster-cert.pem / "
                        "cluster-key.pem; every node passes the same dir")
    p.add_argument("--access-control-rules", default=None,
                   help="(coordinator) JSON file of first-match "
                        "table/column authorization rules")
    args = p.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.function_plugin:
        from presto_tpu.functions import registry

        for spec in args.function_plugin:
            registry().load_plugin(spec)

    catalog = build_catalog(args.catalog)

    if args.coordinator:
        from presto_tpu.exec.runtime import ExecConfig
        from presto_tpu.server.coordinator import Coordinator

        authenticator = spm = None
        tls = access_control = None
        if args.tls_dir:
            from presto_tpu.server.tls import generate_self_signed

            tls = generate_self_signed(args.tls_dir)
        if args.access_control_rules:
            from presto_tpu.server.security import AccessControl

            access_control = AccessControl(path=args.access_control_rules)
        if args.password_file:
            from presto_tpu.server.security import PasswordAuthenticator

            authenticator = PasswordAuthenticator(args.password_file)
        if args.session_properties:
            from presto_tpu.server.security import SessionPropertyManager

            spm = SessionPropertyManager(args.session_properties)
        coord = Coordinator(
            catalog, port=args.port,
            config=ExecConfig(batch_rows=args.batch_rows,
                              memory_pool_bytes=args.memory_pool_bytes,
                              spill_dir=args.spill_dir),
            min_workers=args.min_workers,
            cluster_secret=args.secret,
            authenticator=authenticator,
            session_property_manager=spm,
            query_event_log=args.query_event_log,
            cluster_memory_limit_bytes=args.cluster_memory_limit_bytes,
            access_control=access_control, tls=tls,
        )
        print(f"coordinator listening on {coord.url}", flush=True)
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        try:
            while not stop:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        coord.close()
        return 0

    import os
    import socket

    from presto_tpu.server.worker import Worker

    # default id must be unique per process — the requested port is 0
    # (ephemeral) by default and NodeManager keys announcements by node_id
    node_id = args.node_id or (
        f"worker-{socket.gethostname()}-{os.getpid()}")
    wtls = None
    if args.tls_dir:
        from presto_tpu.server.tls import generate_self_signed

        wtls = generate_self_signed(args.tls_dir)
    w = Worker(
        catalog, node_id=node_id, port=args.port,
        coordinator_url=args.coordinator_url,
        memory_pool_bytes=args.memory_pool_bytes,
        spill_dir=args.spill_dir,
        cluster_secret=args.secret,
        run_slots=args.run_slots,
        tls=wtls,
    )
    print(f"worker {node_id} listening on {w.url}"
          + (f", announcing to {args.coordinator_url}"
             if args.coordinator_url else ""), flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop and w.node_state != "shut_down":
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    w.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
