"""Session + system session properties.

Analog of presto-main's Session.java + SystemSessionProperties.java (1,099
lines of typed PropertyMetadata definitions: join_distribution_type:59,
grouped_execution_*:66-69, pushdown_subfields_enabled:132, ...). A Session
carries the per-query identity, catalog/schema defaults, and a bag of typed
property overrides; `exec_config()` lowers the system properties into the
engine's ExecConfig the way Presto lowers them into TaskManagerConfig /
FeaturesConfig-derived per-query settings.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional

from presto_tpu.exec.runtime import ExecConfig


class SessionPropertyError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    """Typed session property (reference: spi/session/PropertyMetadata)."""

    name: str
    description: str
    py_type: type
    default: Any
    hidden: bool = False
    decoder: Optional[Callable[[str], Any]] = None
    validator: Optional[Callable[[Any], None]] = None

    def decode(self, raw: Any) -> Any:
        if isinstance(raw, str) and self.py_type is not str:
            if self.decoder is not None:
                v = self.decoder(raw)
            elif self.py_type is bool:
                low = raw.strip().lower()
                if low not in ("true", "false"):
                    raise SessionPropertyError(
                        f"{self.name}: expected boolean, got {raw!r}"
                    )
                v = low == "true"
            elif self.py_type is int:
                try:
                    v = int(raw.strip())
                except ValueError:
                    raise SessionPropertyError(
                        f"{self.name}: expected integer, got {raw!r}"
                    )
            elif self.py_type is float:
                try:
                    v = float(raw.strip())
                except ValueError:
                    raise SessionPropertyError(
                        f"{self.name}: expected number, got {raw!r}"
                    )
            else:
                v = raw
        else:
            v = raw
            if self.py_type is float and isinstance(v, int):
                v = float(v)
            if not isinstance(v, self.py_type) and v is not None:
                raise SessionPropertyError(
                    f"{self.name}: expected {self.py_type.__name__}, got {type(v).__name__}"
                )
        if self.validator is not None:
            self.validator(v)
        return v


def _enum(name: str, allowed: List[str]) -> Callable[[Any], None]:
    def check(v):
        if v is not None and v.upper() not in allowed:
            raise SessionPropertyError(f"{name}: must be one of {allowed}, got {v!r}")

    return check


def _positive(name: str) -> Callable[[Any], None]:
    def check(v):
        if v is not None and v <= 0:
            raise SessionPropertyError(f"{name}: must be positive, got {v}")

    return check


def _nonneg(name: str) -> Callable[[Any], None]:
    def check(v):
        if v is not None and v < 0:
            raise SessionPropertyError(
                f"{name}: must be non-negative, got {v}")

    return check


def _objectives(name: str) -> Callable[[Any], None]:
    def check(v):
        if not v:
            return
        from presto_tpu.obs.lifecycle import parse_objectives
        try:
            parse_objectives(v)
        except ValueError as e:
            raise SessionPropertyError(f"{name}: {e}")

    return check


def _pow2_or_off(name: str) -> Callable[[Any], None]:
    def check(v):
        if v is None or v in (0, 1):
            return
        if v < 0 or v & (v - 1):
            raise SessionPropertyError(
                f"{name}: must be a power of two (or 0/1 to disable), "
                f"got {v}")

    return check


class SystemSessionProperties:
    """The engine's per-query flag registry (SystemSessionProperties.java)."""

    def __init__(self):
        self._props: Dict[str, PropertyMetadata] = {}
        for p in self._defaults():
            self._props[p.name] = p

    @staticmethod
    def _defaults() -> List[PropertyMetadata]:
        return [
            # engine execution shape (reference: TaskManagerConfig + task_concurrency)
            PropertyMetadata("batch_rows", "Rows per scan batch", int, 1 << 17,
                             validator=_positive("batch_rows")),
            PropertyMetadata("agg_capacity", "Initial group-table capacity", int, 1 << 12,
                             validator=_positive("agg_capacity")),
            PropertyMetadata("join_out_capacity",
                             "Join output chunk capacity (default: probe batch)",
                             int, None),
            PropertyMetadata("max_growth_retries",
                             "Max geometric capacity growth retries", int, 24),
            PropertyMetadata("collect_stats",
                             "Per-operator stats (EXPLAIN ANALYZE)", bool, False),
            PropertyMetadata("tracing",
                             "Record query-lifecycle spans "
                             "(/v1/query/{id}/trace)", bool, True),
            PropertyMetadata("scan_prefetch",
                             "Background split-prefetch depth (0 disables)",
                             int, 2),
            PropertyMetadata("query_retry_count",
                             "Query-level retries on worker loss", int, 1),
            # distribution (reference: join_distribution_type:59, hash_partition_count)
            PropertyMetadata("join_distribution_type",
                             "AUTOMATIC | PARTITIONED | BROADCAST", str, "AUTOMATIC",
                             validator=_enum("join_distribution_type",
                                             ["AUTOMATIC", "PARTITIONED", "BROADCAST"])),
            PropertyMetadata("hash_partition_count",
                             "Default partitions for hash exchanges", int, 8,
                             validator=_positive("hash_partition_count")),
            PropertyMetadata("redistribute_writes", "Redistribute before write",
                             bool, True),
            # resource limits (reference: query_max_memory, query_max_run_time)
            PropertyMetadata("query_max_memory_mb",
                             "Per-query device memory limit (MB)", int, 16384),
            PropertyMetadata("query_max_run_time_s",
                             "Wall-clock limit per query (s)", float, 3600.0),
            PropertyMetadata("query_priority", "Priority within resource group",
                             int, 1),
            # spill (reference: spill_enabled / MemoryRevokingScheduler thresholds)
            PropertyMetadata("spill_enabled", "Allow spilling to host", bool, True),
            PropertyMetadata("memory_revoking_threshold",
                             "Pool fraction that triggers revocation", float, 0.9),
            PropertyMetadata("memory_revoking_target",
                             "Pool fraction revocation aims for", float, 0.5),
            # dynamic hybrid hash spill (spiller.py recursive repartitioning)
            PropertyMetadata("spill_max_depth",
                             "Recursive-repartition depth bound for spilled "
                             "hybrid hash joins/aggregations",
                             int, 4, validator=_positive("spill_max_depth")),
            PropertyMetadata("spill_dir_budget_mb",
                             "Live-byte budget for the worker spill "
                             "directory (0 = unbounded)",
                             int, 0, validator=_nonneg("spill_dir_budget_mb")),
            # planner
            PropertyMetadata("optimize_plan", "Run optimizer passes", bool, True),
            PropertyMetadata("execution_policy", "all-at-once | phased", str,
                             "all-at-once"),
            # SystemSessionProperties.java:69
            PropertyMetadata("recoverable_grouped_execution",
                             "Re-run only lost lifespans of colocated joins",
                             bool, False),
            # scheduler/NodeScheduler soft-affinity placement
            PropertyMetadata("split_affinity",
                             "Rendezvous-hash split→worker placement",
                             bool, True),
            # radix-partitioned pipeline breakers
            PropertyMetadata("radix_partitions",
                             "Within-worker radix fanout at joins and "
                             "group-bys (power of two; 0/1 disables)",
                             int, 0, validator=_pow2_or_off("radix_partitions")),
            PropertyMetadata("join_spill_budget_bytes",
                             "Per-partition device-byte budget beyond which "
                             "a radix partition spills to host (0 = never)",
                             int, 0, validator=_nonneg("join_spill_budget_bytes")),
            # compile plane (exec/programs.py)
            PropertyMetadata("donate_stepping",
                             "Donate accumulator buffers on linearly-"
                             "threaded stepping programs", bool, True),
            PropertyMetadata("precompile_workers",
                             "Ahead-of-stream precompile thread count "
                             "(0 disables)", int, 0,
                             validator=_nonneg("precompile_workers")),
            PropertyMetadata("max_compiled_shapes_scan",
                             "Compiled-shape budget override for scan-class "
                             "nodes (0 = inherit global)", int, 0,
                             validator=_nonneg("max_compiled_shapes_scan")),
            PropertyMetadata("max_compiled_shapes_breaker",
                             "Compiled-shape budget override for breaker-"
                             "class nodes (0 = inherit global)", int, 0,
                             validator=_nonneg("max_compiled_shapes_breaker")),
            PropertyMetadata("fragment_fusion",
                             "Fold eligible leaf fragments into one fused "
                             "lax.scan program per batch window", bool, True),
            PropertyMetadata("fragment_window",
                             "Max batches stacked per fused fragment "
                             "dispatch", int, 8,
                             validator=_positive("fragment_window")),
            PropertyMetadata("breaker_engine",
                             "Keyed-agg/join breaker engine: auto lets the "
                             "CBO pick per breaker from derived stats; "
                             "sort/hash force one engine", str, "auto",
                             validator=_enum("breaker_engine",
                                             ["AUTO", "SORT", "HASH"])),
            PropertyMetadata("join_mode",
                             "Star-schema join chain collapse: auto lets the "
                             "CBO fold eligible inner/left equi-join chains "
                             "into one multiway probe program from HBO-"
                             "corrected build sizes and selectivities; "
                             "multiway forces every eligible chain; binary "
                             "declines but stamps the verdict in EXPLAIN; "
                             "off skips the pass (pre-collapse plan "
                             "bit-for-bit)", str, "auto",
                             validator=_enum("join_mode",
                                             ["AUTO", "MULTIWAY", "BINARY",
                                              "OFF"])),
            PropertyMetadata("hbo",
                             "History-based optimization: off disables even "
                             "observation (pre-HBO behavior bit-for-bit); "
                             "observe records estimate-vs-actual drift keyed "
                             "on structural fingerprints; correct also feeds "
                             "observed values back into the CBO on a repeat "
                             "of the same structure", str, "observe",
                             validator=_enum("hbo",
                                             ["OFF", "OBSERVE", "CORRECT"])),
            # device cost/HBM accounting plane (obs/devprof.py)
            PropertyMetadata("devprof",
                             "Device cost & HBM accounting: off reproduces "
                             "pre-devprof behavior bit-for-bit; on records "
                             "XLA cost/memory analysis per compiled program, "
                             "samples the device HBM watermark, and "
                             "reconciles it against the memory-pool ledger",
                             str, "OFF",
                             validator=_enum("devprof", ["OFF", "ON"])),
            PropertyMetadata("profile",
                             "Capture a jax.profiler trace per query under "
                             "PRESTO_TPU_CACHE_DIR (profileUri in the "
                             "statement response; no-op with a warning when "
                             "the profiler or cache dir is unavailable)",
                             bool, False),
            # serving-plane SLO telemetry (obs/lifecycle.py)
            PropertyMetadata("lifecycle",
                             "Query lifecycle timeline + live progress + "
                             "cluster events: off reproduces the pre-"
                             "lifecycle serving path bit-for-bit (no "
                             "timeline, no progressUri, no new metric "
                             "families); on decomposes e2e wall into "
                             "queue/plan/compile/exec/drain segments and "
                             "feeds the per-group SLO histograms",
                             str, "on",
                             validator=_enum("lifecycle", ["OFF", "ON"])),
            PropertyMetadata("slo_objectives",
                             "Comma list of segment=seconds latency "
                             "objectives (segments: queue_wait, plan, "
                             "compile, exec, drain, e2e); a completed query "
                             "whose segment exceeds its bound increments "
                             "presto_tpu_slo_violations_total{group,segment}",
                             str, "", validator=_objectives("slo_objectives")),
            PropertyMetadata("latency_regression_factor",
                             "Flag a completed query as a latency regression "
                             "when its e2e wall is at least this many times "
                             "the fingerprint's HBO baseline wall (0 "
                             "disables)", float, 3.0,
                             validator=_nonneg("latency_regression_factor")),
            # semantic result cache (server/result_cache.py)
            PropertyMetadata("result_cache",
                             "Fingerprint-keyed result reuse: off "
                             "reproduces the pre-cache serving path "
                             "bit-for-bit (no consult, no metric families, "
                             "no events); query memoizes final results "
                             "keyed on structural plan sha + catalog "
                             "snapshot token; subplan additionally reuses "
                             "materialized breaker-subplan results",
                             str, "off",
                             validator=_enum("result_cache",
                                             ["OFF", "QUERY", "SUBPLAN"])),
            # compile farm (exec/farm.py)
            PropertyMetadata("shape_bucketing",
                             "pow2 pads merging-output flushes and partial "
                             "jit windows up to their power-of-two bucket so "
                             "each stream compiles one shape instead of a "
                             "per-flush ladder (results identical — padding "
                             "is dead lanes); off reproduces today's shapes "
                             "bit-for-bit", str, "off",
                             validator=_enum("shape_bucketing",
                                             ["OFF", "POW2"])),
            PropertyMetadata("compile_farm",
                             "on records installed plans into the persistent "
                             "farm corpus under PRESTO_TPU_CACHE_DIR and "
                             "arms queue-wait speculative precompile; off "
                             "is a strict no-op (no corpus IO, no claims, "
                             "no metric families)", str, "off",
                             validator=_enum("compile_farm", ["OFF", "ON"])),
            # mid-flight telemetry plane (obs/inflight.py)
            PropertyMetadata("inflight",
                             "Live operator telemetry: off reproduces the "
                             "pre-inflight serving path bit-for-bit (no "
                             "publishes, no watcher thread, no metric "
                             "families); on makes drivers publish operator "
                             "watermarks at window boundaries, arms the "
                             "stall/straggler watcher, and enables "
                             "/v1/query/{id}/inflight and /doctor", str,
                             "off", validator=_enum("inflight",
                                                    ["OFF", "ON"])),
            # in-run adaptation layer (exec/adaptive.py)
            PropertyMetadata("adaptive",
                             "In-run adaptation: off reproduces the "
                             "pre-adaptive engine bit-for-bit (no "
                             "decisions, no events, no metric families); "
                             "observe evaluates every decision point and "
                             "logs what it would do without acting; on "
                             "acts — engine flips between replay waves, "
                             "forward-propagating presize/lane sizing, "
                             "device-radix partition growth, "
                             "largest-partition-first partial revocation",
                             str, "off",
                             validator=_enum("adaptive",
                                             ["OFF", "OBSERVE", "ON"])),
            PropertyMetadata("stall_threshold_s",
                             "Stall detector bound: row watermarks frozen "
                             "this many seconds while the query executes "
                             "fires stall_detected plus a forensics dump",
                             float, 2.0,
                             validator=_positive("stall_threshold_s")),
            PropertyMetadata("straggler_factor",
                             "Straggler detector bound: a fragment site "
                             "this many times behind its siblings' window "
                             "watermark fires straggler_detected", float,
                             4.0, validator=_positive("straggler_factor")),
        ]

    def names(self) -> List[str]:
        return sorted(self._props)

    def metadata(self, name: str) -> PropertyMetadata:
        if name not in self._props:
            raise SessionPropertyError(f"unknown session property: {name}")
        return self._props[name]

    def default(self, name: str) -> Any:
        return self.metadata(name).default

    def decode(self, name: str, raw: Any) -> Any:
        return self.metadata(name).decode(raw)

    def register(self, prop: PropertyMetadata):
        self._props[prop.name] = prop


SYSTEM_PROPERTIES = SystemSessionProperties()

_query_counter = itertools.count(1)


def new_query_id() -> str:
    """Presto query ids look like 20190101_000000_00000_abcde; ours carry a
    date bucket + counter (reference: QueryIdGenerator)."""
    n = next(_query_counter)
    return f"{time.strftime('%Y%m%d_%H%M%S')}_{n:05d}"


@dataclasses.dataclass
class Session:
    """Per-query session (reference: Session.java — identity, defaults,
    property overrides, start time)."""

    user: str = "user"
    source: str = ""
    catalog: Optional[str] = None
    schema: Optional[str] = None
    query_id: str = ""
    start_time: float = 0.0
    properties: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # catalog_name -> {prop: value} (reference: per-connector session props)
    connector_properties: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict
    )
    resource_group: Optional[str] = None

    def __post_init__(self):
        if not self.query_id:
            self.query_id = new_query_id()
        if not self.start_time:
            self.start_time = time.time()

    def get(self, name: str) -> Any:
        if name in self.properties:
            return self.properties[name]
        return SYSTEM_PROPERTIES.default(name)

    def set(self, name: str, raw: Any):
        self.properties[name] = SYSTEM_PROPERTIES.decode(name, raw)

    def unset(self, name: str):
        SYSTEM_PROPERTIES.metadata(name)  # validate the name
        self.properties.pop(name, None)

    def child(self) -> "Session":
        """A fresh query-scoped session inheriting this session's overrides
        (the client session persists across queries; each query gets its own
        id/start time)."""
        return Session(
            user=self.user,
            source=self.source,
            catalog=self.catalog,
            schema=self.schema,
            properties=dict(self.properties),
            connector_properties={k: dict(v) for k, v in self.connector_properties.items()},
            resource_group=self.resource_group,
        )

    def exec_config(self) -> ExecConfig:
        qmax = self.get("query_max_memory_mb")
        return ExecConfig(
            batch_rows=self.get("batch_rows"),
            agg_capacity=self.get("agg_capacity"),
            join_out_capacity=self.get("join_out_capacity"),
            max_growth_retries=self.get("max_growth_retries"),
            collect_stats=self.get("collect_stats"),
            tracing=self.get("tracing"),
            memory_pool_bytes=(qmax * (1 << 20)) if qmax else None,
            spill_enabled=self.get("spill_enabled"),
            memory_revoking_threshold=self.get("memory_revoking_threshold"),
            memory_revoking_target=self.get("memory_revoking_target"),
            scan_prefetch=self.get("scan_prefetch"),
            query_retry_count=self.get("query_retry_count"),
            execution_policy=self.get("execution_policy"),
            recoverable_grouped_execution=self.get(
                "recoverable_grouped_execution"),
            split_affinity=self.get("split_affinity"),
            radix_partitions=self.get("radix_partitions"),
            join_spill_budget_bytes=(self.get("join_spill_budget_bytes")
                                     or None),
            spill_max_depth=self.get("spill_max_depth"),
            spill_dir_budget_bytes=(
                self.get("spill_dir_budget_mb") * (1 << 20)
                if self.get("spill_dir_budget_mb") else None),
            donate_stepping=self.get("donate_stepping"),
            precompile_workers=self.get("precompile_workers"),
            max_compiled_shapes_scan=(self.get("max_compiled_shapes_scan")
                                      or None),
            max_compiled_shapes_breaker=(
                self.get("max_compiled_shapes_breaker") or None),
            fragment_fusion=self.get("fragment_fusion"),
            fragment_window=self.get("fragment_window"),
            breaker_engine=self.get("breaker_engine").lower(),
            join_mode=self.get("join_mode").lower(),
            hbo=self.get("hbo").lower(),
            devprof=self.get("devprof").lower(),
            profile=self.get("profile"),
            lifecycle=self.get("lifecycle").lower(),
            result_cache=self.get("result_cache").lower(),
            shape_bucketing=self.get("shape_bucketing").lower(),
            compile_farm=self.get("compile_farm").lower(),
            inflight=self.get("inflight").lower(),
            adaptive=self.get("adaptive").lower(),
            stall_threshold_s=self.get("stall_threshold_s"),
            straggler_factor=self.get("straggler_factor"),
        )
