"""Client statement protocol — the Presto-compatible paged REST API.

Reference: server/protocol/StatementResource.java:89 (`@Path("/v1/statement")`,
POST :135 create, GET /{queryId}/{token} :174 page fetch, DELETE :277 cancel)
and the client's polling loop (presto-client StatementClientV1.java:340-352:
follow `nextUri` until absent). Session state is client-carried via headers
(X-Presto-Session etc.), mutated by SET/RESET SESSION through
X-Presto-Set-Session response headers — the coordinator itself is stateless
across requests, exactly like the reference.

Coordinator-side statements (SHOW/EXPLAIN/SET) execute inline, the analog of
DataDefinitionExecution + execution/*Task.java running on the coordinator.
"""

from __future__ import annotations

import datetime
import decimal
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.server.querymanager import (
    CANCELED,
    EXPIRED,
    FAILED,
    FINISHED,
    QueryManager,
    QueryResult,
    TERMINAL,
)
from presto_tpu.server.session import SYSTEM_PROPERTIES, Session

_SET_SESSION_RE = re.compile(
    r"^\s*set\s+session\s+([a-zA-Z_][\w.]*)\s*=\s*(.+?)\s*$", re.I | re.S
)
_RESET_SESSION_RE = re.compile(r"^\s*reset\s+session\s+([a-zA-Z_][\w.]*)\s*$", re.I)
_SHOW_SESSION_RE = re.compile(r"^\s*show\s+session\s*$", re.I)
_SHOW_TABLES_RE = re.compile(r"^\s*show\s+tables(?:\s+from\s+([\w.]+))?\s*$", re.I)
_SHOW_CATALOGS_RE = re.compile(r"^\s*show\s+catalogs\s*$", re.I)
_SHOW_COLUMNS_RE = re.compile(
    r"^\s*(?:show\s+columns\s+from|describe)\s+([\w.]+)\s*$", re.I
)
_PREPARE_RE = re.compile(r"^\s*prepare\s+(\w+)\s+from\s+(.+)$",
                         re.I | re.S)
_EXECUTE_RE = re.compile(r"^\s*execute\s+(\w+)(?:\s+using\s+(.+))?\s*$",
                         re.I | re.S)
_DEALLOCATE_RE = re.compile(r"^\s*deallocate\s+prepare\s+(\w+)\s*$", re.I)
_SHOW_FUNCTIONS_RE = re.compile(r"^\s*show\s+functions\s*$", re.I)
_SHOW_SCHEMAS_RE = re.compile(
    r"^\s*show\s+schemas(?:\s+from\s+([\w.]+))?\s*$", re.I)
_SHOW_STATS_RE = re.compile(
    r"^\s*show\s+stats\s+for\s+([\w.]+)\s*$", re.I)
_EXPLAIN_RE = re.compile(
    r"^\s*explain\s+(analyze\s+)?(?:\(\s*type\s+(\w+)\s*\)\s+)?(.+)$",
    re.I | re.S)


def _json_value(v: Any, type_name: str) -> Any:
    """Row value → JSON-safe wire value, by declared SQL type."""
    if v is None:
        return None
    if isinstance(v, (np.generic,)):
        v = v.item()
    if type_name == "date":
        if isinstance(v, int):
            return (datetime.date(1970, 1, 1) + datetime.timedelta(days=v)).isoformat()
        if isinstance(v, datetime.date):
            return v.isoformat()
    if type_name == "timestamp" and isinstance(v, int):
        return datetime.datetime.fromtimestamp(
            v / 1e6, tz=datetime.timezone.utc
        ).strftime("%Y-%m-%d %H:%M:%S.%f")
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, float) and (v != v):  # NaN → null (JSON has no NaN)
        return None
    return v


def result_rows_json(result: QueryResult) -> List[List[Any]]:
    return [
        [_json_value(v, t) for v, t in zip(row, result.types)]
        for row in result.rows
    ]


def _parse_using_args(using: str | None) -> list:
    """EXECUTE ... USING literal list → AST expressions (parsed by the
    real lexer/parser; no raw-text handling anywhere). Only literal-like
    forms are accepted — parameters carry values, not expressions over
    the query's scope."""
    if not using:
        return []
    from presto_tpu.sql import ast as _ast
    from presto_tpu.sql.parser import Parser

    q = Parser(f"select {using}").parse_statement()
    args = [item.expr for item in q.select]

    def literal_like(e) -> bool:
        if isinstance(e, (_ast.Literal, _ast.IntervalLiteral)):
            return True
        if isinstance(e, _ast.UnaryOp) and e.op == "-":
            return literal_like(e.operand)
        if isinstance(e, _ast.Cast):
            return literal_like(e.expr)
        return False

    for a in args:
        if not literal_like(a):
            raise ValueError("EXECUTE ... USING accepts literals only")
    return args


def _bind_statement(body: str, using: str | None):
    """Parse the prepared body (the lexer knows `?`) and bind parameters
    on the AST positionally."""
    from presto_tpu.sql import ast as _ast
    from presto_tpu.sql.parser import parse_sql

    args = _parse_using_args(using)
    stmt = parse_sql(body)
    bound, n_params = _ast.substitute_parameters(stmt, args)
    if n_params != len(args):
        raise ValueError(
            f"prepared statement has {n_params} parameters, "
            f"USING supplies {len(args)}")
    return bound


class StatementProtocol:
    """Stateless request handlers; mounted on the coordinator HTTP server."""

    def __init__(self, query_manager: QueryManager, catalog, base_url: str,
                 page_rows: int = 1000, explain_fn=None,
                 authenticator=None, session_property_manager=None):
        self.qm = query_manager
        self.catalog = catalog
        self.base_url = base_url
        self.page_rows = page_rows
        self.explain_fn = explain_fn  # sql -> plan text
        # client security (server/security.py): optional BASIC password
        # authentication + rule-matched session property defaults
        self.authenticator = authenticator
        self.session_property_manager = session_property_manager
        # prepared statements keyed by (user, name) — a deliberate
        # statefulness deviation: the reference round-trips them in
        # X-Presto-Prepared-Statement headers; this registry serves the
        # same PREPARE/EXECUTE surface for header-less clients, bounded
        # per user (insertion-ordered dict → oldest evicts)
        self._prepared: Dict[tuple, str] = {}
        self.max_prepared_per_user = 64
        # (session, bound_stmt_ast) -> QueryResult; wired by the
        # coordinator so EXECUTE runs the bound AST without re-rendering
        self.execute_stmt_fn = None

    # -- session from headers ---------------------------------------------

    def session_from_headers(self, headers) -> Session:
        user = headers.get("X-Presto-User") or "user"
        if self.authenticator is not None:
            # the authenticated principal is authoritative for the user
            user = self.authenticator.authenticate(
                headers.get("Authorization"))
        source = headers.get("X-Presto-Source") or ""
        props: Dict[str, Any] = {}
        if self.session_property_manager is not None:
            for k, v in self.session_property_manager.defaults_for(
                    user, source).items():
                props[k] = SYSTEM_PROPERTIES.decode(k, str(v))
        raw = headers.get("X-Presto-Session") or headers.get("X-Trino-Session")
        if raw:
            from urllib.parse import unquote

            for pair in raw.split(","):
                if "=" in pair:
                    k, v = pair.split("=", 1)
                    props[k.strip()] = SYSTEM_PROPERTIES.decode(
                        k.strip(), unquote(v.strip())
                    )
        return Session(
            user=user,
            source=source,
            catalog=headers.get("X-Presto-Catalog"),
            schema=headers.get("X-Presto-Schema"),
            properties=props,
        )

    # -- statement handling -------------------------------------------------

    def create(self, sql: str, headers) -> Tuple[dict, Dict[str, str]]:
        """POST /v1/statement → (QueryResults json, extra response headers)."""
        session = self.session_from_headers(headers)
        extra: Dict[str, str] = {}

        m = _SET_SESSION_RE.match(sql)
        if m:
            name, raw = m.group(1), m.group(2).strip().strip("'\"")
            SYSTEM_PROPERTIES.decode(name, raw)  # validate
            extra["X-Presto-Set-Session"] = f"{name}={raw}"
            return self._immediate(session, sql, QueryResult([], [], [])), extra
        m = _RESET_SESSION_RE.match(sql)
        if m:
            SYSTEM_PROPERTIES.metadata(m.group(1))
            extra["X-Presto-Clear-Session"] = m.group(1)
            return self._immediate(session, sql, QueryResult([], [], [])), extra
        m = _SHOW_SESSION_RE.match(sql)
        if m:
            rows = []
            for name in SYSTEM_PROPERTIES.names():
                meta = SYSTEM_PROPERTIES.metadata(name)
                if meta.hidden:
                    continue
                cur = session.properties.get(name, meta.default)
                rows.append((name, str(cur), str(meta.default),
                             meta.py_type.__name__, meta.description))
            r = QueryResult(
                ["name", "value", "default", "type", "description"],
                ["varchar"] * 5, rows)
            return self._immediate(session, sql, r), extra
        m = _SHOW_CATALOGS_RE.match(sql)
        if m:
            r = QueryResult(["catalog"], ["varchar"],
                            [(c,) for c in sorted(self.catalog.connectors)])
            return self._immediate(session, sql, r), extra
        m = _SHOW_TABLES_RE.match(sql)
        if m:
            cname = m.group(1) or session.catalog or self.catalog.default
            conn = self.catalog.connectors[cname]
            r = QueryResult(["table"], ["varchar"],
                            [(t,) for t in sorted(conn.table_names())])
            return self._immediate(session, sql, r), extra
        m = _SHOW_COLUMNS_RE.match(sql)
        if m:
            conn, handle = self.catalog.resolve(m.group(1).split("."))
            r = QueryResult(
                ["column", "type"], ["varchar", "varchar"],
                [(c.name, str(c.type)) for c in handle.columns])
            return self._immediate(session, sql, r), extra
        m = _PREPARE_RE.match(sql)
        if m:
            name, body = m.group(1).lower(), m.group(2).strip()
            from presto_tpu.sql.parser import parse_sql

            parse_sql(body)  # the lexer/parser know `?` — real validation
            key = (session.user, name)
            self._prepared.pop(key, None)
            self._prepared[key] = body
            # bounded per-user registry (oldest-prepared evicts)
            mine = [k for k in self._prepared if k[0] == session.user]
            while len(mine) > self.max_prepared_per_user:
                self._prepared.pop(mine.pop(0), None)
            extra["X-Presto-Added-Prepare"] = name
            return self._immediate(session, sql, QueryResult([], [], [])), extra
        m = _DEALLOCATE_RE.match(sql)
        if m:
            self._prepared.pop((session.user, m.group(1).lower()), None)
            extra["X-Presto-Deallocated-Prepare"] = m.group(1).lower()
            return self._immediate(session, sql, QueryResult([], [], [])), extra
        m = _EXECUTE_RE.match(sql)
        if m:
            name = m.group(1).lower()
            body = self._prepared.get((session.user, name))
            if body is None:
                raise KeyError(f"prepared statement not found: {name}")
            bound = _bind_statement(body, m.group(2))
            if self.execute_stmt_fn is None:
                raise RuntimeError("EXECUTE not supported on this server")
            qe = self.qm.create_query(
                session, sql,
                execute_fn=lambda s, q, stmt=bound:
                    self.execute_stmt_fn(s, stmt))
            return self._results(qe, 0), extra
        m = _SHOW_FUNCTIONS_RE.match(sql)
        if m:
            from presto_tpu.server.functions import list_functions

            r = QueryResult(
                ["function", "kind", "description"], ["varchar"] * 3,
                list_functions())
            return self._immediate(session, sql, r), extra
        m = _SHOW_SCHEMAS_RE.match(sql)
        if m:
            # single-schema connectors: one "default" schema per catalog
            cname = m.group(1) or session.catalog or self.catalog.default
            self.catalog.connectors[cname]  # raise on unknown catalog
            r = QueryResult(["schema"], ["varchar"], [("default",)])
            return self._immediate(session, sql, r), extra
        m = _SHOW_STATS_RE.match(sql)
        if m:
            conn, handle = self.catalog.resolve(m.group(1).split("."))
            rows = []
            for c in handle.columns:
                cs = getattr(c, "stats", None)
                rows.append((
                    c.name,
                    str(cs.ndv) if cs and cs.ndv is not None else None,
                    str(cs.null_fraction) if cs else None,
                    str(cs.min_value) if cs and cs.min_value is not None else None,
                    str(cs.max_value) if cs and cs.max_value is not None else None,
                ))
            rows.append((None, None, None, None, str(handle.row_count)))
            r = QueryResult(
                ["column_name", "distinct_values_count", "nulls_fraction",
                 "low_value", "high_value"],
                ["varchar"] * 5, rows)
            return self._immediate(session, sql, r), extra
        m = _EXPLAIN_RE.match(sql)
        if m and self.explain_fn is not None:
            etype = (m.group(2) or "").lower() or None
            text = self.explain_fn(m.group(3), bool(m.group(1)), session,
                                   etype)
            r = QueryResult(["Query Plan"], ["varchar"],
                            [(line,) for line in text.split("\n")])
            return self._immediate(session, sql, r), extra

        qe = self.qm.create_query(session, sql)
        return self._results(qe, 0), extra

    def _immediate(self, session: Session, sql: str, result: QueryResult) -> dict:
        """Coordinator-side statement: completes with a prepared result but
        still flows through the QueryManager (history, events, admission)."""
        qe = self.qm.create_query(session, sql, execute_fn=lambda s, q: result)
        qe.wait(10.0)
        return self._results(qe, 0, force_data=True)

    def poll(self, query_id: str, token: int, wait_s: float = 0.5) -> dict:
        qe = self.qm.get(query_id)
        if not qe.done:
            qe.wait(wait_s)
        return self._results(qe, token)

    def cancel(self, query_id: str):
        try:
            self.qm.cancel(query_id)
        except KeyError:
            pass

    def _results(self, qe, token: int, force_data: bool = False) -> dict:
        base = f"{self.base_url}/v1/statement/{qe.query_id}"
        out: dict = {
            "id": qe.query_id,
            "infoUri": f"{self.base_url}/v1/query/{qe.query_id}",
            "traceUri": f"{self.base_url}/v1/query/{qe.query_id}/trace",
            "stats": {
                "state": qe.state,
                "queued": qe.state == "QUEUED",
                "elapsedTimeMillis": int(
                    ((qe.end_time or time.time()) - qe.create_time) * 1000
                ),
            },
        }
        if getattr(qe, "timeline", None) is not None:
            # lifecycle plane only (lifecycle=off responses stay
            # bit-for-bit): live fraction-complete endpoint
            out["progressUri"] = (
                f"{self.base_url}/v1/query/{qe.query_id}/progress")
            try:
                # result-cache provenance (result_cache=off responses
                # stay bit-for-bit: no entry, no key)
                from presto_tpu.obs import lifecycle as _lc

                _entry = _lc.get(qe.query_id)
                if _entry is not None and _entry.cache_info is not None:
                    out["stats"]["resultCache"] = dict(_entry.cache_info)
                # compile-farm attribution (farm off: no farm_info, stays
                # bit-for-bit)
                if _entry is not None and _entry.farm_info is not None:
                    out["stats"]["compileFarm"] = dict(_entry.farm_info)
            except Exception:
                pass
        try:
            # `profile` session property: the captured jax.profiler trace
            # directory for this query, when one was recorded
            from presto_tpu.obs import devprof as _devprof

            pdir = _devprof.profile_for(qe.query_id)
            if pdir:
                out["profileUri"] = f"file://{pdir}"
        except Exception:
            pass
        if qe.state == FAILED:
            # user mistakes (parse/analysis/session/admission) are USER_ERROR,
            # everything else INTERNAL (reference: StandardErrorCode types)
            user_error = (qe.error_type or "").startswith(
                ("Parse", "Analysis", "Session", "QUERY_QUEUE", "Key",
                 "AccessDenied")
            )
            out["error"] = {
                "message": qe.error or "query failed",
                "errorName": qe.error_type or "INTERNAL_ERROR",
                "errorType": "USER_ERROR" if user_error else "INTERNAL_ERROR",
            }
            return out
        if qe.state == CANCELED:
            out["error"] = {
                "message": "Query was canceled by the user",
                "errorName": "USER_CANCELED",
                "errorType": "USER_ERROR",
            }
            return out
        if qe.state == EXPIRED:
            # enforcement-loop kill (query_max_run_time_s): resource
            # exhaustion, not a user mistake and not an engine bug
            err = {
                "message": qe.error or "Query expired",
                "errorName": qe.error_type or "EXCEEDED_TIME_LIMIT",
                "errorType": "INSUFFICIENT_RESOURCES",
            }
            if qe.expired_limit_s is not None:
                err["limitS"] = qe.expired_limit_s
                err["elapsedS"] = qe.expired_elapsed_s
            out["error"] = err
            return out
        if qe.state not in TERMINAL:
            out["nextUri"] = f"{base}/{token}"
            return out
        # FINISHED: page the materialized result
        result = qe.result or QueryResult([], [], [])
        out["columns"] = [
            {"name": c, "type": t} for c, t in zip(result.columns, result.types)
        ]
        lo = token * self.page_rows
        hi = lo + self.page_rows
        page = QueryResult(result.columns, result.types, result.rows[lo:hi])
        if page.rows or force_data or token == 0:
            out["data"] = result_rows_json(page)
        if hi < len(result.rows):
            out["nextUri"] = f"{base}/{token + 1}"
        return out
