"""Coordinator proxy — a thin HTTP front with coordinator failover.

Reference: presto-proxy (ProxyServlet forwarding /v1/statement with
rewritten nextUri links so clients only ever talk to the proxy). Serves
the same purpose here: one stable address over N coordinators, health-
checked round-robin with failover on connect errors, and response-body
URI rewriting so paged results route back through the proxy.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional


_FORWARD_HEADERS = ("X-Presto-User", "X-Presto-Source", "X-Presto-Catalog",
                    "X-Presto-Schema", "X-Presto-Session", "Authorization",
                    "Content-Type")


class CoordinatorProxy:
    def __init__(self, coordinator_urls: List[str], port: int = 0):
        self.targets = [u.rstrip("/") for u in coordinator_urls]
        self._rr = 0
        self._lock = threading.Lock()
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _forward(self, method: str):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else None
                headers = {
                    k: v for k, v in self.headers.items()
                    if k in _FORWARD_HEADERS
                }
                out, code, ctype = proxy.forward(
                    method, self.path, body, headers)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

            def do_DELETE(self):
                self._forward("DELETE")

        self._http = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._http.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self._http.serve_forever, daemon=True,
                         name="coordinator-proxy").start()

    # -- forwarding -------------------------------------------------------

    def _order(self) -> List[str]:
        with self._lock:
            i = self._rr
            self._rr += 1
        return self.targets[i % len(self.targets):] + \
            self.targets[: i % len(self.targets)]

    def _rewrite(self, data: bytes, target: str) -> bytes:
        """Point nextUri/infoUri back at the proxy so paging stays on this
        address (ProxyResponseHandler's URI rewriting)."""
        try:
            doc = json.loads(data)
        except Exception:
            return data

        def walk(x):
            if isinstance(x, dict):
                return {k: (v.replace(target, self.url)
                            if isinstance(v, str) and k.lower().endswith("uri")
                            else walk(v))
                        for k, v in x.items()}
            if isinstance(x, list):
                return [walk(v) for v in x]
            return x

        return json.dumps(walk(doc)).encode()

    def forward(self, method: str, path: str, body: Optional[bytes],
                headers: dict):
        last_err: Optional[Exception] = None
        for target in self._order():
            req = urllib.request.Request(
                target + path, data=body, method=method, headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    data = r.read()
                    ctype = r.headers.get("Content-Type", "application/json")
                    if "json" in ctype:
                        data = self._rewrite(data, target)
                    return data, r.status, ctype
            except urllib.error.HTTPError as e:
                # the coordinator answered: its status IS the answer
                data = e.read()
                return (self._rewrite(data, target) if data else b"",
                        e.code, e.headers.get("Content-Type",
                                              "application/json"))
            except Exception as e:  # connect error → fail over
                last_err = e
                continue
        msg = json.dumps({"error": {
            "message": f"no coordinator reachable: {last_err}",
            "errorName": "PROXY_NO_TARGET", "errorType": "INTERNAL_ERROR"}})
        return msg.encode(), 502, "application/json"

    def close(self):
        self._http.shutdown()
        self._http.server_close()
