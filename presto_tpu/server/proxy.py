"""Coordinator proxy — a thin HTTP front with coordinator failover.

Reference: presto-proxy (ProxyServlet forwarding /v1/statement with
rewritten nextUri links so clients only ever talk to the proxy). Serves
the same purpose here: one stable address over N coordinators, health-
checked round-robin with failover on connect errors, and response-body
URI rewriting so paged results route back through the proxy.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional


def _is_connect_error(e: Exception) -> bool:
    """True only for failures that happen BEFORE the request was sent
    (connection refused / unreachable / DNS). Read timeouts and other
    mid-response errors return False: the statement may already be
    executing on the coordinator, and replaying a POST /v1/statement to
    another target would double-execute non-idempotent DML. (The
    reference presto-proxy never replays statements across backends.)"""
    import socket

    if isinstance(e, urllib.error.URLError) and not isinstance(
            e, urllib.error.HTTPError):
        reason = e.reason
        if isinstance(reason, Exception):
            return _is_connect_error(reason)
        return False
    if isinstance(e, socket.gaierror):
        return True
    if isinstance(e, (socket.timeout, TimeoutError)):
        return False  # can't tell connect- from read-timeout: don't replay
    if isinstance(e, ConnectionRefusedError):
        return True
    if isinstance(e, OSError):
        import errno

        return e.errno in (errno.ECONNREFUSED, errno.EHOSTUNREACH,
                           errno.ENETUNREACH, errno.EADDRNOTAVAIL)
    return False


_FORWARD_HEADERS = ("X-Presto-User", "X-Presto-Source", "X-Presto-Catalog",
                    "X-Presto-Schema", "X-Presto-Session", "Authorization",
                    "Content-Type")


class CoordinatorProxy:
    def __init__(self, coordinator_urls: List[str], port: int = 0):
        self.targets = [u.rstrip("/") for u in coordinator_urls]
        self._rr = 0
        self._lock = threading.Lock()
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _forward(self, method: str):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else None
                headers = {
                    k: v for k, v in self.headers.items()
                    if k in _FORWARD_HEADERS
                }
                out, code, ctype = proxy.forward(
                    method, self.path, body, headers)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

            def do_DELETE(self):
                self._forward("DELETE")

        self._http = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._http.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self._http.serve_forever, daemon=True,
                         name="coordinator-proxy").start()

    # -- forwarding -------------------------------------------------------

    def _order(self) -> List[str]:
        with self._lock:
            i = self._rr
            self._rr += 1
        return self.targets[i % len(self.targets):] + \
            self.targets[: i % len(self.targets)]

    def _rewrite(self, data: bytes, target: str) -> bytes:
        """Point nextUri/infoUri back at the proxy so paging stays on this
        address (ProxyResponseHandler's URI rewriting)."""
        try:
            doc = json.loads(data)
        except Exception:
            return data

        def walk(x):
            if isinstance(x, dict):
                return {k: (v.replace(target, self.url)
                            if isinstance(v, str) and k.lower().endswith("uri")
                            else walk(v))
                        for k, v in x.items()}
            if isinstance(x, list):
                return [walk(v) for v in x]
            return x

        return json.dumps(walk(doc)).encode()

    def forward(self, method: str, path: str, body: Optional[bytes],
                headers: dict):
        last_err: Optional[Exception] = None
        for target in self._order():
            req = urllib.request.Request(
                target + path, data=body, method=method, headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    data = r.read()
                    ctype = r.headers.get("Content-Type", "application/json")
                    if "json" in ctype:
                        data = self._rewrite(data, target)
                    return data, r.status, ctype
            except urllib.error.HTTPError as e:
                # the coordinator answered: its status IS the answer
                data = e.read()
                return (self._rewrite(data, target) if data else b"",
                        e.code, e.headers.get("Content-Type",
                                              "application/json"))
            except Exception as e:
                last_err = e
                # Fail over only when the request provably never reached a
                # coordinator (pre-send connect error), or for idempotent
                # methods (GET reads, DELETE cancels). A POST that timed
                # out mid-response may already be executing — surface the
                # error instead of re-POSTing.
                if _is_connect_error(e) or method in ("GET", "DELETE"):
                    continue
                msg = json.dumps({"error": {
                    "message": f"coordinator {target} failed mid-request: "
                               f"{e}",
                    "errorName": "PROXY_TARGET_ERROR",
                    "errorType": "EXTERNAL_ERROR"}})
                return msg.encode(), 502, "application/json"
        msg = json.dumps({"error": {
            "message": f"no coordinator reachable: {last_err}",
            "errorName": "PROXY_NO_TARGET", "errorType": "INTERNAL_ERROR"}})
        return msg.encode(), 502, "application/json"

    def close(self):
        self._http.shutdown()
        self._http.server_close()
