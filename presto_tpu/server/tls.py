"""Cluster TLS: encrypted coordinator/worker/client HTTP.

Reference: the reference's internal-communication TLS
(`InternalCommunicationConfig` https settings) and server/security's
https connectors — here one TlsConfig wraps both the server sockets
(`http.server` + ssl.SSLContext) and the client side (a process-wide
urllib opener that verifies the cluster CA; every coordinator↔worker and
worker↔worker call goes through `urllib.request.urlopen`).

Self-signed bootstrap uses the `openssl` CLI (always present in the
image) — the cert doubles as its own CA, the usual single-cluster
deployment shape.
"""

from __future__ import annotations

import dataclasses
import os
import ssl
import subprocess
import urllib.request
from typing import Optional


@dataclasses.dataclass
class TlsConfig:
    certfile: str
    keyfile: str
    # CA used by CLIENTS to verify servers; for self-signed deployments
    # this is the certfile itself
    cafile: Optional[str] = None


def generate_self_signed(directory: str, cn: str = "127.0.0.1") -> TlsConfig:
    """One-command cluster bootstrap: a self-signed cert valid for
    localhost, written into `directory`. Concurrent node startups race —
    an O_EXCL lockfile elects ONE generator; the others wait for the
    finished pair (a torn cert/key mix would fail load_cert_chain)."""
    import time

    os.makedirs(directory, exist_ok=True)
    cert = os.path.join(directory, "cluster-cert.pem")
    key = os.path.join(directory, "cluster-key.pem")
    if os.path.exists(cert) and os.path.exists(key):
        return TlsConfig(certfile=cert, keyfile=key, cafile=cert)
    lock = os.path.join(directory, ".tls-gen.lock")
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        deadline = time.time() + 60
        while time.time() < deadline:
            if os.path.exists(cert) and os.path.exists(key):
                return TlsConfig(certfile=cert, keyfile=key, cafile=cert)
            time.sleep(0.1)
        raise RuntimeError(
            f"timed out waiting for TLS material in {directory} "
            f"(stale {lock}? delete it and retry)")
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key + ".tmp", "-out", cert + ".tmp", "-days", "7",
             "-subj", f"/CN={cn}",
             "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1"],
            check=True, capture_output=True)
        os.replace(key + ".tmp", key)
        os.replace(cert + ".tmp", cert)
    finally:
        os.close(fd)
        os.unlink(lock)
    return TlsConfig(certfile=cert, keyfile=key, cafile=cert)


def server_context(cfg: TlsConfig) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.certfile, cfg.keyfile)
    return ctx


def client_context(cfg: TlsConfig) -> ssl.SSLContext:
    # the cluster CA is ADDED to the system trust store, not substituted
    # for it — connectors in the same process still reach public-CA
    # services (RemoteServiceConnector over external https)
    ctx = ssl.create_default_context()
    ctx.load_verify_locations(cafile=cfg.cafile or cfg.certfile)
    return ctx


def install_client_context(cfg: TlsConfig) -> None:
    """Route every `urllib.request.urlopen` in the process through an
    opener that trusts the cluster CA. Process-global by design: a node
    belongs to one cluster, and all intra-cluster calls share the CA."""
    opener = urllib.request.build_opener(
        urllib.request.HTTPSHandler(context=client_context(cfg)))
    urllib.request.install_opener(opener)


def wrap_server(server, cfg: Optional[TlsConfig]):
    """Wrap an http.server socket for TLS; returns the URL scheme."""
    if cfg is None:
        return "http"
    server.socket = server_context(cfg).wrap_socket(
        server.socket, server_side=True)
    return "https"
