"""IPADDRESS / IPPREFIX host-side value functions.

Reference surface: presto-main/src/main/java/com/facebook/presto/type/
IpAddressType.java, IpAddressOperators.java and
operator/scalar/IpPrefixFunctions.java.

Design (TPU-first): an IPADDRESS is dictionary-encoded exactly like
VARCHAR, but the dictionary ENTRY is the canonical 16-byte IPv6 form of
the address mapped through the latin-1 bijection (the same trick
types.VarbinaryType uses).  Byte order on the canonical form IS address
order (the reference compares the 16-byte value too), so comparisons,
joins, grouping, sorting and range predicates all ride the existing
order-preserving code machinery with zero new device code.  An IPPREFIX
entry is the 16-byte canonical NETWORK address plus one trailing
prefix-length byte, which sorts by (address, length) — the reference's
IPPREFIX ordering.

Every function here is host-side, evaluated once per dictionary entry
and applied on device as a gather (see expr/compile.py _STR_TO_STR).
Malformed text yields None → SQL NULL, the engine's documented
deviation from the reference's row-level cast errors.
"""

from __future__ import annotations

import ipaddress


def _from_latin1(s: str) -> bytes:
    return s.encode("latin-1")


def _to_latin1(b: bytes) -> str:
    return b.decode("latin-1")


def _as_obj(b16: bytes):
    """16-byte canonical form → IPv4Address (if v4-mapped) or IPv6Address."""
    v6 = ipaddress.IPv6Address(b16)
    v4 = v6.ipv4_mapped
    return v4 if v4 is not None else v6


def _canon_bytes(addr) -> bytes:
    """Address object → canonical 16 bytes (v4 → v4-mapped v6)."""
    if isinstance(addr, ipaddress.IPv4Address):
        return bytes(10) + b"\xff\xff" + addr.packed
    return addr.packed


def parse_address(s: str) -> str | None:
    """Text ('1.2.3.4' or any v6 form) → canonical entry, None if invalid."""
    try:
        return _to_latin1(_canon_bytes(ipaddress.ip_address(s.strip())))
    except ValueError:
        return None


def address_from_bytes(s: str) -> str | None:
    """VARBINARY entry (4 or 16 bytes) → canonical entry (cast varbinary →
    ipaddress; reference IpAddressOperators.castFromVarbinaryToIpAddress)."""
    b = _from_latin1(s)
    if len(b) == 4:
        return _to_latin1(bytes(10) + b"\xff\xff" + b)
    if len(b) == 16:
        return s
    return None


def format_address(entry: str) -> str | None:
    """Canonical entry → display text ('1.2.3.4' for v4-mapped, compressed
    lowercase v6 otherwise — reference castFromIpAddressToVarchar)."""
    b = _from_latin1(entry)
    if len(b) != 16:
        return None
    return str(_as_obj(b))


def parse_prefix(s: str) -> str | None:
    """Text 'addr/len' → canonical prefix entry (network address is masked:
    '192.168.255.255/9' canonicalizes to '192.128.0.0/9')."""
    try:
        net = ipaddress.ip_network(s.strip(), strict=False)
    except ValueError:
        return None
    return _to_latin1(_canon_bytes(net.network_address)
                      + bytes([net.prefixlen]))


def _prefix_obj(b: bytes):
    """Prefix entry bytes → the network's ADDRESS object. Family comes
    from the prefix LENGTH, not the address bytes: a v6 prefix like
    ::ffff:1.2.3.0/120 has a v4-mapped network address but must stay v6
    (lengths > 32 are meaningless for v4)."""
    v6 = ipaddress.IPv6Address(b[:16])
    v4 = v6.ipv4_mapped
    return v4 if (v4 is not None and b[16] <= 32) else v6


def format_prefix(entry: str) -> str | None:
    b = _from_latin1(entry)
    if len(b) != 17:
        return None
    return f"{_prefix_obj(b)}/{b[16]}"


def ip_prefix(entry: str, bits: int) -> str | None:
    """Canonical IPADDRESS entry → IPPREFIX with the given length, masked
    to the network address. v4 addresses take v4 lengths (0-32), v6 take
    0-128 (reference IpPrefixFunctions.ipPrefix). Text input must be
    parsed by the caller first — a 16-char address TEXT is
    indistinguishable from 16 canonical bytes."""
    b = _from_latin1(entry)
    if len(b) != 16:
        return None
    addr = _as_obj(b)
    maxlen = 32 if isinstance(addr, ipaddress.IPv4Address) else 128
    if not 0 <= bits <= maxlen:
        return None
    net = ipaddress.ip_network((addr, bits), strict=False)
    return _to_latin1(_canon_bytes(net.network_address) + bytes([bits]))


def _as_network(entry: str):
    b = _from_latin1(entry)
    if len(b) != 17:
        return None
    try:
        return ipaddress.ip_network((_prefix_obj(b), b[16]), strict=False)
    except ValueError:
        return None


def subnet_min(entry: str) -> str | None:
    """IPPREFIX → lowest address (the network address itself)."""
    net = _as_network(entry)
    if net is None:
        return None
    return _to_latin1(_canon_bytes(net.network_address))


def subnet_max(entry: str) -> str | None:
    """IPPREFIX → highest address (v4 broadcast / v6 last address)."""
    net = _as_network(entry)
    if net is None:
        return None
    return _to_latin1(_canon_bytes(net.broadcast_address))


def _v6_bits(b: bytes) -> int | None:
    """Prefix entry → its length in the 128-bit universe: a v4 prefix
    (/n over a v4-mapped network, n ≤ 32) masks the same bit set as the
    v6 prefix /n+96, so containment can compare raw bits across
    families (the reference compares the 16-byte values directly)."""
    n = b[16]
    if n <= 32 and b[:12] == bytes(10) + b"\xff\xff":
        return n + 96
    return n if n <= 128 else None


def is_subnet_of(prefix_entry: str, entry: str) -> bool:
    """Does `prefix` contain the address (16-byte entry) or the whole
    prefix (17-byte entry)?  Pure bit-level containment over the
    canonical 128-bit forms — ::ffff:1.2.3.0/120 and 1.2.3.0/24 denote
    the same set. Distinct v4/v6 regions are naturally disjoint (a v4
    prefix's mask pins the ::ffff:0:0/96 marker bits)."""
    pb = _from_latin1(prefix_entry)
    if len(pb) != 17:
        return False
    plen = _v6_bits(pb)
    if plen is None:
        return False
    xb = _from_latin1(entry)
    if len(xb) == 16:
        xlen = 128
    elif len(xb) == 17:
        xlen = _v6_bits(xb)
        if xlen is None:
            return False
        xb = xb[:16]
    else:
        return False
    if xlen < plen:
        return False
    mask = ((1 << plen) - 1) << (128 - plen) if plen else 0
    pa = int.from_bytes(pb[:16], "big")
    xa = int.from_bytes(xb, "big")
    return (pa & mask) == (xa & mask)
