"""Row-expression → JAX compilation.

This is the analog of the reference's runtime bytecode generation
(presto-main/.../sql/gen/ExpressionCompiler.java, PageFunctionCompiler.java,
backed by the presto-bytecode ASM DSL): we lower the typed IR into jnp ops at
trace time and let XLA fuse the whole pipeline fragment. There is no
interpreter in the hot path.

Compiled form: fn(batch) -> (values, validity|None), vectorized over the
batch capacity. NULL semantics are SQL three-valued logic; the `live` mask is
NOT consulted here (dead lanes compute garbage harmlessly — branch-free SIMT
style, like Presto's SelectedPositions but without the compaction).

String ops: operands are dictionary codes. Literals are resolved against the
column's Dictionary at trace time (Batch carries dictionaries as static
pytree aux), so equality/range/LIKE/IN on strings become integer compares or
a precomputed boolean-table gather. See presto_tpu.dictionary.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column
from presto_tpu.dictionary import Dictionary
from presto_tpu.expr.ir import (
    Call,
    Constant,
    InputRef,
    LambdaExpr,
    RowExpression,
)
from presto_tpu.expr import structural as _struct
from presto_tpu.expr.structural import StructVal
from presto_tpu.types import (
    BOOLEAN,
    DOUBLE,
    ArrayType,
    DecimalType,
    MapType,
    Type,
    is_floating,
    is_integral,
)

# ---------------------------------------------------------------------------
# helpers


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _const_array(value, typ: Type):
    return jnp.asarray(value, dtype=typ.dtype)


def _round_half_away(v):
    """Half-away-from-zero rounding for floats (SQL ROUND semantics)."""
    return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)


def _div_half_away(v, f: int):
    """Integer divide with half-away-from-zero rounding of dropped digits."""
    av = jnp.abs(v)
    return jnp.sign(v) * ((av + f // 2) // f)


def like_to_regex(pattern: str, escape: str | None = None) -> str:
    """SQL LIKE pattern → anchored python regex (reference:
    operator/scalar/StringFunctions.java likePattern / LikeFunctions)."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "^" + "".join(out) + "$"


# string→string functions evaluated host-side over the dictionary
# (reference: operator/scalar/StringFunctions.java — but O(|dict|) instead of
# O(rows), then one device gather)
# HyperLogLog register count (2^12 → ~1.6% standard error; the reference's
# approx_distinct default standard error is 2.3% at p=11)
HLL_M = 4096

_STR_TO_STR = {
    "substr", "upper", "lower", "trim", "ltrim", "rtrim", "replace",
    "reverse", "lpad", "rpad", "concat", "split_part",
    "regexp_extract", "regexp_replace", "json_extract_scalar",
    # URL / hash / encoding family (operator/scalar/UrlFunctions,
    # VarbinaryFunctions over utf-8 text) — host dictionary transforms
    "url_extract_host", "url_extract_path", "url_extract_query",
    "url_extract_protocol", "url_extract_fragment", "url_encode",
    "url_decode", "md5", "sha1", "sha256", "sha512", "to_base64",
    "from_base64", "normalize",
    # JSON family (operator/scalar/JsonFunctions.java): JSON values are
    # VARCHAR text; every function evaluates ONCE per dictionary entry
    "json_extract", "json_array_get", "json_format", "json_parse",
    # VARBINARY family (VarbinaryFunctions.java): bytes ride the latin-1
    # bijection (types.VarbinaryType), so these are dictionary transforms
    "to_hex", "from_hex", "to_utf8", "from_utf8",
    "__vb_md5", "__vb_sha1", "__vb_sha256", "__vb_sha512", "__vb_to_base64",
    # IPADDRESS/IPPREFIX family (expr/ip.py): canonical-byte dictionary
    # entries, so casts and prefix math are dictionary transforms too
    "__to_ipaddress", "__vb_to_ipaddress", "__ip_to_varchar",
    "__ip_to_bytes", "__to_ipprefix", "__ipprefix_to_varchar",
    "__addr_to_ipprefix", "__ipprefix_to_addr",
    "ip_prefix", "ip_subnet_min", "ip_subnet_max",
    # TDIGEST entries (expr/tdigest.py)
    "scale_tdigest",
}
# string→double functions over dictionary entries (float lut + null lut):
# the TDIGEST scalar family (expr/tdigest.py)
_STR_TO_FLOAT = {"value_at_quantile", "quantile_at_value", "trimmed_mean"}
# string→int functions (code-indexed int lut)
_STR_TO_INT = {"length", "strpos", "codepoint", "json_array_length",
               "json_size", "levenshtein_distance_c", "hamming_distance_c",
               "__hll_cardinality", "bit_length", "__vb_bit_length",
               "date_parse", "from_iso8601_date", "from_iso8601_timestamp"}
# int functions whose python fn may return None = SQL NULL (absent json
# path / non-array input) — carried via a parallel null lut
_STR_INT_NULLABLE = {"json_array_length", "json_size", "__hll_cardinality",
                     "date_parse", "from_iso8601_date",
                     "from_iso8601_timestamp"}

# MySQL date format specifiers → strptime (DateTimeFunctions.java's
# date_parse uses the MySQL vocabulary, not JodaTime's)
_MYSQL_FMT = {"Y": "%Y", "y": "%y", "m": "%m", "c": "%m", "d": "%d",
              "e": "%d", "H": "%H", "k": "%H", "h": "%I", "I": "%I",
              "l": "%I", "i": "%M", "s": "%S", "S": "%S", "f": "%f",
              "p": "%p", "M": "%B", "b": "%b", "a": "%a", "W": "%A",
              "j": "%j", "T": "%H:%M:%S", "r": "%I:%M:%S %p", "%": "%%"}


def mysql_format_to_strptime(fmt: str) -> str:
    """Translate a MySQL date format to strptime; unsupported specifiers
    raise ValueError (the builder surfaces it as an AnalysisError)."""
    out = []
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "%":
            if i + 1 >= len(fmt):
                raise ValueError("trailing % in date format")
            spec = fmt[i + 1]
            if spec not in _MYSQL_FMT:
                raise ValueError(f"unsupported date format specifier %{spec}")
            out.append(_MYSQL_FMT[spec])
            i += 2
        else:
            # strptime treats bare % as special; everything else literal
            out.append(ch)
            i += 1
    return "".join(out)
# string→bool predicate functions (bool lut, like LIKE)
_STR_PRED = {"regexp_like", "starts_with", "ends_with", "contains",
             "json_array_contains", "is_json_scalar",
             "__is_subnet_of_c", "__prefix_contains_c"}


def _sql_substr(s: str, start: int, length: int | None) -> str:
    # SQL substr: 1-based; negative start counts from the end (Presto
    # StringFunctions.substr semantics)
    n = len(s)
    if start == 0:
        return ""
    if start > 0:
        i = start - 1
    else:
        i = n + start
        if i < 0:
            return ""
    if i >= n:
        return ""
    if length is None:
        return s[i:]
    if length <= 0:
        return ""
    return s[i : i + length]


def _str_xform_pyfn(fn: str, cargs: tuple):
    """Host python fn(str)->str for a string transform with constant args."""
    if fn == "substr":
        start = int(cargs[0])
        length = int(cargs[1]) if len(cargs) > 1 and cargs[1] is not None else None
        return lambda s: _sql_substr(s, start, length)
    if fn == "upper":
        return str.upper
    if fn == "lower":
        return str.lower
    if fn in ("url_extract_host", "url_extract_path", "url_extract_query",
              "url_extract_protocol", "url_extract_fragment"):
        from urllib.parse import urlparse

        attr = fn[len("url_extract_"):]
        attr = {"host": "hostname", "protocol": "scheme"}.get(attr, attr)

        def url_part(s, attr=attr):
            try:
                v = getattr(urlparse(s), attr)
            except ValueError:
                return None
            return v if v else None

        return url_part
    if fn == "url_encode":
        from urllib.parse import quote_plus

        return lambda s: quote_plus(s)
    if fn == "url_decode":
        from urllib.parse import unquote_plus

        return lambda s: unquote_plus(s)
    if fn in ("md5", "sha1", "sha256", "sha512"):
        import hashlib as _hl

        algo = fn

        def digest(s, algo=algo):
            return getattr(_hl, algo)(s.encode()).hexdigest()

        return digest
    if fn in ("__vb_md5", "__vb_sha1", "__vb_sha256", "__vb_sha512"):
        import hashlib as _hl

        algo = fn[5:]

        def vb_digest(s, algo=algo):
            raw = getattr(_hl, algo)(s.encode("latin-1")).digest()
            return raw.decode("latin-1")

        return vb_digest
    if fn == "__vb_to_base64":
        import base64 as _b64

        return lambda s: _b64.b64encode(s.encode("latin-1")).decode("ascii")
    if fn == "to_hex":
        return lambda s: s.encode("latin-1").hex().upper()
    if fn == "from_hex":
        def fh(s):
            try:
                return bytes.fromhex(s).decode("latin-1")
            except ValueError:
                return None
        return fh
    if fn == "to_utf8":
        return lambda s: s.encode("utf-8").decode("latin-1")
    if fn == "from_utf8":
        # invalid byte sequences replaced (FromUtf8Function's default)
        return lambda s: s.encode("latin-1").decode("utf-8", "replace")
    if fn == "to_base64":
        import base64 as _b64

        return lambda s: _b64.b64encode(s.encode()).decode()
    if fn == "from_base64":
        import base64 as _b64

        def fb64(s):
            try:
                return _b64.b64decode(s).decode("utf-8", "replace")
            except Exception:
                return None

        return fb64
    if fn == "normalize":
        import unicodedata as _ud

        return lambda s: _ud.normalize("NFC", s)
    if fn in ("__to_ipaddress", "__vb_to_ipaddress", "__ip_to_varchar",
              "__to_ipprefix", "__ipprefix_to_varchar", "__ip_to_bytes",
              "__addr_to_ipprefix", "__ipprefix_to_addr",
              "ip_prefix", "ip_subnet_min", "ip_subnet_max"):
        from presto_tpu.expr import ip as _ip

        if fn == "ip_prefix":
            bits = int(cargs[0])
            return lambda s, _b=bits: _ip.ip_prefix(s, _b)
        if fn == "__addr_to_ipprefix":
            # full-length prefix: /32 for v4-mapped entries, /128 for v6
            def full_pfx(s):
                b = s.encode("latin-1")
                if len(b) != 16:
                    return None
                v4 = b[:12] == bytes(10) + b"\xff\xff"
                return _ip.ip_prefix(s, 32 if v4 else 128)

            return full_pfx
        if fn == "__ipprefix_to_addr":
            return lambda s: s[:16] if len(s) == 17 else None
        if fn == "__ip_to_bytes":
            return lambda s: s  # entries ARE the 16 bytes (latin-1)
        return {"__to_ipaddress": _ip.parse_address,
                "__vb_to_ipaddress": _ip.address_from_bytes,
                "__ip_to_varchar": _ip.format_address,
                "__to_ipprefix": _ip.parse_prefix,
                "__ipprefix_to_varchar": _ip.format_prefix,
                "ip_subnet_min": _ip.subnet_min,
                "ip_subnet_max": _ip.subnet_max}[fn]
    if fn == "scale_tdigest":
        from presto_tpu.expr import tdigest as _td

        factor = float(cargs[0])
        return lambda s, _f=factor: _td.scale(s, _f)
    if fn == "trim":
        return str.strip
    if fn == "ltrim":
        return str.lstrip
    if fn == "rtrim":
        return str.rstrip
    if fn == "reverse":
        return lambda s: s[::-1]
    if fn == "replace":
        old = str(cargs[0])
        new = str(cargs[1]) if len(cargs) > 1 else ""
        return lambda s: s.replace(old, new)
    if fn == "lpad":
        n, fill = int(cargs[0]), str(cargs[1]) if len(cargs) > 1 else " "
        def lpad(s, n=n, fill=fill):
            if len(s) >= n:
                return s[:n]
            pad = (fill * n)[: n - len(s)]
            return pad + s
        return lpad
    if fn == "rpad":
        n, fill = int(cargs[0]), str(cargs[1]) if len(cargs) > 1 else " "
        def rpad(s, n=n, fill=fill):
            if len(s) >= n:
                return s[:n]
            return s + (fill * n)[: n - len(s)]
        return rpad
    if fn == "concat":
        pre, post = str(cargs[0]), str(cargs[1])
        return lambda s: pre + s + post
    if fn == "split_part":
        delim, idx = str(cargs[0]), int(cargs[1])
        def split_part(s, delim=delim, idx=idx):
            parts = s.split(delim)
            return parts[idx - 1] if 0 < idx <= len(parts) else ""
        return split_part
    if fn == "regexp_extract":
        rx = re.compile(str(cargs[0]))
        group = int(cargs[1]) if len(cargs) > 1 and cargs[1] is not None else 0
        def rex(s, rx=rx, group=group):
            m = rx.search(s)
            # Presto returns NULL on no match (and for an unmatched group)
            return m.group(group) if m else None
        return rex
    if fn == "regexp_replace":
        rx = re.compile(str(cargs[0]))
        repl = str(cargs[1]) if len(cargs) > 1 else ""
        # Presto uses $1 for backrefs; python re uses \1
        repl = re.sub(r"\$(\d+)", r"\\\1", repl)
        return lambda s: rx.sub(repl, s)
    if fn == "json_extract_scalar":
        import json as _json

        path = str(cargs[0])
        steps = _parse_json_path(path)
        def jes(s, steps=steps):
            try:
                v = _json.loads(s)
                for st in steps:
                    v = v[st]
            except Exception:
                return None
            if isinstance(v, (dict, list)) or v is None:
                return None  # non-scalar / absent → SQL NULL
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        return jes
    if fn in ("json_extract", "json_array_get"):
        import json as _json

        steps = ([int(cargs[0])] if fn == "json_array_get"
                 else _parse_json_path(str(cargs[0])))

        def jex(s, steps=steps):
            try:
                v = _json.loads(s)
                for st in steps:
                    v = v[st]
            except Exception:
                return None
            return _json.dumps(v, separators=(",", ":"))
        return jex
    if fn == "json_format":
        import json as _json

        def jfmt(s):
            try:
                return _json.dumps(_json.loads(s), separators=(",", ":"))
            except Exception:
                return None
        return jfmt
    if fn == "json_parse":
        import json as _json

        def jp(s):
            try:
                _json.loads(s)
                return s  # JSON is VARCHAR text here; parse = validate
            except Exception:
                # documented deviation: the reference RAISES on malformed
                # input, but dictionary-wide evaluation visits entries
                # that may belong to filtered-out rows — NULL instead
                return None
        return jp
    raise NotImplementedError(fn)


def _parse_json_path(path: str):
    """Subset of JSONPath used by json_extract_scalar: $.a.b[0]['c']."""
    steps = []
    i = 0
    if path.startswith("$"):
        i = 1
    while i < len(path):
        ch = path[i]
        if ch == ".":
            j = i + 1
            while j < len(path) and path[j] not in ".[":
                j += 1
            steps.append(path[i + 1:j])
            i = j
        elif ch == "[":
            j = path.index("]", i)
            inner = path[i + 1:j].strip()
            if inner[:1] in ("'", '"'):
                steps.append(inner[1:-1])
            else:
                steps.append(int(inner))
            i = j + 1
        else:
            raise ValueError(f"bad json path: {path}")
    return steps


def _str_int_pyfn(fn: str, cargs: tuple):
    if fn == "length":
        return len
    if fn == "strpos":
        sub = str(cargs[0])
        return lambda s: s.find(sub) + 1
    if fn == "codepoint":
        return lambda s: ord(s[0]) if s else 0
    if fn == "json_array_length":
        import json as _json

        def jal(s):
            try:
                v = _json.loads(s)
            except Exception:
                return None
            return len(v) if isinstance(v, list) else None  # NULL
        return jal
    if fn == "json_size":
        import json as _json

        steps = _parse_json_path(str(cargs[0]))

        def jsz(s, steps=steps):
            try:
                v = _json.loads(s)
                for st in steps:
                    v = v[st]
            except Exception:
                return None  # absent path → NULL
            return len(v) if isinstance(v, (dict, list)) else 0
        return jsz
    if fn == "__hll_cardinality":
        from presto_tpu.expr.hll import cardinality as _hll_card

        return _hll_card
    if fn == "bit_length":
        return lambda s: 8 * len(s.encode("utf-8"))
    if fn == "__vb_bit_length":
        return lambda s: 8 * len(s)  # latin-1 bijection: 1 char = 1 byte
    if fn == "date_parse":
        from datetime import datetime as _dt

        raw_fmt = str(cargs[0])
        pyfmt = mysql_format_to_strptime(raw_fmt)
        # strptime defaults missing fields to 1900-01-01; the reference
        # defaults to the 1970 epoch — patch the year when the format
        # carries no year directive (month/day already default to 1)
        has_year = any(f"%{c}" in raw_fmt for c in "Yy")
        epoch = _dt(1970, 1, 1)

        def dparse(s, _fmt=pyfmt, _ep=epoch, _hy=has_year):
            try:
                dt = _dt.strptime(s, _fmt)
            except ValueError:
                return None  # unparseable → NULL (documented deviation)
            if not _hy:
                dt = dt.replace(year=1970)
            td = dt - _ep
            return (td.days * 86_400_000_000 + td.seconds * 1_000_000
                    + td.microseconds)

        return dparse
    if fn == "from_iso8601_date":
        import datetime as _d

        def iso_date(s):
            try:
                return _d.date.fromisoformat(s.strip()).toordinal() - 719163
            except ValueError:
                return None

        return iso_date
    if fn == "from_iso8601_timestamp":
        import datetime as _d

        def iso_ts(s):
            try:
                dt = _d.datetime.fromisoformat(s.strip().replace("Z", "+00:00"))
            except ValueError:
                return None
            if dt.tzinfo is not None:
                dt = dt.astimezone(_d.timezone.utc).replace(tzinfo=None)
            td = dt - _d.datetime(1970, 1, 1)
            return (td.days * 86_400_000_000 + td.seconds * 1_000_000
                    + td.microseconds)

        return iso_ts
    if fn == "levenshtein_distance_c":
        other = str(cargs[0])

        def lev(s, other=other):
            if len(s) < len(other):
                s, other = other, s
            prev = list(range(len(other) + 1))
            for i, ca in enumerate(s):
                cur = [i + 1]
                for j, cb in enumerate(other):
                    cur.append(min(prev[j + 1] + 1, cur[j] + 1,
                                   prev[j] + (ca != cb)))
                prev = cur
            return prev[-1]
        return lev
    if fn == "hamming_distance_c":
        other = str(cargs[0])
        return lambda s: sum(a != b for a, b in zip(s, other)) if len(s) == len(other) else -1
    raise NotImplementedError(fn)


def _str_float_pyfn(fn: str, cargs: tuple):
    """TDIGEST scalar family: digest entry → double (None = SQL NULL)."""
    from presto_tpu.expr import tdigest as _td

    if fn == "value_at_quantile":
        q = float(cargs[0])
        return lambda s, _q=q: _td.value_at_quantile(s, _q)
    if fn == "quantile_at_value":
        v = float(cargs[0])
        return lambda s, _v=v: _td.quantile_at_value(s, _v)
    lo, hi = float(cargs[0]), float(cargs[1])
    return lambda s, _lo=lo, _hi=hi: _td.trimmed_mean(s, _lo, _hi)


def _str_pred_pyfn(fn: str, cargs: tuple):
    if fn == "regexp_like":
        rx = re.compile(str(cargs[0]))
        return lambda s: rx.search(s) is not None
    if fn == "starts_with":
        p = str(cargs[0])
        return lambda s: s.startswith(p)
    if fn == "ends_with":
        p = str(cargs[0])
        return lambda s: s.endswith(p)
    if fn == "contains":
        p = str(cargs[0])
        return lambda s: p in s
    if fn == "json_array_contains":
        import json as _json

        want = cargs[0]

        def jac(s, want=want):
            try:
                v = _json.loads(s)
            except Exception:
                return False
            if not isinstance(v, list):
                return False
            for e in v:
                if isinstance(e, bool) or isinstance(want, bool):
                    if e is want:
                        return True
                elif isinstance(e, str) and isinstance(want, str):
                    if e == want:
                        return True
                elif isinstance(e, (int, float)) and isinstance(
                        want, (int, float)):
                    if float(e) == float(want):
                        return True
            return False
        return jac
    if fn == "__is_subnet_of_c":
        # is_subnet_of(<constant prefix>, column): cargs[0] is the
        # canonical 17-byte prefix entry (builder folds the text form)
        from presto_tpu.expr import ip as _ip

        pfx = str(cargs[0])
        return lambda s, _p=pfx: _ip.is_subnet_of(_p, s)
    if fn == "__prefix_contains_c":
        # is_subnet_of(column, <constant address/prefix>): the operand is
        # the prefix column, the constant the contained value
        from presto_tpu.expr import ip as _ip

        inner = str(cargs[0])
        return lambda s, _i=inner: _ip.is_subnet_of(s, _i)
    if fn == "is_json_scalar":
        import json as _json

        def ijs(s):
            try:
                return not isinstance(_json.loads(s), (dict, list))
            except Exception:
                return False
        return ijs
    raise NotImplementedError(fn)


def _xform_parts(e: Call):
    """Split a string-function call into (string_operand, const_args_key).
    For concat, the single non-constant operand with (prefix, suffix)."""
    if e.fn == "concat":
        pre, post, operand = [], [], None
        for a in e.args:
            if isinstance(a, Constant):
                (pre if operand is None else post).append(
                    None if a.value is None else str(a.value)
                )
            elif operand is None:
                operand = a
            else:
                raise NotImplementedError(
                    "concat of two non-constant strings (cross-product "
                    "dictionary) not supported"
                )
        if operand is None:
            raise NotImplementedError("all-constant concat should fold")
        if any(p is None for p in pre + post):
            return operand, None  # NULL operand poisons the whole concat
        return operand, ("".join(pre), "".join(post))
    consts = []
    for a in e.args[1:]:
        if not isinstance(a, Constant):
            raise NotImplementedError(
                f"{e.fn}: non-constant argument {a} not supported "
                "(dictionary transforms need plan-time constants)"
            )
        consts.append(a.value)
    return e.args[0], tuple(consts)


class CompileContext:
    """Static info the compiler needs beyond the IR: the dictionaries of the
    string columns flowing through this fragment, captured at trace time from
    the Batch itself. `out_dict` is the synthesized dictionary for
    string-valued expressions built purely from constants (e.g. CASE WHEN ..
    THEN 'promo' ELSE 'other')."""

    def __init__(self, batch: Batch, out_dict: Dictionary | None = None,
                 extra_dicts: dict | None = None):
        self.batch = batch
        self.out_dict = out_dict
        # lambda-parameter dictionaries (symbol -> Dictionary): params are
        # not batch columns, but string params carry the element dict
        self.extra_dicts = extra_dicts or {}

    def dict_for(self, e: RowExpression) -> Dictionary | None:
        if isinstance(e, InputRef):
            if e.name in self.extra_dicts:
                return self.extra_dicts[e.name]
            return self.batch.dict_of(e.name)
        if isinstance(e, Call):
            if e.fn in _STR_TO_STR:
                nd, _, _ = self.transformed(e)
                return nd
            from presto_tpu.types import ArrayType as _AT, MapType as _MT

            if (e.fn in ("subscript", "element_at") and e.args
                    and isinstance(e.args[0].type, (_AT, _MT))):
                # codes come from the structural operand's element plane
                # (for ARRAY[...] ctors that is the merged literal+column
                # dictionary — the plain arg walk below would return the
                # unmerged column dict and mis-decode)
                return _elem_dict(e.args[0], self)
            for a in e.args:
                d = self.dict_for(a)
                if d is not None:
                    return d
        return None

    def transformed(self, e: Call):
        """(new_dict, remap, operand) for a string-transform call, memoized
        on the operand's dictionary so jit retraces get identical objects.
        remap=None signals a constant-NULL result (NULL in concat)."""
        operand, cargs = _xform_parts(e)
        if cargs is None:
            return None, None, operand
        d = self.dict_for(operand)
        if d is None:
            raise ValueError(f"string function {e.fn} needs a dictionary operand")
        nd, remap = d.transform((e.fn, cargs), _str_xform_pyfn(e.fn, cargs))
        return nd, remap, operand


# ---------------------------------------------------------------------------
# main entry


def _has_string_payload(t: Type) -> bool:
    if t.is_string:
        return True
    if isinstance(t, ArrayType):
        return _has_string_payload(t.element)
    if isinstance(t, MapType):
        return t.key.is_string or t.value.is_string
    return False


def string_output_dictionary(e: RowExpression) -> Dictionary | None:
    """For an expression whose string *values* are all literals (CASE tags,
    ARRAY['a','b'] elements, map() keys and the like), build the
    dictionary those literals resolve against at plan time. Non-string
    output types still need this when structural literals appear inside
    (element_at(map(ARRAY['a'], ...), 'a') is DOUBLE-typed)."""
    if isinstance(e, InputRef):
        return None
    consts: list[str] = []

    def walk(x, value_pos: bool):
        if isinstance(x, Constant) and x.type.is_string and value_pos and x.value is not None:
            consts.append(str(x.value))
        if isinstance(x, Call):
            for i, a in enumerate(x.args):
                # string constants in comparison/LIKE/IN positions resolve
                # against column dictionaries, not the output dictionary
                in_value_pos = x.fn in (
                    "if", "coalesce", "nullif", "array_ctor", "repeat", "map"
                ) or (value_pos and x.fn == "cast")
                walk(a, in_value_pos and a.type.is_string)

    walk(e, True)
    if not consts:
        return None
    import numpy as np

    from presto_tpu.dictionary import safe_str_array

    return Dictionary(np.unique(safe_str_array(
        np.asarray(consts, dtype=object))))


def compile_expr(e: RowExpression):
    """Return fn(batch) -> (values, validity|None)."""
    out_dict = string_output_dictionary(e)

    def fn(batch: Batch):
        ctx = CompileContext(batch, out_dict)
        return _eval(e, ctx)

    fn.out_dict = None
    if isinstance(e.type, (ArrayType, MapType)) and not isinstance(e, InputRef):
        # structural output: (element_dict, key_dict) resolved at trace time
        def sdicts(batch: Batch):
            return struct_dicts(e, CompileContext(batch, out_dict))

        fn.sdicts = sdicts
    if e.type.is_string and not isinstance(e, InputRef):
        # dictionary of the output column depends on the input batch's
        # dictionaries (string transforms, structural subscripts); resolved
        # at trace time — batch dicts are static pytree aux, so this is
        # jit-cache coherent. All-literal expressions (CASE tags) fall back
        # to the plan-time literal dictionary.
        def dyn_dict(batch: Batch):
            d = CompileContext(batch, out_dict).dict_for(e)
            return d if d is not None else out_dict

        fn.dyn_dict = dyn_dict
    return fn


def compile_predicate(e: RowExpression):
    """Return fn(batch) -> bool mask (NULL → False, like Presto filters:
    operator/project/PageFilter discards non-TRUE rows)."""
    out_dict = string_output_dictionary(e)

    def fn(batch: Batch):
        ctx = CompileContext(batch, out_dict)
        v, valid = _eval(e, ctx)
        mask = v.astype(bool)
        if valid is not None:
            mask = mask & valid
        return mask

    return fn


# ---------------------------------------------------------------------------
# evaluation (at trace time)


def _eval(e: RowExpression, ctx: CompileContext):
    if isinstance(e, InputRef):
        c = ctx.batch.column(e.name)
        if c.sizes is not None:
            return StructVal(c.values, c.sizes, c.evalid, c.keys), c.validity
        if c.hi is not None:
            # long decimal (two-limb int128): expressions compute over the
            # combined float64 unscaled value — exact below 2^53, the lossy
            # escape hatch for arithmetic over aggregated sums
            return c.combined_f64(), c.validity
        return c.values, c.validity
    if isinstance(e, Constant):
        return _eval_constant(e, ctx, None)
    if isinstance(e, Call):
        return _eval_call(e, ctx)
    raise NotImplementedError(f"cannot compile {e!r}")


def _eval_constant(e: Constant, ctx: CompileContext, sibling: RowExpression | None):
    """Constants; string constants resolve against the sibling's dictionary."""
    if e.value is None:
        cap = ctx.batch.capacity
        return (
            jnp.zeros(cap, dtype=e.type.dtype),
            jnp.zeros(cap, dtype=bool),
        )
    if e.raw:
        return _const_array(e.value, e.type), None
    if e.type.is_string:
        d = ctx.dict_for(sibling) if sibling is not None else None
        if d is None:
            d = ctx.out_dict
        if d is None:
            raise ValueError("string constant without dictionary context")
        code = d.code_of(str(e.value))
        return jnp.asarray(code, dtype=jnp.int32), None
    if isinstance(e.type, DecimalType):
        unscaled = int(round(float(e.value) * (10 ** e.type.scale)))
        return _const_array(unscaled, e.type), None
    return _const_array(e.value, e.type), None


def _eval_arg(a: RowExpression, ctx, sibling=None):
    if isinstance(a, Constant):
        return _eval_constant(a, ctx, sibling)
    return _eval(a, ctx)


_CMP = {
    "eq": jnp.equal,
    "ne": jnp.not_equal,
    "lt": jnp.less,
    "le": jnp.less_equal,
    "gt": jnp.greater,
    "ge": jnp.greater_equal,
}


_STRUCT_ONLY_FNS = {
    "array_ctor", "array_position", "array_min", "array_max", "array_sum",
    "array_average", "array_distinct", "array_sort", "slice", "sequence",
    "repeat", "map", "map_keys", "map_values",
    "transform", "filter", "reduce", "any_match", "all_match", "none_match",
    "transform_values", "map_filter",
    "array_union", "array_intersect", "array_except", "arrays_overlap",
    "map_concat", "zip_with", "split", "regexp_split", "array_remove",
}
# polymorphic names: structural only when the first arg is ARRAY/MAP
_STRUCT_POLY_FNS = {"cardinality", "contains", "concat", "element_at",
                    "subscript"}


_GEO_FNS = {
    "st_geometryfromtext", "st_point", "st_x", "st_y", "st_distance",
    "st_contains", "st_intersects", "st_area", "st_perimeter", "st_length",
    "st_npoints", "st_xmin", "st_xmax", "st_ymin", "st_ymax", "st_centroid",
    "great_circle_distance",
}


def _eval_call(e: Call, ctx: CompileContext):
    fn = e.fn

    # ---- registered (plugin/user) scalars --------------------------------
    # the analyzer tags them "udf:<name>" so built-ins can never be
    # shadowed (presto_tpu/functions.py — FunctionManager analog); the
    # lowering traces straight into the surrounding fused XLA program
    if fn.startswith("udf:"):
        from presto_tpu.functions import registry as _freg

        udf = _freg().scalar(fn[4:])
        if udf is None:
            raise ValueError(f"function {fn[4:]} is no longer registered")
        cap = ctx.batch.capacity
        vals, valids = [], []
        for a in e.args:
            v, va = _eval_arg(a, ctx)
            if getattr(v, "ndim", 1) == 0:
                v = jnp.broadcast_to(v, (cap,))
            vals.append(v)
            valids.append(va)
        agg_valid = None
        for va in valids:
            agg_valid = _and_valid(agg_valid, va)
        if udf.null_propagating:
            return udf.lower(*vals), agg_valid
        return udf.lower(vals, valids)

    # ---- geospatial ------------------------------------------------------
    if fn in _GEO_FNS:
        return _eval_geo(e, ctx)

    # ---- structural (ARRAY / MAP) ---------------------------------------
    if fn in _STRUCT_ONLY_FNS or (
        fn in _STRUCT_POLY_FNS
        and e.args
        and isinstance(e.args[0].type, (ArrayType, MapType))
    ):
        return _eval_structural(e, ctx)

    # ---- comparisons (incl. dictionary-code string compares) -------------
    if fn in _CMP:
        l, r = e.args
        if l.type.is_string or r.type.is_string:
            return _string_compare(fn, l, r, ctx)
        lv, lval = _eval_arg(l, ctx, r)
        rv, rval = _eval_arg(r, ctx, l)
        lv, rv = _numeric_align(l.type, r.type, lv, rv)
        return _CMP[fn](lv, rv), _and_valid(lval, rval)

    # ---- boolean (Kleene) ------------------------------------------------
    if fn == "and":
        vals, valids = zip(*[_eval_arg(a, ctx) for a in e.args])
        v = vals[0].astype(bool)
        for x in vals[1:]:
            v = v & x.astype(bool)
        # AND is null iff no operand is definitively false and any is null
        known_false = jnp.zeros_like(v)
        any_null = None
        for x, va in zip(vals, valids):
            if va is not None:
                known_false = known_false | (~x.astype(bool) & va)
                any_null = (
                    ~va if any_null is None else (any_null | ~va)
                )
            else:
                known_false = known_false | ~x.astype(bool)
        if any_null is None:
            return v, None
        valid = known_false | ~any_null
        return v & valid, valid
    if fn == "or":
        vals, valids = zip(*[_eval_arg(a, ctx) for a in e.args])
        v = vals[0].astype(bool)
        for x in vals[1:]:
            v = v | x.astype(bool)
        known_true = jnp.zeros_like(v)
        any_null = None
        for x, va in zip(vals, valids):
            if va is not None:
                known_true = known_true | (x.astype(bool) & va)
                any_null = ~va if any_null is None else (any_null | ~va)
            else:
                known_true = known_true | x.astype(bool)
        if any_null is None:
            return v, None
        valid = known_true | ~any_null
        return v, valid
    if fn == "not":
        v, valid = _eval_arg(e.args[0], ctx)
        return ~v.astype(bool), valid

    # ---- null handling ---------------------------------------------------
    if fn == "is_null":
        v, valid = _eval_arg(e.args[0], ctx)
        if valid is None:
            return jnp.zeros(jnp.shape(v), dtype=bool), None
        return ~valid, None
    if fn == "is_not_null":
        v, valid = _eval_arg(e.args[0], ctx)
        if valid is None:
            return jnp.ones(jnp.shape(v), dtype=bool), None
        return valid, None
    if fn == "coalesce":
        out_v, out_valid = _eval_arg(e.args[0], ctx)
        out_v = out_v.astype(e.type.dtype)
        for a in e.args[1:]:
            av, avalid = _eval_arg(a, ctx)
            av = av.astype(e.type.dtype)
            if out_valid is None:
                break
            out_v = jnp.where(out_valid, out_v, av)
            out_valid = out_valid | (
                avalid if avalid is not None else jnp.ones_like(out_valid)
            )
            if avalid is None:
                out_valid = None if out_valid is None else jnp.ones_like(out_v, dtype=bool)
                # fully covered
                return out_v, None
        return out_v, out_valid
    if fn == "nullif":
        av, avalid = _eval_arg(e.args[0], ctx, e.args[1])
        bv, bvalid = _eval_arg(e.args[1], ctx, e.args[0])
        eq = av == bv
        if bvalid is not None:
            eq = eq & bvalid
        valid = avalid if avalid is not None else jnp.ones(jnp.shape(av), bool)
        return av, valid & ~eq

    # ---- control flow ----------------------------------------------------
    if fn == "if":
        cond, then, els = e.args
        cv, cvalid = _eval_arg(cond, ctx)
        cmask = cv.astype(bool)
        if cvalid is not None:
            cmask = cmask & cvalid
        tv, tvalid = _eval_arg(then, ctx, els)
        ev, evalid = _eval_arg(els, ctx, then)
        tv = tv.astype(e.type.dtype)
        ev = ev.astype(e.type.dtype)
        out = jnp.where(cmask, tv, ev)
        if tvalid is None and evalid is None:
            return out, None
        tva = tvalid if tvalid is not None else jnp.ones(jnp.shape(out), bool)
        eva = evalid if evalid is not None else jnp.ones(jnp.shape(out), bool)
        return out, jnp.where(cmask, tva, eva)

    # ---- membership ------------------------------------------------------
    if fn == "in":
        val = e.args[0]
        if val.type.is_string:
            d = ctx.dict_for(val)
            codes = [d.code_of(str(c.value)) for c in e.args[1:]]
            vv, vvalid = _eval(val, ctx)
            m = jnp.zeros(jnp.shape(vv), dtype=bool)
            for c in codes:
                m = m | (vv == c)
            return m, vvalid
        vv, vvalid = _eval_arg(val, ctx)
        m = jnp.zeros(jnp.shape(vv), dtype=bool)
        for c in e.args[1:]:
            cv, _ = _eval_arg(c, ctx, val)
            m = m | (vv == cv)
        return m, vvalid
    if fn == "between":
        v, lo, hi = e.args
        ge = _eval_call(Call(BOOLEAN, "ge", (v, lo)), ctx)
        le = _eval_call(Call(BOOLEAN, "le", (v, hi)), ctx)
        return ge[0] & le[0], _and_valid(ge[1], le[1])

    # ---- LIKE over dictionary -------------------------------------------
    if fn == "like":
        val, pat = e.args[0], e.args[1]
        escape = str(e.args[2].value) if len(e.args) > 2 else None
        d = ctx.dict_for(val)
        if d is None:
            raise ValueError("LIKE on non-dictionary column")
        rx = re.compile(like_to_regex(str(pat.value), escape))
        table = d.int_lut(("like", pat.value, escape),
                          lambda s: rx.match(s) is not None, dtype=np.bool_)
        vv, vvalid = _eval(val, ctx)
        out = jnp.asarray(table)[vv + 1]
        return out, vvalid

    # ---- string functions over dictionaries ------------------------------
    if fn in _STR_TO_STR:
        _, remap, operand = ctx.transformed(e)
        if remap is None:  # NULL constant operand → NULL result
            cap = ctx.batch.capacity
            return jnp.zeros(cap, jnp.int32), jnp.zeros(cap, bool)
        codes, valid = _eval(operand, ctx)
        out = jnp.asarray(remap)[codes + 1]
        if bool((remap[1:] < 0).any()):
            # transform produced NULLs (regexp_extract no-match, absent
            # json path): a negative new code means SQL NULL
            nullable = out >= 0
            valid = nullable if valid is None else (valid & nullable)
        return out, valid
    if fn in _STR_TO_FLOAT:
        # digest entry → double, with a parallel null lut (invalid digest
        # or out-of-domain argument → SQL NULL)
        operand, cargs = _xform_parts(e)
        d = ctx.dict_for(operand)
        if d is None:
            raise ValueError(f"{fn} needs a dictionary operand")
        pyfn = _str_float_pyfn(fn, cargs)
        fmemo: dict = {}

        def ff(s, _m=fmemo, _f=pyfn):
            if s not in _m:
                _m[s] = _f(s)
            return _m[s]

        table = d.int_lut((fn, cargs, "v"),
                          lambda s: ff(s) if ff(s) is not None else 0.0,
                          dtype=np.float64)
        nulls = d.int_lut((fn, cargs, "null"),
                          lambda s: ff(s) is None, dtype=np.bool_)
        codes, valid = _eval(operand, ctx)
        notnull = ~jnp.asarray(nulls)[codes + 1]
        valid = notnull if valid is None else valid & notnull
        return jnp.asarray(table)[codes + 1], valid
    if fn in _STR_TO_INT or fn in _STR_PRED:
        operand, cargs = _xform_parts(e)
        d = ctx.dict_for(operand)
        if d is None:
            raise ValueError(f"{fn} needs a dictionary operand")
        if fn in _STR_TO_INT:
            pyfn = _str_int_pyfn(fn, cargs)
            if fn in _STR_INT_NULLABLE:
                memo: dict = {}  # one parse per entry, not one per lut

                def pf(s, _m=memo, _f=pyfn):
                    if s not in _m:
                        _m[s] = _f(s)
                    return _m[s]

                table = d.int_lut((fn, cargs, "v"),
                                  lambda s: pf(s) or 0)
                nulls = d.int_lut((fn, cargs, "null"),
                                  lambda s: pf(s) is None, dtype=np.bool_)
                codes, valid = _eval(operand, ctx)
                notnull = ~jnp.asarray(nulls)[codes + 1]
                valid = notnull if valid is None else valid & notnull
                # e.type drives the device dtype (DATE luts are int32)
                return jnp.asarray(table)[codes + 1].astype(e.type.dtype), valid
            table = d.int_lut((fn, cargs), pyfn)
        else:
            table = d.int_lut((fn, cargs), _str_pred_pyfn(fn, cargs),
                              dtype=np.bool_)
        codes, valid = _eval(operand, ctx)
        return jnp.asarray(table)[codes + 1], valid

    # ---- HyperLogLog primitives (approx_distinct lowering) ----------------
    # __hll_reg(x): register index = low log2(m) bits of a 64-bit content
    # hash; __hll_rank(x): 1 + leading-zero count of the top 32 hash bits
    # (ranks 1..33 — counts to ~2^32 distinct). The builder lowers
    # approx_distinct into (reg, max(rank)) aggregates over these
    # (reference: ApproximateCountDistinctAggregations' HLL state; here the
    # registers ARE group-table rows so the state rides the existing
    # partial/exchange/final machinery).
    if fn in ("__hll_reg", "__hll_rank"):
        from presto_tpu.ops.hashing import splitmix64

        a = e.args[0]
        av, avalid = _eval(a, ctx)
        if a.type.is_string:
            d = ctx.dict_for(a)
            lut = jnp.asarray(d.content_hash_lut())
            h = splitmix64(lut[av.astype(jnp.int32) + 1].astype(jnp.uint64))
        elif jnp.issubdtype(av.dtype, jnp.floating):
            # hash the BIT PATTERN — astype(int64) would value-truncate and
            # collapse all sub-integer-distinct doubles onto one hash
            bits = jax.lax.bitcast_convert_type(
                av.astype(jnp.float64), jnp.int64)
            # canonicalize -0.0 → +0.0 so equal SQL values hash equal
            bits = jnp.where(av == 0.0, jnp.int64(0), bits)
            h = splitmix64(bits)
        else:
            h = splitmix64(av.astype(jnp.int64))
        if fn == "__hll_reg":
            return (h & jnp.uint64(HLL_M - 1)).astype(jnp.int64), avalid
        w = ((h >> jnp.uint64(32)) & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
        f = jnp.maximum(w.astype(jnp.float64), 1.0)
        rank = jnp.where(w == 0, 33, 32 - jnp.floor(jnp.log2(f)))
        return rank.astype(jnp.int64), avalid

    # ---- quantile-sketch primitive (approx_percentile lowering) ----------
    # __qsk_bucket(x): order-preserving quantization of x as float64 —
    # monotone IEEE-754 integer encoding truncated to its top 24 bits
    # (sign + exponent + 12 mantissa bits → value-space relative error
    # ≤ 2⁻¹² per bucket). The sketch is a histogram over these buckets
    # (reference: qdigest's value-universe compression).
    if fn == "__qsk_bucket":
        av, avalid = _eval_arg(e.args[0], ctx)
        x = av.astype(jnp.float64)
        x = jnp.where(x == 0.0, 0.0, x)  # canonicalize -0.0
        bits = jax.lax.bitcast_convert_type(x, jnp.uint64)
        flip = jnp.where(
            bits >> jnp.uint64(63),
            jnp.uint64(0xFFFFFFFFFFFFFFFF),
            jnp.uint64(0x8000000000000000),
        )
        mono = bits ^ flip
        return (mono >> jnp.uint64(40)).astype(jnp.int64), avalid

    if fn == "__host_date_format":
        raise NotImplementedError(
            "date_format is supported in the top-level SELECT list only "
            "(it is a host finishing projection)")

    # ---- cast ------------------------------------------------------------
    if fn == "cast":
        return _eval_cast(e, ctx)

    # ---- arithmetic ------------------------------------------------------
    if fn in ("add", "sub", "mul", "div", "mod"):
        return _eval_arith(e, ctx)
    if fn == "neg":
        v, valid = _eval_arg(e.args[0], ctx)
        return -v, valid
    if fn == "abs":
        v, valid = _eval_arg(e.args[0], ctx)
        return jnp.abs(v), valid

    # ---- math ------------------------------------------------------------
    _MATH = {
        "sqrt": jnp.sqrt,
        "exp": jnp.exp,
        "ln": jnp.log,
        "floor": jnp.floor,
        "ceil": jnp.ceil,
        "sin": jnp.sin,
        "cos": jnp.cos,
        "tan": jnp.tan,
        "asin": jnp.arcsin,
        "acos": jnp.arccos,
        "atan": jnp.arctan,
        "sinh": jnp.sinh,
        "cosh": jnp.cosh,
        "tanh": jnp.tanh,
        "log2": jnp.log2,
        "log10": jnp.log10,
        "cbrt": jnp.cbrt,
        "degrees": jnp.degrees,
        "radians": jnp.radians,
        "sign": jnp.sign,
        "truncate": jnp.trunc,
    }
    if fn in _MATH:
        v, valid = _eval_arg(e.args[0], ctx)
        return _MATH[fn](v.astype(e.type.dtype)), valid
    if fn == "atan2":
        a, avalid = _eval_arg(e.args[0], ctx)
        b, bvalid = _eval_arg(e.args[1], ctx)
        return jnp.arctan2(a.astype(e.type.dtype), b.astype(e.type.dtype)), _and_valid(avalid, bvalid)
    if fn in ("greatest", "least"):
        # SQL: NULL if any argument is NULL (Presto MathFunctions.greatest)
        op = jnp.maximum if fn == "greatest" else jnp.minimum
        out_v, out_valid = _eval_arg(e.args[0], ctx)
        out_v = out_v.astype(e.type.dtype)
        for a in e.args[1:]:
            av, avalid = _eval_arg(a, ctx)
            out_v = op(out_v, av.astype(e.type.dtype))
            out_valid = _and_valid(out_valid, avalid)
        return out_v, out_valid
    if fn == "round":
        # SQL ROUND is half-away-from-zero (Presto MathFunctions.round),
        # not jnp.round's half-to-even
        v, valid = _eval_arg(e.args[0], ctx)
        if isinstance(e.type, DecimalType):
            if len(e.args) > 1:
                digits = int(e.args[1].value)
            else:
                digits = 0
            src_scale = e.args[0].type.scale
            if digits >= src_scale:
                return v, valid
            f = 10 ** (src_scale - digits)
            return _div_half_away(v, f) * f, valid
        if len(e.args) > 1:
            digits = int(e.args[1].value)
            f = 10.0 ** digits
            return _round_half_away(v * f) / f, valid
        return _round_half_away(v), valid
    if fn == "power":
        a, avalid = _eval_arg(e.args[0], ctx)
        b, bvalid = _eval_arg(e.args[1], ctx)
        return jnp.power(a.astype(e.type.dtype), b.astype(e.type.dtype)), _and_valid(avalid, bvalid)
    if fn in ("bitwise_and", "bitwise_or", "bitwise_xor",
              "bitwise_left_shift", "bitwise_right_shift"):
        a, avalid = _eval_arg(e.args[0], ctx)
        b, bvalid = _eval_arg(e.args[1], ctx)
        a = a.astype(jnp.int64)
        b = b.astype(jnp.int64)
        out = {
            "bitwise_and": lambda: a & b,
            "bitwise_or": lambda: a | b,
            "bitwise_xor": lambda: a ^ b,
            "bitwise_left_shift": lambda: a << b,
            "bitwise_right_shift": lambda: jax.lax.shift_right_logical(a, b),
        }[fn]()
        return out, _and_valid(avalid, bvalid)
    if fn == "bitwise_not":
        v, valid = _eval_arg(e.args[0], ctx)
        return ~v.astype(jnp.int64), valid
    if fn in ("is_nan", "is_finite", "is_infinite"):
        v, valid = _eval_arg(e.args[0], ctx)
        out = {"is_nan": jnp.isnan, "is_finite": jnp.isfinite,
               "is_infinite": jnp.isinf}[fn](v.astype(jnp.float64))
        return out, valid
    if fn == "from_unixtime":
        v, valid = _eval_arg(e.args[0], ctx)
        return (v.astype(jnp.float64) * 1e6).astype(jnp.int64), valid
    if fn == "to_unixtime":
        v, valid = _eval_arg(e.args[0], ctx)
        return v.astype(jnp.float64) / 1e6, valid
    if fn == "width_bucket":
        v, valid = _eval_arg(e.args[0], ctx)
        lo = float(e.args[1].value)
        hi = float(e.args[2].value)
        nb = int(e.args[3].value)
        x = v.astype(jnp.float64)
        bucket = jnp.floor((x - lo) / (hi - lo) * nb).astype(jnp.int64) + 1
        bucket = jnp.clip(bucket, 0, nb + 1)
        return bucket, valid

    # ---- date ------------------------------------------------------------
    def _as_days(a, v):
        # TIMESTAMP operands (micros since epoch) reduce to civil days;
        # DATE is already days
        if a.type.name == "timestamp":
            return jnp.floor_divide(v.astype(jnp.int64),
                                    86_400_000_000).astype(jnp.int32)
        return v.astype(jnp.int32)

    if fn in ("year", "month", "day"):
        v, valid = _eval_arg(e.args[0], ctx)
        y, m, d = _civil_from_days(_as_days(e.args[0], v))
        return {"year": y, "month": m, "day": d}[fn].astype(jnp.int64), valid
    if fn == "quarter":
        v, valid = _eval_arg(e.args[0], ctx)
        _, m, _ = _civil_from_days(_as_days(e.args[0], v))
        return ((m - 1) // 3 + 1).astype(jnp.int64), valid
    if fn in ("__time_hour", "__time_minute", "__time_second"):
        # TIME (micros-of-day) and TIMESTAMP (micros-since-epoch) both
        # reduce mod one day
        v, valid = _eval_arg(e.args[0], ctx)
        tod = jnp.mod(v.astype(jnp.int64), 86_400_000_000)
        if fn == "__time_hour":
            out = tod // 3_600_000_000
        elif fn == "__time_minute":
            out = (tod // 60_000_000) % 60
        else:
            out = (tod // 1_000_000) % 60
        return out, valid
    if fn == "day_of_week":
        # ISO: 1 = Monday … 7 = Sunday; epoch day 0 (1970-01-01) is Thursday
        v, valid = _eval_arg(e.args[0], ctx)
        return (jnp.mod(_as_days(e.args[0], v).astype(jnp.int64) + 3, 7)
                + 1), valid
    if fn == "day_of_year":
        v, valid = _eval_arg(e.args[0], ctx)
        days = _as_days(e.args[0], v)
        y, _, _ = _civil_from_days(days)
        return (days - _days_from_civil_vec(y, 1, 1) + 1).astype(jnp.int64), valid
    if fn == "date_add_days":
        v, valid = _eval_arg(e.args[0], ctx)
        dv, dvalid = _eval_arg(e.args[1], ctx)
        return v + dv.astype(v.dtype), _and_valid(valid, dvalid)
    if fn == "date_trunc":
        unit = str(e.args[0].value).lower()
        v, valid = _eval_arg(e.args[1], ctx)
        days = v.astype(jnp.int32)
        if unit == "day":
            return days, valid
        if unit == "week":
            return days - jnp.mod(days + 3, 7), valid
        y, m, _ = _civil_from_days(days)
        if unit == "month":
            return _days_from_civil_vec(y, m, 1), valid
        if unit == "quarter":
            return _days_from_civil_vec(y, ((m - 1) // 3) * 3 + 1, 1), valid
        if unit == "year":
            return _days_from_civil_vec(y, 1, 1), valid
        raise NotImplementedError(f"date_trunc unit {unit}")
    if fn == "date_diff":
        unit = str(e.args[0].value).lower()
        a, avalid = _eval_arg(e.args[1], ctx)
        b, bvalid = _eval_arg(e.args[2], ctx)
        valid = _and_valid(avalid, bvalid)
        a64, b64 = a.astype(jnp.int64), b.astype(jnp.int64)
        if unit == "day":
            return b64 - a64, valid
        if unit == "week":
            return (b64 - a64) // 7, valid
        ya, ma, da = _civil_from_days(a.astype(jnp.int32))
        yb, mb, db = _civil_from_days(b.astype(jnp.int32))
        months = (yb.astype(jnp.int64) * 12 + mb) - (ya.astype(jnp.int64) * 12 + ma)
        # truncate toward zero on the day-of-month remainder
        months = months - jnp.where((months > 0) & (db < da), 1, 0)
        months = months + jnp.where((months < 0) & (db > da), 1, 0)
        if unit == "month":
            return months, valid
        if unit == "quarter":
            return months // 3, valid
        if unit == "year":
            return months // 12, valid
        raise NotImplementedError(f"date_diff unit {unit}")
    if fn == "date_add_unit":
        unit = str(e.args[0].value).lower()
        n, nvalid = _eval_arg(e.args[1], ctx)
        v, valid = _eval_arg(e.args[2], ctx)
        valid = _and_valid(valid, nvalid)
        days = v.astype(jnp.int32)
        n = n.astype(jnp.int32)
        if unit == "day":
            return days + n, valid
        if unit == "week":
            return days + 7 * n, valid
        y, m, d = _civil_from_days(days)
        mult = {"month": 1, "quarter": 3, "year": 12}.get(unit)
        if mult is None:
            raise NotImplementedError(f"date_add unit {unit}")
        total = y * 12 + (m - 1) + n * mult
        y2 = total // 12
        m2 = jnp.mod(total, 12) + 1
        d2 = jnp.minimum(d, _days_in_month(y2, m2))
        return _days_from_civil_vec(y2, m2, d2), valid

    raise NotImplementedError(f"scalar function not implemented: {fn}")


# ---------------------------------------------------------------------------
# structural (ARRAY / MAP) evaluation


def _array_ctor_dict(e: Call, ctx: CompileContext) -> Dictionary | None:
    """Element dictionary of ARRAY[...] over string operands: the UNION of
    every operand column's dictionary and the literal elements — a literal
    absent from a column dictionary must still get a real code (operand
    codes are remapped into this union at evaluation time)."""
    import numpy as np

    d = None
    for a in e.args:
        if isinstance(a, Constant):
            continue
        ad = ctx.dict_for(a)
        if ad is not None:
            d = ad if d is None or d is ad else Dictionary.merge(d, ad)
    lits = sorted({str(a.value) for a in e.args
                   if isinstance(a, Constant) and a.value is not None})
    if lits:
        # object dtype: dtype=str would drop trailing NULs of canonical
        # VARBINARY/IPADDRESS entries (dictionary.safe_str_array)
        ld, _ = Dictionary.encode(np.asarray(lits, dtype=object))
        d = ld if d is None else Dictionary.merge(d, ld)
    return d


def _setop_elem_dict(e: Call, ctx: CompileContext) -> Dictionary | None:
    """Merged element dictionary across every operand of an array/map
    set-style function (codes must share one space to compare)."""
    from presto_tpu.types import ArrayType as _AT, MapType as _MT

    t0 = e.args[0].type
    elem = t0.element if isinstance(t0, _AT) else t0.value
    if not elem.is_string:
        return None
    d = None
    for a in e.args:
        ad = _elem_dict(a, ctx)
        if ad is not None:
            d = ad if d is None or d is ad else Dictionary.merge(d, ad)
    return d


def _setop_key_dict(e: Call, ctx: CompileContext) -> Dictionary | None:
    d = None
    for a in e.args:
        ad = _key_dict(a, ctx)
        if ad is not None:
            d = ad if d is None or d is ad else Dictionary.merge(d, ad)
    return d


def regexp_split_pieces(pattern: str):
    """Splitter matching the reference: capture groups in the pattern
    must NOT leak into the result (Python re.split interleaves them at
    positions that are not multiples of groups+1)."""
    rx = re.compile(pattern)
    if not rx.groups:
        return rx.split
    step = rx.groups + 1
    return lambda s, _rx=rx, _st=step: _rx.split(s)[::_st]


def _split_tables(d: Dictionary, fn: str, cargs: tuple):
    """split/regexp_split over a dictionary: per-entry piece lists →
    (element_dict, [len+1, W] code plane, [len+1] sizes), row 0 = NULL.
    Memoized on the dictionary like transform()."""
    key = ("__split", fn, cargs)
    hit = d._memo.get(key)
    if hit is not None:
        return hit
    if fn == "split":
        delim = str(cargs[0])
        limit = int(cargs[1]) if len(cargs) > 1 else None
        # SQL limit = max array size; the last element takes the rest
        splitter = (lambda s: s.split(delim) if limit is None
                    else s.split(delim, limit - 1))
    else:
        splitter = regexp_split_pieces(str(cargs[0]))
    pieces = [splitter(str(v)) for v in d.values]
    from presto_tpu.dictionary import safe_str_array

    uniq = sorted({p for ps in pieces for p in ps}) or [""]
    ed = Dictionary(np.unique(safe_str_array(
        np.asarray(uniq, dtype=object))))
    w = max((len(ps) for ps in pieces), default=1) or 1
    n = len(d.values)
    plane = np.zeros((n + 1, w), np.int32)
    sizes = np.zeros(n + 1, np.int32)
    for i, ps in enumerate(pieces):
        sizes[i + 1] = len(ps)
        for j, p in enumerate(ps):
            plane[i + 1, j] = ed.code_of(p)
    d._memo[key] = (ed, plane, sizes)
    return ed, plane, sizes


def _elem_dict(e: RowExpression, ctx: CompileContext) -> Dictionary | None:
    """Dictionary of a structural expression's (string) element plane."""
    if isinstance(e, InputRef):
        return ctx.batch.dict_of(e.name)
    if isinstance(e, Call):
        if e.fn == "array_ctor" and e.type.element.is_string:
            return _array_ctor_dict(e, ctx)
        if e.fn in ("split", "regexp_split"):
            operand, cargs = _xform_parts(e)
            d = ctx.dict_for(operand)
            return None if d is None else _split_tables(d, e.fn, cargs)[0]
        if e.fn == "array_remove":
            return _elem_dict(e.args[0], ctx)
        if e.fn == "map":
            return _elem_dict(e.args[1], ctx)
        if e.fn == "map_keys":
            return _key_dict(e.args[0], ctx)
        if e.fn in ("array_union", "array_intersect", "array_except",
                    "map_concat"):
            return _setop_elem_dict(e, ctx)
        if e.fn in ("transform", "transform_values"):
            # output element dict = the body's dict with the params bound
            # to the input's element/key dicts (dict transforms are
            # dictionary-level, so no element batch is needed here)
            le = e.args[1]
            bound = dict(ctx.extra_dicts)
            if e.fn == "transform":
                bound[le.params[0][0]] = _elem_dict(e.args[0], ctx)
            else:
                bound[le.params[0][0]] = _key_dict(e.args[0], ctx)
                bound[le.params[1][0]] = _elem_dict(e.args[0], ctx)
            sub = CompileContext(ctx.batch, ctx.out_dict, bound)
            return sub.dict_for(le.body)
        for a in e.args:
            if isinstance(a.type, (ArrayType, MapType)) or a.type.is_string:
                d = _elem_dict(a, ctx) if isinstance(
                    a.type, (ArrayType, MapType)) else ctx.dict_for(a)
                if d is not None:
                    return d
    return ctx.out_dict


def _key_dict(e: RowExpression, ctx: CompileContext) -> Dictionary | None:
    """Dictionary of a map expression's (string) key plane."""
    if isinstance(e, InputRef):
        return ctx.batch.dict_of(e.name + "#keys")
    if isinstance(e, Call):
        if e.fn == "map":
            return _elem_dict(e.args[0], ctx)
        if e.fn in ("transform_values", "map_filter"):
            return _key_dict(e.args[0], ctx)
        if e.fn == "map_concat":
            return _setop_key_dict(e, ctx)
        for a in e.args:
            if isinstance(a.type, MapType):
                d = _key_dict(a, ctx)
                if d is not None:
                    return d
    return None


def struct_dicts(e: RowExpression, ctx: CompileContext):
    """(element_dict, key_dict) a projected structural column should carry."""
    t = e.type
    ed = kd = None
    if isinstance(t, ArrayType) and t.element.is_string:
        ed = _elem_dict(e, ctx)
    if isinstance(t, MapType):
        if t.value.is_string:
            ed = _elem_dict(e, ctx)
        if t.key.is_string:
            kd = _key_dict(e, ctx)
    return ed, kd


def _eval_struct_const(a: Constant, ctx, d: Dictionary | None):
    """A scalar constant appearing inside a structural expression; string
    constants resolve against the element/key dictionary `d`."""
    if a.value is None:
        cap = ctx.batch.capacity
        return jnp.zeros(cap, a.type.dtype), jnp.zeros(cap, bool)
    if a.type.is_string:
        if d is None:
            d = ctx.out_dict
        if d is None:
            raise ValueError("string constant in structural expression "
                             "without a dictionary context")
        return jnp.asarray(d.code_of(str(a.value)), jnp.int32), None
    return _eval_constant(a, ctx, None)


def _eval_structural(e: Call, ctx: CompileContext):
    fn = e.fn
    cap = ctx.batch.capacity

    def scalar_arg(a: RowExpression, d: Dictionary | None = None):
        if isinstance(a, Constant):
            v, valid = _eval_struct_const(a, ctx, d)
        else:
            v, valid = _eval(a, ctx)
        return jnp.broadcast_to(v, (cap,)), valid

    if fn == "array_ctor":
        et = e.type.element
        if et.is_string:
            # unified element dictionary: operand codes remap into the
            # union so column values and literals share one code space
            d = _array_ctor_dict(e, ctx)
            parts = []
            for a in e.args:
                if isinstance(a, Constant):
                    v, valid = _eval_struct_const(a, ctx, d)
                else:
                    v, valid = _eval(a, ctx)
                    ad = ctx.dict_for(a)
                    if ad is not None and ad is not d:
                        remap = jnp.asarray(ad.map_to(d))
                        v = remap[v.astype(jnp.int32) + 1]
                parts.append((jnp.broadcast_to(v, (cap,)), valid))
            return _struct.array_ctor(parts, cap, et.dtype), None
        parts = [scalar_arg(a) for a in e.args]
        return _struct.array_ctor(parts, cap, et.dtype), None

    if fn in ("split", "regexp_split"):
        # per-dictionary-entry expansion (StringFunctions.split): pieces
        # and sizes are host tables over the operand dictionary; rows get
        # them via one 2D gather, so the device never sees text
        operand, cargs = _xform_parts(e)
        d = ctx.dict_for(operand)
        if d is None:
            raise ValueError(f"{fn} needs a dictionary operand")
        _, plane, sizes = _split_tables(d, fn, cargs)
        codes, valid = _eval(operand, ctx)
        return _struct.StructVal(
            jnp.asarray(plane)[codes.astype(jnp.int32) + 1],
            jnp.asarray(sizes)[codes.astype(jnp.int32) + 1], None), valid

    if fn == "array_remove":
        sv0, rvalid0 = _eval(e.args[0], ctx)
        d = (_elem_dict(e.args[0], ctx)
             if e.args[0].type.element.is_string else None)
        xv, xvalid = scalar_arg(e.args[1], d)
        # equality only counts for present, non-null elements; NULL
        # elements are retained (unknown ≠ element, Presto semantics).
        # Mixed numeric widths compare in float64 (truncating 1.5 to an
        # int element dtype would remove the WRONG elements)
        xb = jnp.broadcast_to(xv, (cap,))
        if xb.dtype != sv0.values.dtype:
            equal = (sv0.values.astype(jnp.float64)
                     == xb.astype(jnp.float64)[:, None])
        else:
            equal = sv0.values == xb[:, None]
        keep = sv0.present() & ~(equal & sv0.element_valid())
        out = _struct.filter_elements(sv0, keep)
        # NULL element argument → NULL result (ArrayRemoveFunction)
        return out, _and_valid(rvalid0, xvalid)

    if fn == "sequence":
        lo = int(e.args[0].value)
        hi = int(e.args[1].value)
        step = int(e.args[2].value) if len(e.args) > 2 else (
            1 if hi >= lo else -1)
        return _struct.sequence(lo, hi, step, cap), None

    if fn == "repeat":
        n = int(e.args[1].value)
        et = e.type.element
        d = _elem_dict(e, ctx) if et.is_string else None
        v, valid = scalar_arg(e.args[0], d)
        return _struct.repeat_val(v, valid, n, cap, et.dtype), None

    if fn == "map":
        ksv, kvalid = _eval(e.args[0], ctx)
        vsv, vvalid = _eval(e.args[1], ctx)
        return _struct.map_from_arrays(ksv, vsv), _and_valid(kvalid, vvalid)

    if fn == "reduce":
        return _eval_reduce(e, ctx)

    if fn == "zip_with":
        return _eval_zip_with(e, ctx)

    # remaining forms evaluate their structural operand first
    sv, rvalid = _eval(e.args[0], ctx)
    t0 = e.args[0].type

    if fn == "cardinality":
        return _struct.cardinality(sv, rvalid)
    if fn in ("subscript", "element_at"):
        if isinstance(t0, MapType):
            d = _key_dict(e.args[0], ctx) if t0.key.is_string else None
            kv, kvalid = scalar_arg(e.args[1], d)
            return _struct.map_element_at(sv, kv, kvalid, rvalid)
        iv, ivalid = scalar_arg(e.args[1])
        return _struct.subscript(sv, iv.astype(jnp.int64), ivalid, rvalid,
                                 null_oob=(fn == "element_at"))
    if fn == "contains":
        d = _elem_dict(e.args[0], ctx) if t0.element.is_string else None
        xv, xvalid = scalar_arg(e.args[1], d)
        return _struct.contains(sv, xv, xvalid, rvalid)
    if fn == "array_position":
        d = _elem_dict(e.args[0], ctx) if t0.element.is_string else None
        xv, xvalid = scalar_arg(e.args[1], d)
        return _struct.array_position(sv, xv, xvalid, rvalid)
    if fn in ("array_min", "array_max"):
        return _struct.array_minmax(sv, rvalid, fn == "array_min")
    if fn in ("array_sum", "array_average"):
        return _struct.array_sum(sv, rvalid, e.type.dtype,
                                 fn == "array_average")
    if fn == "array_sort":
        return _struct.array_sort(sv), rvalid
    if fn == "array_distinct":
        return _struct.array_distinct(sv), rvalid
    if fn == "slice":
        sv0 = sv
        s, svalid = scalar_arg(e.args[1])
        ln, lvalid = scalar_arg(e.args[2])
        out = _struct.slice_array(sv0, s.astype(jnp.int64),
                                  ln.astype(jnp.int64))
        return out, _and_valid(rvalid, _and_valid(svalid, lvalid))
    if fn == "concat":
        out, valid = sv, rvalid
        for a in e.args[1:]:
            asv, avalid = _eval(a, ctx)
            out = _struct.concat_arrays(out, asv)
            valid = _and_valid(valid, avalid)
        return out, valid
    if fn == "map_keys":
        return _struct.map_keys(sv), rvalid
    if fn == "map_values":
        return _struct.map_values(sv), rvalid
    if fn in ("array_union", "array_intersect", "array_except",
              "arrays_overlap", "map_concat"):
        t0 = e.args[0].type
        target = _setop_elem_dict(e, ctx)
        ktarget = (_setop_key_dict(e, ctx)
                   if fn == "map_concat" and t0.key.is_string else None)

        def aligned(arg, s):
            if target is not None:
                d = _elem_dict(arg, ctx)
                if d is not None and d is not target:
                    remap = jnp.asarray(d.map_to(target))
                    s = s._replace(
                        values=remap[s.values.astype(jnp.int32) + 1])
            if ktarget is not None:
                d = _key_dict(arg, ctx)
                if d is not None and d is not ktarget:
                    remap = jnp.asarray(d.map_to(ktarget))
                    s = s._replace(
                        keys=remap[s.keys.astype(jnp.int32) + 1])
            return s

        out, valid = aligned(e.args[0], sv), rvalid
        for a in e.args[1:]:
            osv, ovalid = _eval(a, ctx)
            osv = aligned(a, osv)
            valid = _and_valid(valid, ovalid)
            if fn == "array_union":
                out = _struct.array_union(out, osv)
            elif fn == "array_intersect":
                out = _struct.array_intersect(out, osv)
            elif fn == "array_except":
                out = _struct.array_except(out, osv)
            elif fn == "map_concat":
                out = _struct.map_concat(out, osv)
            else:
                return _struct.arrays_overlap(out, osv), valid
        return out, valid
    if fn in ("transform", "filter", "any_match", "all_match", "none_match"):
        return _eval_higher_order(e, ctx, sv, rvalid)
    if fn in ("transform_values", "map_filter"):
        return _eval_map_higher_order(e, ctx, sv, rvalid)
    raise NotImplementedError(f"structural function not implemented: {fn}")


def _eval_map_higher_order(e: Call, ctx: CompileContext, sv: StructVal,
                           rvalid):
    """transform_values / map_filter: the (k, v) lambda evaluates over the
    flattened key+value planes together."""
    fn = e.fn
    cap = ctx.batch.capacity
    le: LambdaExpr = e.args[1]
    (ksym, kt), (vsym, vt) = le.params
    w = sv.width
    if w == 0:
        return sv, rvalid
    present = sv.present()
    evalid = sv.element_valid()
    kdict = _key_dict(e.args[0], ctx) if kt.is_string else None
    vdict = _elem_dict(e.args[0], ctx) if vt.is_string else None
    eb, extra = _element_batch(ctx, w, [
        (ksym, kt, sv.keys.reshape(-1), present.reshape(-1), kdict),
        (vsym, vt, sv.values.reshape(-1), evalid.reshape(-1), vdict),
    ])
    bctx = CompileContext(eb, ctx.out_dict, extra)
    bv, bvalid = _eval(le.body, bctx)
    bv = jnp.broadcast_to(bv, (cap * w,)).reshape(cap, w)
    bvalid2 = (jnp.broadcast_to(bvalid, (cap * w,)).reshape(cap, w)
               if bvalid is not None else None)
    if fn == "transform_values":
        out = StructVal(bv.astype(le.type.dtype), sv.sizes, bvalid2,
                        keys=sv.keys)
        return out, rvalid
    truth = bv.astype(bool)
    if bvalid2 is not None:
        truth = truth & bvalid2
    return _struct.filter_elements(sv, truth & present), rvalid


def _repeat_column(c, w: int):
    """Row i of the outer batch → rows i*w..(i+1)*w-1 (lambda bodies may
    capture outer columns). gather() replicates every plane."""
    cap = c.values.shape[0]
    idx = jnp.repeat(jnp.arange(cap, dtype=jnp.int32), w)
    return c.gather(idx)


def _element_batch(ctx: CompileContext, w: int, param_cols) -> Batch:
    """Synthetic [cap*w]-row batch: outer columns repeated per element
    slot + the lambda parameter columns (flattened element planes). The
    lambda body compiles over it exactly like any row expression —
    vectorized over every element of every row at once."""
    b = ctx.batch
    names = list(b.names)
    types = list(b.types)
    cols = [_repeat_column(c, w) for c in b.columns]
    dicts = dict(b.dicts)
    extra = {}
    for sym, t, vals, valid, d in param_cols:
        names.append(sym)
        types.append(t)
        cols.append(Column(vals, valid))
        if d is not None:
            dicts[sym] = d
            extra[sym] = d
    live = jnp.repeat(b.live, w)
    eb = Batch(names, types, cols, live, dicts)
    return eb, extra


def _eval_higher_order(e: Call, ctx: CompileContext, sv: StructVal, rvalid):
    """transform/filter/…_match: the lambda body evaluates once over the
    flattened [cap*w] element plane (no per-element interpretation —
    LambdaDefinitionExpression codegen redesigned as plane vectorization)."""
    fn = e.fn
    cap = ctx.batch.capacity
    le: LambdaExpr = e.args[1]
    (psym, pt), = le.params
    w = sv.width
    if w == 0:
        if fn == "transform":
            return StructVal(jnp.zeros((cap, 0), le.type.dtype), sv.sizes,
                             None), rvalid
        if fn == "filter":
            return sv, rvalid
        empty = jnp.zeros(cap, bool)
        return (~empty if fn in ("all_match", "none_match") else empty), rvalid

    present = sv.present()
    evalid = sv.element_valid()
    pdict = _elem_dict(e.args[0], ctx) if pt.is_string else None
    eb, extra = _element_batch(
        ctx, w,
        [(psym, pt, sv.values.reshape(-1), evalid.reshape(-1), pdict)])
    bctx = CompileContext(eb, ctx.out_dict, extra)
    bv, bvalid = _eval(le.body, bctx)
    bv = jnp.broadcast_to(bv, (cap * w,)).reshape(cap, w)
    bvalid2 = (jnp.broadcast_to(bvalid, (cap * w,)).reshape(cap, w)
               if bvalid is not None else None)

    if fn == "transform":
        out = StructVal(bv.astype(le.type.dtype), sv.sizes, bvalid2)
        return out, rvalid
    truth = bv.astype(bool)
    if bvalid2 is not None:
        truth = truth & bvalid2  # NULL predicate counts as not-matching
    if fn == "filter":
        return _struct.filter_elements(sv, truth & present), rvalid
    if fn == "any_match":
        return jnp.any(truth & present, axis=1), rvalid
    if fn == "all_match":
        return jnp.all(truth | ~present, axis=1), rvalid
    return ~jnp.any(truth & present, axis=1), rvalid  # none_match


def _eval_zip_with(e: Call, ctx: CompileContext):
    """zip_with(a, b, (x, y) -> ...): planes pad to the longer array (the
    shorter side's missing elements are NULL params — Presto's padding);
    the lambda body evaluates once over the paired flattened planes."""
    from presto_tpu.expr.structural import pad_plane_width

    asv, avalid = _eval(e.args[0], ctx)
    bsv, bvalid = _eval(e.args[1], ctx)
    le: LambdaExpr = e.args[2]
    (xsym, xt), (ysym, yt) = le.params
    cap = ctx.batch.capacity
    w = max(asv.width, bsv.width, 1)
    av = pad_plane_width(asv.values, w)
    bv = pad_plane_width(bsv.values, w)
    aev = pad_plane_width(asv.element_valid(), w, False)
    bev = pad_plane_width(bsv.element_valid(), w, False)
    xdict = _elem_dict(e.args[0], ctx) if xt.is_string else None
    ydict = _elem_dict(e.args[1], ctx) if yt.is_string else None
    eb, extra = _element_batch(ctx, w, [
        (xsym, xt, av.reshape(-1), aev.reshape(-1), xdict),
        (ysym, yt, bv.reshape(-1), bev.reshape(-1), ydict),
    ])
    bctx = CompileContext(eb, ctx.out_dict, extra)
    ov, ovalid = _eval(le.body, bctx)
    ov = jnp.broadcast_to(ov, (cap * w,)).reshape(cap, w)
    ovalid2 = (jnp.broadcast_to(ovalid, (cap * w,)).reshape(cap, w)
               if ovalid is not None else None)
    sizes = jnp.maximum(asv.sizes, bsv.sizes)
    out = StructVal(ov.astype(le.type.dtype), sizes, ovalid2)
    return out, _and_valid(avalid, bvalid)


def _eval_reduce(e: Call, ctx: CompileContext):
    """reduce(arr, init, (state, x) -> ...): trace-time unrolled fold over
    the W element slots — each step is one vectorized body evaluation over
    all rows (W is the static plane width, typically small)."""
    sv, rvalid = _eval(e.args[0], ctx)
    iv, ivalid = _eval_arg(e.args[1], ctx)
    le: LambdaExpr = e.args[2]
    (ssym, st), (xsym, xt) = le.params
    cap = ctx.batch.capacity
    acc_v = jnp.broadcast_to(iv, (cap,)).astype(st.dtype)
    acc_valid = (jnp.broadcast_to(ivalid, (cap,)) if ivalid is not None
                 else jnp.ones(cap, bool))
    present = sv.present()
    evalid = sv.element_valid()
    xdict = _elem_dict(e.args[0], ctx) if xt.is_string else None
    for j in range(sv.width):
        eb, extra = _element_batch(ctx, 1, [
            (ssym, st, acc_v, acc_valid, None),
            (xsym, xt, sv.values[:, j], evalid[:, j], xdict),
        ])
        bctx = CompileContext(eb, ctx.out_dict, extra)
        bv, bvalid = _eval(le.body, bctx)
        bv = jnp.broadcast_to(bv, (cap,)).astype(st.dtype)
        bvalid = (jnp.broadcast_to(bvalid, (cap,))
                  if bvalid is not None else jnp.ones(cap, bool))
        active = present[:, j]
        acc_v = jnp.where(active, bv, acc_v)
        acc_valid = jnp.where(active, bvalid, acc_valid)
    valid = acc_valid
    if rvalid is not None:
        valid = valid & rvalid
    return acc_v, valid


def _days_in_month(y, m):
    base = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])[m - 1]
    leap = ((jnp.mod(y, 4) == 0) & (jnp.mod(y, 100) != 0)) | (jnp.mod(y, 400) == 0)
    return jnp.where((m == 2) & leap, 29, base)


def _days_from_civil_vec(y, m, d):
    """Vectorized inverse of _civil_from_days (same Hinnant algorithm)."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _numeric_align(lt: Type, rt: Type, lv, rv):
    """Align device representations for comparison (analyzer guarantees the
    SQL types are comparable; decimals arrive same-scale via casts)."""
    if lv.dtype != rv.dtype:
        target = jnp.result_type(lv.dtype, rv.dtype)
        lv = lv.astype(target)
        rv = rv.astype(target)
    return lv, rv


def _string_compare(op: str, l: RowExpression, r: RowExpression, ctx):
    """Dictionary-code string comparison. Order-preserving dictionaries make
    range compares on codes correct when both sides share one dictionary;
    cross-dictionary equality remaps codes via a host-built table."""
    lconst = isinstance(l, Constant)
    rconst = isinstance(r, Constant)
    if lconst and not rconst:
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
        return _string_compare(flip.get(op, op), r, l, ctx)
    if rconst:
        d = ctx.dict_for(l)
        if d is None:
            raise ValueError(f"no dictionary for {l}")
        s = str(r.value)
        lv, lvalid = _eval(l, ctx)
        if op in ("eq", "ne"):
            code = d.code_of(s)
            m = lv == code
            return (m if op == "eq" else ~m), lvalid
        # range predicate via searchsorted position in the sorted dictionary
        if op == "lt":
            pos = d.range_codes(s, "left")
            return lv < pos, lvalid
        if op == "le":
            pos = d.range_codes(s, "right")
            return lv < pos, lvalid
        if op == "gt":
            pos = d.range_codes(s, "right")
            return lv >= pos, lvalid
        if op == "ge":
            pos = d.range_codes(s, "left")
            return lv >= pos, lvalid
    # column vs column
    ld = ctx.dict_for(l)
    rd = ctx.dict_for(r)
    lv, lvalid = _eval(l, ctx)
    rv, rvalid = _eval(r, ctx)
    valid = _and_valid(lvalid, rvalid)
    if ld is rd or rd is None or ld is None:
        return _CMP[op](lv, rv), valid
    if op in ("eq", "ne"):
        remap = jnp.asarray(ld.map_to(rd))
        lv2 = remap[lv + 1]
        m = (lv2 == rv) & (lv2 >= 0)
        return (m if op == "eq" else ~m), valid
    raise NotImplementedError("cross-dictionary range comparison")


def _eval_arith(e: Call, ctx):
    l, r = e.args
    lv, lvalid = _eval_arg(l, ctx, r)
    rv, rvalid = _eval_arg(r, ctx, l)
    valid = _and_valid(lvalid, rvalid)
    out_t = e.type
    ldec = isinstance(l.type, DecimalType)
    rdec = isinstance(r.type, DecimalType)
    if isinstance(out_t, DecimalType):
        # exact scaled-int64 arithmetic (reference: short-decimal paths in
        # spi/type/DecimalOperators); analyzer pre-aligned scales for add/sub
        if e.fn == "div":
            return _decimal_div(lv, rv, l.type, r.type, out_t, valid)
        lv = lv.astype(jnp.int64)
        rv = rv.astype(jnp.int64)
        if e.fn == "add":
            return lv + rv, valid
        if e.fn == "sub":
            return lv - rv, valid
        if e.fn == "mul":
            return lv * rv, valid  # scale(out) = scale(l) + scale(r)
        if e.fn == "mod":
            return jnp.mod(lv, rv), valid
        raise NotImplementedError(f"decimal {e.fn}")
    # float / integer paths
    if out_t is DOUBLE or is_floating(out_t):
        if ldec:
            lv = lv.astype(out_t.dtype) / (10.0 ** l.type.scale)
        else:
            lv = lv.astype(out_t.dtype)
        if rdec:
            rv = rv.astype(out_t.dtype) / (10.0 ** r.type.scale)
        else:
            rv = rv.astype(out_t.dtype)
    else:
        lv = lv.astype(out_t.dtype)
        rv = rv.astype(out_t.dtype)
    if e.fn == "add":
        return lv + rv, valid
    if e.fn == "sub":
        return lv - rv, valid
    if e.fn == "mul":
        return lv * rv, valid
    if e.fn == "div":
        if is_integral(out_t):
            # SQL integer division truncates toward zero
            q = jnp.sign(lv) * jnp.sign(rv) * (jnp.abs(lv) // jnp.maximum(jnp.abs(rv), 1))
            div_ok = rv != 0
            return q.astype(out_t.dtype), _and_valid(valid, div_ok)
        div_ok = rv != 0.0
        return jnp.where(div_ok, lv / jnp.where(div_ok, rv, 1.0), 0.0), _and_valid(valid, div_ok)
    if e.fn == "mod":
        safe = jnp.where(rv == 0, 1, rv)
        m = lv - jnp.trunc(lv / safe) * safe if is_floating(out_t) else jnp.sign(lv) * (jnp.abs(lv) % jnp.abs(safe))
        return m, _and_valid(valid, rv != 0)
    raise NotImplementedError(e.fn)


def _two_prod(a, b):
    """Dekker/Veltkamp exact two-product: a*b = hi + lo with hi = fl(a*b).
    Pure f64 elementwise ops — XLA preserves FP semantics (no unsafe
    reassociation), so the error term is exact."""
    p = a * b
    c = jnp.float64(134217729.0)  # 2^27 + 1 (Veltkamp splitter)
    ac = a * c
    ah = ac - (ac - a)
    al = a - ah
    bc = b * c
    bh = bc - (bc - b)
    bl = b - bh
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def _decimal_div(lv, rv, lt, rt, out_t, valid):
    """DECIMAL ÷ DECIMAL with Presto semantics (DecimalOperators.divide /
    UnscaledDecimal128Arithmetic.divideRoundUp): the numerator rescales by
    10^(s_out + s_r - s_l), the quotient rounds HALF AWAY FROM ZERO.

    Exactness ladder on TPU int64/f64 lanes (reference is int128-exact to
    38 digits):
      1. numerator fits 18 digits → pure int64, bit-exact;
      2. otherwise a Dekker two-product f64 path with exact-remainder
         correction — bit-exact while the operands are f64-exact
         (< 2^53), the rescale shift ≤ 22, and the quotient < 2^53;
      3. beyond those bounds the result is the f64 approximation
         (documented deviation — 16+ significant digit quotients).
    """
    from presto_tpu.types import DecimalType as _DT

    ls = lt.scale if isinstance(lt, _DT) else 0
    rs = rt.scale if isinstance(rt, _DT) else 0
    lp = lt.precision if isinstance(lt, _DT) else 18
    shift = out_t.scale + rs - ls
    div_ok = rv != 0
    valid = _and_valid(valid, div_ok)
    int_in = (not jnp.issubdtype(lv.dtype, jnp.floating)
              and not jnp.issubdtype(rv.dtype, jnp.floating))
    if int_in and shift >= 0 and lp + shift <= 18:
        n = lv.astype(jnp.int64) * (10 ** shift)
        d = jnp.where(div_ok, rv.astype(jnp.int64), jnp.ones((), jnp.int64))
        an, ad = jnp.abs(n), jnp.abs(d)
        q = (an + ad // 2) // ad  # round half away on |·|
        return (jnp.sign(n) * jnp.sign(d) * q).astype(jnp.int64), valid

    nf = jnp.abs(lv.astype(jnp.float64))
    da = jnp.abs(jnp.where(div_ok, rv.astype(jnp.float64), 1.0))
    sgn = jnp.sign(lv.astype(jnp.float64)) * jnp.sign(
        jnp.where(div_ok, rv.astype(jnp.float64), 1.0))
    if shift < 0 or shift > 22:  # 10^shift not f64-exact: plain f64 tail
        q = jnp.round(nf * (10.0 ** shift) / da)
        return (sgn * q).astype(jnp.int64), valid
    n_hi, n_lo = _two_prod(nf, jnp.float64(10.0 ** shift))
    qa = jnp.floor(n_hi / da)
    for _ in range(2):  # each sweep shrinks the error ~2^-52
        p_hi, p_lo = _two_prod(qa, da)
        r = ((n_hi - p_hi) - p_lo) + n_lo
        qa = qa + jnp.floor(r / da)
    p_hi, p_lo = _two_prod(qa, da)
    r = ((n_hi - p_hi) - p_lo) + n_lo  # exact remainder in [0, da)
    q = qa + (2.0 * r >= da)  # half away from zero on |·|
    return (sgn * q).astype(jnp.int64), valid


def parse_string_to(tt, s: str):
    """SQL text → the internal value of type `tt`, or None when
    unparseable (shared by varchar-cast LUTs and constant folding)."""
    from presto_tpu.types import DATE as _DATE

    def _time_micros(txt: str) -> int:
        hms, _, frac = txt.partition(".")
        parts = list(map(int, hms.split(":")))
        while len(parts) < 3:
            parts.append(0)
        hh, mm, ss = parts[:3]
        micros = (hh * 3600 + mm * 60 + ss) * 1_000_000
        if frac:
            micros += int(frac[:6].ljust(6, "0"))
        return micros

    try:
        s = s.strip()
        if tt is _DATE:
            y, m, dd = map(int, s.split("-"))
            return days_from_civil(y, m, dd)
        if tt.name == "timestamp":
            datepart, _, timepart = s.partition(" ")
            y, m, dd = map(int, datepart.split("-"))
            micros = days_from_civil(y, m, dd) * 86_400_000_000
            if timepart:
                micros += _time_micros(timepart)
            return micros
        if tt.name == "time":
            return _time_micros(s)
        if tt is BOOLEAN:
            if s.lower() in ("true", "t", "1"):
                return 1
            if s.lower() in ("false", "f", "0"):
                return 0
            return None
        if isinstance(tt, DecimalType):
            import decimal as _dec

            return int(_dec.Decimal(s).scaleb(tt.scale)
                       .to_integral_value(rounding=_dec.ROUND_HALF_UP))
        if is_floating(tt):
            return float(s)
        return int(float(s)) if "." in s or "e" in s.lower() else int(s)
    except Exception:
        return None


def _eval_cast(e: Call, ctx):
    src = e.args[0]
    st, tt = src.type, e.type
    if st.is_string and not tt.is_string:
        # varchar → numeric/date/boolean: parse each DICTIONARY value on
        # the host, one device gather (codes must never be value-cast!).
        # Unparseable values yield NULL — a documented deviation from the
        # reference's row-level cast error (no exception channel exists on
        # device; try(cast(..)) is therefore equivalent to cast(..))
        d = ctx.dict_for(src)
        if d is None:
            raise ValueError("cast from varchar requires a dictionary")
        import numpy as _np

        def val_of(s):
            v = parse_string_to(tt, s)
            return 0 if v is None else v

        def ok_of(s):
            return parse_string_to(tt, s) is not None

        npdt = _np.float64 if is_floating(tt) else _np.int64
        vlut = d.int_lut(("cast_val", tt.name), val_of, dtype=npdt)
        olut = d.int_lut(("cast_ok", tt.name), ok_of, dtype=_np.bool_)
        codes, valid = _eval(src, ctx)
        out = jnp.asarray(vlut)[codes + 1].astype(tt.dtype)
        ok = jnp.asarray(olut)[codes + 1]
        return out, ok if valid is None else (valid & ok)
    if tt.is_string and not st.is_string:
        raise NotImplementedError(
            "cast to varchar from non-string types is supported in the "
            "top-level SELECT list only (it runs as a HostProject "
            "finishing projection — no input dictionary exists to "
            "transform on the device)")
    v, valid = _eval_arg(src, ctx)
    if st == tt:
        return v, valid
    sdec = isinstance(st, DecimalType)
    tdec = isinstance(tt, DecimalType)
    if sdec and tdec:
        # rescale
        if tt.scale >= st.scale:
            return v * (10 ** (tt.scale - st.scale)), valid
        f = 10 ** (st.scale - tt.scale)
        return _div_half_away(v, f), valid
    if sdec and is_floating(tt):
        return v.astype(tt.dtype) / (10.0 ** st.scale), valid
    if sdec and is_integral(tt):
        return _div_half_away(v, 10 ** st.scale).astype(tt.dtype), valid
    if tdec and is_integral(st):
        return v.astype(jnp.int64) * (10 ** tt.scale), valid
    if tdec and is_floating(st):
        return _round_half_away(v * (10.0 ** tt.scale)).astype(jnp.int64), valid
    if tt is BOOLEAN:
        return v.astype(bool), valid
    return v.astype(tt.dtype), valid


def _civil_from_days(z):
    """days-since-epoch → (year, month, day). Howard Hinnant's algorithm,
    branch-free integer math (vectorizes on the VPU)."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def days_from_civil(y: int, m: int, d: int) -> int:
    """Host-side date literal → days since epoch."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# ---------------------------------------------------------------------------
# geospatial (expr/geo.py): WKT parses once per dictionary, row ops are
# vectorized plane programs (reference: presto-geospatial GeoFunctions)

# bounded LRUs: long-running servers compile unboundedly many plans
from collections import OrderedDict as _OD

_GEO_PLANES_CACHE: "_OD" = _OD()   # id(geoms) -> (geoms, np planes)
_GEO_CONST_CACHE: "_OD" = _OD()    # wkt literal -> (geoms, ok) singleton


def _geo_planes(geoms: tuple):
    from presto_tpu.expr import geo as G

    hit = _GEO_PLANES_CACHE.get(id(geoms))
    if hit is not None and hit[0] is geoms:
        _GEO_PLANES_CACHE.move_to_end(id(geoms))
        return hit[1]
    planes = G.edge_planes(geoms)
    _GEO_PLANES_CACHE[id(geoms)] = (geoms, planes)
    while len(_GEO_PLANES_CACHE) > 128:
        _GEO_PLANES_CACHE.popitem(last=False)
    return planes


def _geo_parse_all(values):
    """Lenient WKT parse: (geoms tuple, ok ndarray). Unparseable values
    (incl. the '' null sentinel some connectors store) become invalid
    rows, not query failures."""
    from presto_tpu.expr import geo as G

    parsed, ok = [], []
    fallback = G.parse_wkt("POINT(0 0)")
    for v in values:
        try:
            parsed.append(G.parse_wkt(str(v)))
            ok.append(True)
        except G.WktError:
            parsed.append(fallback)
            ok.append(False)
    return tuple(parsed), np.asarray(ok, bool)


def _geo_lut(gv, func, dtype=jnp.float64):
    """geometry→scalar as a host table gathered by code."""
    table = jnp.asarray(np.array([func(g) for g in gv.geoms]).astype(dtype))
    return table[jnp.clip(gv.codes, 0, len(gv.geoms) - 1)]


def _geo_points(gv):
    """(x, y) coordinate arrays of a GeomVal; None when it holds
    non-point geometries."""
    from presto_tpu.expr import geo as G

    if gv.kind == "points":
        return gv.x, gv.y
    if all(G.is_point(g) for g in gv.geoms):
        return (_geo_lut(gv, lambda g: G.point_xy(g)[0]),
                _geo_lut(gv, lambda g: G.point_xy(g)[1]))
    return None


def _eval_geom_arg(a: RowExpression, ctx):
    """Evaluate a GEOMETRY-typed subexpression to (GeomVal, valid)."""
    v, valid = _eval(a, ctx)
    from presto_tpu.expr.geo import GeomVal

    if not isinstance(v, GeomVal):
        raise NotImplementedError(
            "GEOMETRY values only flow between geospatial functions")
    return v, valid


def _eval_geo(e: Call, ctx: CompileContext):
    from presto_tpu.expr import geo as G
    from presto_tpu.expr.geo import GeomVal

    fn = e.fn
    if fn == "great_circle_distance":
        vals = [_eval_arg(a, ctx) for a in e.args]
        valid = None
        for _, va in vals:
            valid = _and_valid(valid, va)
        lat1, lon1, lat2, lon2 = (v.astype(jnp.float64) for v, _ in vals)
        return G.great_circle_distance(lat1, lon1, lat2, lon2), valid

    if fn == "st_geometryfromtext":
        a = e.args[0]
        cap = ctx.batch.capacity
        if isinstance(a, Constant):
            key = str(a.value) if a.value is not None else None
            if key is None:
                geoms, ok = _geo_parse_all([""])
            else:
                hit = _GEO_CONST_CACHE.get(key)
                if hit is None:
                    hit = _geo_parse_all([key])
                    _GEO_CONST_CACHE[key] = hit
                    while len(_GEO_CONST_CACHE) > 256:
                        _GEO_CONST_CACHE.popitem(last=False)
                else:
                    _GEO_CONST_CACHE.move_to_end(key)
                geoms, ok = hit
            valid = None if bool(ok[0]) else jnp.zeros(cap, bool)
            return GeomVal("coded", jnp.zeros(cap, jnp.int32), geoms,
                           None, None), valid
        codes, valid = _eval(a, ctx)
        hit = ctx.dict_for(a)
        if hit is None:
            raise NotImplementedError(
                "ST_GeometryFromText needs a dictionary-encoded varchar")
        d = hit
        memo = d._memo.get("__geoms__")
        if memo is None:
            memo = _geo_parse_all(d.values)
            d._memo["__geoms__"] = memo
        geoms, ok = memo
        if not geoms:
            geoms, ok = _geo_parse_all([""])
            return (GeomVal("coded", jnp.zeros(cap, jnp.int32), geoms,
                            None, None), jnp.zeros(cap, bool))
        okv = jnp.asarray(ok)[jnp.clip(codes, 0, len(geoms) - 1)]
        okv = okv & (codes >= 0)
        return GeomVal("coded", codes, geoms, None, None), _and_valid(
            valid, okv)

    if fn == "st_point":
        (x, xv), (y, yv) = (_eval_arg(a, ctx) for a in e.args)

        def vec(v):
            v = v.astype(jnp.float64)
            # literal coordinates arrive 0-d; plane gathers need [rows]
            return (jnp.broadcast_to(v, (ctx.batch.capacity,))
                    if jnp.ndim(v) == 0 else v)

        return (GeomVal("points", None, None, vec(x), vec(y)),
                _and_valid(xv, yv))

    if fn in ("st_area", "st_perimeter", "st_length", "st_npoints",
              "st_xmin", "st_xmax", "st_ymin", "st_ymax", "st_x", "st_y",
              "st_centroid"):
        gv, valid = _eval_geom_arg(e.args[0], ctx)
        if gv.kind == "points":
            if fn in ("st_x", "st_xmin", "st_xmax"):
                return gv.x, valid
            if fn in ("st_y", "st_ymin", "st_ymax"):
                return gv.y, valid
            if fn == "st_centroid":
                return gv, valid
            if fn == "st_npoints":
                return jnp.ones_like(gv.x, dtype=jnp.int64), valid
            return jnp.zeros_like(gv.x), valid  # area/perimeter/length
        if fn in ("st_x", "st_y"):
            if not all(G.is_point(g) for g in gv.geoms):
                raise NotImplementedError(f"{fn} needs POINT geometries")
            i = 0 if fn == "st_x" else 1
            return _geo_lut(gv, lambda g: G.point_xy(g)[i]), valid
        if fn == "st_centroid":
            return (GeomVal("points", None, None,
                            _geo_lut(gv, lambda g: G.geom_centroid(g)[0]),
                            _geo_lut(gv, lambda g: G.geom_centroid(g)[1])),
                    valid)
        host = {"st_area": G.geom_area, "st_perimeter": G.geom_perimeter,
                "st_length": G.geom_length,
                "st_xmin": lambda g: G.geom_bbox(g)[0],
                "st_ymin": lambda g: G.geom_bbox(g)[1],
                "st_xmax": lambda g: G.geom_bbox(g)[2],
                "st_ymax": lambda g: G.geom_bbox(g)[3]}
        if fn == "st_npoints":
            return _geo_lut(gv, G.geom_npoints, jnp.int64), valid
        return _geo_lut(gv, host[fn]), valid

    # binary geometry relations
    ga, va = _eval_geom_arg(e.args[0], ctx)
    gb, vb = _eval_geom_arg(e.args[1], ctx)
    valid = _and_valid(va, vb)
    pa, pb = _geo_points(ga), _geo_points(gb)

    if fn in ("st_contains", "st_intersects"):
        def point_in(poly, px, py):
            # only area kinds enclose points (linestrings never do)
            inside = G.point_in_coded(_geo_planes(poly.geoms), poly.codes,
                                      px, py)
            area = _geo_lut(poly, lambda g: float(G.is_area(g))) > 0
            return inside & area

        # polygon side contains / intersects a point probe (even-odd)
        if ga.kind == "coded" and pb is not None and pa is None:
            return point_in(ga, pb[0], pb[1]), valid
        if (fn == "st_intersects" and gb.kind == "coded"
                and pa is not None and pb is None):
            return point_in(gb, pa[0], pa[1]), valid
        if pa is not None and pb is not None:
            eqv = (pa[0] == pb[0]) & (pa[1] == pb[1])
            return eqv, valid
        if fn == "st_contains" and pa is not None and pb is None:
            # a point never contains a polygon/linestring
            return jnp.zeros_like(pa[0], dtype=bool), valid
        raise NotImplementedError(
            f"{fn} between two non-point geometries is not supported")

    if fn == "st_distance":
        if pa is not None and pb is not None:
            return jnp.hypot(pa[0] - pb[0], pa[1] - pb[1]), valid
        poly, pt = (ga, pb) if pa is None else (gb, pa)
        if pt is None:
            raise NotImplementedError(
                "ST_Distance between two non-point geometries is not "
                "supported")
        d = G.point_seg_distance(_geo_planes(poly.geoms), poly.codes,
                                 pt[0], pt[1])
        inside = G.point_in_coded(_geo_planes(poly.geoms), poly.codes,
                                  pt[0], pt[1])
        area = _geo_lut(poly, lambda g: float(G.is_area(g))) > 0
        return jnp.where(inside & area, 0.0, d), valid

    raise NotImplementedError(f"geospatial function {fn}")
