"""T-digest sketches as dictionary-entry values.

Reference surface: presto-main/src/main/java/com/facebook/presto/
tdigest/TDigest.java and operator/aggregation/TDigestAggregationFunction
/ operator/scalar/TDigestFunctions.java (tdigest_agg, merge,
value_at_quantile(s), quantile_at_value, scale_tdigest, trimmed_mean).

Design (TPU-first): a TDIGEST value is a serialized centroid list stored
as a dictionary ENTRY (like every other string-shaped value in this
engine), so digests ride joins/exchanges/spill as int32 codes and every
scalar function over them evaluates once per distinct digest as a
host-side LUT. Construction happens at the materialized single-task
aggregation (the fragmenter gathers non-decomposable aggregates), where
the full value array is available — so the centroid assignment is a
VECTORIZED one-shot pass over the sorted data rather than the
reference's streaming per-row insertion: cluster id = ⌊k(q) − k(0)⌋
with the k₁ scale function k(q) = δ/(2π)·asin(2q−1), which yields
≤ δ/2 + 1 centroids and the same tail-concentrated size invariant.

Serialization is exact ASCII (`repr` floats round-trip binary64), so
digests survive the wire codec and spill byte-identically.
"""

from __future__ import annotations

import math

import numpy as np

DEFAULT_COMPRESSION = 100.0

_MAGIC = "TD1"


def _k(q: np.ndarray, d: float) -> np.ndarray:
    """k₁ scale function (TDigest.java integratedLocation analog)."""
    return d / (2.0 * math.pi) * np.arcsin(np.clip(2.0 * q - 1.0, -1.0, 1.0))


def serialize(compression: float, total: float, vmin: float, vmax: float,
              means: np.ndarray, weights: np.ndarray) -> str:
    cents = ",".join(f"{repr(float(m))}:{repr(float(w))}"
                     for m, w in zip(means, weights))
    return (f"{_MAGIC};{repr(float(compression))};{repr(float(total))};"
            f"{repr(float(vmin))};{repr(float(vmax))};{cents}")


def deserialize(entry: str):
    """entry → (compression, total, min, max, means, weights) or None."""
    parts = entry.split(";")
    if len(parts) != 6 or parts[0] != _MAGIC:
        return None
    try:
        compression, total, vmin, vmax = map(float, parts[1:5])
        if parts[5]:
            pairs = [c.split(":") for c in parts[5].split(",")]
            means = np.asarray([float(p[0]) for p in pairs])
            weights = np.asarray([float(p[1]) for p in pairs])
        else:
            means = np.zeros(0)
            weights = np.zeros(0)
    except (ValueError, IndexError):
        return None
    return compression, total, vmin, vmax, means, weights


def build(values, weights=None, compression: float = DEFAULT_COMPRESSION) -> str | None:
    """One-shot t-digest over a value array (aggregation-time path)."""
    v = np.asarray(values, np.float64)
    w = (np.ones_like(v) if weights is None
         else np.asarray(weights, np.float64))
    keep = w > 0
    v, w = v[keep], w[keep]
    if v.size == 0:
        return None
    order = np.argsort(v, kind="stable")
    return _compress(v[order], w[order], compression)


def _compress(v: np.ndarray, w: np.ndarray, compression: float) -> str:
    """Sorted values+weights → serialized digest (vectorized cluster
    assignment in k-space; one segment-sum per plane)."""
    total = float(w.sum())
    q_right = np.cumsum(w) / total
    cluster = np.floor(_k(q_right, compression)
                       - _k(np.zeros(1), compression)[0]).astype(np.int64)
    cluster = np.minimum(cluster, int(compression))  # q=1 edge cell
    # collapse empty cells so centroid count is the occupied-cell count
    _, seg = np.unique(cluster, return_inverse=True)
    n = int(seg.max()) + 1 if seg.size else 0
    wsum = np.bincount(seg, weights=w, minlength=n)
    msum = np.bincount(seg, weights=v * w, minlength=n)
    means = msum / wsum
    return serialize(compression, total, float(v[0]), float(v[-1]),
                     means, wsum)


def merge(entries) -> str | None:
    """Merge serialized digests (the reference's merge(tdigest) aggregate
    / TDigest.merge): concatenate centroids, re-compress sorted."""
    parsed = [p for p in (deserialize(e) for e in entries) if p is not None]
    if not parsed:
        return None
    compression = max(p[0] for p in parsed)
    vmin = min(p[2] for p in parsed)
    vmax = max(p[3] for p in parsed)
    means = np.concatenate([p[4] for p in parsed])
    weights = np.concatenate([p[5] for p in parsed])
    if means.size == 0:
        return None
    order = np.argsort(means, kind="stable")
    out = _compress(means[order], weights[order], compression)
    # centroid means can contract the observed extremes; restore them
    p = deserialize(out)
    return serialize(p[0], p[1], vmin, vmax, p[4], p[5])


def _midpoints(weights: np.ndarray) -> np.ndarray:
    cum = np.cumsum(weights)
    return cum - weights / 2.0


def value_at_quantile(entry: str, q: float) -> float | None:
    """Quantile → value by linear interpolation between centroid
    midpoints, clamped to the observed [min, max]
    (TDigest.getQuantile)."""
    p = deserialize(entry)
    if p is None or not 0.0 <= q <= 1.0:
        return None
    _, total, vmin, vmax, means, weights = p
    if means.size == 0:
        return None
    target = q * total
    mid = _midpoints(weights)
    if target <= mid[0]:
        # below the first midpoint: interpolate from the true minimum
        f = target / mid[0] if mid[0] > 0 else 1.0
        return float(vmin + f * (means[0] - vmin))
    if target >= mid[-1]:
        span = total - mid[-1]
        f = (target - mid[-1]) / span if span > 0 else 1.0
        return float(means[-1] + f * (vmax - means[-1]))
    i = int(np.searchsorted(mid, target, side="right")) - 1
    span = mid[i + 1] - mid[i]
    f = (target - mid[i]) / span if span > 0 else 0.0
    return float(means[i] + f * (means[i + 1] - means[i]))


def quantile_at_value(entry: str, x: float) -> float | None:
    """Value → rank estimate in [0, 1] (TDigest.getCdf)."""
    p = deserialize(entry)
    if p is None:
        return None
    _, total, vmin, vmax, means, weights = p
    if means.size == 0:
        return None
    if x < vmin:
        return 0.0
    if x >= vmax:
        return 1.0
    mid = _midpoints(weights)
    if x <= means[0]:
        span = means[0] - vmin
        f = (x - vmin) / span if span > 0 else 1.0
        return float(f * mid[0] / total)
    if x >= means[-1]:
        span = vmax - means[-1]
        f = (x - means[-1]) / span if span > 0 else 0.0
        return float((mid[-1] + f * (total - mid[-1])) / total)
    i = int(np.searchsorted(means, x, side="right")) - 1
    span = means[i + 1] - means[i]
    f = (x - means[i]) / span if span > 0 else 0.0
    return float((mid[i] + f * (mid[i + 1] - mid[i])) / total)


def scale(entry: str, factor: float) -> str | None:
    """scale_tdigest: multiply all centroid weights (TDigestFunctions
    .scaleTDigest; factor must be positive)."""
    p = deserialize(entry)
    if p is None or factor <= 0:
        return None
    compression, total, vmin, vmax, means, weights = p
    return serialize(compression, total * factor, vmin, vmax,
                     means, weights * factor)


def trimmed_mean(entry: str, lo: float, hi: float) -> float | None:
    """Mean of the values between the lo and hi quantiles: centroid
    weights clipped to the [lo·total, hi·total] rank window
    (TDigestFunctions.trimmedMean)."""
    p = deserialize(entry)
    if p is None or not 0.0 <= lo <= hi <= 1.0:
        return None
    _, total, _, _, means, weights = p
    if means.size == 0 or hi == lo:
        return None
    cum = np.cumsum(weights)
    left = cum - weights
    overlap = np.minimum(cum, hi * total) - np.maximum(left, lo * total)
    overlap = np.maximum(overlap, 0.0)
    wsum = overlap.sum()
    if wsum <= 0:
        return None
    return float((means * overlap).sum() / wsum)
