"""Geospatial functions, TPU-native.

Reference analog: presto-geospatial GeoFunctions.java (ST_Contains,
ST_Distance, ST_Area ... over ESRI geometry objects, one row at a time).
The TPU redesign: WKT parses ONCE per distinct dictionary value on the
host; per-row geometry ops run as vectorized array programs —

- geometry→scalar (area, perimeter, bbox, centroid, npoints) become
  host-computed lookup tables gathered by dictionary code (the same LUT
  trick as varchar casts in expr/compile.py),
- point-in-polygon is even-odd ray casting over a padded [G, E] edge
  plane gathered to [rows, E] — elementwise compares + a parity sum, no
  per-row loops (holes fall out of the even-odd rule for free),
- point-to-polygon distance is a min-reduce of the point-segment
  distance formula over the same edge plane.

Geometries never hit storage: GEOMETRY-typed expressions exist only
inside one expression tree as GeomVal pytrees (codes into a parsed table,
or raw point coordinate arrays)."""

from __future__ import annotations

import math
import re
from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np


class Geom(NamedTuple):
    kind: str                  # point | linestring | polygon | multipolygon
    polys: tuple               # tuple of polygons; each = tuple of rings;
                               # each ring = tuple of (x, y). point /
                               # linestring: one poly with one "ring"


class GeomVal(NamedTuple):
    """Runtime value of a GEOMETRY-typed expression (compile-time pytree;
    `geoms` rides as static aux via tuple identity)."""

    kind: str                          # "coded" | "points"
    codes: Optional[jnp.ndarray]       # int32 codes into geoms (coded)
    geoms: Optional[tuple]             # tuple[Geom] aligned with codes
    x: Optional[jnp.ndarray]           # points kind
    y: Optional[jnp.ndarray]


_NUM = r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?"
_PAIR = re.compile(rf"({_NUM})\s+({_NUM})")


class WktError(ValueError):
    pass


def parse_wkt(s: str) -> Geom:
    """POINT / LINESTRING / POLYGON / MULTIPOLYGON (reference: the ESRI
    WKT importer behind GeoFunctions.ST_GeometryFromText)."""
    s = s.strip()
    m = re.match(r"(?i)^(point|linestring|polygon|multipolygon)\s*(.*)$", s,
                 re.DOTALL)
    if not m:
        raise WktError(f"unsupported WKT: {s[:40]!r}")
    kind = m.group(1).lower()
    body = m.group(2).strip()

    def pairs(text):
        out = tuple((float(a), float(b)) for a, b in _PAIR.findall(text))
        if not out:
            raise WktError(f"no coordinates in WKT: {s[:40]!r}")
        return out

    def rings(text):
        # "( (...), (...) )" → one tuple per parenthesized ring
        return tuple(pairs(r) for r in re.findall(r"\(([^()]*)\)", text))

    if kind == "point":
        return Geom("point", ((pairs(body)[:1],),))
    if kind == "linestring":
        return Geom("linestring", ((pairs(body),),))
    if kind == "polygon":
        rs = rings(body)
        if not rs:
            raise WktError(f"empty polygon: {s[:40]!r}")
        return Geom("polygon", (rs,))
    # multipolygon: split top-level "((...),(...))" groups
    polys = []
    depth = 0
    start = None
    for i, ch in enumerate(body):
        if ch == "(":
            if depth == 1 and start is None:
                start = i
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 1 and start is not None:
                polys.append(rings(body[start:i + 1]))
                start = None
    if not polys:
        raise WktError(f"empty multipolygon: {s[:40]!r}")
    return Geom("multipolygon", tuple(polys))


# -- host-side per-geometry metrics (LUT sources) ---------------------------


def _ring_area2(ring) -> float:
    """Twice the signed shoelace area."""
    a = 0.0
    n = len(ring)
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        a += x1 * y2 - x2 * y1
    return a


def geom_area(g: Geom) -> float:
    if g.kind in ("point", "linestring"):
        return 0.0
    total = 0.0
    for rings in g.polys:
        ext = abs(_ring_area2(rings[0])) / 2.0
        holes = sum(abs(_ring_area2(r)) / 2.0 for r in rings[1:])
        total += ext - holes
    return total


def _chain_length(pts, closed: bool) -> float:
    n = len(pts)
    if n < 2:
        return 0.0
    total = 0.0
    last = n if closed else n - 1
    for i in range(last):
        x1, y1 = pts[i]
        x2, y2 = pts[(i + 1) % n]
        total += math.hypot(x2 - x1, y2 - y1)
    return total


def geom_perimeter(g: Geom) -> float:
    if g.kind in ("point", "linestring"):
        return 0.0
    return sum(_chain_length(r, True) for rings in g.polys for r in rings)


def geom_length(g: Geom) -> float:
    if g.kind == "linestring":
        return _chain_length(g.polys[0][0], False)
    return 0.0


def geom_npoints(g: Geom) -> int:
    return sum(len(r) for rings in g.polys for r in rings)


def geom_bbox(g: Geom):
    xs = [p[0] for rings in g.polys for r in rings for p in r]
    ys = [p[1] for rings in g.polys for r in rings for p in r]
    return min(xs), min(ys), max(xs), max(ys)


def geom_centroid(g: Geom):
    if g.kind in ("point", "linestring"):
        pts = g.polys[0][0]
        return (sum(p[0] for p in pts) / len(pts),
                sum(p[1] for p in pts) / len(pts))
    # area-weighted centroid; holes subtract (signed shoelace terms)
    sx = sy = sa = 0.0
    for rings in g.polys:
        for ri, ring in enumerate(rings):
            a2 = _ring_area2(ring)
            sign = 1.0 if ri == 0 else -1.0
            w = sign * abs(a2)
            cx = cy = 0.0
            n = len(ring)
            if abs(a2) < 1e-30:
                continue
            for i in range(n):
                x1, y1 = ring[i]
                x2, y2 = ring[(i + 1) % n]
                cross = x1 * y2 - x2 * y1
                cx += (x1 + x2) * cross
                cy += (y1 + y2) * cross
            # cross terms carry the ring's own sign; normalize to |area|
            cx = cx / (3.0 * a2) * abs(a2)
            cy = cy / (3.0 * a2) * abs(a2)
            sx += sign * cx
            sy += sign * cy
            sa += w
    if sa == 0.0:
        return geom_bbox(g)[:2]
    return sx / sa, sy / sa


def is_point(g: Geom) -> bool:
    return g.kind == "point"


def is_area(g: Geom) -> bool:
    """Only polygons enclose area — ray-casting parity is meaningless
    for points/linestrings."""
    return g.kind in ("polygon", "multipolygon")


def point_xy(g: Geom):
    p = g.polys[0][0][0]
    return p[0], p[1]


# -- padded edge planes (device containment / distance) ---------------------


def edge_planes(geoms: tuple):
    """[G, E] edge endpoint planes over every ring of every geometry
    (even-odd ray casting is hole-correct over the concatenated rings).
    Padding edges are NaN — every comparison against them is False."""
    all_edges = []
    for g in geoms:
        edges = []
        closed = g.kind in ("polygon", "multipolygon")
        for rings in g.polys:
            for ring in rings:
                n = len(ring)
                if n < 2:
                    continue
                # open chains (linestrings) have n-1 edges — no phantom
                # closing segment
                for i in range(n if closed else n - 1):
                    x1, y1 = ring[i]
                    x2, y2 = ring[(i + 1) % n]
                    edges.append((x1, y1, x2, y2))
        all_edges.append(edges)
    emax = max((len(e) for e in all_edges), default=1) or 1
    G = len(geoms)
    planes = np.full((4, G, emax), np.nan)
    for gi, edges in enumerate(all_edges):
        for ei, (x1, y1, x2, y2) in enumerate(edges):
            planes[0, gi, ei] = x1
            planes[1, gi, ei] = y1
            planes[2, gi, ei] = x2
            planes[3, gi, ei] = y2
    # host numpy on purpose: callers convert per trace (a cached jnp
    # array would leak tracers across jit traces)
    return planes


def point_in_coded(planes, codes, px, py):
    """Even-odd ray casting: [rows] bool. planes [4, G, E]; codes [rows]
    int; px/py [rows] float (a horizontal ray to +inf; NaN pad edges
    never cross)."""
    planes = jnp.asarray(planes)
    c = jnp.clip(codes, 0, planes.shape[1] - 1)
    ex1, ey1, ex2, ey2 = (planes[i][c] for i in range(4))  # [rows, E]
    pyc = py[:, None]
    pxc = px[:, None]
    straddle = (ey1 > pyc) != (ey2 > pyc)
    # x coordinate where the edge crosses the ray's y
    t = (pyc - ey1) / (ey2 - ey1)
    xcross = ex1 + t * (ex2 - ex1)
    crossing = straddle & (pxc < xcross)
    return (jnp.sum(crossing, axis=1) % 2).astype(bool)


def point_seg_distance(planes, codes, px, py):
    """Min distance from each point to its geometry's edges: [rows]
    float64 (inf where the geometry has no edges)."""
    planes = jnp.asarray(planes)
    c = jnp.clip(codes, 0, planes.shape[1] - 1)
    ex1, ey1, ex2, ey2 = (planes[i][c] for i in range(4))
    pxc, pyc = px[:, None], py[:, None]
    dx, dy = ex2 - ex1, ey2 - ey1
    ll = dx * dx + dy * dy
    t = jnp.where(ll > 0, ((pxc - ex1) * dx + (pyc - ey1) * dy)
                  / jnp.where(ll > 0, ll, 1.0), 0.0)
    t = jnp.clip(t, 0.0, 1.0)
    cx, cy = ex1 + t * dx, ey1 + t * dy
    d = jnp.hypot(pxc - cx, pyc - cy)
    d = jnp.where(jnp.isnan(d), jnp.inf, d)
    return jnp.min(d, axis=1)


def great_circle_distance(lat1, lon1, lat2, lon2):
    """Haversine in kilometres (reference: GeoFunctions.
    greatCircleDistance, same earth radius 6371.01 km)."""
    r = 6371.01
    p1, p2 = jnp.radians(lat1), jnp.radians(lat2)
    dphi = p2 - p1
    dlam = jnp.radians(lon2) - jnp.radians(lon1)
    a = (jnp.sin(dphi / 2.0) ** 2
         + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlam / 2.0) ** 2)
    return 2.0 * r * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
