from presto_tpu.expr.ir import (
    RowExpression,
    InputRef,
    Constant,
    Call,
)
from presto_tpu.expr.compile import compile_expr, compile_predicate

__all__ = [
    "RowExpression",
    "InputRef",
    "Constant",
    "Call",
    "compile_expr",
    "compile_predicate",
]
