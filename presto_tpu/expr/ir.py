"""Typed row-expression IR.

Analog of the reference's post-analysis expression IR
(presto-spi/src/main/java/com/facebook/presto/spi/relation/RowExpression.java,
CallExpression.java, SpecialFormExpression.java, ConstantExpression.java,
InputReferenceExpression.java) — the form the planner optimizes and the
"codegen" consumes. Here the consumer is the XLA tracer instead of ASM
bytecode (sql/gen/ExpressionCompiler.java).

Expressions are frozen/hashable so plans can be cached and compared.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from presto_tpu.types import Type


@dataclasses.dataclass(frozen=True)
class RowExpression:
    type: Type


@dataclasses.dataclass(frozen=True)
class InputRef(RowExpression):
    """Reference to a column of the input batch by name."""

    name: str = ""

    def __str__(self):
        return self.name


@dataclasses.dataclass(frozen=True)
class Constant(RowExpression):
    """A literal. value=None means typed NULL. Strings stay as python str
    until compile time, when they are resolved against the relevant
    dictionary. raw=True means the value is already in device representation
    (e.g. an unscaled decimal bound from a scalar subquery result)."""

    value: object = None
    raw: bool = False

    def __str__(self):
        return repr(self.value)


@dataclasses.dataclass(frozen=True)
class Call(RowExpression):
    """Function / operator / special-form application.

    fn names (the built-in scalar surface, analog of operator/scalar/*):
      arithmetic: add sub mul div mod neg abs
      comparison: eq ne lt le gt ge
      boolean:    and or not          (Kleene three-valued logic)
      null:       is_null is_not_null coalesce nullif
      control:    if  (cond, then, else)  case handled by nesting ifs
      membership: in (value, *constants)  between (v, lo, hi)
      string:     like (value, pattern-const)  [host-evaluated over dict]
      cast:       cast (target type = self.type)
      math:       sqrt exp ln floor ceil round power
      date:       year month day extract_* date_add_days
    """

    fn: str = ""
    args: Tuple[RowExpression, ...] = ()

    def __str__(self):
        return f"{self.fn}({', '.join(map(str, self.args))})"


@dataclasses.dataclass(frozen=True)
class LambdaExpr(RowExpression):
    """Typed lambda argument of a higher-order function. `params` are
    (unique plan symbol, element Type) pairs; the body references them as
    InputRefs. `type` is the body's type (spi/relation/
    LambdaDefinitionExpression analog)."""

    params: Tuple[Tuple[str, "Type"], ...] = ()
    body: Optional[RowExpression] = None

    def __str__(self):
        ps = ", ".join(n for n, _ in self.params)
        return f"({ps}) -> {self.body}"


@dataclasses.dataclass(frozen=True)
class Param(RowExpression):
    """Placeholder bound before compilation — carries the value of an
    uncorrelated scalar subquery (reference: SubqueryPlanner's handling of
    uncorrelated scalar subqueries, applied at execution time here)."""

    name: str = ""

    def __str__(self):
        return f"${self.name}"


def substitute_params(e: RowExpression, bindings: dict) -> RowExpression:
    """Replace Param nodes with Constants (bindings: name -> Constant)."""
    if isinstance(e, Param):
        if e.name not in bindings:
            raise KeyError(f"unbound parameter {e.name}")
        return bindings[e.name]
    if isinstance(e, Call):
        new_args = tuple(substitute_params(a, bindings) for a in e.args)
        if new_args != e.args:
            return Call(e.type, e.fn, new_args)
    return e


def substitute_refs(e: RowExpression, mapping: dict) -> RowExpression:
    """Rename InputRefs (symbol -> symbol), for pushdown through Project."""
    if isinstance(e, InputRef) and e.name in mapping:
        m = mapping[e.name]
        return m if isinstance(m, RowExpression) else InputRef(e.type, m)
    if isinstance(e, LambdaExpr):
        # lambda params shadow outer symbols
        inner = {k: v for k, v in mapping.items()
                 if k not in {n for n, _ in e.params}}
        nb = substitute_refs(e.body, inner)
        if nb is not e.body:
            return LambdaExpr(e.type, e.params, nb)
        return e
    if isinstance(e, Call):
        new_args = tuple(substitute_refs(a, mapping) for a in e.args)
        if new_args != e.args:
            return Call(e.type, e.fn, new_args)
    return e


def expr_inputs(e: RowExpression, acc: Optional[set] = None) -> set:
    """Collect referenced input column names (for projection pruning)."""
    if acc is None:
        acc = set()
    if isinstance(e, InputRef):
        acc.add(e.name)
    elif isinstance(e, LambdaExpr):
        inner: set = set()
        expr_inputs(e.body, inner)
        acc |= inner - {n for n, _ in e.params}
    elif isinstance(e, Call):
        for a in e.args:
            expr_inputs(a, acc)
    return acc
