"""HyperLogLog sketches as dictionary-entry values.

Reference surface: presto-main/.../type/HyperLogLogType.java,
operator/aggregation/ApproximateSetAggregation (approx_set),
MergeHyperLogLogAggregation (merge), and
operator/scalar/HyperLogLogFunctions.java (cardinality,
empty_approx_set).

Design: same shape as expr/tdigest.py — a sketch value is a serialized
sparse register list stored as a dictionary ENTRY, so sketches ride
joins/exchanges/spill as int32 codes and cardinality() is a code-indexed
LUT. The hash pipeline and the bias-corrected estimator are IDENTICAL to
the approx_distinct lowering (expr/compile.py __hll_reg/__hll_rank and
plan/builder._plan_hll), so `cardinality(approx_set(x))` and
`approx_distinct(x)` return the same number for the same input.
"""

from __future__ import annotations

import math

import numpy as np

# must equal expr.compile.HLL_M (asserted by tests): 2^12 registers,
# standard error 1.04/sqrt(m) ≈ 1.6%
HLL_M = 4096

_MAGIC = "HL1"

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _np_splitmix64(x: np.ndarray) -> np.ndarray:
    """numpy twin of ops.hashing.splitmix64 (same constants/shifts)."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        return x ^ (x >> np.uint64(31))


def regs_and_ranks(values: np.ndarray,
                   content_hashes: np.ndarray | None = None):
    """Per-row (register, rank) exactly like the device lowering:
    register = low log2(m) hash bits; rank = 1 + clz of the top 32 bits.
    `content_hashes` (already int64) takes precedence — string columns
    hash by dictionary content, not code."""
    if content_hashes is not None:
        h = content_hashes.astype(np.int64)
    elif np.issubdtype(values.dtype, np.floating):
        v = values.astype(np.float64)
        h = v.view(np.int64).copy()
        h[v == 0.0] = 0  # canonicalize -0.0 → +0.0
    else:
        h = values.astype(np.int64)
    h = _np_splitmix64(h.view(np.uint64))
    reg = (h & np.uint64(HLL_M - 1)).astype(np.int64)
    w = ((h >> np.uint64(32)) & np.uint64(0xFFFFFFFF)).astype(np.int64)
    f = np.maximum(w.astype(np.float64), 1.0)
    rank = np.where(w == 0, 33, 32 - np.floor(np.log2(f))).astype(np.int64)
    return reg, rank


def serialize(ranks: np.ndarray) -> str:
    """Dense m-register rank array → sparse ASCII entry."""
    nz = np.nonzero(ranks)[0]
    body = ",".join(f"{int(i)}:{int(ranks[i])}" for i in nz)
    return f"{_MAGIC};{HLL_M};{body}"


def deserialize(entry: str) -> np.ndarray | None:
    parts = entry.split(";")
    if len(parts) != 3 or parts[0] != _MAGIC:
        return None
    try:
        m = int(parts[1])
        if m <= 0:
            return None
        ranks = np.zeros(m, np.int64)
        if parts[2]:
            for pair in parts[2].split(","):
                i, r = pair.split(":")
                i = int(i)
                if not 0 <= i < m:  # negative would wrap via Python indexing
                    return None
                ranks[i] = max(ranks[i], int(r))
    except (ValueError, IndexError):
        return None
    return ranks


def empty() -> str:
    return serialize(np.zeros(HLL_M, np.int64))


def build(reg: np.ndarray, rank: np.ndarray) -> str:
    ranks = np.zeros(HLL_M, np.int64)
    np.maximum.at(ranks, reg, rank)
    return serialize(ranks)


def merge(entries) -> str | None:
    """Elementwise register max (MergeHyperLogLogAggregation). Sketches
    with differing register counts are INCOMPATIBLE states — fail the
    query loudly (the reference throws too) rather than undercount."""
    acc = None
    for e in entries:
        r = deserialize(e)
        if r is None:
            continue
        if acc is None:
            acc = r.copy()
        elif len(r) != len(acc):
            raise ValueError(
                f"cannot merge HyperLogLog sketches with different "
                f"register counts ({len(acc)} vs {len(r)})")
        else:
            np.maximum(acc, r, out=acc)
    return None if acc is None else serialize(acc)


def cardinality(entry: str) -> int | None:
    """Bias-corrected harmonic-mean estimate with the small-range
    linear-counting correction — the SAME estimator _plan_hll builds in
    plan nodes, so approx_set→cardinality == approx_distinct."""
    ranks = deserialize(entry)
    if ranks is None:
        return None
    m = float(len(ranks))
    occupied = ranks > 0
    zeros = m - float(occupied.sum())
    s = float(np.sum(np.power(2.0, -ranks[occupied].astype(np.float64))))
    alpha = 0.7213 / (1.0 + 1.079 / m)
    S = s + zeros
    raw = alpha * m * m / S
    if raw <= 2.5 * m and zeros > 0:
        return int(round(m * math.log(m / zeros)))
    return int(round(raw))
