"""ARRAY / MAP expression evaluation over the dense padded layout.

Reference surface: operator/scalar/ArrayFunctions + MapFunctions and the
block-level ColumnarArray/ColumnarMap (presto-main/.../operator/scalar/,
presto-spi/.../block/ColumnarArray.java). The reference walks
offsets-into-flat-blocks per position; here every function is one
vectorized op over the whole [capacity, W] plane:

- an array value is StructVal(values[cap, W], sizes[cap], evalid, keys)
  where W is the static per-batch width;
- "present" elements are those with column index < sizes[row]; present
  elements may still be SQL NULL via the evalid plane;
- maps carry an aligned keys plane (map keys are non-null).

Sorting/dedup inside arrays uses `jax.lax.sort` along the W axis with
absent/null ranks as leading keys — the same scatter-free style as the
engine's GROUP BY (ops/grouping.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.types import (
    ArrayType,
    MapType,
    Type,
)


class StructVal(NamedTuple):
    """Evaluated array/map expression: the structural planes of a Column.
    Row-level validity travels separately (like scalar evaluation)."""

    values: jnp.ndarray                 # [cap, W] element values
    sizes: jnp.ndarray                  # [cap] int32 cardinalities
    evalid: Optional[jnp.ndarray]       # [cap, W] element validity or None
    keys: Optional[jnp.ndarray] = None  # [cap, W] map keys or None

    @property
    def width(self) -> int:
        return self.values.shape[1]

    def present(self) -> jnp.ndarray:
        """[cap, W] mask of in-size element slots."""
        w = self.values.shape[1]
        return jnp.arange(w, dtype=jnp.int32)[None, :] < self.sizes[:, None]

    def element_valid(self) -> jnp.ndarray:
        """[cap, W] mask of present AND non-null elements."""
        p = self.present()
        return p if self.evalid is None else (p & self.evalid)


def pad_plane_width(plane, w: int, fill=0):
    """Widen a [n, w0] plane to [n, w] with `fill` padding."""
    w0 = plane.shape[1]
    if w0 == w:
        return plane
    pad = jnp.full((plane.shape[0], w - w0), fill, plane.dtype)
    return jnp.concatenate([plane, pad], axis=1)


def _minmax_ident(dtype, want_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if want_min else -jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.array(want_min, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if want_min else info.min, dtype)


def array_ctor(parts, cap: int, dtype) -> StructVal:
    """ARRAY[e1, .., eN]: stack N evaluated scalars into a [cap, N] plane.
    parts: list of (values, validity|None)."""
    if not parts:
        return StructVal(jnp.zeros((cap, 0), dtype),
                         jnp.zeros(cap, jnp.int32), None)
    vals = jnp.stack(
        [jnp.broadcast_to(v, (cap,)).astype(dtype) for v, _ in parts], axis=1)
    if any(valid is not None for _, valid in parts):
        evalid = jnp.stack(
            [jnp.ones(cap, bool) if valid is None
             else jnp.broadcast_to(valid, (cap,)) for _, valid in parts],
            axis=1)
    else:
        evalid = None
    sizes = jnp.full(cap, len(parts), jnp.int32)
    return StructVal(vals, sizes, evalid)


def subscript(sv: StructVal, idx, idx_valid, rvalid, *, null_oob: bool):
    """arr[i] (1-based; negative counts from the end, element_at
    semantics). Returns (values, validity). Out-of-bounds access yields
    NULL (`null_oob` distinguishes element_at from [] only in spirit —
    with no exception channel on-device, both return NULL)."""
    sizes = sv.sizes
    pos = jnp.where(idx >= 0, idx - 1, sizes.astype(idx.dtype) + idx)
    in_range = (pos >= 0) & (pos < sizes.astype(pos.dtype))
    posc = jnp.clip(pos, 0, max(sv.width - 1, 0)).astype(jnp.int32)
    if sv.width == 0:
        out = jnp.zeros(sizes.shape[0], sv.values.dtype)
        return out, jnp.zeros(sizes.shape[0], bool)
    out = jnp.take_along_axis(sv.values, posc[:, None], axis=1)[:, 0]
    valid = in_range
    if sv.evalid is not None:
        ev = jnp.take_along_axis(sv.evalid, posc[:, None], axis=1)[:, 0]
        valid = valid & ev
    if idx_valid is not None:
        valid = valid & idx_valid
    if rvalid is not None:
        valid = valid & rvalid
    return out, valid


def map_element_at(sv: StructVal, key, key_valid, rvalid):
    """element_at(map, k): first matching key's value, NULL if absent."""
    match = (sv.keys == key[:, None] if key.ndim else sv.keys == key)
    match = match & sv.present()
    found = jnp.any(match, axis=1)
    j = jnp.argmax(match, axis=1).astype(jnp.int32)
    if sv.width == 0:
        out = jnp.zeros(sv.sizes.shape[0], sv.values.dtype)
        return out, jnp.zeros(sv.sizes.shape[0], bool)
    out = jnp.take_along_axis(sv.values, j[:, None], axis=1)[:, 0]
    valid = found
    if sv.evalid is not None:
        ev = jnp.take_along_axis(sv.evalid, j[:, None], axis=1)[:, 0]
        valid = valid & ev
    if key_valid is not None:
        valid = valid & key_valid
    if rvalid is not None:
        valid = valid & rvalid
    return out, valid


def cardinality(sv: StructVal, rvalid):
    return sv.sizes.astype(jnp.int64), rvalid


def _null_if_unfound_with_nulls(found, sv: StructVal, valid):
    """Three-valued semantics shared by contains/array_position: a miss on
    an array that holds NULL elements is unknown, not FALSE/0 (Presto
    ArrayContains/ArrayPosition return NULL there)."""
    if sv.evalid is None:
        return valid
    has_null = jnp.any(sv.present() & ~sv.evalid, axis=1)
    unknown = ~found & has_null
    return ~unknown if valid is None else (valid & ~unknown)


def contains(sv: StructVal, x, x_valid, rvalid):
    m = (sv.values == (x[:, None] if getattr(x, "ndim", 0) else x))
    m = m & sv.element_valid()
    out = jnp.any(m, axis=1)
    valid = rvalid
    if x_valid is not None:
        valid = x_valid if valid is None else (valid & x_valid)
    valid = _null_if_unfound_with_nulls(out, sv, valid)
    return out, valid


def array_position(sv: StructVal, x, x_valid, rvalid):
    m = (sv.values == (x[:, None] if getattr(x, "ndim", 0) else x))
    m = m & sv.element_valid()
    found = jnp.any(m, axis=1)
    pos = jnp.where(found, jnp.argmax(m, axis=1) + 1, 0).astype(jnp.int64)
    valid = rvalid
    if x_valid is not None:
        valid = x_valid if valid is None else (valid & x_valid)
    valid = _null_if_unfound_with_nulls(found, sv, valid)
    return pos, valid


def array_minmax(sv: StructVal, rvalid, want_min: bool):
    """array_min/array_max: NULL for empty arrays or arrays containing a
    NULL element (Presto ArrayMinMaxUtils semantics)."""
    ident = _minmax_ident(sv.values.dtype, want_min)
    ev = sv.element_valid()
    masked = jnp.where(ev, sv.values, ident)
    out = jnp.min(masked, axis=1) if want_min else jnp.max(masked, axis=1)
    has_null = jnp.any(sv.present() & ~ev, axis=1)
    valid = (sv.sizes > 0) & ~has_null
    if rvalid is not None:
        valid = valid & rvalid
    return out, valid


def array_sum(sv: StructVal, rvalid, dtype, average: bool):
    """array_sum/array_average over non-null elements (NULL elements are
    skipped; all-null/empty arrays yield NULL)."""
    ev = sv.element_valid()
    contrib = jnp.where(ev, sv.values.astype(dtype), jnp.zeros((), dtype))
    total = jnp.sum(contrib, axis=1)
    n = jnp.sum(ev, axis=1)
    if average:
        total = total / jnp.maximum(n, 1).astype(dtype)
    valid = n > 0
    if rvalid is not None:
        valid = valid & rvalid
    return total, valid


def concat_arrays(a: StructVal, b: StructVal) -> StructVal:
    """a || b: out[j] = j < |a| ? a[j] : b[j - |a|]; width Wa + Wb."""
    wa, wb = a.width, b.width
    w = wa + wb
    cap = a.sizes.shape[0]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    sa = a.sizes[:, None]
    from_a = j < sa
    ja = jnp.clip(j, 0, max(wa - 1, 0))
    jb = jnp.clip(j - sa, 0, max(wb - 1, 0))
    def _plane(pa, pb, dtype):
        va = jnp.take_along_axis(pa, ja, axis=1) if wa else jnp.zeros((cap, w), dtype)
        vb = jnp.take_along_axis(pb, jb, axis=1) if wb else jnp.zeros((cap, w), dtype)
        return jnp.where(from_a, va, vb)
    vals = _plane(a.values, b.values.astype(a.values.dtype), a.values.dtype)
    if a.evalid is not None or b.evalid is not None:
        ea = a.evalid if a.evalid is not None else jnp.ones((cap, max(wa, 1)), bool)[:, :wa]
        eb = b.evalid if b.evalid is not None else jnp.ones((cap, max(wb, 1)), bool)[:, :wb]
        evalid = _plane(ea, eb, jnp.bool_)
    else:
        evalid = None
    return StructVal(vals, a.sizes + b.sizes, evalid)


def _sort_planes(sv: StructVal):
    """Sort elements along W: present non-null ascending, NULL elements
    after them, absent slots last. Returns (rank, values, evalid_sorted)."""
    p = sv.present()
    ev = sv.element_valid()
    # 0 = valid element, 1 = null element, 2 = absent slot
    rank = jnp.where(ev, 0, jnp.where(p, 1, 2)).astype(jnp.int32)
    rank_s, vals_s = jax.lax.sort((rank, sv.values), dimension=1, num_keys=2)
    return rank_s, vals_s


def array_sort(sv: StructVal) -> StructVal:
    """array_sort: ascending, NULL elements last (Presto array_sort)."""
    rank_s, vals_s = _sort_planes(sv)
    evalid = rank_s == 0 if sv.evalid is not None else None
    return StructVal(vals_s, sv.sizes, evalid)


def array_distinct(sv: StructVal) -> StructVal:
    """array_distinct (order: sorted ascending, one NULL kept last —
    documented deviation from the reference's first-occurrence order; SQL
    imposes no order on array_distinct results and a sorted canonical
    order is what the scatter-free layout produces naturally)."""
    rank_s, vals_s = _sort_planes(sv)
    w = sv.width
    if w == 0:
        return sv
    prev_same = jnp.zeros_like(rank_s, dtype=bool).at[:, 1:].set(
        (vals_s[:, 1:] == vals_s[:, :-1]) & (rank_s[:, 1:] == rank_s[:, :-1])
    )
    keep = (rank_s < 2) & ~prev_same
    # push dropped slots to the end, preserving sorted order of the kept
    rank2 = jnp.where(keep, rank_s, 2)
    rank_f, vals_f = jax.lax.sort((rank2, vals_s), dimension=1, num_keys=2)
    sizes = jnp.sum(keep, axis=1).astype(jnp.int32)
    evalid = rank_f == 0 if sv.evalid is not None else None
    return StructVal(vals_f, sizes, evalid)


def slice_array(sv: StructVal, start, length) -> StructVal:
    """slice(arr, start, length): 1-based start; negative start counts
    from the end (Presto ArraySliceFunction). A start that falls outside
    the array (including a negative start reaching before the first
    element) yields an empty array — the on-device stand-in for the
    reference's invalid-start error."""
    sizes = sv.sizes.astype(jnp.int64)
    s0 = jnp.where(start >= 0, start - 1, sizes + start)
    ok = (s0 >= 0) & (start != 0) & (length >= 0)
    w = sv.width
    j = jnp.arange(w, dtype=jnp.int64)[None, :]
    src = s0[:, None] + j  # front-aligned: out slot j reads src s0+j
    in_src = (ok[:, None] & (src < sizes[:, None]) & (j < length[:, None]))
    srcc = jnp.clip(src, 0, max(w - 1, 0)).astype(jnp.int32)
    vals = jnp.take_along_axis(sv.values, srcc, axis=1)
    new_sizes = jnp.sum(in_src, axis=1).astype(jnp.int32)
    if sv.evalid is not None:
        evalid = jnp.take_along_axis(sv.evalid, srcc, axis=1) & in_src
    else:
        evalid = in_src
    return StructVal(vals, new_sizes, evalid)


def sequence(lo: int, hi: int, step: int, cap: int) -> StructVal:
    """sequence(lo, hi[, step]) with constant bounds (static W)."""
    if step == 0:
        raise ValueError("sequence step must not be zero")
    n = max(0, (hi - lo) // step + 1) if (hi - lo) * step >= 0 else 0
    vals = jnp.broadcast_to(
        (lo + step * jnp.arange(n, dtype=jnp.int64))[None, :], (cap, n))
    return StructVal(vals, jnp.full(cap, n, jnp.int32), None)


def repeat_val(v, v_valid, n: int, cap: int, dtype) -> StructVal:
    vals = jnp.broadcast_to(
        jnp.broadcast_to(v, (cap,)).astype(dtype)[:, None], (cap, n))
    evalid = None
    if v_valid is not None:
        evalid = jnp.broadcast_to(v_valid[:, None], (cap, n))
    return StructVal(vals, jnp.full(cap, n, jnp.int32), evalid)


def _membership(a: StructVal, b: StructVal) -> jnp.ndarray:
    """[cap, Wa] mask: a's element equals ANY present non-null element of
    b (elementwise [cap, Wa, Wb] compare — widths are small statics)."""
    if a.width == 0 or b.width == 0:
        return jnp.zeros(a.values.shape, bool)
    eq = a.values[:, :, None] == b.values[:, None, :]
    eq = eq & b.element_valid()[:, None, :]
    return jnp.any(eq, axis=2)


def array_union(a: StructVal, b: StructVal) -> StructVal:
    return array_distinct(concat_arrays(a, b))


def array_intersect(a: StructVal, b: StructVal) -> StructVal:
    keep = a.element_valid() & _membership(a, b)
    return array_distinct(filter_elements(a, keep))


def array_except(a: StructVal, b: StructVal) -> StructVal:
    keep = a.element_valid() & ~_membership(a, b)
    return array_distinct(filter_elements(a, keep))


def arrays_overlap(a: StructVal, b: StructVal) -> jnp.ndarray:
    return jnp.any(a.element_valid() & _membership(a, b), axis=1)


def map_concat(a: StructVal, b: StructVal) -> StructVal:
    """map_concat(m1, m2): m2 wins on duplicate keys. Concatenate the
    aligned planes, then keep the LAST occurrence of each key: one stable
    sort along W by key, runs scanned right-to-left."""
    w = a.width + b.width
    cap = a.sizes.shape[0]
    if w == 0:
        return a

    def cat_plane(pa, pb, fill, dtype):
        pa = pa if pa is not None else jnp.full((cap, a.width), fill, dtype)
        pb = pb if pb is not None else jnp.full((cap, b.width), fill, dtype)
        return jnp.concatenate([pa.astype(dtype), pb.astype(dtype)], axis=1)

    keys = cat_plane(a.keys, b.keys, 0, a.keys.dtype)
    vals = cat_plane(a.values, b.values, 0, a.values.dtype)
    present = jnp.concatenate([a.present(), b.present()], axis=1)
    evalid = jnp.concatenate([a.element_valid(), b.element_valid()], axis=1)
    pos = jnp.broadcast_to(jnp.arange(w, dtype=jnp.int32)[None, :],
                           (cap, w))
    # absent slots sort last; within a key run, position ascending —
    # the LAST position of each run is the winning (m2) entry
    krank = jnp.where(present, jnp.int64(0), jnp.int64(1))
    krank_s, keys_s, pos_s, vals_s, ev_s = jax.lax.sort(
        (krank, keys.astype(jnp.int64), pos, vals, evalid.astype(jnp.int32)),
        dimension=1, num_keys=3)
    present_s = krank_s == 0
    next_same = jnp.zeros((cap, w), bool).at[:, :-1].set(
        (keys_s[:, :-1] == keys_s[:, 1:]) & present_s[:, 1:])
    keep = present_s & ~next_same
    # pre-filter StructVal treats every slot as present (sizes=w) so
    # filter_elements sees the true element validity at the ORIGINAL slot
    # positions; it recomputes sizes from `keep` after compaction
    out = StructVal(vals_s, jnp.full(cap, w, jnp.int32),
                    ev_s.astype(bool), keys=keys_s.astype(a.keys.dtype))
    return filter_elements(out, keep)


def filter_elements(sv: StructVal, keep: jnp.ndarray) -> StructVal:
    """Keep elements where `keep` is True, compacted to the front with
    original order preserved: one stable sort along W by the drop flag
    (the scatter-free analog of the reference's per-position copy).
    Map key planes ride the same permutation."""
    w = sv.width
    if w == 0:
        return sv
    drop = (~keep).astype(jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(w, dtype=jnp.int32)[None, :],
                           drop.shape)
    ev = sv.element_valid().astype(jnp.int32)
    operands = [drop, pos, sv.values, ev]
    if sv.keys is not None:
        operands.append(sv.keys)
    out = jax.lax.sort(tuple(operands), dimension=1, num_keys=2)
    vals_s, ev_s = out[2], out[3]
    keys_s = out[4] if sv.keys is not None else None
    sizes = jnp.sum(keep, axis=1).astype(jnp.int32)
    present = jnp.arange(w, dtype=jnp.int32)[None, :] < sizes[:, None]
    return StructVal(vals_s, sizes, ev_s.astype(bool) & present,
                     keys=keys_s)


def map_from_arrays(k: StructVal, v: StructVal) -> StructVal:
    """map(array, array): aligned planes; sizes from the key array.

    With no exception channel on-device, a cardinality mismatch cannot
    raise like the reference's 'Key and value arrays must be the same
    length' — instead keys beyond the value cardinality map to NULL
    values (element validity is bounded by the value array's sizes)."""
    w = max(k.width, v.width)
    keys = pad_plane_width(k.values, w)
    vals = pad_plane_width(v.values, w)
    in_vals = v.present() if v.evalid is None else v.element_valid()
    evalid = pad_plane_width(in_vals, w, fill=False)
    return StructVal(vals, k.sizes, evalid, keys=keys)


def map_keys(sv: StructVal) -> StructVal:
    return StructVal(sv.keys, sv.sizes, None)


def map_values(sv: StructVal) -> StructVal:
    return StructVal(sv.values, sv.sizes, sv.evalid)
