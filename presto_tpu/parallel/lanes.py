"""Fused exchange lanes — one collective per dtype bucket, not per array.

Reference contrast: PartitionedOutputOperator serializes a page's blocks
into ONE wire buffer per destination (PagesSerde), so the HTTP shuffle
always ships a single stream per consumer. The prototype mesh exchange
instead issued one `all_to_all` per column plane (values, validity, hi,
live) — a Q3-shaped exchange with 6 columns dispatched ~14 collectives,
each paying ICI latency and a fresh XLA collective op.

This module is the PagesSerde analog for the collective path: every plane
of a Batch is assigned a LANE in a dense [L, n] buffer, planes are
bucketed by dtype (a collective moves one dtype), and the exchange issues
exactly one `all_to_all` per dtype bucket — O(1) collectives per exchange
regardless of column count. Unpacking is pure slicing, so the round trip
is bit-exact: the packed path must be indistinguishable from the
per-column path (tests/test_mesh_exchange.py property-checks this).

Lane order is deterministic (live first, then per column: values,
validity, hi) so a LanePlan derived from a Batch TEMPLATE applies to any
batch with the same schema — the plan is trace-time static.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import Batch, Column

# plane kinds, in deterministic enumeration order per column
_VALUES, _VALIDITY, _HI = "values", "validity", "hi"


class LanePlan:
    """Static description of a Batch's planes → (bucket, lane) mapping.

    buckets: list of (np.dtype, n_lanes). entries: one (kind, col_idx,
    bucket_idx, lane_idx) per plane; col_idx == -1 is the live mask.
    """

    def __init__(self, buckets, entries):
        self.buckets: List[Tuple[np.dtype, int]] = buckets
        self.entries: List[Tuple[str, int, int, int]] = entries

    @property
    def n_collectives(self) -> int:
        return len(self.buckets)

    def nbytes(self, capacity: int) -> int:
        """Per-device packed bytes for buffers of row capacity `capacity`."""
        return sum(nl * capacity * dt.itemsize for dt, nl in self.buckets)

    def describe(self) -> dict:
        """Static lane-layout summary — JSON-safe attrs for the mesh
        executor's lane_pack trace markers."""
        return {
            "collectives": self.n_collectives,
            "lanes": len(self.entries),
            "dtypes": ",".join(f"{dt.name}x{nl}"
                               for dt, nl in self.buckets),
        }


def plan_lanes(batch: Batch) -> Optional[LanePlan]:
    """Derive the lane plan for a batch's schema, or None when the batch
    holds planes the packer doesn't model (structural array/map columns) —
    callers fall back to the per-column exchange."""
    planes: List[Tuple[str, int, np.dtype]] = [
        ("live", -1, np.dtype(bool))]
    for ci, c in enumerate(batch.columns):
        if c.sizes is not None or c.evalid is not None or c.keys is not None:
            return None
        if c.values.ndim != 1:
            return None
        planes.append((_VALUES, ci, np.dtype(c.values.dtype)))
        if c.validity is not None:
            planes.append((_VALIDITY, ci, np.dtype(bool)))
        if c.hi is not None:
            planes.append((_HI, ci, np.dtype(c.hi.dtype)))
    buckets: List[Tuple[np.dtype, int]] = []
    index = {}
    entries = []
    for kind, ci, dt in planes:
        bi = index.get(dt)
        if bi is None:
            bi = index[dt] = len(buckets)
            buckets.append((dt, 0))
        dt0, nl = buckets[bi]
        entries.append((kind, ci, bi, nl))
        buckets[bi] = (dt0, nl + 1)
    return LanePlan(buckets, entries)


def _source_plane(batch: Batch, kind: str, ci: int):
    if ci == -1:
        return batch.live
    c = batch.columns[ci]
    return {_VALUES: c.values, _VALIDITY: c.validity, _HI: c.hi}[kind]


def pack_batch(batch: Batch, plan: LanePlan) -> List[jnp.ndarray]:
    """Stack every plane into its bucket buffer: one [L, capacity] array
    per dtype bucket, lanes in plan order."""
    per_bucket: List[List[jnp.ndarray]] = [[] for _ in plan.buckets]
    for kind, ci, bi, _lane in plan.entries:
        dt = plan.buckets[bi][0]
        per_bucket[bi].append(_source_plane(batch, kind, ci).astype(dt))
    return [jnp.stack(ps) for ps in per_bucket]


def pack_partitioned(batch: Batch, plan: LanePlan, sperm, dest, routed,
                     out_n: int) -> List[jnp.ndarray]:
    """Partition + pack in one scatter per bucket: permute each bucket's
    stacked planes by the partition sort and scatter all lanes at once
    along the row axis (ops/partition.partition_layout supplies
    sperm/dest/routed). Bit-identical to partition_for_exchange followed
    by pack_batch, but K column scatters collapse into B bucket scatters
    and the packed buffers feed all_to_all directly."""
    bufs = []
    for bi, (dt, nl) in enumerate(plan.buckets):
        rows = []
        for kind, ci, b, _lane in plan.entries:
            if b != bi:
                continue
            if ci == -1:
                # live lane: routed is already in sorted order — rows that
                # landed in a lane are live there by construction
                rows.append(routed.astype(dt))
            else:
                rows.append(_source_plane(batch, kind, ci)[sperm].astype(dt))
        src = jnp.stack(rows)  # [nl, n] in sorted row order
        buf = jnp.zeros((nl, out_n), dtype=dt)
        bufs.append(buf.at[:, dest].set(src, mode="drop"))
    return bufs


def unpack_batch(template: Batch, plan: LanePlan,
                 bufs: Sequence[jnp.ndarray]) -> Batch:
    """Rebuild a Batch (same schema/dicts as `template`, capacity =
    buffer row count) from packed bucket buffers."""
    lane_of = {(kind, ci): (bi, lane)
               for kind, ci, bi, lane in plan.entries}

    def plane(kind, ci, dtype):
        bi, lane = lane_of[(kind, ci)]
        return bufs[bi][lane].astype(dtype)

    cols = []
    for ci, c in enumerate(template.columns):
        validity = (plane(_VALIDITY, ci, bool)
                    if (_VALIDITY, ci) in lane_of else None)
        hi = (plane(_HI, ci, c.hi.dtype)
              if (_HI, ci) in lane_of else None)
        cols.append(Column(plane(_VALUES, ci, c.values.dtype), validity, hi))
    live = plane("live", -1, bool)
    return Batch(template.names, template.types, cols, live, template.dicts)
