"""Mesh SQL executor — a fragmented DistributedPlan as ONE shard_map program.

Reference mapping (SURVEY §2e "TPU-native equivalent"): the reference moves
pages between fragments through PartitionedOutputOperator.partitionPage:377
→ OutputBuffer → HTTP → ExchangeClient.java:69. Within a TPU slice the
same dataflow is a synchronous collective: every OUT_HASH exchange lowers
to a hash-partition kernel + `jax.lax.all_to_all`, OUT_BROADCAST /
OUT_GATHER lower to `all_gather`, and the fragments themselves — scan
chains, partial/final aggregation, co-located hash joins — trace into one
XLA program executed SPMD over the mesh. The HTTP cluster
(server/coordinator.py) remains the cross-host path; this executor is the
intra-slice path where the shuffle rides ICI and the host never touches
row data.

Supported fragment shapes (the TPC-H star-join/aggregate core and beyond):
scans with filter/project chains, partial→final aggregate splits,
broadcast and hash-partitioned joins (unique and bounded-fanout; INNER /
LEFT / FULL OUTER — RIGHT normalizes to LEFT at analysis), semi joins,
window functions (one-sort closed-form kernels), UNION [ALL] /
INTERSECT / EXCEPT, UNNEST, gathered sort/topn/limit/output.
Data-dependent sizes (join fanout, exchange partition skew, group counts)
use static capacities with device-side overflow counters, psum-reduced and
checked on the host after execution — the driver retries with doubled
capacities on overflow (the mesh analog of the streaming engine's
capacity-growth replay)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from presto_tpu.batch import Batch, Column, round_up_capacity
from presto_tpu.connector import Catalog
from presto_tpu.exec.runtime import (
    ExecConfig,
    _input_state,
    _renorm_limbs,
    build_agg_finalizer,
    collapse_chain,
)
from presto_tpu.ops.grouping import KeyCol, StateCol, grouped_merge
from presto_tpu.ops.join import (
    align_probe_strings,
    build_side,
    gather_join_output,
    probe_counts,
    probe_expand,
    probe_unique,
)
from presto_tpu.ops.partition import partition_for_exchange
from presto_tpu.ops.sort import limit_batch, sort_batch
from presto_tpu.parallel.mesh import WORKERS, shard_map
from presto_tpu.plan.agg_states import (
    agg_state_layout,
    limb_pairs,
    state_types as layout_state_types,
)
from presto_tpu.plan.fragmenter import (
    OUT_BROADCAST,
    OUT_GATHER,
    OUT_HASH,
    DistributedPlan,
    fragment_plan,
)
from presto_tpu.plan.nodes import (
    Aggregate,
    Filter,
    HashJoin,
    Limit,
    Output,
    PlanNode,
    Project,
    RemoteSource,
    SemiJoin,
    Sort,
    TableScan,
    Window,
)
from presto_tpu.exec.runtime import _sort_keys


class MeshOverflow(RuntimeError):
    pass


def _all_to_all_batch(b: Batch, n_dev: int, per_cap: int) -> Batch:
    def a2a(x):
        if x is None:
            return None
        y = jax.lax.all_to_all(x.reshape(n_dev, per_cap), WORKERS,
                               split_axis=0, concat_axis=0, tiled=False)
        return y.reshape(-1)

    cols = [Column(a2a(c.values), a2a(c.validity), a2a(c.hi))
            for c in b.columns]
    return Batch(b.names, b.types, cols, a2a(b.live), b.dicts)


def _gather_batch(b: Batch) -> Batch:
    """Replicate all rows on every device (OUT_GATHER / OUT_BROADCAST)."""

    def ag(x):
        if x is None:
            return None
        return jax.lax.all_gather(x, WORKERS, tiled=True)

    cols = [Column(ag(c.values), ag(c.validity), ag(c.hi)) for c in b.columns]
    return Batch(b.names, b.types, cols, ag(b.live), b.dicts)


class MeshExecutor:
    """Executes SQL over an n-device mesh with collective exchanges."""

    def __init__(self, catalog: Catalog, mesh, config: Optional[ExecConfig] = None,
                 fanout_budget: int = 4, max_retries: int = 3):
        self.catalog = catalog
        self.mesh = mesh
        self.n_dev = mesh.shape[WORKERS]
        self.config = config or ExecConfig()
        self.fanout_budget = fanout_budget
        self.max_retries = max_retries
        # doubled on each MeshOverflow retry; scales every static capacity
        # (group tables, exchange lanes, join fanout)
        self._cap_boost = 1

    # -- host-side staging -------------------------------------------------

    def _stage_scan(self, scan: TableScan, sharded: bool) -> Batch:
        """Read splits per device; build a row-sharded (SOURCE/HASH
        fragments: splits d::N per device) or replicated (SINGLE fragments:
        every device reads all splits) global Batch."""
        conn = self.catalog.connectors[scan.catalog]
        handle = conn.get_table(scan.table)
        nrows = int(handle.row_count or 0)
        nsplits = max(self.n_dev, -(-nrows // self.config.batch_rows))
        columns = list(scan.assignments.values())
        symbols = list(scan.assignments.keys())
        out_types = dict(scan.output)
        if not columns and handle.columns:
            # COUNT(*)-style scan: stage one carrier column purely for row
            # multiplicity (the streaming engine fabricates liveness; the
            # mesh stager derives liveness from column data)
            columns = [handle.columns[0].name]
            symbols = ["__rowcount__"]
            out_types = {"__rowcount__": handle.columns[0].type}
        splits = conn.splits(handle, nsplits)
        if sharded:
            if any(s.bucket is not None for s in splits):
                # bucketed table: place by bucket id so colocated joins
                # stay aligned across tables (bucket b of every table
                # lands on device b % N)
                per_splits = [
                    [s for s in splits if s.bucket % self.n_dev == d]
                    for d in range(self.n_dev)
                ]
            else:
                per_splits = [splits[d::self.n_dev]
                              for d in range(self.n_dev)]
            per_dev: List[List[Batch]] = [
                [conn.read_split(s, columns) for s in ss]
                for ss in per_splits
            ]
        else:
            all_b = [conn.read_split(s, columns) for s in splits]
            per_dev = [all_b]  # one logical copy; replicated by sharding
        cap = max((sum(int(np.asarray(b.live).sum()) for b in bs) or 1)
                  for bs in per_dev)
        cap = round_up_capacity(cap)
        names, types = symbols, [out_types[s] for s in symbols]
        groups = len(per_dev)
        data = {}
        live = np.zeros((groups, cap), bool)
        dicts = {}
        for ci, cname in enumerate(columns):
            arrs = np.zeros((groups, cap), dtype=types[ci].dtype)
            valid = None
            for d, bs in enumerate(per_dev):
                pos = 0
                for b in bs:
                    lv = np.asarray(b.live)
                    v = np.asarray(b.column(cname).values)[lv]
                    arrs[d, pos:pos + len(v)] = v
                    bv = b.column(cname).validity
                    if bv is not None:
                        if valid is None:
                            valid = np.ones((groups, cap), bool)
                        valid[d, pos:pos + len(v)] = np.asarray(bv)[lv]
                    if ci == 0:
                        live[d, pos:pos + len(v)] = True
                    pos += len(v)
                    if cname in b.dicts:
                        dicts[symbols[ci]] = b.dicts[cname]
            data[symbols[ci]] = (arrs, valid)
        spec = P(WORKERS) if sharded else P()
        sharding = NamedSharding(self.mesh, spec)
        cols = [
            Column(jax.device_put(data[s][0].reshape(-1), sharding),
                   None if data[s][1] is None
                   else jax.device_put(data[s][1].reshape(-1), sharding))
            for s in symbols
        ]
        return Batch(names, types, cols,
                     jax.device_put(live.reshape(-1), sharding), dicts)

    # -- trace-time node lowering -----------------------------------------

    def _lower_agg(self, node: Aggregate, child: Batch, cap: int,
                   diags: list) -> Batch:
        in_types = dict(node.child.output)
        layout = agg_state_layout(node.aggs, in_types)
        lpairs = limb_pairs(layout)
        key_syms = node.group_keys
        key_types = [in_types[k] for k in key_syms]
        final_mode = node.step == "final"
        if final_mode:
            st_types = [in_types[name] for name, _, _ in layout]
        else:
            st_types = layout_state_types(layout, in_types)
        b = child
        keys = [KeyCol(b.column(k).values, b.column(k).validity,
                       len(b.dicts[k]) if k in b.dicts else None)
                for k in key_syms]
        states = []
        for (name, op, a), st in zip(layout, st_types):
            if final_mode:
                c = b.column(name)
                states.append(StateCol(c.values.astype(st.dtype), c.validity, op))
            else:
                states.append(_input_state(b, name, op, a, st, in_types))
        kout, sout, out_live, ng = grouped_merge(keys, states, b.live, cap)
        sout = _renorm_limbs(list(sout), lpairs)
        diags.append(jnp.maximum(ng - cap, 0))
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, s.validity if s.op != "count_add" else None)
            for s in sout
        ]
        names = list(key_syms) + [name for name, _, _ in layout]
        types = key_types + st_types
        dicts = {k: b.dicts[k] for k in key_syms if k in b.dicts}
        for name, op, a in layout:
            if op in ("min", "max"):
                if a.arg in b.dicts:
                    dicts[name] = b.dicts[a.arg]
                elif name in b.dicts:  # final mode: state col carries it
                    dicts[name] = b.dicts[name]
        acc = Batch(names, types, cols, out_live, dicts)
        if node.step == "partial":
            return acc
        fin = build_agg_finalizer(node, key_syms, key_types, in_types)
        return fin(acc)

    def _build_remainder(self, node: HashJoin, table, bm) -> Batch:
        """FULL OUTER tail: build rows no probe row matched, NULL probe
        columns (LookupJoinOperators.fullOuterJoin's lookup-outer pass).
        Correct on-mesh because the fragmenter never broadcasts a FULL
        join's build side (plan/fragmenter.py:157) — each device owns a
        disjoint hash partition of the build rows."""
        lsyms = [n for n, _ in node.left.output]
        rsyms = [n for n, _ in node.right.output]
        ltypes = dict(node.left.output)
        cap = table.hashes.shape[0]
        names, types, cols = [], [], []
        for c in lsyms:
            names.append(c)
            types.append(ltypes[c])
            cols.append(Column(jnp.zeros(cap, ltypes[c].dtype),
                               jnp.zeros(cap, bool)))
        for c in rsyms:
            names.append(c)
            types.append(table.batch.type_of(c))
            cols.append(table.batch.column(c))
        live = table.orig_live & ~bm
        return Batch(names, types, cols, live,
                     {c: table.batch.dicts[c] for c in rsyms
                      if c in table.batch.dicts})

    def _expand_pairs(self, probe: Batch, table, pba, lkeys, rkeys,
                      diags: list):
        """Bounded-fanout pair expansion with overflow accounting — shared
        by joins and residual semijoins so the capacity formula and the
        MeshOverflow diag protocol can't diverge."""
        lo, counts, offsets, total, _, _ovf = probe_counts(table, pba, lkeys,
                                                           rkeys)
        out_cap = probe.capacity * self.fanout_budget * self._cap_boost
        pr, bi, ol = probe_expand(table, pba, lkeys, rkeys,
                                  lo, counts, offsets, 0, out_cap)
        diags.append(jnp.maximum(total - out_cap, 0))
        return pr, bi, ol

    def _lower_join(self, node: HashJoin, probe: Batch, build: Batch,
                    diags: list) -> Batch:
        lsyms = [n for n, _ in node.left.output]
        rsyms = [n for n, _ in node.right.output]
        table = build_side(build, tuple(node.right_keys))
        pba = align_probe_strings(probe, tuple(node.left_keys), table,
                                  tuple(node.right_keys))
        build_cap = table.hashes.shape[0]
        if node.build_unique:
            idx, matched = probe_unique(table, pba, tuple(node.left_keys),
                                        tuple(node.right_keys))
            out = gather_join_output(
                probe, table, jnp.arange(probe.capacity, dtype=jnp.int32),
                idx, probe.live, lsyms, rsyms)
            if node.kind == "inner":
                return out.with_live(out.live & matched)
            cols = list(out.columns)
            for i, nme in enumerate(out.names):
                if nme in rsyms:
                    c = cols[i]
                    valid = (c.validity if c.validity is not None
                             else jnp.ones(out.capacity, bool))
                    cols[i] = Column(c.values, valid & matched, c.hi)
            out = Batch(out.names, out.types, cols, out.live, out.dicts)
            if node.kind == "full":
                bm = (jnp.zeros(build_cap, bool)
                      .at[idx].max(matched & probe.live, mode="drop"))
                out = _trace_concat(out, self._build_remainder(node, table,
                                                               bm))
            return out
        # bounded fanout: one expansion chunk of probe_cap × fanout_budget
        pr, bi, ol = self._expand_pairs(
            probe, table, pba, tuple(node.left_keys),
            tuple(node.right_keys), diags)
        out = gather_join_output(probe, table, pr, bi, ol, lsyms, rsyms)
        if node.kind in ("left", "full"):
            exists = (jnp.zeros(probe.capacity, dtype=jnp.int32)
                      .at[pr].max(ol.astype(jnp.int32), mode="drop")
                      .astype(bool))
            tail = gather_join_output(
                probe, table, jnp.arange(probe.capacity, dtype=jnp.int32),
                jnp.zeros(probe.capacity, dtype=jnp.int32),
                probe.live & ~exists, lsyms, rsyms)
            tcols = [
                Column(c.values, (jnp.zeros(tail.capacity, bool)
                                  if nme in rsyms else c.validity), c.hi)
                for nme, c in zip(tail.names, tail.columns)
            ]
            tail = Batch(tail.names, tail.types, tcols, tail.live, tail.dicts)
            out = _trace_concat(out, tail)
        if node.kind == "full":
            bm = (jnp.zeros(build_cap, bool)
                  .at[bi].max(ol, mode="drop"))
            out = _trace_concat(out, self._build_remainder(node, table, bm))
        return out

    def _lower(self, node: PlanNode, fragments, staged, memo, diags) -> Batch:
        """Per-device local lowering of a fragment subtree."""
        base, chain = collapse_chain(node)
        if chain is not None:
            return chain(self._lower(base, fragments, staged, memo, diags))
        if isinstance(node, TableScan):
            return staged[id(node)]
        if isinstance(node, RemoteSource):
            return self._lower_exchange(node.fragment_id, fragments, staged,
                                        memo, diags)
        if isinstance(node, Aggregate):
            child = self._lower(node.child, fragments, staged, memo, diags)
            cap = self._agg_cap(node)
            return self._lower_agg(node, child, cap, diags)
        if isinstance(node, HashJoin):
            probe = self._lower(node.left, fragments, staged, memo, diags)
            build = self._lower(node.right, fragments, staged, memo, diags)
            return self._lower_join(node, probe, build, diags)
        if isinstance(node, SemiJoin):
            probe = self._lower(node.left, fragments, staged, memo, diags)
            build = self._lower(node.right, fragments, staged, memo, diags)
            lkeys, rkeys = tuple(node.left_keys), tuple(node.right_keys)
            table = build_side(build, rkeys)
            pba = align_probe_strings(probe, lkeys, table, rkeys)
            if node.residual is None:
                _, matched = probe_unique(table, pba, lkeys, rkeys)
            else:
                # correlated EXISTS with non-equi conjuncts (Q21 shape):
                # bounded pair expansion + residual + per-probe-row ANY —
                # the mesh form of _execute_semijoin's residual path
                from presto_tpu.expr.compile import compile_predicate

                lsyms = [n for n, _ in node.left.output]
                rsyms = [n for n, _ in node.right.output]
                pred = compile_predicate(node.residual)
                pr, bi, ol = self._expand_pairs(probe, table, pba,
                                                lkeys, rkeys, diags)
                pair = gather_join_output(probe, table, pr, bi, ol,
                                          lsyms, rsyms)
                ok = pred(pair) & pair.live
                matched = (jnp.zeros(probe.capacity, dtype=jnp.int32)
                           .at[pr].max(ok.astype(jnp.int32), mode="drop")
                           .astype(bool))
            if node.negated:
                keep = ~matched
                if node.null_aware and node.residual is None:
                    # NOT IN three-valued logic (same as the local
                    # engine): a NULL probe key against a non-empty set
                    # is NULL → row filtered
                    key_valid = jnp.ones(probe.capacity, bool)
                    for lk in lkeys:
                        kv = probe.column(lk).validity
                        if kv is not None:
                            key_valid = key_valid & kv
                    keep = keep & (key_valid | (table.n_rows == 0))
            else:
                keep = matched
            return probe.with_live(probe.live & keep)
        if isinstance(node, Sort):
            child = self._lower(node.child, fragments, staged, memo, diags)
            return sort_batch(child, _sort_keys(node, child), limit=node.limit)
        if isinstance(node, Limit):
            child = self._lower(node.child, fragments, staged, memo, diags)
            return limit_batch(child, node.count)
        if isinstance(node, Output):
            child = self._lower(node.child, fragments, staged, memo, diags)
            return child.select(node.symbols).rename(node.names)
        from presto_tpu.plan.nodes import SetOp, Unnest

        if isinstance(node, Unnest):
            from presto_tpu.exec.runtime import unnest_expand

            child = self._lower(node.child, fragments, staged, memo, diags)
            return unnest_expand(node, child)
        if isinstance(node, SetOp) and node.kind == "union":
            from presto_tpu.exec.runtime import (
                _distinct_rows,
                _unify_batch_dicts,
            )

            left = self._lower(node.left, fragments, staged, memo, diags)
            right = self._lower(node.right, fragments, staged, memo, diags)
            left = left.rename(node.symbols)
            right = right.rename(node.symbols)
            left, right = _unify_batch_dicts([left, right])
            merged = _trace_concat(left, right)
            if node.all:
                return merged
            return _distinct_rows(merged)
        if isinstance(node, SetOp) and node.kind in ("intersect", "except"):
            # membership on ALL columns, then distinct — the runtime's
            # _execute_setop shape, traced per device (inputs arrive
            # co-partitioned: the fragmenter hash-exchanges both branches
            # on the full column list)
            from presto_tpu.exec.runtime import (
                _distinct_rows,
                _unify_batch_dicts,
            )

            left = self._lower(node.left, fragments, staged, memo, diags)
            right = self._lower(node.right, fragments, staged, memo, diags)
            left = left.rename(node.symbols)
            right = right.rename(node.symbols)
            left, right = _unify_batch_dicts([left, right])
            keys = tuple(node.symbols)
            table = build_side(right, keys)
            pba = align_probe_strings(left, keys, table, keys)
            _, matched = probe_unique(table, pba, keys, keys)
            keep = matched if node.kind == "intersect" else ~matched
            return _distinct_rows(left.with_live(left.live & keep))
        if isinstance(node, Window):
            from presto_tpu.exec.runtime import build_window_compute

            child = self._lower(node.child, fragments, staged, memo, diags)
            return build_window_compute(node)(child)
        raise NotImplementedError(
            f"mesh executor: {type(node).__name__}")

    def _lower_exchange(self, fid: int, fragments, staged, memo, diags) -> Batch:
        if fid in memo:
            return memo[fid]
        f = fragments[fid]
        out = self._lower(f.root, fragments, staged, memo, diags)
        if f.output_partitioning == OUT_HASH:
            per_cap = round_up_capacity(
                max(out.capacity // self.n_dev, 128) * 2 * self._cap_boost)
            parts, _, ovf = partition_for_exchange(
                out, list(f.output_keys), self.n_dev, per_cap)
            diags.append(ovf)
            out = _all_to_all_batch(parts, self.n_dev, per_cap)
        elif f.output_partitioning in (OUT_GATHER, OUT_BROADCAST):
            out = _gather_batch(out)
        elif f.output_partitioning == "rr":
            # round-robin redistribution exists to balance load; on-mesh
            # every device already holds its share — rows stay put
            pass
        memo[fid] = out
        return out

    def _agg_cap(self, node: Aggregate) -> int:
        cap = self.config.agg_capacity
        try:
            from presto_tpu.plan.stats import derive

            st = derive(node, self.catalog)
        except Exception:
            st = None
        if st is not None and st.rows:
            cap = max(cap, round_up_capacity(
                int(min(st.rows * 1.25, float(1 << 22)))))
        return cap * self._cap_boost

    # -- entry -------------------------------------------------------------

    def run_batch(self, sql: str) -> Batch:
        from presto_tpu.plan.builder import plan_query
        from presto_tpu.plan.optimizer import optimize

        qp = optimize(plan_query(sql, self.catalog), self.catalog)
        if qp.scalar_subqueries:
            # bind uncorrelated scalar subqueries before fragmenting (they
            # gather to one value; the local streaming engine computes
            # them host-side — shared helper with run_plan/coordinator)
            from presto_tpu.exec.runtime import (
                ExecContext,
                bind_scalar_subqueries,
            )

            bind_scalar_subqueries(qp, ExecContext(self.catalog, self.config))
        dplan = fragment_plan(qp, self.catalog)
        return self.run_dplan(dplan)

    def run_dplan(self, dplan: DistributedPlan) -> Batch:
        """Execute with automatic capacity-doubling retries on overflow
        (the mesh analog of the streaming engine's growth replay)."""
        last = None
        for _ in range(self.max_retries + 1):
            try:
                return self._run_dplan_once(dplan)
            except MeshOverflow as e:
                last = e
                self._cap_boost *= 2
        raise last

    def _run_dplan_once(self, dplan: DistributedPlan) -> Batch:
        fragments = dplan.fragments
        staged: Dict[int, Batch] = {}
        scan_nodes: List[TableScan] = []
        scan_sharded: List[bool] = []

        def find_scans(n: PlanNode, sharded: bool):
            if isinstance(n, TableScan):
                scan_nodes.append(n)
                scan_sharded.append(sharded)
            for c in n.children():
                find_scans(c, sharded)

        from presto_tpu.plan.fragmenter import SINGLE

        for f in fragments.values():
            find_scans(f.root, f.partitioning != SINGLE)
        for s, sh in zip(scan_nodes, scan_sharded):
            staged[id(s)] = self._stage_scan(s, sh)

        root = fragments[dplan.root_fid]
        multi = len(fragments) > 1

        def program(*scan_batches):
            st = {nid: b for nid, b in zip([id(s) for s in scan_nodes],
                                           scan_batches)}
            diags: list = []
            memo: Dict[int, Batch] = {}
            out = self._lower(root.root, fragments, st, memo, diags)
            ovf = (sum(jax.lax.psum(d, WORKERS) for d in diags)
                   if diags else jax.lax.psum(jnp.int64(0), WORKERS))
            return out, ovf

        in_specs = tuple(P(WORKERS) if sh else P()
                         for sh in scan_sharded)
        # the root fragment is always SINGLE (fragment_plan gathers before
        # it), so with multiple fragments every device computes an identical
        # replica; a one-fragment plan is row-sharded and the global view
        # IS the concatenated result
        out_spec = P(WORKERS)
        prog = jax.jit(shard_map(
            program, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(out_spec, P()),
            check_vma=False,
        ))
        out, ovf = prog(*[staged[id(s)] for s in scan_nodes])
        if int(ovf) > 0:
            raise MeshOverflow(
                f"static capacity overflow ({int(ovf)} rows dropped) — "
                "raise agg_capacity / fanout_budget")
        if multi:
            # keep the first replica's rows
            from presto_tpu.exec.runtime import _truncate

            return _truncate(out, out.capacity // self.n_dev)
        return out

    def run(self, sql: str):
        return self.run_batch(sql).to_pandas()


def _trace_concat(a: Batch, b: Batch) -> Batch:
    from presto_tpu.exec.runtime import _concat2

    return _concat2(a, b)
