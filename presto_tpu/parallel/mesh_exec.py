"""Mesh SQL executor — a fragmented DistributedPlan as ONE shard_map program.

Reference mapping (SURVEY §2e "TPU-native equivalent"): the reference moves
pages between fragments through PartitionedOutputOperator.partitionPage:377
→ OutputBuffer → HTTP → ExchangeClient.java:69. Within a TPU slice the
same dataflow is a synchronous collective: every OUT_HASH exchange lowers
to a hash-partition kernel + `jax.lax.all_to_all`, OUT_BROADCAST /
OUT_GATHER lower to `all_gather`, and the fragments themselves — scan
chains, partial/final aggregation, co-located hash joins — trace into one
XLA program executed SPMD over the mesh. The HTTP cluster
(server/coordinator.py) remains the cross-host path; this executor is the
intra-slice path where the shuffle rides ICI and the host never touches
row data.

Supported fragment shapes (the TPC-H star-join/aggregate core and beyond):
scans with filter/project chains, partial→final aggregate splits,
broadcast and hash-partitioned joins (unique and bounded-fanout; INNER /
LEFT / FULL OUTER — RIGHT normalizes to LEFT at analysis), semi joins,
window functions (one-sort closed-form kernels), UNION [ALL] /
INTERSECT / EXCEPT, UNNEST, gathered sort/topn/limit/output.

The exchange plane is production-shaped along four axes:

1. **Stats-sized lanes** — an OUT_HASH exchange's per-lane capacity comes
   from the producing fragment's CBO estimate (Fragment.est_rows /
   est_key_ndv via plan/stats.exchange_lane_rows) with a skew headroom
   factor, clamped by the pessimistic padding bound, so ICI bytes track
   estimated rows instead of `capacity // n_dev * 2` padding.
2. **Fused single-buffer collectives** — every exchanged plane (values /
   validity / hi / live) is packed into dtype-bucketed dense buffers
   (parallel/lanes.py) and the exchange issues ONE all_to_all per dtype
   bucket instead of one per array; the partition scatter and the packing
   fuse into a single scatter per bucket (ops/partition.partition_layout).
3. **Surgical overflow replay** — every data-dependent capacity (exchange
   lane, group table, join fanout width, join output) claims a SITE in
   lowering order; its overflow diagnostic is psum-reduced into a per-site
   vector checked on the host. A retry re-traces with ONLY the overflowing
   sites' capacities doubled — not the old global `_cap_boost *= 2` that
   re-padded every capacity and stayed sticky across queries.
4. **Hash-engine breakers on-mesh** — `choose_breaker_engine` (the PR 7
   CBO) routes small-NDV/high-duplication aggregates and small-build
   joins/semijoins to the Pallas linear-probing kernels inside the
   shard_map program (`interpret=True` off-TPU keeps CPU sweeps exact);
   the engine choice is part of the traced structure, so it keys the
   mesh program cache.

Structurally identical queries reuse the compiled shard_map program via a
per-executor cache keyed on (fragment canonical JSON, per-site boosts,
config fingerprint) — the mesh analog of exec/programs.py.
"""

from __future__ import annotations

import hashlib
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from presto_tpu.batch import Batch, Column, round_up_capacity
from presto_tpu.connector import Catalog
from presto_tpu.exec.runtime import (
    ExecConfig,
    _input_state,
    _join_plan_cdt,
    _renorm_limbs,
    build_agg_finalizer,
    collapse_chain,
)
from presto_tpu.ops.grouping import KeyCol, StateCol, grouped_merge
from presto_tpu.ops.join import (
    align_probe_strings,
    build_side,
    gather_join_output,
    hash_build_side,
    hash_probe_counts,
    hash_probe_expand,
    hash_probe_unique,
    join_compare_dtypes,
    probe_counts,
    probe_expand,
    probe_unique,
)
from presto_tpu.ops.partition import partition_for_exchange, partition_layout
from presto_tpu.ops.sort import limit_batch, sort_batch
from presto_tpu.parallel import lanes
from presto_tpu.parallel.mesh import WORKERS, shard_map
from presto_tpu.plan.agg_states import (
    agg_state_layout,
    limb_pairs,
    state_types as layout_state_types,
)
from presto_tpu.plan.fragmenter import (
    OUT_BROADCAST,
    OUT_GATHER,
    OUT_HASH,
    DistributedPlan,
    fragment_plan,
)
from presto_tpu.plan.nodes import (
    Aggregate,
    Filter,
    HashJoin,
    Limit,
    Output,
    PlanNode,
    Project,
    RemoteSource,
    SemiJoin,
    Sort,
    TableScan,
    Window,
)
from presto_tpu.exec.runtime import _sort_keys
from presto_tpu.obs import trace as _obs_trace
from presto_tpu.scan import metrics as _scan_metrics


class MeshOverflow(RuntimeError):
    """A capacity site overflowed. `sites` maps site id → globally dropped
    rows; `site_caps` maps site id → the capacity that overflowed (for
    diagnostics); `labels` names each site."""

    def __init__(self, msg: str, sites=None, site_caps=None, labels=None):
        super().__init__(msg)
        self.sites: Dict[int, int] = dict(sites or {})
        self.site_caps: Dict[int, int] = dict(site_caps or {})
        self.labels = list(labels or [])


class _SiteTracker:
    """Per-trace registry of data-dependent capacity sites.

    A site id is the claim ORDER during lowering — deterministic because
    lowering walks the fragment DAG identically on every trace of the
    same plan — so a host-side {site: boost} map survives re-tracing and
    a retry can double exactly the site that overflowed. Each claimed
    site must `record` exactly one overflow diagnostic."""

    def __init__(self, boosts: Dict[int, int],
                 lane_overrides: Optional[Dict[int, int]] = None):
        self._boosts = boosts
        # adaptive lane resize: fid -> observed lane_max from a failed
        # attempt THIS run — the retry sizes that exchange exactly instead
        # of walking the ×2 boost ladder
        self.lane_overrides = lane_overrides or {}
        self.labels: List[tuple] = []
        self.caps: List[Optional[int]] = []
        self.diags: List[Optional[jnp.ndarray]] = []
        # OUT_HASH exchange accounting, in exchange order:
        self.exchanges: List[dict] = []       # static per-exchange meta
        self.lane_used: List[jnp.ndarray] = []  # traced occupied-slot counts
        # traced UNCAPPED per-lane row maxima (pmax-reduced): the true lane
        # capacity this exchange needed — obs/runstats records it against
        # est_lane_rows so a repeat run sizes lanes from observation
        self.lane_max: List[jnp.ndarray] = []

    def claim(self, label: tuple) -> Tuple[int, int]:
        i = len(self.labels)
        self.labels.append(label)
        self.caps.append(None)
        self.diags.append(None)
        return i, self._boosts.get(i, 1)

    def record(self, site: int, diag, cap: Optional[int] = None) -> None:
        self.diags[site] = diag
        if cap is not None:
            self.caps[site] = cap


class _CachedProgram:
    __slots__ = ("fn", "meta")

    def __init__(self):
        self.fn = None
        # filled at trace time: n_sites, labels, caps, exchanges, traces
        self.meta: dict = {"traces": 0}


def _all_to_all_batch(b: Batch, n_dev: int, per_cap: int) -> Batch:
    """Per-plane exchange — the fallback when the lane packer declines the
    batch (structural columns); one all_to_all per array."""

    def a2a(x):
        if x is None:
            return None
        y = jax.lax.all_to_all(x.reshape(n_dev, per_cap), WORKERS,
                               split_axis=0, concat_axis=0, tiled=False)
        return y.reshape(-1)

    cols = [Column(a2a(c.values), a2a(c.validity), a2a(c.hi))
            for c in b.columns]
    return Batch(b.names, b.types, cols, a2a(b.live), b.dicts)


def _fused_all_to_all(bufs, n_dev: int, per_cap: int):
    """Exchange packed lane buffers: one collective per dtype bucket. Each
    buffer is [L, n_dev*per_cap]; splitting the folded partition axis and
    concatenating received chunks on the same axis preserves the (device,
    partition, slot) addressing the per-plane path uses."""
    out = []
    for buf in bufs:
        nl = buf.shape[0]
        y = jax.lax.all_to_all(buf.reshape(nl, n_dev, per_cap), WORKERS,
                               split_axis=1, concat_axis=1, tiled=False)
        out.append(y.reshape(nl, n_dev * per_cap))
    return out


def _gather_batch(b: Batch) -> Batch:
    """Replicate all rows on every device (OUT_GATHER / OUT_BROADCAST)."""

    def ag(x):
        if x is None:
            return None
        return jax.lax.all_gather(x, WORKERS, tiled=True)

    cols = [Column(ag(c.values), ag(c.validity), ag(c.hi)) for c in b.columns]
    return Batch(b.names, b.types, cols, ag(b.live), b.dicts)


class MeshExecutor:
    """Executes SQL over an n-device mesh with collective exchanges."""

    def __init__(self, catalog: Catalog, mesh, config: Optional[ExecConfig] = None,
                 fanout_budget: int = 4, max_retries: int = 6):
        self.catalog = catalog
        self.mesh = mesh
        self.n_dev = mesh.shape[WORKERS]
        self.config = config or ExecConfig()
        self.fanout_budget = fanout_budget
        self.max_retries = max_retries
        # structural program cache: (plan digest, boosts) → compiled
        # shard_map program + its trace-time site/exchange metadata
        self._progs: Dict[tuple, _CachedProgram] = {}
        # observability snapshot of the most recent run_dplan: retries,
        # per-site boosts (always fresh per run — overflow inflation must
        # not leak into later queries), and per-attempt site/exchange meta
        self.last_run: Optional[dict] = None

    # -- host-side staging -------------------------------------------------

    def _stage_scan(self, scan: TableScan, sharded: bool) -> Batch:
        """Read splits per device; build a row-sharded (SOURCE/HASH
        fragments: splits d::N per device) or replicated (SINGLE fragments:
        every device reads all splits) global Batch."""
        conn = self.catalog.connectors[scan.catalog]
        handle = conn.get_table(scan.table)
        nrows = int(handle.row_count or 0)
        nsplits = max(self.n_dev, -(-nrows // self.config.batch_rows))
        columns = list(scan.assignments.values())
        symbols = list(scan.assignments.keys())
        out_types = dict(scan.output)
        if not columns and handle.columns:
            # COUNT(*)-style scan: stage one carrier column purely for row
            # multiplicity (the streaming engine fabricates liveness; the
            # mesh stager derives liveness from column data)
            columns = [handle.columns[0].name]
            symbols = ["__rowcount__"]
            out_types = {"__rowcount__": handle.columns[0].type}
        splits = conn.splits(handle, nsplits)
        if sharded:
            if any(s.bucket is not None for s in splits):
                # bucketed table: place by bucket id so colocated joins
                # stay aligned across tables (bucket b of every table
                # lands on device b % N)
                per_splits = [
                    [s for s in splits if s.bucket % self.n_dev == d]
                    for d in range(self.n_dev)
                ]
            else:
                per_splits = [splits[d::self.n_dev]
                              for d in range(self.n_dev)]
            per_dev: List[List[Batch]] = [
                [conn.read_split(s, columns) for s in ss]
                for ss in per_splits
            ]
        else:
            all_b = [conn.read_split(s, columns) for s in splits]
            per_dev = [all_b]  # one logical copy; replicated by sharding
        cap = max((sum(int(np.asarray(b.live).sum()) for b in bs) or 1)
                  for bs in per_dev)
        cap = round_up_capacity(cap)
        names, types = symbols, [out_types[s] for s in symbols]
        groups = len(per_dev)
        data = {}
        live = np.zeros((groups, cap), bool)
        dicts = {}
        for ci, cname in enumerate(columns):
            arrs = np.zeros((groups, cap), dtype=types[ci].dtype)
            valid = None
            for d, bs in enumerate(per_dev):
                pos = 0
                for b in bs:
                    lv = np.asarray(b.live)
                    v = np.asarray(b.column(cname).values)[lv]
                    arrs[d, pos:pos + len(v)] = v
                    bv = b.column(cname).validity
                    if bv is not None:
                        if valid is None:
                            valid = np.ones((groups, cap), bool)
                        valid[d, pos:pos + len(v)] = np.asarray(bv)[lv]
                    if ci == 0:
                        live[d, pos:pos + len(v)] = True
                    pos += len(v)
                    if cname in b.dicts:
                        dicts[symbols[ci]] = b.dicts[cname]
            data[symbols[ci]] = (arrs, valid)
        spec = P(WORKERS) if sharded else P()
        sharding = NamedSharding(self.mesh, spec)
        cols = [
            Column(jax.device_put(data[s][0].reshape(-1), sharding),
                   None if data[s][1] is None
                   else jax.device_put(data[s][1].reshape(-1), sharding))
            for s in symbols
        ]
        return Batch(names, types, cols,
                     jax.device_put(live.reshape(-1), sharding), dicts)

    # -- engine choice (CBO) -----------------------------------------------

    def _engine_for(self, node: PlanNode) -> str:
        """Breaker engine for an on-mesh Aggregate/join: the session
        override, else the CBO thresholds. Stamped on the node (EXPLAIN)
        and counted on the shared engine-dispatch families. Runs at trace
        time, so a cached mesh program keeps its engine choice."""
        from presto_tpu.plan.stats import choose_breaker_engine

        override = getattr(self.config, "breaker_engine", "auto")
        hbo = getattr(self.config, "hbo", "observe")
        try:
            engine, why = choose_breaker_engine(node, self.catalog, override,
                                                hbo=hbo)
        except Exception:
            engine, why = "sort", "stats derivation failed"
        node.__dict__["_breaker_engine"] = engine
        node.__dict__["_breaker_engine_why"] = why
        _scan_metrics.record(f"breaker_dispatches_{engine}", 1)
        if "(hbo: observed)" in why:
            try:
                from presto_tpu.obs import runstats
                runstats.record_correction("breaker_engine")
            except Exception:
                pass
        tracer = _obs_trace.current()
        if tracer.enabled:
            t = time.time()
            tracer.record("breaker_engine", "breaker_engine", t, t,
                          node=type(node).__name__, engine=engine, why=why)
        return engine

    def _join_engine(self, node, build: Batch):
        """(engine, probe_dtypes, compare_dtypes) for a HashJoin/SemiJoin.
        Mirrors the streaming engine's guard (_JoinProber): a build batch
        whose key dtypes deviate from the plan's output types would
        mis-encode the hash planes — fall back to the sort engine."""
        engine = self._engine_for(node)
        ltypes = dict(node.left.output)
        probe_dtypes = tuple(
            jnp.dtype(ltypes[lk].dtype) for lk in node.left_keys)
        cdt = _join_plan_cdt(node)
        if engine == "hash" and join_compare_dtypes(
                build, tuple(node.right_keys), probe_dtypes) != cdt:
            engine = "sort"
            node.__dict__["_breaker_engine"] = "sort"
            node.__dict__["_breaker_engine_why"] = (
                "build batch dtypes deviate from plan types")
        return engine, probe_dtypes, cdt

    def _build_table(self, node, build: Batch, engine: str,
                     probe_dtypes):
        if engine == "hash":
            return hash_build_side(build, tuple(node.right_keys),
                                   probe_dtypes)
        return build_side(build, tuple(node.right_keys))

    # -- trace-time node lowering -----------------------------------------

    def _lower_agg(self, node: Aggregate, child: Batch, cap: int,
                   sites: _SiteTracker, site: int) -> Batch:
        in_types = dict(node.child.output)
        layout = agg_state_layout(node.aggs, in_types)
        lpairs = limb_pairs(layout)
        key_syms = node.group_keys
        key_types = [in_types[k] for k in key_syms]
        final_mode = node.step == "final"
        if final_mode:
            st_types = [in_types[name] for name, _, _ in layout]
        else:
            st_types = layout_state_types(layout, in_types)
        b = child
        keys = [KeyCol(b.column(k).values, b.column(k).validity,
                       len(b.dicts[k]) if k in b.dicts else None)
                for k in key_syms]
        states = []
        for (name, op, a), st in zip(layout, st_types):
            if final_mode:
                c = b.column(name)
                states.append(StateCol(c.values.astype(st.dtype), c.validity, op))
            else:
                states.append(_input_state(b, name, op, a, st, in_types))
        engine = self._engine_for(node)
        kout, sout, out_live, ng = grouped_merge(keys, states, b.live, cap,
                                                 engine=engine)
        sout = _renorm_limbs(list(sout), lpairs)
        sites.record(site, jnp.maximum(ng - cap, 0), cap)
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, s.validity if s.op != "count_add" else None)
            for s in sout
        ]
        names = list(key_syms) + [name for name, _, _ in layout]
        types = key_types + st_types
        dicts = {k: b.dicts[k] for k in key_syms if k in b.dicts}
        for name, op, a in layout:
            if op in ("min", "max"):
                if a.arg in b.dicts:
                    dicts[name] = b.dicts[a.arg]
                elif name in b.dicts:  # final mode: state col carries it
                    dicts[name] = b.dicts[name]
        acc = Batch(names, types, cols, out_live, dicts)
        if node.step == "partial":
            return acc
        fin = build_agg_finalizer(node, key_syms, key_types, in_types)
        return fin(acc)

    def _build_remainder(self, node: HashJoin, table, bm) -> Batch:
        """FULL OUTER tail: build rows no probe row matched, NULL probe
        columns (LookupJoinOperators.fullOuterJoin's lookup-outer pass).
        Correct on-mesh because the fragmenter never broadcasts a FULL
        join's build side (plan/fragmenter.py:157) — each device owns a
        disjoint hash partition of the build rows. Engine-agnostic: both
        BuildTable and HashJoinTable keep the hashes/orig_live/batch
        shape contract."""
        lsyms = [n for n, _ in node.left.output]
        rsyms = [n for n, _ in node.right.output]
        ltypes = dict(node.left.output)
        cap = table.hashes.shape[0]
        names, types, cols = [], [], []
        for c in lsyms:
            names.append(c)
            types.append(ltypes[c])
            cols.append(Column(jnp.zeros(cap, ltypes[c].dtype),
                               jnp.zeros(cap, bool)))
        for c in rsyms:
            names.append(c)
            types.append(table.batch.type_of(c))
            cols.append(table.batch.column(c))
        live = table.orig_live & ~bm
        return Batch(names, types, cols, live,
                     {c: table.batch.dicts[c] for c in rsyms
                      if c in table.batch.dicts})

    def _expand_pairs(self, probe: Batch, table, pba, lkeys, rkeys,
                      sites: _SiteTracker, engine: str = "sort", cdt=None):
        """Bounded-fanout pair expansion with overflow accounting — shared
        by joins and residual semijoins so the capacity formula and the
        per-site overflow protocol can't diverge. The hash engine claims a
        SECOND site for the match-matrix width: its surgical replay IS the
        streaming engine's fanout-widening ladder."""
        site, boost = sites.claim(("join_out",))
        out_cap = probe.capacity * self.fanout_budget * boost
        if engine == "hash":
            wsite, wboost = sites.claim(("join_fanout",))
            fanout = 8 * wboost  # pow2 — the probe kernel requires it
            mm, counts, offsets, total, _, wovf = hash_probe_counts(
                table, pba, lkeys, cdt, max_fanout_scan=fanout)
            sites.record(wsite, wovf, fanout)
            pr, bi, ol = hash_probe_expand(table, mm, counts, offsets,
                                           0, out_cap)
        else:
            lo, counts, offsets, total, _, _ovf = probe_counts(
                table, pba, lkeys, rkeys)
            pr, bi, ol = probe_expand(table, pba, lkeys, rkeys,
                                      lo, counts, offsets, 0, out_cap)
        sites.record(site, jnp.maximum(total - out_cap, 0), out_cap)
        return pr, bi, ol

    def _lower_join(self, node: HashJoin, probe: Batch, build: Batch,
                    sites: _SiteTracker) -> Batch:
        lsyms = [n for n, _ in node.left.output]
        rsyms = [n for n, _ in node.right.output]
        lkeys, rkeys = tuple(node.left_keys), tuple(node.right_keys)
        engine, probe_dtypes, cdt = self._join_engine(node, build)
        table = self._build_table(node, build, engine, probe_dtypes)
        pba = align_probe_strings(probe, lkeys, table, rkeys)
        build_cap = table.hashes.shape[0]
        if node.build_unique:
            if engine == "hash":
                idx, matched = hash_probe_unique(table, pba, lkeys, cdt)
            else:
                idx, matched = probe_unique(table, pba, lkeys, rkeys)
            out = gather_join_output(
                probe, table, jnp.arange(probe.capacity, dtype=jnp.int32),
                idx, probe.live, lsyms, rsyms)
            if node.kind == "inner":
                return out.with_live(out.live & matched)
            cols = list(out.columns)
            for i, nme in enumerate(out.names):
                if nme in rsyms:
                    c = cols[i]
                    valid = (c.validity if c.validity is not None
                             else jnp.ones(out.capacity, bool))
                    cols[i] = Column(c.values, valid & matched, c.hi)
            out = Batch(out.names, out.types, cols, out.live, out.dicts)
            if node.kind == "full":
                bm = (jnp.zeros(build_cap, bool)
                      .at[idx].max(matched & probe.live, mode="drop"))
                out = _trace_concat(out, self._build_remainder(node, table,
                                                               bm))
            return out
        # bounded fanout: one expansion chunk of probe_cap × fanout_budget
        pr, bi, ol = self._expand_pairs(probe, table, pba, lkeys, rkeys,
                                        sites, engine, cdt)
        out = gather_join_output(probe, table, pr, bi, ol, lsyms, rsyms)
        if node.kind in ("left", "full"):
            exists = (jnp.zeros(probe.capacity, dtype=jnp.int32)
                      .at[pr].max(ol.astype(jnp.int32), mode="drop")
                      .astype(bool))
            tail = gather_join_output(
                probe, table, jnp.arange(probe.capacity, dtype=jnp.int32),
                jnp.zeros(probe.capacity, dtype=jnp.int32),
                probe.live & ~exists, lsyms, rsyms)
            tcols = [
                Column(c.values, (jnp.zeros(tail.capacity, bool)
                                  if nme in rsyms else c.validity), c.hi)
                for nme, c in zip(tail.names, tail.columns)
            ]
            tail = Batch(tail.names, tail.types, tcols, tail.live, tail.dicts)
            out = _trace_concat(out, tail)
        if node.kind == "full":
            bm = (jnp.zeros(build_cap, bool)
                  .at[bi].max(ol, mode="drop"))
            out = _trace_concat(out, self._build_remainder(node, table, bm))
        return out

    def _lower(self, node: PlanNode, fragments, staged, memo,
               sites: _SiteTracker) -> Batch:
        """Per-device local lowering of a fragment subtree."""
        base, chain = collapse_chain(node)
        if chain is not None:
            return chain(self._lower(base, fragments, staged, memo, sites))
        if isinstance(node, TableScan):
            return staged[id(node)]
        if isinstance(node, RemoteSource):
            return self._lower_exchange(node.fragment_id, fragments, staged,
                                        memo, sites)
        if isinstance(node, Aggregate):
            child = self._lower(node.child, fragments, staged, memo, sites)
            site, boost = sites.claim(("agg", node.step or "single"))
            cap = self._agg_cap(node) * boost
            return self._lower_agg(node, child, cap, sites, site)
        if isinstance(node, HashJoin):
            probe = self._lower(node.left, fragments, staged, memo, sites)
            build = self._lower(node.right, fragments, staged, memo, sites)
            return self._lower_join(node, probe, build, sites)
        if isinstance(node, SemiJoin):
            probe = self._lower(node.left, fragments, staged, memo, sites)
            build = self._lower(node.right, fragments, staged, memo, sites)
            lkeys, rkeys = tuple(node.left_keys), tuple(node.right_keys)
            engine, probe_dtypes, cdt = self._join_engine(node, build)
            table = self._build_table(node, build, engine, probe_dtypes)
            pba = align_probe_strings(probe, lkeys, table, rkeys)
            if node.residual is None:
                if engine == "hash":
                    _, matched = hash_probe_unique(table, pba, lkeys, cdt)
                else:
                    _, matched = probe_unique(table, pba, lkeys, rkeys)
            else:
                # correlated EXISTS with non-equi conjuncts (Q21 shape):
                # bounded pair expansion + residual + per-probe-row ANY —
                # the mesh form of _execute_semijoin's residual path
                from presto_tpu.expr.compile import compile_predicate

                lsyms = [n for n, _ in node.left.output]
                rsyms = [n for n, _ in node.right.output]
                pred = compile_predicate(node.residual)
                pr, bi, ol = self._expand_pairs(probe, table, pba,
                                                lkeys, rkeys, sites,
                                                engine, cdt)
                pair = gather_join_output(probe, table, pr, bi, ol,
                                          lsyms, rsyms)
                ok = pred(pair) & pair.live
                matched = (jnp.zeros(probe.capacity, dtype=jnp.int32)
                           .at[pr].max(ok.astype(jnp.int32), mode="drop")
                           .astype(bool))
            if node.negated:
                keep = ~matched
                if node.null_aware and node.residual is None:
                    # NOT IN three-valued logic (same as the local
                    # engine): a NULL probe key against a non-empty set
                    # is NULL → row filtered
                    key_valid = jnp.ones(probe.capacity, bool)
                    for lk in lkeys:
                        kv = probe.column(lk).validity
                        if kv is not None:
                            key_valid = key_valid & kv
                    keep = keep & (key_valid | (table.n_rows == 0))
            else:
                keep = matched
            return probe.with_live(probe.live & keep)
        if isinstance(node, Sort):
            child = self._lower(node.child, fragments, staged, memo, sites)
            return sort_batch(child, _sort_keys(node, child), limit=node.limit)
        if isinstance(node, Limit):
            child = self._lower(node.child, fragments, staged, memo, sites)
            return limit_batch(child, node.count)
        if isinstance(node, Output):
            child = self._lower(node.child, fragments, staged, memo, sites)
            return child.select(node.symbols).rename(node.names)
        from presto_tpu.plan.nodes import SetOp, Unnest

        if isinstance(node, Unnest):
            from presto_tpu.exec.runtime import unnest_expand

            child = self._lower(node.child, fragments, staged, memo, sites)
            return unnest_expand(node, child)
        if isinstance(node, SetOp) and node.kind == "union":
            from presto_tpu.exec.runtime import (
                _distinct_rows,
                _unify_batch_dicts,
            )

            left = self._lower(node.left, fragments, staged, memo, sites)
            right = self._lower(node.right, fragments, staged, memo, sites)
            left = left.rename(node.symbols)
            right = right.rename(node.symbols)
            left, right = _unify_batch_dicts([left, right])
            merged = _trace_concat(left, right)
            if node.all:
                return merged
            return _distinct_rows(merged)
        if isinstance(node, SetOp) and node.kind in ("intersect", "except"):
            # membership on ALL columns, then distinct — the runtime's
            # _execute_setop shape, traced per device (inputs arrive
            # co-partitioned: the fragmenter hash-exchanges both branches
            # on the full column list)
            from presto_tpu.exec.runtime import (
                _distinct_rows,
                _unify_batch_dicts,
            )

            left = self._lower(node.left, fragments, staged, memo, sites)
            right = self._lower(node.right, fragments, staged, memo, sites)
            left = left.rename(node.symbols)
            right = right.rename(node.symbols)
            left, right = _unify_batch_dicts([left, right])
            keys = tuple(node.symbols)
            table = build_side(right, keys)
            pba = align_probe_strings(left, keys, table, keys)
            _, matched = probe_unique(table, pba, keys, keys)
            keep = matched if node.kind == "intersect" else ~matched
            return _distinct_rows(left.with_live(left.live & keep))
        if isinstance(node, Window):
            from presto_tpu.exec.runtime import build_window_compute

            child = self._lower(node.child, fragments, staged, memo, sites)
            return build_window_compute(node)(child)
        raise NotImplementedError(
            f"mesh executor: {type(node).__name__}")

    def _exchange_fp(self, f) -> str:
        """obs/runstats history key for an exchange: the producing
        fragment's root structure + catalog snapshot."""
        from presto_tpu.obs import runstats

        return runstats.node_fingerprint(f.root, self.catalog)

    def _observed_lane_rows(self, f) -> Optional[float]:
        """Observed per-lane row maximum from a prior run of the same
        structure, when hbo=correct and history exists."""
        if getattr(self.config, "hbo", "observe") != "correct":
            return None
        try:
            from presto_tpu.obs import runstats

            h = runstats.lookup(self._exchange_fp(f), "exchange_lane")
            if h and h.get("actual"):
                return float(h["actual"])
        except Exception:
            pass
        return None

    def _exchange_cap(self, f, out: Batch, boost: int,
                      observed_lane_rows: Optional[float] = None) -> int:
        """Per-lane row capacity of an OUT_HASH exchange. Observation-sized
        when hbo=correct and a prior run of the same structure recorded the
        true lane maximum; else stats-sized when the fragmenter stamped an
        estimate (exchange_lane_rows: uniform rows/n_dev² vs low-NDV
        concentration, × skew headroom), else the pessimistic
        capacity//n_dev×2 padding. The site boost doubles it on surgical
        replay; a lane never needs to exceed the producing batch's own
        capacity (it can hold every local row), which bounds the replay
        ladder."""
        fallback = max(out.capacity // self.n_dev, 128) * 2
        cap = fallback
        rows = getattr(f, "est_rows", None)
        if rows or observed_lane_rows is not None:
            from presto_tpu.plan.stats import exchange_lane_rows

            est = exchange_lane_rows(rows or 0.0,
                                     getattr(f, "est_key_ndv", None),
                                     self.n_dev,
                                     observed_lane_rows=observed_lane_rows)
            cap = int(min(max(est, 64.0), float(max(out.capacity, 64))))
        cap = min(cap * boost, round_up_capacity(out.capacity, minimum=64))
        return round_up_capacity(cap, minimum=64)

    def _lower_exchange(self, fid: int, fragments, staged, memo,
                        sites: _SiteTracker) -> Batch:
        if fid in memo:
            return memo[fid]
        f = fragments[fid]
        out = self._lower(f.root, fragments, staged, memo, sites)
        if f.output_partitioning == OUT_HASH:
            site, boost = sites.claim(("exchange", fid))
            obs_rows = self._observed_lane_rows(f)
            ovr = sites.lane_overrides.get(fid)
            if ovr is not None:
                # adaptive lane resize: the failed attempt MEASURED this
                # exchange's true per-lane requirement — size to it
                # exactly (clamped like _exchange_cap) instead of
                # replaying through the ×2 boost ladder
                per_cap = min(round_up_capacity(max(int(ovr), 64),
                                                minimum=64),
                              round_up_capacity(out.capacity, minimum=64))
            else:
                per_cap = self._exchange_cap(f, out, boost, obs_rows)
            if obs_rows is not None:
                try:
                    from presto_tpu.obs import runstats
                    runstats.record_correction("exchange_lane")
                except Exception:
                    pass
            keys = list(f.output_keys)
            out_n = self.n_dev * per_cap
            plan = lanes.plan_lanes(out)
            if plan is not None:
                sperm, dest, counts, routed, ovf = partition_layout(
                    out, keys, self.n_dev, per_cap)
                bufs = lanes.pack_partitioned(out, plan, sperm, dest,
                                              routed, out_n)
                bufs = _fused_all_to_all(bufs, self.n_dev, per_cap)
                exch = lanes.unpack_batch(out, plan, bufs)
                nbytes = plan.nbytes(out_n) * self.n_dev
                n_coll = plan.n_collectives
            else:
                parts, counts, ovf = partition_for_exchange(
                    out, keys, self.n_dev, per_cap)
                exch = _all_to_all_batch(parts, self.n_dev, per_cap)
                planes = [p for c in parts.columns
                          for p in (c.values, c.validity, c.hi)
                          if p is not None] + [parts.live]
                nbytes = sum(int(p.size) * p.dtype.itemsize
                             for p in planes) * self.n_dev
                n_coll = len(planes)
            sites.record(site, ovf, per_cap)
            sites.lane_used.append(
                jnp.sum(jnp.minimum(counts, per_cap)).astype(jnp.int64))
            sites.lane_max.append(jnp.max(counts).astype(jnp.int64))
            try:
                fp = self._exchange_fp(f)
            except Exception:
                fp = ""
            sites.exchanges.append({
                "fid": fid, "site": site, "per_cap": per_cap,
                "lanes_total": self.n_dev * self.n_dev * per_cap,
                "bytes": int(nbytes), "a2a": n_coll,
                "fused": plan is not None,
                # what the pre-stats sizing rule would have allocated —
                # bench/tests measure the utilization win against it
                "naive_per_cap": round_up_capacity(
                    max(out.capacity // self.n_dev, 128) * 2),
                # runstats plane: history key, the pure static estimate
                # (no boost, no HBO) the drift is measured against, and
                # whether observation sized this run's lanes
                "fp": fp,
                "est_lane_rows": self._exchange_cap(f, out, 1),
                "hbo_sized": obs_rows is not None,
                "lane_plan": plan.describe() if plan is not None else None,
            })
            out = exch
        elif f.output_partitioning in (OUT_GATHER, OUT_BROADCAST):
            out = _gather_batch(out)
        elif f.output_partitioning == "rr":
            # round-robin redistribution exists to balance load; on-mesh
            # every device already holds its share — rows stay put
            pass
        memo[fid] = out
        return out

    def _agg_cap(self, node: Aggregate) -> int:
        cap = self.config.agg_capacity
        try:
            from presto_tpu.plan.stats import derive

            st = derive(node, self.catalog)
        except Exception:
            st = None
        rows = st.rows if (st is not None and st.rows) else None
        if getattr(self.config, "hbo", "observe") == "correct":
            # observed group count from a prior run of this structure
            # (streaming or mesh — the fingerprint space is shared)
            try:
                from presto_tpu.obs import runstats

                h = runstats.lookup_node(node, self.catalog, "agg_groups")
                if h and h.get("actual"):
                    rows = float(h["actual"])
                    runstats.record_correction("agg_presize")
            except Exception:
                pass
        if rows:
            cap = max(cap, round_up_capacity(
                int(min(rows * 1.25, float(1 << 22)))))
        return cap

    # -- entry -------------------------------------------------------------

    def run_batch(self, sql: str) -> Batch:
        from presto_tpu.plan.builder import plan_query
        from presto_tpu.plan.optimizer import optimize

        qp = optimize(plan_query(sql, self.catalog), self.catalog)
        if qp.scalar_subqueries:
            # bind uncorrelated scalar subqueries before fragmenting (they
            # gather to one value; the local streaming engine computes
            # them host-side — shared helper with run_plan/coordinator)
            from presto_tpu.exec.runtime import (
                ExecContext,
                bind_scalar_subqueries,
            )

            bind_scalar_subqueries(qp, ExecContext(self.catalog, self.config))
        dplan = fragment_plan(qp, self.catalog,
                              hbo=getattr(self.config, "hbo", "observe"))
        return self.run_dplan(dplan)

    def run_dplan(self, dplan: DistributedPlan) -> Batch:
        """Execute with surgical per-site overflow replay: a retry doubles
        ONLY the sites that overflowed. Boosts are local to this call —
        an overflow on one query must not permanently inflate every later
        query's capacities (the old executor-level _cap_boost did)."""
        boosts: Dict[int, int] = {}
        lane_overrides: Dict[int, int] = {}
        adaptive_state = None
        if getattr(self.config, "adaptive", "off") != "off":
            try:
                from presto_tpu.exec.adaptive import AdaptiveState

                adaptive_state = AdaptiveState(
                    self.config.adaptive,
                    query_id=getattr(_obs_trace.current(), "trace_id",
                                     "") or "")
            except Exception:
                adaptive_state = None
        attempts: List[dict] = []
        last = None
        for _ in range(self.max_retries + 1):
            try:
                out = self._run_dplan_once(dplan, boosts, attempts,
                                           lane_overrides)
                self.last_run = {
                    "retries": len(attempts) - 1,
                    "boosts": dict(boosts),
                    "lane_overrides": dict(lane_overrides),
                    "attempts": attempts,
                }
                return out
            except MeshOverflow as e:
                last = e
                # adaptive lane resize: the failed attempt already pmax'd
                # each exchange's TRUE per-lane requirement — feed it back
                # as an exact override so the retry fits in one replay
                # instead of walking the ×2 boost ladder site by site
                handled = set()
                if adaptive_state is not None and attempts:
                    for ex in attempts[-1].get("exchanges", ()):
                        s = ex.get("site")
                        if s not in e.sites or ex.get("lane_max", 0) <= 0:
                            continue
                        new_cap = round_up_capacity(
                            max(int(ex["lane_max"]), 64), minimum=64)
                        if new_cap <= ex["per_cap"]:
                            continue
                        acted = adaptive_state.decide(
                            "lane_resize",
                            site=f"exchange_f{ex['fid']}",
                            before=int(ex["per_cap"]), after=int(new_cap),
                            detail=(f"lane f{ex['fid']} "
                                    f"{ex['per_cap']}->{new_cap}"),
                            lane_max=int(ex["lane_max"]))
                        if acted:
                            lane_overrides[ex["fid"]] = int(ex["lane_max"])
                            handled.add(s)
                for s in e.sites:
                    if s not in handled:
                        boosts[s] = boosts.get(s, 1) * 2
                _scan_metrics.record("mesh_exchange_overflow_retries", 1)
                _scan_metrics.record("breaker_replay_waves", 1)
                tracer = _obs_trace.current()
                if tracer.enabled:
                    t = time.time()
                    tracer.record(
                        "overflow_replay", "overflow_replay", t, t,
                        sites=",".join(str(s) for s in sorted(e.sites)),
                        cap_to=",".join(
                            str(e.site_caps.get(s, 0) * 2)
                            for s in sorted(e.sites)))
        self.last_run = {"retries": len(attempts) - 1,
                         "boosts": dict(boosts),
                         "lane_overrides": dict(lane_overrides),
                         "attempts": attempts}
        raise last

    def _dplan_key(self, dplan: DistributedPlan):
        """Structural digest for the mesh program cache. None (no caching)
        when a fragment has no canonical codec form."""
        from presto_tpu.exec.programs import config_fingerprint
        from presto_tpu.plan.codec import canonical_node_json

        h = hashlib.sha256()
        h.update(config_fingerprint(self.config).encode())
        h.update(f"|n={self.n_dev}|fb={self.fanout_budget}".encode())
        hbo = getattr(self.config, "hbo", "observe")
        if hbo == "correct":
            # corrected capacities are baked into the trace; mixing the
            # history generation in forces a re-trace once new
            # observations land ("hbo" itself is a volatile config field,
            # so config_fingerprint alone would collide with observe-mode)
            try:
                from presto_tpu.obs import runstats
                h.update(f"|hbo=c{runstats.generation()}".encode())
            except Exception:
                h.update(b"|hbo=c?")
        try:
            for fid in sorted(dplan.fragments):
                f = dplan.fragments[fid]
                h.update((f"|{fid}|{f.partitioning}|{f.output_partitioning}"
                          f"|{','.join(f.output_keys)}|").encode())
                h.update(canonical_node_json(f.root).encode())
        except Exception:
            return None
        h.update(f"|root={dplan.root_fid}".encode())
        return h.hexdigest()

    def _build_program(self, dplan, scan_nodes, scan_sharded,
                       boosts: Dict[int, int],
                       lane_overrides: Optional[Dict[int, int]] = None,
                       ) -> _CachedProgram:
        fragments = dplan.fragments
        root = fragments[dplan.root_fid]
        boosts = dict(boosts)
        lane_overrides = dict(lane_overrides or {})
        entry = _CachedProgram()
        meta = entry.meta

        def program(*scan_batches):
            # the body runs at TRACE time only — meta capture is free on
            # cached executions
            meta["traces"] = meta.get("traces", 0) + 1
            st = {nid: b for nid, b in zip([id(s) for s in scan_nodes],
                                           scan_batches)}
            sites = _SiteTracker(boosts, lane_overrides)
            memo: Dict[int, Batch] = {}
            out = self._lower(root.root, fragments, st, memo, sites)
            meta["n_sites"] = len(sites.labels)
            meta["labels"] = list(sites.labels)
            meta["caps"] = list(sites.caps)
            meta["exchanges"] = [dict(e) for e in sites.exchanges]
            diags = [jnp.int64(0) if d is None else d.astype(jnp.int64)
                     for d in sites.diags]
            # one psum over the stacked site vector (trailing sentinel 0
            # keeps the stack non-empty for site-free plans)
            ovf = jax.lax.psum(jnp.stack(diags + [jnp.int64(0)]), WORKERS)
            used = jax.lax.psum(
                jnp.stack(sites.lane_used + [jnp.int64(0)]), WORKERS)
            # pmax, not psum: the lane maximum is a high-water mark — the
            # worst (src device, dst partition) lane anywhere on the mesh
            lmax = jax.lax.pmax(
                jnp.stack(sites.lane_max + [jnp.int64(0)]), WORKERS)
            return out, ovf, used, lmax

        in_specs = tuple(P(WORKERS) if sh else P()
                         for sh in scan_sharded)
        # the root fragment is always SINGLE (fragment_plan gathers before
        # it), so with multiple fragments every device computes an identical
        # replica; a one-fragment plan is row-sharded and the global view
        # IS the concatenated result
        entry.fn = jax.jit(shard_map(
            program, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(WORKERS), P(), P(), P()),
            check_vma=False,
        ))
        return entry

    def _run_dplan_once(self, dplan: DistributedPlan,
                        boosts: Dict[int, int],
                        attempts: List[dict],
                        lane_overrides: Optional[Dict[int, int]] = None,
                        ) -> Batch:
        fragments = dplan.fragments
        staged: Dict[int, Batch] = {}
        scan_nodes: List[TableScan] = []
        scan_sharded: List[bool] = []

        def find_scans(n: PlanNode, sharded: bool):
            if isinstance(n, TableScan):
                scan_nodes.append(n)
                scan_sharded.append(sharded)
            for c in n.children():
                find_scans(c, sharded)

        from presto_tpu.plan.fragmenter import SINGLE

        for f in fragments.values():
            find_scans(f.root, f.partitioning != SINGLE)
        for s, sh in zip(scan_nodes, scan_sharded):
            staged[id(s)] = self._stage_scan(s, sh)

        pkey = self._dplan_key(dplan)
        # lane overrides fork the program key exactly like boosts: an
        # adaptively resized exchange compiles different lane shapes
        key = (None if pkey is None
               else (pkey, tuple(sorted(boosts.items())),
                     tuple(sorted((lane_overrides or {}).items()))))
        entry = None if key is None else self._progs.get(key)
        if entry is None:
            entry = self._build_program(dplan, scan_nodes, scan_sharded,
                                        boosts, lane_overrides)
            if key is not None:
                self._progs[key] = entry
            from presto_tpu.obs import devprof as _devprof

            if _devprof.active():
                # devprof plane: analyze the whole-mesh program once on
                # build (the lowering is cheap; the compile the analysis
                # forces is the same one the first call pays anyway)
                try:
                    lowered = entry.fn.lower(
                        *[staged[id(s)] for s in scan_nodes])
                    rec = _devprof.analyze_lowered(lowered)
                    _devprof.record_program(
                        f"mesh|{pkey or 'uncached'}", rec,
                        kind="mesh_program", key=len(scan_nodes))
                except Exception:
                    pass

        t0 = time.time()
        out, ovf_vec, used_vec, lmax_vec = entry.fn(
            *[staged[id(s)] for s in scan_nodes])
        meta = entry.meta
        n_sites = meta.get("n_sites", 0)
        ovf = np.asarray(ovf_vec)[:n_sites]
        exchanges = [dict(e) for e in meta.get("exchanges", ())]
        used = np.asarray(used_vec)[:len(exchanges)]
        lmax = np.asarray(lmax_vec)[:len(exchanges)]
        t1 = time.time()

        total_bytes = total_slots = total_used = 0
        for e, u, lm in zip(exchanges, used, lmax):
            e["lanes_used"] = int(u)
            e["lane_max"] = int(lm)
            e["util"] = (float(u) / e["lanes_total"]
                         if e["lanes_total"] else 0.0)
            total_bytes += e["bytes"]
            total_slots += e["lanes_total"]
            total_used += int(u)
        _scan_metrics.record("mesh_exchange_bytes", total_bytes)
        _scan_metrics.record("mesh_exchange_lanes_used", total_used)
        _scan_metrics.record("mesh_exchange_lanes_total", total_slots)

        # mid-flight telemetry: per-site overflow watermarks + per-exchange
        # lane utilization into the inflight plane (no-op unless the query
        # registered with inflight=on; the vectors above are already host)
        if getattr(self.config, "inflight", "off") == "on":
            try:
                from presto_tpu.obs import inflight as _obs_inflight

                qid = getattr(_obs_trace.current(), "trace_id", None)
                if qid is not None and _obs_inflight.get(qid) is not None:
                    labels = meta.get("labels", [])
                    for i, v in enumerate(ovf):
                        _obs_inflight.publish(
                            qid, f"site{i}:{labels[i]}" if i < len(labels)
                            else f"site{i}", windows=1,
                            overflow=int(v), site=i)
                    for e in exchanges:
                        _obs_inflight.publish(
                            qid, f"exchange_f{e['fid']}",
                            task_id=f"mesh.f{e['fid']}",
                            fragment=int(e["fid"]), windows=1,
                            laneUtil=round(e["util"], 4),
                            lanesUsed=e["lanes_used"],
                            lanesTotal=e["lanes_total"])
            except Exception:
                pass
        attempts.append({
            "labels": list(meta.get("labels", ())),
            "site_caps": list(meta.get("caps", ())),
            "exchanges": exchanges,
            "overflow": [int(v) for v in ovf],
        })

        # runstats observation — BEFORE the overflow raise, so even a run
        # that overflows teaches the next one its true lane maxima
        if getattr(self.config, "hbo", "observe") != "off":
            try:
                from presto_tpu.obs import runstats

                for e in exchanges:
                    if e.get("fp") and e.get("lane_max", 0) > 0:
                        runstats.observe(
                            e["fp"], "exchange_lane", "exchange",
                            float(e.get("est_lane_rows") or 0.0),
                            float(e["lane_max"]),
                            extra={"util": round(e["util"], 4)})
            except Exception:
                pass

        # host-side trace spans: the fused program bypasses the tracer
        # (everything inside shard_map is traced code), so the dispatch
        # wall is covered by one mesh_program span with per-exchange
        # exchange_wait markers and lane_pack layout markers under it
        tracer = _obs_trace.current()
        if tracer.enabled:
            sp = tracer.record(
                "mesh_program", "mesh_program", t0, t1,
                n_sites=n_sites, exchanges=len(exchanges),
                traces=meta.get("traces", 0))
            for e in exchanges:
                tracer.record(
                    f"exchange f{e['fid']}", "exchange_wait", t1, t1,
                    parent_id=sp.span_id, fid=e["fid"], bytes=e["bytes"],
                    a2a=e["a2a"], per_cap=e["per_cap"],
                    lanes_used=e["lanes_used"],
                    lanes_total=e["lanes_total"],
                    util=round(e["util"], 4))
                if e.get("lane_plan"):
                    tracer.record(
                        f"lane_pack f{e['fid']}", "lane_pack", t1, t1,
                        parent_id=sp.span_id, fid=e["fid"],
                        **e["lane_plan"])

        bad = {i: int(v) for i, v in enumerate(ovf) if int(v) > 0}
        if bad:
            labels = meta.get("labels", [])
            caps = meta.get("caps", [])
            desc = ", ".join(
                f"site {i} {labels[i]} cap={caps[i]} dropped={n}"
                for i, n in bad.items())
            raise MeshOverflow(
                f"static capacity overflow: {desc}",
                sites=bad,
                site_caps={i: caps[i] for i in bad if caps[i] is not None},
                labels=labels)

        # stamp the exchange telemetry onto the plan for EXPLAIN-style
        # rendering (DistributedPlan.to_string shows [mesh: …] markers)
        for e in exchanges:
            frag = fragments.get(e["fid"])
            if frag is not None:
                frag.__dict__["_mesh_a2a"] = {
                    "a2a": e["a2a"], "bytes": e["bytes"], "util": e["util"],
                    "per_cap": e["per_cap"], "fused": e["fused"],
                }

        if len(fragments) > 1:
            # keep the first replica's rows
            from presto_tpu.exec.runtime import _truncate

            return _truncate(out, out.capacity // self.n_dev)
        return out

    def run(self, sql: str):
        return self.run_batch(sql).to_pandas()


def _trace_concat(a: Batch, b: Batch) -> Batch:
    from presto_tpu.exec.runtime import _concat2

    return _concat2(a, b)
