"""Device mesh management.

The engine's parallelism vocabulary (reference: SystemPartitioningHandle's
FIXED_HASH_DISTRIBUTION / SOURCE_DISTRIBUTION etc., SURVEY §2d) maps onto a
1-D jax mesh axis "workers": every worker holds a hash slice of each
repartitioned relation; scans shard by row ranges (SOURCE_DISTRIBUTION);
exchanges are XLA collectives over ICI instead of HTTP buffer pulls.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


WORKERS = "workers"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (WORKERS,))
