"""Device mesh management.

The engine's parallelism vocabulary (reference: SystemPartitioningHandle's
FIXED_HASH_DISTRIBUTION / SOURCE_DISTRIBUTION etc., SURVEY §2d) maps onto a
1-D jax mesh axis "workers": every worker holds a hash slice of each
repartitioned relation; scans shard by row ranges (SOURCE_DISTRIBUTION);
exchanges are XLA collectives over ICI instead of HTTP buffer pulls.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


WORKERS = "workers"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """`jax.shard_map` across jax versions.

    The top-level alias (and the check_rep -> check_vma rename) only exist
    from jax 0.5/0.7; on older jax the same function lives at
    jax.experimental.shard_map.shard_map with the old kwarg name."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (WORKERS,))
