"""Distributed relational primitives over a device mesh.

The reference's shuffle is an asynchronous HTTP pull between worker buffers
(PartitionedOutputOperator → OutputBuffer → ExchangeClient, SURVEY §2e).
On TPU the shuffle *within a slice* is a synchronous collective over ICI:

    rows --[hash-partition kernel]--> (P, C) lanes --all_to_all--> peers

Each worker (device) owns one hash slice of every repartitioned relation:
FIXED_HASH_DISTRIBUTION becomes "device d holds rows with
hash(key) % P == d". Partial-aggregate → exchange → final-aggregate is the
AddExchanges partial/final aggregation split; partitioned joins co-locate
both sides' slices before a local build/probe.

Everything here runs under jax.shard_map on a 1-D mesh and composes with
jit; the host never touches row data between stages.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from presto_tpu.batch import Batch, Column, round_up_capacity
from presto_tpu.ops.grouping import KeyCol, StateCol, grouped_merge
from presto_tpu.ops.join import build_side, gather_join_output, probe_unique
from presto_tpu.ops.partition import partition_for_exchange
from presto_tpu.parallel.mesh import WORKERS, shard_map


def _specs_like(batch: Batch, spec):
    return jax.tree.map(lambda _: spec, batch)


def shard_batch_arrays(data: dict, types: dict, mesh, dicts=None,
                       capacity_per_device: Optional[int] = None) -> Batch:
    """Host numpy columns → a global Batch row-sharded over the mesh.

    Rows are split round-robin-contiguously; each device's lanes are padded
    to a common capacity (SOURCE_DISTRIBUTION: splits go wherever capacity
    exists, here statically balanced)."""
    n_dev = mesh.shape[WORKERS]
    names = list(data.keys())
    n = len(next(iter(data.values()))) if names else 0
    per = -(-n // n_dev) if n else 1
    cap = capacity_per_device or round_up_capacity(per)
    cols = {}
    live = np.zeros((n_dev, cap), dtype=bool)
    for d in range(n_dev):
        lo, hi = d * per, min((d + 1) * per, n)
        if hi > lo:
            live[d, : hi - lo] = True
    for name in names:
        arr = np.asarray(data[name])
        t = types[name]
        buf = np.zeros((n_dev, cap), dtype=t.dtype)
        for d in range(n_dev):
            lo, hi = d * per, min((d + 1) * per, n)
            if hi > lo:
                buf[d, : hi - lo] = arr[lo:hi]
        cols[name] = buf.reshape(-1)
    sharding = NamedSharding(mesh, P(WORKERS))
    batch = Batch(
        names,
        [types[k] for k in names],
        [Column(jax.device_put(cols[k], sharding), None) for k in names],
        jax.device_put(live.reshape(-1), sharding),
        dicts or {},
    )
    return batch


def _all_to_all_batch(b: Batch, n_dev: int, per_cap: int) -> Batch:
    """Exchange a partitioned (P*C rows) local batch so each peer receives
    its hash slice from everyone → (P*C rows) local again."""

    def a2a(x):
        if x is None:
            return None
        x2 = x.reshape(n_dev, per_cap)
        y = jax.lax.all_to_all(x2, WORKERS, split_axis=0, concat_axis=0, tiled=False)
        return y.reshape(-1)

    cols = [Column(a2a(c.values), a2a(c.validity), a2a(c.hi)) for c in b.columns]
    return Batch(b.names, b.types, cols, a2a(b.live), b.dicts)


def distributed_aggregate(
    mesh,
    batch: Batch,
    key_syms: Sequence[str],
    states: Sequence[Tuple[str, str, str]],  # (state_name, source_col, op)
    group_cap: int,
    part_cap: Optional[int] = None,
) -> Tuple[Batch, jnp.ndarray]:
    """Row-sharded batch → hash-partitioned global group table.

    Per device: partial grouped_merge → hash-partition partials by key →
    all_to_all → final grouped_merge. Output: global Batch whose rows are the
    union of per-device group-table slices (device d holds groups with
    hash % P == d). Second return: total partition overflow count (0 means
    the exchange was lossless; caller re-runs with bigger part_cap if not).
    """
    n_dev = mesh.shape[WORKERS]
    pc = part_cap or group_cap
    key_types = [batch.type_of(k) for k in key_syms]
    state_types = [batch.type_of(src) for _, src, _ in states]

    def local(b: Batch):
        keys = [KeyCol(b.column(k).values, b.column(k).validity) for k in key_syms]
        scols = []
        for name, src, op in states:
            c = b.column(src)
            if op == "count_add":
                vals = (
                    c.validity.astype(jnp.int64)
                    if c.validity is not None
                    else b.live.astype(jnp.int64)
                )
                scols.append(StateCol(vals, None, op))
            else:
                scols.append(StateCol(c.values, c.validity, op))
        kout, sout, out_live, _ = grouped_merge(keys, scols, b.live, group_cap)
        from presto_tpu.types import BIGINT

        names = list(key_syms) + [name for name, _, _ in states]
        types = key_types + [
            BIGINT if op == "count_add" else batch.type_of(src)
            for _, src, op in states
        ]
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, None if s.op == "count_add" else s.validity) for s in sout
        ]
        return Batch(names, types, cols, out_live, {k: batch.dicts[k] for k in key_syms if k in batch.dicts})

    def device_program(b: Batch):
        partial = local(b)
        parts, counts, ovf = partition_for_exchange(partial, list(key_syms), n_dev, pc)
        received = _all_to_all_batch(parts, n_dev, pc)
        # merge the received partials (states merge with their ops)
        keys = [KeyCol(received.column(k).values, received.column(k).validity) for k in key_syms]
        scols = [
            StateCol(
                received.column(name).values,
                received.column(name).validity,
                "sum" if op == "count_add" else op,
            )
            for name, _, op in states
        ]
        kout, sout, out_live, _ = grouped_merge(keys, scols, received.live, group_cap)
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, None if states[i][2] == "count_add" else s.validity)
            for i, s in enumerate(sout)
        ]
        out = Batch(partial.names, partial.types, cols, out_live, partial.dicts)
        return out, jax.lax.psum(ovf, WORKERS)

    prog = shard_map(
        device_program,
        mesh=mesh,
        in_specs=(_specs_like(batch, P(WORKERS)),),
        out_specs=(
            jax.tree.map(lambda _: P(WORKERS), _template_out(batch, key_syms, states, group_cap)),
            P(),
        ),
        check_vma=False,
    )
    return prog(batch)


def _template_out(batch, key_syms, states, group_cap):
    """Structure template for out_specs (same pytree as device_program's
    first output)."""
    from presto_tpu.types import BIGINT

    names = list(key_syms) + [name for name, _, _ in states]
    types = [batch.type_of(k) for k in key_syms] + [
        BIGINT if op == "count_add" else batch.type_of(src) for _, src, op in states
    ]
    cols = []
    for k in key_syms:
        c = batch.column(k)
        cols.append(Column(jnp.zeros(group_cap, c.values.dtype),
                           None if c.validity is None else jnp.zeros(group_cap, bool)))
    for _, src, op in states:
        c = batch.column(src)
        dt = jnp.int64 if op == "count_add" else c.values.dtype
        # grouped_merge emits a validity array for sum/min/max states even
        # when the input column had none (empty groups are NULL)
        cols.append(Column(jnp.zeros(group_cap, dt),
                           None if op == "count_add" else jnp.zeros(group_cap, bool)))
    return Batch(names, types, cols, jnp.zeros(group_cap, bool),
                 {k: batch.dicts[k] for k in key_syms if k in batch.dicts})


def distributed_join_probe(
    mesh,
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    probe_out: Sequence[str],
    build_out: Sequence[str],
    part_cap: int,
) -> Tuple[Batch, jnp.ndarray]:
    """Partitioned hash join over the mesh (inner, unique build keys).

    Both sides are row-sharded; each is hash-partitioned on its join key and
    exchanged so device d holds both sides' hash-slice d, then joined
    locally — the FIXED_HASH_DISTRIBUTION co-located join (AddExchanges
    partitioned join path). Returns the (row-sharded) join output and the
    total partition overflow count.
    """
    n_dev = mesh.shape[WORKERS]

    def device_program(pb: Batch, bb: Batch):
        bparts, _, bovf = partition_for_exchange(bb, list(build_keys), n_dev, part_cap)
        brecv = _all_to_all_batch(bparts, n_dev, part_cap)
        table = build_side(brecv, tuple(build_keys))
        pparts, _, povf = partition_for_exchange(pb, list(probe_keys), n_dev, part_cap)
        precv = _all_to_all_batch(pparts, n_dev, part_cap)
        idx, matched = probe_unique(table, precv, tuple(probe_keys), tuple(build_keys))
        out = gather_join_output(
            precv, table,
            jnp.arange(precv.capacity, dtype=jnp.int32), idx,
            precv.live & matched, list(probe_out), list(build_out),
        )
        return out, jax.lax.psum(bovf + povf, WORKERS)

    # build out_specs template
    tmpl_cols = []
    names, types = [], []
    dicts = {}
    for c in probe_out:
        names.append(c)
        types.append(probe.type_of(c))
        col = probe.column(c)
        tmpl_cols.append(Column(jnp.zeros(1, col.values.dtype),
                                None if col.validity is None else jnp.zeros(1, bool),
                                None if col.hi is None else jnp.zeros(1, col.hi.dtype)))
        if c in probe.dicts:
            dicts[c] = probe.dicts[c]
    for c in build_out:
        names.append(c)
        types.append(build.type_of(c))
        col = build.column(c)
        tmpl_cols.append(Column(jnp.zeros(1, col.values.dtype),
                                None if col.validity is None else jnp.zeros(1, bool),
                                None if col.hi is None else jnp.zeros(1, col.hi.dtype)))
        if c in build.dicts:
            dicts[c] = build.dicts[c]
    tmpl = Batch(names, types, tmpl_cols, jnp.zeros(1, bool), dicts)

    prog = shard_map(
        device_program,
        mesh=mesh,
        in_specs=(
            _specs_like(probe, P(WORKERS)),
            _specs_like(build, P(WORKERS)),
        ),
        out_specs=(jax.tree.map(lambda _: P(WORKERS), tmpl), P()),
        check_vma=False,
    )
    return prog(probe, build)
