from presto_tpu.parallel.mesh import make_mesh
from presto_tpu.parallel.dist import (
    distributed_aggregate,
    distributed_join_probe,
    shard_batch_arrays,
)

__all__ = [
    "make_mesh",
    "distributed_aggregate",
    "distributed_join_probe",
    "shard_batch_arrays",
]
