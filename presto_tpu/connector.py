"""Connector SPI — the plugin boundary between engine and data sources.

Analog of presto-spi's connector surface (spi/connector/ConnectorMetadata.java,
ConnectorSplitManager, ConnectorPageSourceProvider.java:24), reduced to the
read path: a Connector names tables, describes their schemas (including the
per-column string Dictionary, which is first-class metadata here), produces
Splits, and reads a Split into a Batch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from presto_tpu.batch import Batch
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import Type


@dataclasses.dataclass
class ColumnStats:
    """Per-column statistics for the cost-based optimizer (reference:
    spi/statistics/ColumnStatistics — NDV, null fraction, range)."""

    ndv: Optional[float] = None            # distinct non-null values
    null_fraction: Optional[float] = None  # in [0, 1]
    min_value: Optional[float] = None      # numeric/date low (None: unknown)
    max_value: Optional[float] = None
    # equi-DEPTH histogram: tuple of bin edges (quantiles); each adjacent
    # pair holds an equal share of the rows. The reference models
    # distributions via NDV+range only; quantile edges make range
    # selectivities robust to skew (mass concentration moves the edges,
    # not the per-bin counts)
    histogram: Optional[tuple] = None


@dataclasses.dataclass
class ColumnInfo:
    name: str
    type: Type
    dictionary: Optional[Dictionary] = None
    stats: Optional[ColumnStats] = None


@dataclasses.dataclass
class TableHandle:
    catalog: str
    name: str
    columns: List[ColumnInfo]
    # statistics + constraints the planner uses (reference:
    # ConnectorMetadata.getTableStatistics / primary-key-ness is implicit in
    # Presto via hidden bucketing metadata; here it is first-class)
    row_count: Optional[float] = None
    primary_key: Optional[List[str]] = None
    # connector-bucketed partitioning (reference:
    # ConnectorNodePartitioningProvider / hive bucketed tables):
    # (key column names, bucket count) — rows are hash(keys) % count
    # co-partitioned on disk, so equal-bucketed joins skip the shuffle
    bucketing: Optional[tuple] = None

    def column(self, name: str) -> ColumnInfo:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)


@dataclasses.dataclass
class Split:
    """A unit of scan parallelism (spi/ConnectorSplit). `part` indexes into
    the table's row partitioning; `total` is the partition count. `bucket`
    tags splits of bucketed tables with their bucket id (lifespan) so the
    scheduler can drive grouped execution (Lifespan.java:26-38)."""

    table: str
    part: int
    total: int
    bucket: Optional[int] = None


class ConnectorIndex:
    """Keyed-lookup capability on a table — the analog of the reference's
    spi `ConnectorIndex` resolved through `IndexManager` and driven by
    `operator/index/IndexLoader.java`: instead of scanning + hashing the
    whole table, the engine feeds probe-side key values and receives only
    the matching rows.

    `lookup` takes {key column: numpy array of probe values} (deduplicated
    by the caller; string keys arrive as decoded Python strings so the
    index never sees dictionary codes) and returns a Batch of `columns`
    containing every table row whose key combination appears in the
    input."""

    def lookup(self, keys: Dict[str, "np.ndarray"], columns: Sequence[str],
               capacity: Optional[int] = None) -> Batch:
        raise NotImplementedError


class Connector:
    name: str = ""

    def get_index(self, handle: "TableHandle",
                  key_columns: Sequence[str]) -> Optional[ConnectorIndex]:
        """An index over `key_columns`, or None (reference:
        ConnectorIndexProvider.getIndex — most connectors return none)."""
        return None

    def split_stats(self, handle: TableHandle, split: Split):
        """Per-split min/max/null-count statistics (scan.pruning.SplitStats)
        in the STORAGE value domain, or None when the connector has no
        stats for this split. Drives the default `prune_splits` so
        eliminated splits are never opened (the reference's stripe/row-group
        skipping via TupleDomain + file statistics)."""
        return None

    def prune_splits(self, handle: TableHandle, splits: Sequence[Split],
                     min_max: Dict[str, tuple]) -> List[Split]:
        """Drop splits whose statistics prove no row can match `min_max`
        (storage-domain inclusive bounds). Connectors with a cheaper native
        path (parquet footers) override this wholesale; connectors without
        stats inherit a no-op via split_stats → None."""
        from presto_tpu.scan.pruning import split_prunable

        keep = []
        for s in splits:
            st = self.split_stats(handle, s)
            if st is not None and split_prunable(st, min_max):
                continue
            keep.append(s)
        return keep

    def table_names(self) -> List[str]:
        raise NotImplementedError

    def get_table(self, name: str) -> TableHandle:
        raise NotImplementedError

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        raise NotImplementedError

    def read_split(
        self,
        split: Split,
        columns: Sequence[str],
        capacity: Optional[int] = None,
    ) -> Batch:
        raise NotImplementedError

    # -- write path (reference: ConnectorMetadata.beginCreateTable/
    # beginInsert + ConnectorPageSink; connectors that stay read-only
    # simply inherit the failures) --------------------------------------

    def create_table_from(self, name: str, batches: Sequence[Batch],
                          if_not_exists: bool = False,
                          properties: Optional[dict] = None) -> int:
        raise NotImplementedError(
            f"connector {self.name!r} does not support CREATE TABLE")

    def insert_into(self, name: str, batches: Sequence[Batch]) -> int:
        raise NotImplementedError(
            f"connector {self.name!r} does not support INSERT")

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        raise NotImplementedError(
            f"connector {self.name!r} does not support DROP TABLE")


class Catalog:
    """Catalog/metadata facade (reference: metadata/MetadataManager.java +
    CatalogManager)."""

    def __init__(self):
        self.connectors: Dict[str, Connector] = {}
        self.default: Optional[str] = None
        # engine-level views: name -> stored query AST, expanded at plan
        # time like CTEs (reference: view definitions in connector
        # metadata; engine-level is the deliberate simplification)
        self.views: Dict[str, object] = {}

    def register(self, name: str, connector: Connector, default: bool = False):
        connector.name = name  # the registered name is authoritative
        self.connectors[name] = connector
        if default or self.default is None:
            self.default = name

    def connector_for(self, parts) -> tuple[Connector, str]:
        """Resolve a (possibly qualified) table name to (connector,
        table_name) WITHOUT requiring the table to exist (DDL targets)."""
        if len(parts) == 1:
            cname, tname = self.default, parts[0]
        else:
            cname, tname = parts[-2], parts[-1]
        if cname not in self.connectors:
            raise KeyError(f"unknown catalog {cname}")
        return self.connectors[cname], tname

    def resolve(self, parts) -> tuple[Connector, TableHandle]:
        conn, tname = self.connector_for(parts)
        return conn, conn.get_table(tname)
