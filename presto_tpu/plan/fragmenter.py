"""Distributed planning: exchange insertion + plan fragmentation.

Reference: sql/planner/optimizations/AddExchanges.java:141 (decides
partitioned vs broadcast joins, splits aggregations into partial/final
around hash exchanges) and sql/planner/PlanFragmenter.java:153 (cuts the
plan at exchanges into PlanFragments with a PartitioningScheme each).

TPU-first shape: a fragment is a program executed by one task per worker
(or one task total for SINGLE); its sink hash-partitions / broadcasts /
gathers output pages into per-consumer buffers pulled over HTTP (across
hosts) — within a slice the same partitioning runs as all_to_all collectives
(presto_tpu.parallel.dist). Partitioning vocabulary mirrors
SystemPartitioningHandle.java:59-66: SOURCE, FIXED_HASH, SINGLE on the
fragment side; HASH / BROADCAST / GATHER on the output side.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.plan.nodes import (
    Aggregate,
    Filter,
    HashJoin,
    Limit,
    Output,
    PlanNode,
    Project,
    QueryPlan,
    RemoteSource,
    SemiJoin,
    SetOp,
    Sort,
    TableScan,
    Window,
)

SOURCE = "source"       # leaf scans; splits assigned across tasks
HASH = "hash"           # one task per worker, rows owned by hash(keys) % n
SINGLE = "single"       # exactly one task
ARBITRARY = "arbitrary"  # one task per worker, rows owned by no key
                         # (round-robin redistributed — the reference's
                         # FIXED_ARBITRARY_DISTRIBUTION)

OUT_HASH = "hash"
OUT_GATHER = "gather"
OUT_BROADCAST = "broadcast"
OUT_RR = "rr"  # page-level round robin (ArbitraryOutputBuffer analog)


@dataclasses.dataclass
class Fragment:
    fid: int
    root: PlanNode
    partitioning: str              # SOURCE | HASH | SINGLE
    output_partitioning: str       # OUT_HASH | OUT_GATHER | OUT_BROADCAST
    output_keys: List[str] = dataclasses.field(default_factory=list)
    # the consumer breaker radix-partitions on output_keys (join/agg): the
    # sink may additionally tag each page with its radix id so the consumer
    # skips the device re-partition sort (partition-aligned exchange)
    radix_align: bool = False
    # CBO estimates of the fragment's OUTPUT, stamped at cut time
    # (plan/stats.derive): the mesh executor sizes OUT_HASH exchange lanes
    # from these instead of padding every lane to capacity//n_dev*2
    est_rows: Optional[float] = None
    est_key_ndv: Optional[float] = None

    def remote_sources(self) -> List[RemoteSource]:
        out = []

        def walk(n: PlanNode):
            if isinstance(n, RemoteSource):
                out.append(n)
            for c in n.children():
                walk(c)

        walk(self.root)
        return out


@dataclasses.dataclass
class DistributedPlan:
    fragments: Dict[int, Fragment]
    root_fid: int
    output_names: List[str]

    def to_string(self, node_stats=None) -> str:
        from presto_tpu.plan.nodes import plan_to_string

        parts = []
        for fid in sorted(self.fragments):
            f = self.fragments[fid]
            head = f"Fragment {fid} [{f.partitioning}] → {f.output_partitioning}"
            if f.output_keys:
                head += f"({', '.join(f.output_keys)})"
            if f.radix_align:
                head += " radix_align"
            if f.est_rows is not None:
                head += f" ~rows={f.est_rows:.3g}"
                if getattr(f, "_est_src", None) == "hbo":
                    head += " (hbo: observed)"
            mesh = getattr(f, "_mesh_a2a", None)
            if mesh:
                # stamped by the mesh executor after a run: collectives
                # issued, global bytes shipped, lane (slot) utilization
                head += (f" [mesh: a2a={mesh['a2a']}"
                         f" bytes={mesh['bytes']}"
                         f" util={100.0 * mesh['util']:.0f}%]")
            parts.append(head + "\n"
                         + plan_to_string(f.root, 1, node_stats=node_stats))
        return "\n".join(parts)


def scan_bucketing(node, catalog):
    """Resolve a scan-chain subtree (Filter/Project over TableScan,
    projects restricted to pure renames) to its table's bucketing:
    returns (symbol→bucket-position map, count, n_bucket_cols) or None.
    Nested colocated joins extend the chain: a join already marked
    colocated with the same spec exposes its probe side's mapping."""
    from presto_tpu.expr.ir import InputRef

    rename: dict = {}
    cur = node
    while True:
        if isinstance(cur, Filter):
            cur = cur.child
            continue
        if isinstance(cur, Project):
            nxt = {}
            for sym, e in cur.exprs:
                if isinstance(e, InputRef):
                    nxt[sym] = e.name
                # computed columns can't be bucket keys but don't
                # disqualify the chain
            cur = cur.child
            rename = {s: rename.get(c, c) for s, c in nxt.items()} \
                if rename else nxt
            continue
        break
    if isinstance(cur, HashJoin) and cur.colocated:
        inner = scan_bucketing(cur.left, catalog)
        if inner is None:
            return None
        pos, count, nb = inner
        if rename:
            pos = {s: pos[c] for s, c in rename.items() if c in pos}
        return (pos, count, nb) if pos else None
    if not isinstance(cur, TableScan):
        return None
    if catalog is None:
        return None
    try:
        handle = catalog.connectors[cur.catalog].get_table(cur.table)
    except Exception:
        return None
    if handle.bucketing is None:
        return None
    bcols, count = handle.bucketing
    col_pos = {c: i for i, c in enumerate(bcols)}
    pos = {}
    for sym, col in cur.assignments.items():
        if col in col_pos:
            pos[sym] = col_pos[col]
    if len(pos) != len(bcols):
        return None
    if rename:
        pos = {s: pos[c] for s, c in rename.items() if c in pos}
    return (pos, count, len(bcols)) if pos else None


def colocated_buckets(node, catalog) -> int:
    """Bucket count when this join can run colocated: both sides'
    tables bucketed with equal counts, and for EVERY bucket-key
    position there is a join equi-pair mapping to it on BOTH sides
    (HiveBucketing: same hash + same count ⇒ same bucket)."""
    lb = scan_bucketing(node.left, catalog)
    rb = scan_bucketing(node.right, catalog)
    if lb is None or rb is None:
        return 0
    (lpos, lcount, lnb), (rpos, rcount, rnb) = lb, rb
    if lcount != rcount or lnb != rnb:
        return 0
    covered = set()
    for lk, rk in zip(node.left_keys, node.right_keys):
        pl, pr = lpos.get(lk), rpos.get(rk)
        if pl is not None and pl == pr:
            covered.add(pl)
    return lcount if covered == set(range(lnb)) else 0


def tag_colocated_joins(node: PlanNode, catalog) -> None:
    """Mark bucket-colocated joins on a plan executed WITHOUT fragmentation
    (LocalRunner / a single-task fragment): the GroupedExecutionTagger
    analog for local execution. Bottom-up so nested colocated joins chain.
    The runtime's lifespan sweep (exec/runtime._execute_join /
    _execute_aggregate) then drives these bucket-by-bucket, bounding peak
    memory to one bucket's build side."""
    for c in node.children():
        tag_colocated_joins(c, catalog)
    if isinstance(node, HashJoin) and not node.colocated:
        node.colocated = colocated_buckets(node, catalog)


class _Fragmenter:
    def _scan_bucketing(self, node):
        return scan_bucketing(node, self.catalog)

    def _colocated_buckets(self, node) -> int:
        return colocated_buckets(node, self.catalog)

    def __init__(self, catalog, broadcast_threshold_rows: float,
                 stats_fn=None, hbo: str = "off"):
        self.fragments: Dict[int, Fragment] = {}
        self._next = 0
        self.catalog = catalog
        self.broadcast_threshold = broadcast_threshold_rows
        self.hbo = hbo
        # optional row-count estimator (CBO hook): node -> Optional[float]
        if stats_fn is None:
            def stats_fn(n, _catalog=catalog):
                # CBO-derived estimate (StatsCalculator analog); the legacy
                # fixed-selectivity walk is the no-statistics fallback
                from presto_tpu.plan.stats import derive

                s = derive(n, _catalog)
                if s is not None:
                    return s.rows
                return estimate_rows(n, _catalog)
        self.stats_fn = stats_fn

    def cut(self, root: PlanNode, partitioning: str,
            out_part: str, keys: Optional[List[str]] = None,
            radix_align: bool = False) -> RemoteSource:
        fid = self._next
        self._next += 1
        try:
            from presto_tpu.plan.stats import combined_key_ndv, derive

            st = derive(root, self.catalog)
        except Exception:
            st = None
        frag = Fragment(fid, root, partitioning, out_part,
                        list(keys or []), radix_align=radix_align)
        if st is not None:
            frag.est_rows = st.rows
            if keys:
                frag.est_key_ndv = combined_key_ndv(st, keys)
        if self.hbo == "correct":
            # history-refined output estimate: a prior run of the same
            # fragment-root structure recorded its true output row count
            # (scan_rows for scan chains, agg_groups for breaker roots) —
            # trust the observation over the static derivation
            try:
                from presto_tpu.obs import runstats

                fp = runstats.node_fingerprint(root, self.catalog)
                h = (runstats.lookup(fp, "scan_rows")
                     or runstats.lookup(fp, "agg_groups"))
                if h and h.get("actual"):
                    frag.est_rows = float(h["actual"])
                    frag.__dict__["_est_src"] = "hbo"
            except Exception:
                pass
        self.fragments[fid] = frag
        rs = RemoteSource(fid, list(root.output))
        # a cut is transparent to stats: stamping the producing fragment's
        # estimate as the RemoteSource's memo lets downstream derivations
        # (final-agg capacity, breaker engine choice, consumer exchange
        # sizing) see through the fragment boundary instead of derive()'s
        # None-on-RemoteSource. strip_runtime_state removes it before the
        # wire, and codec never serializes underscore state.
        rs.__dict__["_node_stats"] = st
        return rs

    # returns (node-in-current-fragment, partitioning of current fragment)
    def process(self, node: PlanNode) -> Tuple[PlanNode, str]:
        if isinstance(node, TableScan):
            return node, SOURCE
        if isinstance(node, Filter):
            node.child, p = self.process(node.child)
            return node, p
        if isinstance(node, Project):
            node.child, p = self.process(node.child)
            return node, p
        if isinstance(node, Aggregate):
            from presto_tpu.plan.agg_states import is_decomposable

            child, cpart = self.process(node.child)
            if cpart == SINGLE:
                # already on one task — no exchange needed
                node.child = child
                return node, SINGLE
            if not is_decomposable(node.aggs):
                # order-dependent states (approx_percentile / max_by / min_by)
                # have no mergeable partial form: gather raw rows to one task
                node.child = self.cut(child, cpart, OUT_GATHER)
                return node, SINGLE
            partial = Aggregate(child, node.group_keys, node.aggs, step="partial")
            if node.group_keys:
                rs = self.cut(partial, cpart, OUT_HASH, node.group_keys,
                              radix_align=True)
                final = Aggregate(rs, node.group_keys, node.aggs, step="final")
                return final, HASH
            rs = self.cut(partial, cpart, OUT_GATHER)
            final = Aggregate(rs, [], node.aggs, step="final")
            return final, SINGLE
        if isinstance(node, HashJoin):
            # colocated bucketed join first (GroupedExecutionTagger +
            # ConnectorNodePartitioningProvider): both sides scan tables
            # bucketed on the join keys with the same count — no exchange,
            # the runtime drives the join bucket-by-bucket (lifespans)
            # estimate BEFORE fragmenting the build side: process() splices
            # RemoteSources into the subtree, which would blind the estimator
            build_rows = self.stats_fn(node.right)
            cob = self._colocated_buckets(node)
            left, lpart = self.process(node.left)
            right, rpart = self.process(node.right)
            if cob and lpart == SOURCE and rpart == SOURCE:
                node.left, node.right = left, right
                node.colocated = cob
                return node, SOURCE
            if (build_rows is not None
                    and build_rows <= self.broadcast_threshold
                    and node.kind != "full"):
                # BROADCAST join (DetermineJoinDistributionType REPLICATED):
                # build side is replicated to every probe task. FULL OUTER
                # must NOT broadcast — every task would re-emit the same
                # unmatched build rows; hash partitioning gives each build
                # row exactly one owner (LookupJoinOperators.fullOuterJoin
                # is likewise partitioned-only in the reference)
                if rpart == SINGLE and lpart == SINGLE:
                    node.left, node.right = left, right
                    return node, SINGLE
                node.left = left
                node.right = self.cut(right, rpart, OUT_BROADCAST)
                return node, lpart
            # PARTITIONED join: co-locate both sides by hash(join keys)
            node.left = self.cut(left, lpart, OUT_HASH, node.left_keys,
                                 radix_align=True)
            node.right = self.cut(right, rpart, OUT_HASH, node.right_keys,
                                  radix_align=True)
            return node, HASH
        from presto_tpu.plan.nodes import MultiwayJoin

        if isinstance(node, MultiwayJoin):
            # the probe pipeline keeps its partitioning; every build table is
            # replicated to each probe task (the collapse pass only fuses
            # chains whose build sides are broadcast-sized, so REPLICATED is
            # always the right distribution here). SINGLE/SINGLE needs no cut.
            probe, ppart = self.process(node.probe)
            node.probe = probe
            new_builds = []
            for b in node.builds:
                rb, rpart = self.process(b)
                if rpart == SINGLE and ppart == SINGLE:
                    new_builds.append(rb)
                else:
                    new_builds.append(self.cut(rb, rpart, OUT_BROADCAST))
            node.builds = new_builds
            return node, ppart
        if isinstance(node, SemiJoin):
            left, lpart = self.process(node.left)
            right, rpart = self.process(node.right)
            node.left = left
            if rpart == SINGLE and lpart == SINGLE:
                node.right = right
                return node, SINGLE
            node.right = self.cut(right, rpart, OUT_BROADCAST)
            return node, lpart
        from presto_tpu.plan.nodes import IndexJoin, NestedLoopJoin

        if isinstance(node, IndexJoin):
            # the index side is a connector keyed lookup, available on any
            # worker — the probe keeps its partitioning, no exchange
            node.left, p = self.process(node.left)
            return node, p

        if isinstance(node, NestedLoopJoin):
            # probe keeps its partitioning; the build is replicated
            # (NestedLoopBuildOperator is broadcast-only in the reference)
            left, lpart = self.process(node.left)
            right, rpart = self.process(node.right)
            node.left = left
            if rpart == SINGLE and lpart == SINGLE:
                node.right = right
                return node, SINGLE
            node.right = self.cut(right, rpart, OUT_BROADCAST)
            return node, lpart
        if isinstance(node, Window):
            child, cpart = self.process(node.child)
            if cpart == SINGLE:
                node.child = child
                return node, SINGLE
            if node.partition_keys:
                node.child = self.cut(child, cpart, OUT_HASH, node.partition_keys)
                return node, HASH
            node.child = self.cut(child, cpart, OUT_GATHER)
            return node, SINGLE
        if isinstance(node, Sort):
            child, cpart = self.process(node.child)
            if cpart == SINGLE:
                node.child = child
                return node, SINGLE
            if node.limit is not None:
                # distributed TopN: partial TopN per task, merge at gather
                partial = Sort(child, node.keys, node.limit)
                node.child = self.cut(partial, cpart, OUT_GATHER)
                return node, SINGLE
            # distributed sort: partial sort per task + final merge
            # (admin/dist-sort.rst); final re-sort on gathered runs
            node.child = self.cut(Sort(child, node.keys), cpart, OUT_GATHER)
            return node, SINGLE
        if isinstance(node, Limit):
            child, cpart = self.process(node.child)
            if cpart == SINGLE:
                node.child = child
                return node, SINGLE
            partial = Limit(child, node.count)
            node.child = self.cut(partial, cpart, OUT_GATHER)
            return node, SINGLE
        if isinstance(node, SetOp):
            left, lpart = self.process(node.left)
            right, rpart = self.process(node.right)
            if node.kind == "union" and node.all and not (
                    lpart == SINGLE and rpart == SINGLE):
                # UNION ALL streams: children round-robin pages across the
                # union fragment's tasks (FIXED_ARBITRARY distribution) —
                # no gather bottleneck, downstream partials run per task
                node.left = self.cut(left, lpart, OUT_RR)
                node.right = self.cut(right, rpart, OUT_RR)
                return node, ARBITRARY
            # DISTINCT variants need global visibility: gather
            node.left = left if lpart == SINGLE else self.cut(left, lpart, OUT_GATHER)
            node.right = (right if rpart == SINGLE
                          else self.cut(right, rpart, OUT_GATHER))
            return node, SINGLE
        if isinstance(node, Output):
            # nested Output (set-operation children are whole sub-plans):
            # keep the projection wrapper, fragment through it
            child, cpart = self.process(node.child)
            node.child = child
            return node, cpart
        if isinstance(node, RemoteSource):
            return node, SINGLE
        from presto_tpu.plan.nodes import OneRow, TableWriter, Unnest

        if isinstance(node, Unnest):
            # streaming row expansion: stays in its child's fragment
            node.child, p = self.process(node.child)
            return node, p
        if isinstance(node, TableWriter):
            # scaled writers: the writer rides its child's partitioning —
            # every task writes its own part (SCALED_WRITER_DISTRIBUTION)
            node.child, p = self.process(node.child)
            return node, p
        if isinstance(node, OneRow):
            return node, SINGLE
        from presto_tpu.plan.nodes import HostProject

        if isinstance(node, HostProject):
            # host finishing projection: runs where the rows materialize —
            # the single root task
            child, cpart = self.process(node.child)
            if cpart == SINGLE:
                node.child = child
                return node, SINGLE
            node.child = self.cut(child, cpart, OUT_GATHER)
            return node, SINGLE
        raise NotImplementedError(f"fragmenter: {type(node).__name__}")


def estimate_rows(node: PlanNode, catalog=None) -> Optional[float]:
    """Build-size estimate for join distribution choice. Replaced by the
    cost-based StatsCalculator when table statistics are available."""
    if isinstance(node, TableScan):
        if catalog is None:
            return None
        try:
            conn = catalog.connectors[node.catalog]
            return float(conn.get_table(node.table).row_count or 1e6)
        except Exception:
            return None
    if isinstance(node, Filter):
        r = estimate_rows(node.child, catalog)
        return None if r is None else r * 0.25
    if isinstance(node, Project):
        return estimate_rows(node.child, catalog)
    if isinstance(node, Aggregate):
        r = estimate_rows(node.child, catalog)
        return None if r is None else max(1.0, r * 0.1)
    if isinstance(node, (Sort, Window)):
        if isinstance(node, Sort) and node.limit is not None:
            return float(node.limit)
        return estimate_rows(node.child, catalog)
    if isinstance(node, Limit):
        return float(node.count)
    if isinstance(node, HashJoin):
        return estimate_rows(node.left, catalog)
    if isinstance(node, SemiJoin):
        return estimate_rows(node.left, catalog)
    if isinstance(node, SetOp):
        a = estimate_rows(node.left, catalog)
        b = estimate_rows(node.right, catalog)
        if a is None or b is None:
            return None
        return a + b
    return None


def fragment_plan(plan: QueryPlan, catalog=None,
                  broadcast_threshold_rows: float = 1_000_000,
                  stats_fn=None, hbo: str = "off") -> DistributedPlan:
    """Cut an optimized single-node plan into a distributed fragment DAG.

    Scalar subqueries must have been bound first (the coordinator executes
    them before fragmenting, like the reference runs them as separate
    stages feeding semi-join/filter constants).

    `hbo="correct"` lets the cut-time estimates consult the obs/runstats
    history store: a repeated structure's fragment output estimate comes
    from the prior run's observation instead of the static derivation
    (rendered as "(hbo: observed)" in DistributedPlan.to_string).
    """
    f = _Fragmenter(catalog, broadcast_threshold_rows, stats_fn, hbo=hbo)
    out = plan.root
    child, cpart = f.process(out.child)
    if cpart != SINGLE:
        child = f.cut(child, cpart, OUT_GATHER)
    root = Output(child, out.names, out.symbols)
    fid = f._next
    f.fragments[fid] = Fragment(fid, root, SINGLE, OUT_GATHER, [])
    return DistributedPlan(f.fragments, fid, list(out.names))


def strip_runtime_state(node: PlanNode):
    """Remove runtime state before pickling a fragment for the wire.

    Anything underscore-prefixed in a node's instance dict is runtime-only
    by convention (`_jit_cache` / `_jit_stats` memos, `_collapsed`,
    `_probe_shim`, `_node_stats`, ...) — no declared plan field starts
    with an underscore, so popping the prefix wholesale keeps the wire
    image equal to the logical plan. plan/codec.py enforces the same
    contract structurally (only declared fields serialize)."""
    for key in [k for k in node.__dict__ if k.startswith("_")]:
        node.__dict__.pop(key, None)
    for c in node.children():
        strip_runtime_state(c)
