"""Aggregate state layouts shared by the planner (fragmenter) and runtime.

Reference: AggregationNode.Step (PARTIAL/INTERMEDIATE/FINAL/SINGLE) and the
accumulator state classes (operator/aggregation/state/*, e.g.
VarianceState, CovarianceState, CorrelationState): a partial aggregation
emits *state columns* (avg → sum+count, variance → count+sum+sumsq) that
travel through the exchange and are merged by the final aggregation.

Decomposable aggregates expand into columns each merged with one of the
kernel ops (sum / min / max / count_add — ops/grouping.py). Aggregates with
no mergeable fixed-width state (approx_percentile, max_by/min_by) are
non-decomposable: the fragmenter gathers their input to a single task and
the runtime computes them over materialized sorted input.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from presto_tpu.types import BIGINT, DOUBLE, TINYINT, DecimalType, Type

# fn → list of (state-suffix, merge-op); "" suffix = the agg's own symbol.
# The suffix doubles as the input-transform tag (runtime in_to_states).
_VARIANCE_FNS = {"variance", "var_samp", "var_pop", "stddev", "stddev_samp",
                 "stddev_pop"}
_COVAR_FNS = {"covar_pop", "covar_samp"}
_NON_DECOMPOSABLE = {"approx_percentile", "__approx_percentile_w",
                     "max_by", "min_by", "array_agg", "map_agg",
                     "numeric_histogram", "tdigest_agg", "merge",
                     "approx_set",
                     "count_distinct", "sum_distinct", "avg_distinct"}


def is_decomposable(aggs) -> bool:
    return all(a.fn not in _NON_DECOMPOSABLE for a in aggs)


def _decimal_arg(a, in_types) -> bool:
    t = in_types.get(a.arg) if a.arg else None
    if isinstance(t, DecimalType):
        return True
    # final step: the child output carries the partial's limb state columns
    return (a.symbol + "$hi") in in_types or (a.symbol + "$sum_hi") in in_types


def agg_state_layout(aggs, in_types: Dict[str, Type]) -> List[Tuple[str, str, object]]:
    """Each AggSpec expands to one or more (state_name, merge_op, spec).

    Decimal sums accumulate in TWO int64 limb states ($hi carries the
    arithmetic high limb, $lo the nonnegative low 32 bits) so int128-exact
    totals survive any row count — the reference's
    UnscaledDecimal128Arithmetic state (presto-spi/.../type/
    UnscaledDecimal128Arithmetic.java) on TPU-friendly int64 lanes."""
    layout = []
    for a in aggs:
        if a.fn == "sum":
            if _decimal_arg(a, in_types):
                layout.append((a.symbol + "$hi", "sum", a))
                layout.append((a.symbol + "$lo", "sum", a))
            else:
                layout.append((a.symbol, "sum", a))
        elif a.fn in ("count", "count_star", "count_if"):
            layout.append((a.symbol, "count_add", a))
        elif a.fn == "avg":
            if _decimal_arg(a, in_types):
                layout.append((a.symbol + "$sum_hi", "sum", a))
                layout.append((a.symbol + "$sum_lo", "sum", a))
            else:
                layout.append((a.symbol + "$sum", "sum", a))
            layout.append((a.symbol + "$cnt", "count_add", a))
        elif a.fn in ("min", "max"):
            layout.append((a.symbol, a.fn, a))
        elif a.fn in ("arbitrary", "any_value"):
            layout.append((a.symbol, "min", a))
        elif a.fn in ("bool_and", "every"):
            layout.append((a.symbol, "min", a))
        elif a.fn == "bool_or":
            layout.append((a.symbol, "max", a))
        elif a.fn == "checksum":
            layout.append((a.symbol, "sum", a))
        elif a.fn in _VARIANCE_FNS:
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$sum", "sum", a))
            layout.append((a.symbol + "$sumsq", "sum", a))
        elif a.fn in _COVAR_FNS:
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$sx", "sum", a))
            layout.append((a.symbol + "$sy", "sum", a))
            layout.append((a.symbol + "$sxy", "sum", a))
        elif a.fn == "corr":
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$sx", "sum", a))
            layout.append((a.symbol + "$sy", "sum", a))
            layout.append((a.symbol + "$sxy", "sum", a))
            layout.append((a.symbol + "$sxx", "sum", a))
            layout.append((a.symbol + "$syy", "sum", a))
        elif a.fn == "geometric_mean":
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$lsum", "sum", a))
        else:
            udf = _registered_aggregate(a.fn)
            if udf is None:
                raise NotImplementedError(f"aggregate {a.fn}")
            for suffix, op, _transform in udf.states:
                layout.append((a.symbol + suffix, op, a))
    return layout


def _registered_aggregate(fn: str):
    from presto_tpu.functions import registry

    return registry().aggregate(fn)


def sum_state_type(a, in_types: Dict[str, Type]) -> Type:
    t = in_types[a.arg]
    if isinstance(t, DecimalType):
        return DecimalType(18, t.scale)
    if t.name in ("tinyint", "smallint", "integer", "bigint"):
        return BIGINT
    return DOUBLE


def limb_pairs(layout) -> List[Tuple[int, int]]:
    """(hi_index, lo_index) state pairs needing carry renormalization after
    each merge (lo kept canonical in [0, 2^32))."""
    idx = {name: i for i, (name, _, _) in enumerate(layout)}
    pairs = []
    for name, i in idx.items():
        if name.endswith("$hi") or name.endswith("$sum_hi"):
            lo_name = name[: -len("hi")] + "lo"
            if lo_name in idx:
                pairs.append((i, idx[lo_name]))
    return pairs


def state_types(layout, in_types: Dict[str, Type]) -> List[Type]:
    out = []
    for name, op, a in layout:
        if op == "count_add":
            out.append(BIGINT)
        elif name.endswith(("$hi", "$sum_hi")):
            out.append(BIGINT)
        elif name.endswith(("$lo", "$sum_lo")):
            # the low limb carries the value's scale through the exchange
            t = in_types.get(a.arg)
            scale = t.scale if isinstance(t, DecimalType) else 0
            out.append(DecimalType(38, scale))
        elif a.fn == "checksum":
            out.append(BIGINT)
        elif a.fn in ("bool_and", "bool_or", "every"):
            out.append(TINYINT)
        elif a.fn in _VARIANCE_FNS or a.fn in _COVAR_FNS or a.fn in (
                "corr", "geometric_mean"):
            out.append(DOUBLE)
        elif _registered_aggregate(a.fn) is not None:
            # registered UDAF states accumulate in float64 lanes
            out.append(DOUBLE)
        elif op == "sum":
            if a.fn in ("avg", "sum"):
                out.append(sum_state_type(a, in_types) if a.arg else BIGINT)
            else:
                out.append(DOUBLE)
        elif op in ("min", "max"):
            t = in_types[a.arg]
            if isinstance(t, DecimalType) and t.is_long:
                out.append(DOUBLE)  # combined-f64 extremes (see builder)
            else:
                out.append(t)
        else:
            out.append(DOUBLE)
    return out


def partial_output(child_output, group_keys, aggs) -> List[Tuple[str, Type]]:
    """Schema of a step='partial' aggregation: keys then state columns."""
    in_types = dict(child_output)
    layout = agg_state_layout(aggs, in_types)
    return [(k, in_types[k]) for k in group_keys] + list(
        zip([name for name, _, _ in layout], state_types(layout, in_types))
    )
