"""Aggregate state layouts shared by the planner (fragmenter) and runtime.

Reference: AggregationNode.Step (PARTIAL/INTERMEDIATE/FINAL/SINGLE) and the
accumulator state classes (operator/aggregation/state/*): a partial
aggregation emits *state columns* (avg → sum+count) that travel through the
exchange and are merged by the final aggregation.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from presto_tpu.types import BIGINT, DOUBLE, DecimalType, Type


def agg_state_layout(aggs) -> List[Tuple[str, str, object]]:
    """Each AggSpec expands to one or more (state_name, merge_op, spec)."""
    layout = []
    for a in aggs:
        if a.fn == "sum":
            layout.append((a.symbol, "sum", a))
        elif a.fn in ("count", "count_star"):
            layout.append((a.symbol, "count_add", a))
        elif a.fn == "avg":
            layout.append((a.symbol + "$sum", "sum", a))
            layout.append((a.symbol + "$cnt", "count_add", a))
        elif a.fn in ("min", "max"):
            layout.append((a.symbol, a.fn, a))
        else:
            raise NotImplementedError(f"aggregate {a.fn}")
    return layout


def sum_state_type(a, in_types: Dict[str, Type]) -> Type:
    t = in_types[a.arg]
    if isinstance(t, DecimalType):
        return DecimalType(18, t.scale)
    if t.name in ("tinyint", "smallint", "integer", "bigint"):
        return BIGINT
    return DOUBLE


def state_types(layout, in_types: Dict[str, Type]) -> List[Type]:
    out = []
    for name, op, a in layout:
        if op == "count_add":
            out.append(BIGINT)
        elif op == "sum":
            if a.fn == "avg" or a.fn == "sum":
                out.append(sum_state_type(a, in_types) if a.arg else BIGINT)
            else:
                out.append(DOUBLE)
        elif op in ("min", "max"):
            out.append(in_types[a.arg])
        else:
            out.append(DOUBLE)
    return out


def partial_output(child_output, group_keys, aggs) -> List[Tuple[str, Type]]:
    """Schema of a step='partial' aggregation: keys then state columns."""
    in_types = dict(child_output)
    layout = agg_state_layout(aggs)
    return [(k, in_types[k]) for k in group_keys] + list(
        zip([name for name, _, _ in layout], state_types(layout, in_types))
    )
