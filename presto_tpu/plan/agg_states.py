"""Aggregate state layouts shared by the planner (fragmenter) and runtime.

Reference: AggregationNode.Step (PARTIAL/INTERMEDIATE/FINAL/SINGLE) and the
accumulator state classes (operator/aggregation/state/*, e.g.
VarianceState, CovarianceState, CorrelationState): a partial aggregation
emits *state columns* (avg → sum+count, variance → count+sum+sumsq) that
travel through the exchange and are merged by the final aggregation.

Decomposable aggregates expand into columns each merged with one of the
kernel ops (sum / min / max / count_add — ops/grouping.py). Aggregates with
no mergeable fixed-width state (approx_percentile, max_by/min_by) are
non-decomposable: the fragmenter gathers their input to a single task and
the runtime computes them over materialized sorted input.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from presto_tpu.types import BIGINT, DOUBLE, TINYINT, DecimalType, Type

# fn → list of (state-suffix, merge-op); "" suffix = the agg's own symbol.
# The suffix doubles as the input-transform tag (runtime in_to_states).
_VARIANCE_FNS = {"variance", "var_samp", "var_pop", "stddev", "stddev_samp",
                 "stddev_pop"}
_COVAR_FNS = {"covar_pop", "covar_samp"}
_NON_DECOMPOSABLE = {"approx_percentile", "max_by", "min_by"}


def is_decomposable(aggs) -> bool:
    return all(a.fn not in _NON_DECOMPOSABLE for a in aggs)


def agg_state_layout(aggs) -> List[Tuple[str, str, object]]:
    """Each AggSpec expands to one or more (state_name, merge_op, spec)."""
    layout = []
    for a in aggs:
        if a.fn == "sum":
            layout.append((a.symbol, "sum", a))
        elif a.fn in ("count", "count_star", "count_if"):
            layout.append((a.symbol, "count_add", a))
        elif a.fn == "avg":
            layout.append((a.symbol + "$sum", "sum", a))
            layout.append((a.symbol + "$cnt", "count_add", a))
        elif a.fn in ("min", "max"):
            layout.append((a.symbol, a.fn, a))
        elif a.fn in ("arbitrary", "any_value"):
            layout.append((a.symbol, "min", a))
        elif a.fn in ("bool_and", "every"):
            layout.append((a.symbol, "min", a))
        elif a.fn == "bool_or":
            layout.append((a.symbol, "max", a))
        elif a.fn == "checksum":
            layout.append((a.symbol, "sum", a))
        elif a.fn in _VARIANCE_FNS:
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$sum", "sum", a))
            layout.append((a.symbol + "$sumsq", "sum", a))
        elif a.fn in _COVAR_FNS:
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$sx", "sum", a))
            layout.append((a.symbol + "$sy", "sum", a))
            layout.append((a.symbol + "$sxy", "sum", a))
        elif a.fn == "corr":
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$sx", "sum", a))
            layout.append((a.symbol + "$sy", "sum", a))
            layout.append((a.symbol + "$sxy", "sum", a))
            layout.append((a.symbol + "$sxx", "sum", a))
            layout.append((a.symbol + "$syy", "sum", a))
        elif a.fn == "geometric_mean":
            layout.append((a.symbol + "$cnt", "count_add", a))
            layout.append((a.symbol + "$lsum", "sum", a))
        else:
            raise NotImplementedError(f"aggregate {a.fn}")
    return layout


def sum_state_type(a, in_types: Dict[str, Type]) -> Type:
    t = in_types[a.arg]
    if isinstance(t, DecimalType):
        return DecimalType(18, t.scale)
    if t.name in ("tinyint", "smallint", "integer", "bigint"):
        return BIGINT
    return DOUBLE


def state_types(layout, in_types: Dict[str, Type]) -> List[Type]:
    out = []
    for name, op, a in layout:
        if op == "count_add":
            out.append(BIGINT)
        elif a.fn == "checksum":
            out.append(BIGINT)
        elif a.fn in ("bool_and", "bool_or", "every"):
            out.append(TINYINT)
        elif a.fn in _VARIANCE_FNS or a.fn in _COVAR_FNS or a.fn in (
                "corr", "geometric_mean"):
            out.append(DOUBLE)
        elif op == "sum":
            if a.fn in ("avg", "sum"):
                out.append(sum_state_type(a, in_types) if a.arg else BIGINT)
            else:
                out.append(DOUBLE)
        elif op in ("min", "max"):
            out.append(in_types[a.arg])
        else:
            out.append(DOUBLE)
    return out


def partial_output(child_output, group_keys, aggs) -> List[Tuple[str, Type]]:
    """Schema of a step='partial' aggregation: keys then state columns."""
    in_types = dict(child_output)
    layout = agg_state_layout(aggs)
    return [(k, in_types[k]) for k in group_keys] + list(
        zip([name for name, _, _ in layout], state_types(layout, in_types))
    )
