"""Subquery decorrelation — AST→AST rewrites applied before planning.

The reference implements decorrelation as plan rewrites
(TransformCorrelatedScalarAggregationToJoin, TransformExistsApplyToLateralNode,
PlanNodeDecorrelator under sql/planner/optimizations + iterative/rule).
Here the classic cases are rewritten at the AST level, which composes with
the existing planner without an Apply/Lateral node:

1. [NOT] EXISTS (SELECT ... FROM t WHERE outer = inner AND rest)
     → outer [NOT] IN (SELECT inner FROM t WHERE rest)          (Q4, Q21-lite)

2. expr CMP (SELECT agg(x) FROM t WHERE inner = outer [AND rest])   (Q2, Q17)
     → join a grouped derived table on the correlation key:
       FROM ..., (SELECT inner AS __ck, agg(x) AS __agg FROM t
                  [WHERE rest] GROUP BY inner) __dtN
       WHERE __dtN.__ck = outer AND expr CMP __dtN.__agg
   (valid in WHERE position: an empty subquery yields NULL which fails the
   comparison, exactly like the dropped row of the inner join)

Correlation detection is name-based: a column referenced in the subquery
that does not resolve against the subquery's own FROM (via catalog schemas)
is an outer reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from presto_tpu.connector import Catalog
from presto_tpu.sql import ast


def _relation_columns(rel, catalog: Catalog, ctes: Dict[str, ast.Query]) -> Set[str]:
    """Column names visible from a FROM tree (unqualified)."""
    if rel is None:
        return set()
    if isinstance(rel, ast.Table):
        name = rel.name[-1]
        if len(rel.name) == 1 and name in ctes:
            sub = ctes[name]
            out = set()
            for it in sub.select:
                if it.alias:
                    out.add(it.alias)
                elif isinstance(it.expr, ast.Identifier):
                    out.add(it.expr.parts[-1])
            return out
        try:
            _, handle = catalog.resolve(rel.name)
        except KeyError:
            return set()
        return {c.name for c in handle.columns}
    if isinstance(rel, ast.SubqueryRelation):
        out = set()
        for it in rel.query.select:
            if it.alias:
                out.add(it.alias)
            elif isinstance(it.expr, ast.Identifier):
                out.add(it.expr.parts[-1])
        return out
    if isinstance(rel, ast.Join):
        return _relation_columns(rel.left, catalog, ctes) | _relation_columns(
            rel.right, catalog, ctes
        )
    return set()


def _relation_names(rel) -> Set[str]:
    """Relation aliases/names visible from a FROM tree — the qualifiers
    an identifier may carry to resolve INSIDE the subquery."""
    if rel is None:
        return set()
    if isinstance(rel, ast.Table):
        return {rel.alias or rel.name[-1]}
    if isinstance(rel, ast.SubqueryRelation):
        return {rel.alias} if rel.alias else set()
    if isinstance(rel, ast.Join):
        return _relation_names(rel.left) | _relation_names(rel.right)
    return set()


def _split_conjuncts(e) -> List:
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _combine(es: List) -> Optional[ast.Node]:
    if not es:
        return None
    out = es[0]
    for e in es[1:]:
        out = ast.BinaryOp("and", out, e)
    return out


def _factor_or(c) -> List:
    """(a AND x) OR (a AND y) → a AND (x OR y). Returns conjunct list."""
    if not (isinstance(c, ast.BinaryOp) and c.op == "or"):
        return [c]

    def branches(n):
        if isinstance(n, ast.BinaryOp) and n.op == "or":
            return branches(n.left) + branches(n.right)
        return [n]

    from presto_tpu.plan.builder import ast_key

    brs = [_split_conjuncts(b) for b in branches(c)]
    if len(brs) < 2:
        return [c]
    common_keys = set(ast_key(x) for x in brs[0])
    for b in brs[1:]:
        common_keys &= {ast_key(x) for x in b}
    if not common_keys:
        return [c]
    hoisted = [x for x in brs[0] if ast_key(x) in common_keys]
    residual_branches = []
    for b in brs:
        rest = [x for x in b if ast_key(x) not in common_keys]
        if not rest:
            # a branch fully covered by the common part → OR is implied true
            residual_branches = None
            break
        residual_branches.append(_combine(rest))
    out = list(hoisted)
    if residual_branches is not None:
        orr = residual_branches[0]
        for b in residual_branches[1:]:
            orr = ast.BinaryOp("or", orr, b)
        out.append(orr)
    return out


def _find_correlation(
    sub: ast.Query, catalog: Catalog, ctes: Dict[str, ast.Query]
) -> Optional[Tuple[List[Tuple[ast.Identifier, ast.Identifier]], List]]:
    """If sub's WHERE contains `inner_col = outer_col` conjuncts (one side
    resolving in sub's FROM, the other not), return
    ([(outer_ident, inner_ident), ...], remaining_conjuncts)."""
    if sub.where is None:
        return None
    inner_cols = _relation_columns(sub.from_, catalog, ctes)
    inner_rels = _relation_names(sub.from_)

    def is_inner(ident: ast.Identifier) -> bool:
        # unqualified: resolves against the subquery's columns;
        # qualified: the qualifier must name a subquery relation —
        # `t1.k` stays an OUTER ref even when the inner table also has
        # a column `k`
        if len(ident.parts) == 1:
            return ident.parts[0] in inner_cols
        return ident.parts[0] in inner_rels

    conjs = _split_conjuncts(sub.where)
    pairs: List[Tuple[ast.Identifier, ast.Identifier]] = []
    rest = []
    for c in conjs:
        if (
            isinstance(c, ast.BinaryOp)
            and c.op == "eq"
            and isinstance(c.left, ast.Identifier)
            and isinstance(c.right, ast.Identifier)
        ):
            l_in = is_inner(c.left)
            r_in = is_inner(c.right)
            if l_in and not r_in:
                pairs.append((c.right, c.left))
                continue
            if r_in and not l_in:
                pairs.append((c.left, c.right))
                continue
        rest.append(c)
    if not pairs:
        return None
    # any remaining outer references → too correlated for these rewrites
    outer_refs = set()

    def scan(n):
        if isinstance(n, ast.Identifier) and not is_inner(n):
            outer_refs.add(".".join(n.parts))
        for ch in _children(n):
            scan(ch)

    for c in rest:
        scan(c)
    for it in sub.select:
        scan(it.expr)
    if outer_refs:
        return None
    return pairs, rest


def _children(n):
    from presto_tpu.plan.builder import _ast_children

    return _ast_children(n)


class Decorrelator:
    def __init__(self, catalog: Catalog, ctes: Dict[str, ast.Query]):
        self.catalog = catalog
        self.ctes = ctes
        self.derived: List[ast.Join] = []  # pending joins to graft onto FROM
        self.counter = 0

    def rewrite_where(self, q: ast.Query) -> None:
        """Rewrite EXISTS and correlated scalar subqueries in q.where;
        grafts derived-table joins onto q.from_."""
        if q.where is None:
            return
        conjs = _split_conjuncts(q.where)
        # OR factoring: hoist conjuncts common to every OR branch
        # (ExtractCommonPredicatesExpressionRewriter analog) — unlocks the
        # Q19 shape where the equi-join conjunct lives inside each branch
        expanded = []
        for c in conjs:
            expanded.extend(_factor_or(c))
        conjs = expanded
        self._mode = "cross"
        out = []
        for c in conjs:
            out.append(self._rewrite_conjunct(c))
        # graft derived tables: plain aggregates become cross joins +
        # WHERE equi-conjuncts (the planner's comma-join assembly orders
        # them with everything else); count-like ones must LEFT-join with
        # the condition in ON (a WHERE conjunct would re-drop the
        # null-extended row whose true count is 0)
        for kind, dt, cond in self._pending:
            if kind == "left":
                q.from_ = ast.Join("left", q.from_, dt, cond)
            else:
                q.from_ = ast.Join("cross", q.from_, dt, None)
                out.append(cond)
        self._pending = []
        q.where = _combine(out)

    def rewrite_select(self, q: ast.Query) -> None:
        """Correlated scalar-aggregate subqueries in the SELECT list:
        LEFT-JOIN the grouped derived table (a missing group must yield
        NULL, not drop the outer row — the semantic difference from the
        WHERE-position rewrite; reference:
        TransformCorrelatedScalarAggregationToJoin)."""
        if q.from_ is None:
            return
        self._mode = "left"
        self._pending = []
        for it in q.select:
            it.expr = self._rewrite_scalar(it.expr)
        for _, dt, cond in self._pending:
            q.from_ = ast.Join("left", q.from_, dt, cond)
        self._pending = []

    _pending: List

    def _rewrite_conjunct(self, c):
        self._pending = getattr(self, "_pending", [])
        # EXISTS stays an AST node — the planner lowers it directly to a
        # SemiJoin with keys + residual (null_aware=False)
        # comparisons containing correlated scalar aggregates
        if isinstance(c, ast.BinaryOp) and c.op in ("eq", "ne", "lt", "le", "gt", "ge"):
            c.left = self._rewrite_scalar(c.left)
            c.right = self._rewrite_scalar(c.right)
        return c

    def _rewrite_scalar(self, e):
        """Replace a correlated scalar-aggregate subquery inside an
        expression with a reference into a grouped derived table."""
        if isinstance(e, ast.ScalarSubquery):
            from presto_tpu.plan.builder import _contains_agg

            sub = e.query
            if (
                sub.group_by
                or len(sub.select) != 1
                or not _contains_agg(sub.select[0].expr)
            ):
                return e
            # count over an empty group is 0, not NULL: bare count()
            # rewrites with a coalesce + LEFT join; count buried in an
            # expression (count(*)+1) has no join-side compensation —
            # leave it to fail loudly rather than answer wrongly
            expr0 = sub.select[0].expr
            is_count = (isinstance(expr0, ast.FunctionCall)
                        and expr0.name.lower() in ("count", "count_if"))
            if not is_count and _contains_count(expr0):
                return e
            corr = _find_correlation(sub, self.catalog, self.ctes)
            if corr is None:
                return e  # uncorrelated: handled as a Param at plan time
            pairs, rest = corr
            self.counter += 1
            alias = f"__dt{self.counter}"
            key_items = [
                ast.SelectItem(inner, f"__ck{i}") for i, (_, inner) in enumerate(pairs)
            ]
            dq = ast.Query(
                select=key_items + [ast.SelectItem(sub.select[0].expr, "__agg")],
                from_=sub.from_,
                where=_combine(rest),
                group_by=[inner for _, inner in pairs],
            )
            dq.ctes = sub.ctes
            dt = ast.SubqueryRelation(dq, alias)
            cond = _combine([
                ast.BinaryOp("eq", ast.Identifier((alias, f"__ck{i}")), outer)
                for i, (outer, _) in enumerate(pairs)
            ])
            self._pending.append(
                ("left" if is_count else self._mode, dt, cond))
            ident = ast.Identifier((alias, "__agg"))
            if is_count:
                return ast.FunctionCall(
                    "coalesce", [ident, ast.Literal(0, "integer", "0")])
            return ident
        if isinstance(e, ast.BinaryOp):
            e.left = self._rewrite_scalar(e.left)
            e.right = self._rewrite_scalar(e.right)
        if isinstance(e, ast.UnaryOp):
            e.operand = self._rewrite_scalar(e.operand)
        if isinstance(e, ast.FunctionCall):
            e.args = [self._rewrite_scalar(a) for a in e.args]
        if isinstance(e, ast.Cast):
            e.value = self._rewrite_scalar(e.value)
        if isinstance(e, ast.Case):
            if e.operand is not None:
                e.operand = self._rewrite_scalar(e.operand)
            e.whens = [(self._rewrite_scalar(w), self._rewrite_scalar(t))
                       for w, t in e.whens]
            if e.default is not None:
                e.default = self._rewrite_scalar(e.default)
        if isinstance(e, ast.Between):
            e.value = self._rewrite_scalar(e.value)
            e.low = self._rewrite_scalar(e.low)
            e.high = self._rewrite_scalar(e.high)
        if isinstance(e, ast.IsNull):
            e.value = self._rewrite_scalar(e.value)
        if isinstance(e, ast.InList):
            e.value = self._rewrite_scalar(e.value)
            e.items = [self._rewrite_scalar(x) for x in e.items]
        return e


def _contains_count(n) -> bool:
    if isinstance(n, ast.FunctionCall) and n.name.lower() in ("count",
                                                              "count_if"):
        return True
    return any(_contains_count(c) for c in _children(n))


def decorrelate(q: ast.Query, catalog: Catalog, ctes: Dict[str, ast.Query]) -> ast.Query:
    import copy

    # the rewrites mutate expressions and FROM trees in place; a CTE body
    # is re-planned per reference from the SAME stored AST, so rewrite a
    # private deep copy (the reference rewrites immutable plan trees)
    q = copy.deepcopy(q)
    d = Decorrelator(catalog, dict(ctes))
    for name, sub in q.ctes:
        d.ctes[name] = sub
    d._pending = []
    d.rewrite_where(q)
    d.rewrite_select(q)
    return q
