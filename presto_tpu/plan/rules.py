"""Iterative rule-based optimizer — pattern-matched plan rewrites to
fixpoint.

Reference: sql/planner/iterative/IterativeOptimizer.java + Rule.java and
the presto-matching pattern DSL (Pattern.typeOf().matching(...)): rules
declare a node pattern and a rewrite; the driver applies them bottom-up
until no rule fires (with a trip-count guard). The big visitor passes
(filter pushdown, column pruning — plan/optimizer.py) stay as passes;
this engine hosts the local algebraic rewrites the reference expresses
as iterative/rule/*.java.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from presto_tpu.expr.ir import InputRef, substitute_refs
from presto_tpu.plan.nodes import (
    Filter,
    Limit,
    PlanNode,
    Project,
    Sort,
)


class Pattern:
    """typeOf(cls).matching(pred) — the matching-DSL surface."""

    def __init__(self, node_type, pred: Optional[Callable] = None):
        self.node_type = node_type
        self.pred = pred

    @staticmethod
    def type_of(node_type) -> "Pattern":
        return Pattern(node_type)

    def matching(self, pred: Callable) -> "Pattern":
        return Pattern(self.node_type, pred)

    def matches(self, node) -> bool:
        if not isinstance(node, self.node_type):
            return False
        return self.pred is None or bool(self.pred(node))


class Rule:
    """Subclasses set `pattern` and implement apply() → replacement node
    or None (no change)."""

    pattern: Pattern

    def apply(self, node: PlanNode) -> Optional[PlanNode]:
        raise NotImplementedError


# -- the rule set -----------------------------------------------------------


class MergeAdjacentFilters(Rule):
    """Filter(Filter(x)) → Filter(x, a AND b)
    (reference: iterative/rule/MergeFilters)."""

    pattern = Pattern.type_of(Filter).matching(
        lambda n: isinstance(n.child, Filter))

    def apply(self, node: Filter):
        from presto_tpu.expr.ir import Call
        from presto_tpu.types import BOOLEAN

        inner = node.child
        return Filter(inner.child,
                      Call(BOOLEAN, "and", (inner.predicate, node.predicate)))


class RemoveIdentityProject(Rule):
    """Project that re-emits its child's columns unchanged disappears
    (reference: iterative/rule/RemoveRedundantIdentityProjections)."""

    pattern = Pattern.type_of(Project)

    def apply(self, node: Project):
        child_names = [n for n, _ in node.child.output]
        if len(node.exprs) != len(child_names):
            return None
        if all(isinstance(e, InputRef) and e.name == s and s == cn
               for (s, e), cn in zip(node.exprs, child_names)):
            return node.child
        return None


class CollapseAdjacentProjects(Rule):
    """Project(Project(x)) → Project(x) with inner expressions substituted
    into the outer ones (reference: iterative/rule/MergeProjections /
    InlineProjections). Substitution only when every outer reference to a
    non-trivial inner expression is used ONCE — duplicating a computed
    expression would re-evaluate it."""

    pattern = Pattern.type_of(Project).matching(
        lambda n: isinstance(n.child, Project))

    def apply(self, node: Project):
        from presto_tpu.expr.ir import Call, LambdaExpr

        inner: Project = node.child
        mapping = {s: e for s, e in inner.exprs}
        uses: dict = {}

        def count(e):  # per OCCURRENCE, not per distinct symbol
            if isinstance(e, InputRef):
                uses[e.name] = uses.get(e.name, 0) + 1
            elif isinstance(e, LambdaExpr):
                count(e.body)
            elif isinstance(e, Call):
                for a in e.args:
                    count(a)

        for _, e in node.exprs:
            count(e)
        for s, e in inner.exprs:
            if not isinstance(e, InputRef) and uses.get(s, 0) > 1:
                return None  # would duplicate a computed expression
        new_exprs = [(s, substitute_refs(e, mapping)) for s, e in node.exprs]
        return Project(inner.child, new_exprs)


class MergeLimits(Rule):
    """Limit(Limit(x)) → Limit(x, min) (reference: MergeLimits)."""

    pattern = Pattern.type_of(Limit).matching(
        lambda n: isinstance(n.child, Limit))

    def apply(self, node: Limit):
        return Limit(node.child.child, min(node.count, node.child.count))


class LimitIntoSort(Rule):
    """Limit(Sort(x)) → Sort(x, limit) — a TopN instead of a full sort
    (reference: LimitPushDown / TopN creation)."""

    pattern = Pattern.type_of(Limit).matching(
        lambda n: isinstance(n.child, Sort))

    def apply(self, node: Limit):
        s: Sort = node.child
        limit = node.count if s.limit is None else min(node.count, s.limit)
        return Sort(s.child, s.keys, limit)


class LimitThroughProject(Rule):
    """Limit(Project(x)) → Project(Limit(x)) — limits travel toward the
    source (reference: PushLimitThroughProject)."""

    pattern = Pattern.type_of(Limit).matching(
        lambda n: isinstance(n.child, Project))

    def apply(self, node: Limit):
        p: Project = node.child
        return Project(Limit(p.child, node.count), p.exprs)


DEFAULT_RULES: List[Rule] = [
    MergeAdjacentFilters(),
    CollapseAdjacentProjects(),
    RemoveIdentityProject(),
    MergeLimits(),
    LimitIntoSort(),
    LimitThroughProject(),
]

_CHILD_ATTRS = ("child", "left", "right")


class IterativeOptimizer:
    """Bottom-up fixpoint driver with a trip-count guard
    (IterativeOptimizer.java's exploration loop without the memo/groups —
    the plan is a tree here, not a DAG of group references)."""

    def __init__(self, rules: Optional[List[Rule]] = None,
                 max_passes: int = 20):
        self.rules = list(rules or DEFAULT_RULES)
        self.max_passes = max_passes

    def optimize(self, root: PlanNode) -> PlanNode:
        for _ in range(self.max_passes):
            root, changed = self._rewrite(root)
            if not changed:
                break
        return root

    def _rewrite(self, node: PlanNode):
        changed = False
        for attr in _CHILD_ATTRS:
            child = getattr(node, attr, None)
            if isinstance(child, PlanNode):
                new_child, ch = self._rewrite(child)
                if ch:
                    setattr(node, attr, new_child)
                    changed = True
        for rule in self.rules:
            if rule.pattern.matches(node):
                out = rule.apply(node)
                if out is not None and out is not node:
                    return out, True
        return node, changed
