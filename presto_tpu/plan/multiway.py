"""Multiway join collapse: left-deep chains of inner/left equi-joins
sharing one probe pipeline (the star-schema shape of q3/q5/q9/q64) fold
into a single MultiwayJoin node — N resident builds, one probe pass, one
breaker program per fragment instead of one per join (PAPERS.md
1905.13376; ROADMAP item 6).

Runs AFTER optimize(), at plan-install time, because the verdict is
config-dependent (`join_mode` session property) and history-corrected
(HBO): the same SQL collapses differently per session. `join_mode=off`
skips the pass entirely — the plan is bit-for-bit the pre-collapse tree.

Eligibility is structural; the binary-vs-multiway choice is
plan/stats.choose_join_mode's. A chain join is collapsible when it is an
inner/left HashJoin with no residual and no colocated bucketing, and
every probe key resolves against the base probe's output or the payload
of an EARLIER build with `build_unique` — a probe row then has at most
one match there, so the key value is well-defined per probe row without
materializing the intermediate (snowflake chains like
lineitem⋈orders⋈customer)."""

from __future__ import annotations

from presto_tpu.plan.nodes import HashJoin, MultiwayJoin, PlanNode
from presto_tpu.plan.stats import choose_join_mode, invalidate

# child attributes rewritten in place while walking (plan nodes are
# dataclasses; `builds` is MultiwayJoin's own list attr)
_CHILD_ATTRS = ("child", "left", "right", "probe")


def _chain_join_ok(j: HashJoin) -> bool:
    return (isinstance(j, HashJoin) and j.kind in ("inner", "left")
            and j.residual is None and not j.colocated)


def _gather_chain(top: HashJoin):
    """(base, chain bottom-up) for the maximal left spine of collapsible
    joins under `top`; chain[0] probes `base`."""
    chain = []
    cur: PlanNode = top
    while _chain_join_ok(cur):
        chain.append(cur)
        cur = cur.left
    chain.reverse()
    return cur, chain


def _eligible_prefix(base: PlanNode, chain):
    """Length of the longest bottom-up prefix whose probe keys all
    resolve against the base output or an earlier unique build's
    payload."""
    avail = {s for s, _ in base.output}
    unique_payload = set()
    m = 0
    for j in chain:
        ok = all(k in avail or k in unique_payload for k in j.left_keys)
        if not ok:
            break
        m += 1
        if j.build_unique:
            unique_payload |= {s for s, _ in j.right.output}
        # non-unique payload is never a later key source, but it IS part
        # of the probe pipeline's passthrough output — no avail update
    return m


def _key_source(sym: str, base: PlanNode, chain_prefix):
    """-1 when `sym` is a base-probe column, else the 0-based index of
    the (unique) build whose payload carries it."""
    if sym in {s for s, _ in base.output}:
        return -1
    for i, j in enumerate(chain_prefix):
        if sym in {s for s, _ in j.right.output}:
            return i
    raise KeyError(sym)


def _collapse(top: HashJoin, catalog, mode: str, hbo: str):
    """One collapse attempt at `top`. Returns the replacement node (the
    MultiwayJoin, possibly still nested under the chain's upper
    non-collapsed joins) or None to keep the binary tree."""
    base, chain = _gather_chain(top)
    m = _eligible_prefix(base, chain)
    if m < 2:
        return None
    chain_m = chain[:m]
    verdict, why = choose_join_mode(chain_m, catalog, override=mode,
                                    hbo=hbo)
    if verdict != "multiway":
        top.__dict__["_join_mode"] = "binary"
        top.__dict__["_join_mode_why"] = why
        return None
    node = MultiwayJoin(
        probe=base,
        builds=[j.right for j in chain_m],
        kinds=[j.kind for j in chain_m],
        probe_keys=[list(j.left_keys) for j in chain_m],
        build_keys=[list(j.right_keys) for j in chain_m],
        build_unique=[bool(j.build_unique) for j in chain_m],
    )
    node.__dict__["_join_mode"] = "multiway"
    node.__dict__["_join_mode_why"] = why
    try:
        # local-only provenance: the top collapsed join's structural
        # fingerprint, so the multiway run can feed selectivity history
        # back to the fingerprint choose_join_mode consults next time
        # (stripped from wire plans by strip_runtime_state)
        from presto_tpu.obs import runstats
        node.__dict__["_origin_fp"] = runstats.node_fingerprint(
            chain_m[-1], catalog)
        # the ORIGINAL binary joins' fingerprints, leg by leg: the
        # executor feeds per-leg build rows and the bottom join's probe
        # selectivity back to the exact fps choose_join_mode consults
        node.__dict__["_leg_fps"] = [
            runstats.node_fingerprint(j, catalog) for j in chain_m]
    except Exception:
        pass
    # joins above the eligible prefix stay binary on top of the collapse
    for j in chain[m:]:
        j.left = node
        node = j
    return node


def collapse_multiway(root: PlanNode, catalog, mode: str = "auto",
                      hbo: str = "off") -> PlanNode:
    """Walk the tree collapsing eligible chains (top-down: the outermost
    chain wins its full length). Mutates children in place like the
    optimizer passes; returns the (possibly new) root."""
    if isinstance(root, HashJoin):
        replaced = _collapse(root, catalog, mode, hbo)
        if replaced is not None:
            root = replaced
    for attr in _CHILD_ATTRS:
        c = getattr(root, attr, None)
        if isinstance(c, PlanNode):
            setattr(root, attr, collapse_multiway(c, catalog, mode, hbo))
    if isinstance(root, MultiwayJoin):
        root.builds = [collapse_multiway(b, catalog, mode, hbo)
                       for b in root.builds]
    return root


def apply_join_mode(qp, catalog, config) -> None:
    """Config-gated entry point: rewrite a QueryPlan in place after
    optimize(). `join_mode=off` leaves the plan untouched (bit-for-bit
    the pre-collapse path)."""
    mode = getattr(config, "join_mode", "auto")
    if mode == "off":
        return
    hbo = getattr(config, "hbo", "observe")
    root = collapse_multiway(qp.root, catalog, mode, hbo)
    invalidate(root)
    qp.root = root
