from presto_tpu.plan.nodes import (
    PlanNode,
    TableScan,
    Filter,
    Project,
    Aggregate,
    AggSpec,
    HashJoin,
    SemiJoin,
    Sort,
    SortItem,
    Limit,
    Output,
    QueryPlan,
)
from presto_tpu.plan.builder import plan_query

__all__ = [
    "PlanNode", "TableScan", "Filter", "Project", "Aggregate", "AggSpec",
    "HashJoin", "SemiJoin", "Sort", "SortItem", "Limit", "Output",
    "QueryPlan", "plan_query",
]
