"""Cost-based statistics derivation — CBO v1.

Reference: presto-main/.../cost/ (44 files): StatsCalculator walks the plan
deriving PlanNodeStatsEstimate per node; FilterStatsCalculator estimates
conjunct selectivities from column NDV/range stats; JoinStatsRule estimates
join output as |L|·|R| / max(NDV); consumed by ReorderJoins.java:94 and
DetermineJoinDistributionType.java:46.

TPU-native shape: connectors supply ColumnStats (NDV, null fraction,
min/max — exact for the generator connectors, footer-derived for parquet).
`derive(node)` recursively computes (rows, per-symbol ColumnStats),
memoized on the node. Consumers: join ordering (builder._assemble_joins),
broadcast-vs-partitioned choice (fragmenter stats_fn), and group-table
capacity selection (Aggregate.estimated_groups → ExecConfig.agg_capacity
override)."""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from presto_tpu.connector import ColumnStats
from presto_tpu.expr.ir import Call, Constant, InputRef, RowExpression
from presto_tpu.plan.nodes import (
    Aggregate,
    Filter,
    HashJoin,
    Limit,
    MultiwayJoin,
    Output,
    PlanNode,
    Project,
    RemoteSource,
    SemiJoin,
    SetOp,
    Sort,
    TableScan,
    Window,
)

# fallback selectivities when column stats can't answer (the reference's
# FilterStatsCalculator UNKNOWN_FILTER_COEFFICIENT is 0.9; we keep the
# legacy engine defaults, which are tuned for TPC-H-ish predicates)
UNKNOWN_FILTER_SEL = 0.25
UNKNOWN_EQ_SEL = 0.1


@dataclasses.dataclass
class NodeStats:
    rows: float
    columns: Dict[str, ColumnStats] = dataclasses.field(default_factory=dict)

    def col(self, sym: str) -> Optional[ColumnStats]:
        return self.columns.get(sym)


def _scalar(v) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _range_fraction(cs: ColumnStats, lo: Optional[float], hi: Optional[float]) -> Optional[float]:
    """Fraction of ROWS in [lo, hi]: histogram-weighted when the column
    carries one (robust to skew), else the uniform [min,max] model
    (FilterStatsCalculator's range estimate)."""
    if cs.min_value is None or cs.max_value is None:
        return None
    width = cs.max_value - cs.min_value
    if width <= 0:
        return 1.0
    a = cs.min_value if lo is None else max(lo, cs.min_value)
    b = cs.max_value if hi is None else min(hi, cs.max_value)
    if b < a:
        return 0.0
    if cs.histogram and len(cs.histogram) >= 2:
        edges = cs.histogram  # equi-depth: each bin holds 1/nb of rows
        nb = len(edges) - 1
        covered = 0.0
        for i in range(nb):
            blo, bhi = edges[i], edges[i + 1]
            if bhi <= blo:
                # zero-width bin (heavy repeated value): counted fully
                # when the point lies inside [a, b]
                covered += 1.0 if a <= blo <= b else 0.0
                continue
            olo, ohi = max(a, blo), min(b, bhi)
            if ohi > olo:
                covered += (ohi - olo) / (bhi - blo)
        return min(1.0, covered / nb)
    return min(1.0, (b - a) / width)


def _conjunct_selectivity(e: RowExpression, stats: NodeStats) -> float:
    if isinstance(e, Call):
        fn = e.fn
        if fn == "and":
            return (_conjunct_selectivity(e.args[0], stats)
                    * _conjunct_selectivity(e.args[1], stats))
        if fn == "or":
            a = _conjunct_selectivity(e.args[0], stats)
            b = _conjunct_selectivity(e.args[1], stats)
            return min(1.0, a + b - a * b)
        if fn == "not":
            return max(0.0, 1.0 - _conjunct_selectivity(e.args[0], stats))
        ref = next((a for a in e.args if isinstance(a, InputRef)), None)
        const = next((a for a in e.args if isinstance(a, Constant)), None)
        cs = stats.col(ref.name) if ref is not None else None
        if fn == "eq" and cs is not None and cs.ndv:
            return min(1.0, 1.0 / cs.ndv)
        if fn == "ne" and cs is not None and cs.ndv:
            return max(0.0, 1.0 - 1.0 / cs.ndv)
        if fn in ("lt", "le", "gt", "ge") and cs is not None and const is not None:
            # normalize to "ref OP const": a constant on the LEFT mirrors
            # the comparison (const < ref  ≡  ref > const)
            if len(e.args) >= 2 and isinstance(e.args[0], Constant):
                fn = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}[fn]
            v = _scalar(const.value)
            if v is not None:
                frac = (_range_fraction(cs, None, v) if fn in ("lt", "le")
                        else _range_fraction(cs, v, None))
                if frac is not None:
                    return frac
        if fn == "between" and cs is not None and len(e.args) == 3:
            lo = _scalar(e.args[1].value) if isinstance(e.args[1], Constant) else None
            hi = _scalar(e.args[2].value) if isinstance(e.args[2], Constant) else None
            frac = _range_fraction(cs, lo, hi)
            if frac is not None:
                return frac
        if fn == "in":
            k = max(1, len(e.args) - 1)
            if cs is not None and cs.ndv:
                return min(1.0, k / cs.ndv)
            return min(1.0, k * UNKNOWN_EQ_SEL)
        if fn == "is_null":
            return cs.null_fraction if cs is not None and cs.null_fraction is not None else 0.05
        if fn == "is_not_null":
            nf = cs.null_fraction if cs is not None and cs.null_fraction is not None else 0.05
            return 1.0 - nf
        if fn == "eq":
            return UNKNOWN_EQ_SEL
        if fn == "like":
            return UNKNOWN_FILTER_SEL
    return UNKNOWN_FILTER_SEL


def filter_selectivity(pred: RowExpression, stats: NodeStats) -> float:
    return max(1e-6, min(1.0, _conjunct_selectivity(pred, stats)))


def _scale_ndv(cs: ColumnStats, factor: float) -> ColumnStats:
    """NDV after keeping `factor` of rows (capped at NDV — the reference
    caps distinct counts by output rows the same way)."""
    ndv = cs.ndv
    if ndv is not None and factor < 1.0:
        # uniform-draw model: expected distinct after sampling
        ndv = ndv * (1.0 - math.exp(-max(factor, 1e-9)))
        ndv = max(1.0, min(cs.ndv, ndv / (1.0 - math.exp(-1.0))))
    # equi-depth edges describe the value distribution, which filtering on
    # OTHER columns leaves unchanged — carry them through
    return ColumnStats(ndv, cs.null_fraction, cs.min_value, cs.max_value,
                       histogram=cs.histogram)


def derive(node: PlanNode, catalog) -> Optional[NodeStats]:
    """Recursive memoized stats derivation (StatsCalculator.getStats)."""
    memo = node.__dict__.get("_node_stats", "__unset__")
    if memo != "__unset__":
        return memo
    s = _derive(node, catalog)
    node.__dict__["_node_stats"] = s
    return s


def invalidate(node: PlanNode):
    node.__dict__.pop("_node_stats", None)
    for c in node.children():
        invalidate(c)


def _derive(node: PlanNode, catalog) -> Optional[NodeStats]:
    if isinstance(node, TableScan):
        if catalog is None:
            return None
        try:
            conn = catalog.connectors[node.catalog]
            handle = conn.get_table(node.table)
        except Exception:
            return None
        rows = float(handle.row_count or 0) or 1e6
        cols = {}
        for sym, cname in node.assignments.items():
            try:
                ci = handle.column(cname)
            except KeyError:
                continue
            if ci.stats is not None:
                cols[sym] = ci.stats
            elif ci.dictionary is not None:
                cols[sym] = ColumnStats(ndv=float(len(ci.dictionary)))
        if handle.primary_key and len(handle.primary_key) == 1:
            pk = handle.primary_key[0]
            for sym, cname in node.assignments.items():
                if cname == pk:
                    prev = cols.get(sym) or ColumnStats()
                    cols[sym] = dataclasses.replace(
                        prev, ndv=rows, null_fraction=0.0)
        # NOTE: scan `constraints` are split-pruning hints extracted from a
        # Filter that REMAINS in the plan — scaling here too would double
        # count the selectivity (the Filter rule above accounts for it)
        return NodeStats(rows, cols)
    if isinstance(node, Filter):
        child = derive(node.child, catalog)
        if child is None:
            return None
        sel = filter_selectivity(node.predicate, child)
        return NodeStats(max(1.0, child.rows * sel),
                         {k: _scale_ndv(v, sel) for k, v in child.columns.items()})
    if isinstance(node, Project):
        child = derive(node.child, catalog)
        if child is None:
            return None
        cols = {}
        for sym, e in node.exprs:
            if isinstance(e, InputRef) and e.name in child.columns:
                cols[sym] = child.columns[e.name]
        return NodeStats(child.rows, cols)
    if isinstance(node, HashJoin):
        left = derive(node.left, catalog)
        right = derive(node.right, catalog)
        if left is None or right is None:
            return None
        ndvs = []
        for lk, rk in zip(node.left_keys, node.right_keys):
            lc, rc = left.col(lk), right.col(rk)
            if lc is not None and lc.ndv:
                ndvs.append(lc.ndv)
            if rc is not None and rc.ndv:
                ndvs.append(rc.ndv)
        if ndvs:
            out_rows = left.rows * right.rows / max(ndvs)
        else:
            out_rows = max(left.rows, right.rows)
        if node.kind in ("left", "full"):
            out_rows = max(out_rows, left.rows)
        if node.kind == "full":
            out_rows = out_rows + right.rows * 0.1
        cols = dict(left.columns)
        cols.update(right.columns)
        return NodeStats(max(1.0, out_rows), cols)
    if isinstance(node, MultiwayJoin):
        cur = derive(node.probe, catalog)
        if cur is None:
            return None
        rows = cur.rows
        cols = dict(cur.columns)
        # leg-by-leg application of the binary join model — the collapse
        # is semantics-preserving, so the chain estimate is too
        for b, kind, pks, bks in zip(node.builds, node.kinds,
                                     node.probe_keys, node.build_keys):
            bs = derive(b, catalog)
            if bs is None:
                return None
            ndvs = []
            for lk, rk in zip(pks, bks):
                lc, rc = cols.get(lk), bs.col(rk)
                if lc is not None and lc.ndv:
                    ndvs.append(lc.ndv)
                if rc is not None and rc.ndv:
                    ndvs.append(rc.ndv)
            out = rows * bs.rows / max(ndvs) if ndvs else max(rows, bs.rows)
            if kind == "left":
                out = max(out, rows)
            rows = out
            cols.update(bs.columns)
        return NodeStats(max(1.0, rows), cols)
    if isinstance(node, SemiJoin):
        left = derive(node.left, catalog)
        if left is None:
            return None
        sel = 0.5
        return NodeStats(max(1.0, left.rows * sel), left.columns)
    if isinstance(node, Aggregate):
        child = derive(node.child, catalog)
        if child is None:
            return None
        if not node.group_keys:
            return NodeStats(1.0, {})
        prod = 1.0
        known = True
        for k in node.group_keys:
            cs = child.col(k)
            if cs is not None and cs.ndv:
                prod *= cs.ndv
            else:
                known = False
        groups = min(prod, child.rows) if known else max(1.0, child.rows * 0.1)
        cols = {k: child.columns[k] for k in node.group_keys if k in child.columns}
        return NodeStats(max(1.0, groups), cols)
    if isinstance(node, SetOp):
        left = derive(node.left, catalog)
        right = derive(node.right, catalog)
        if left is None or right is None:
            return None
        rows = left.rows + right.rows
        if node.kind == "intersect":
            rows = min(left.rows, right.rows)
        elif node.kind == "except":
            rows = left.rows
        return NodeStats(rows, {})
    if isinstance(node, (Sort, Window)):
        child = derive(node.child, catalog)
        if child is None:
            return None
        if isinstance(node, Sort) and node.limit is not None:
            return NodeStats(min(float(node.limit), child.rows), child.columns)
        return NodeStats(child.rows, child.columns)
    if isinstance(node, Limit):
        child = derive(node.child, catalog)
        rows = float(node.count)
        if child is not None:
            rows = min(rows, child.rows)
        return NodeStats(rows, child.columns if child else {})
    if isinstance(node, Output):
        return derive(node.child, catalog)
    if isinstance(node, RemoteSource):
        return None
    return None


# ---------------------------------------------------------------------------
# exchange lane sizing: how many rows the fullest (src device, dst
# partition) lane of an OUT_HASH exchange must hold. The prototype mesh
# exchange padded every lane to capacity//n_dev*2 — ICI bytes tracked the
# batch's padding, not its rows. Stats size the lane instead; under-
# estimates are safe because the executor's per-site overflow replay
# (parallel/mesh_exec) doubles exactly the lane that overflowed.

# multiplied onto the per-lane row estimate: absorbs hash placement
# variance and moderate skew without triggering a replay
EXCHANGE_SKEW_HEADROOM = 2.0


def combined_key_ndv(stats: NodeStats, keys) -> Optional[float]:
    """Combined NDV of a key tuple: product of per-key NDVs capped by the
    row count (the reference caps distinct counts by output rows the same
    way). None when no key has an estimate."""
    prod, known = 1.0, False
    for k in keys:
        cs = stats.col(k)
        if cs is not None and cs.ndv:
            prod *= cs.ndv
            known = True
    if not known:
        return None
    return min(prod, stats.rows) if stats.rows else prod


def exchange_lane_rows(rows: float, key_ndv: Optional[float],
                       n_dev: int,
                       observed_lane_rows: Optional[float] = None) -> float:
    """Estimated rows in the FULLEST lane of an n_dev-way hash exchange.

    A lane is one (source device, destination partition) bucket: each
    device holds ~rows/n_dev and splits them n_dev ways, so the uniform
    expectation is rows/n_dev². Low-NDV keys concentrate load: partition
    p receives ~ceil(ndv/n_dev) whole keys of ~rows/ndv rows each, of
    which each source device contributes a 1/n_dev share — the max of the
    two models sizes the lane, times EXCHANGE_SKEW_HEADROOM.

    ``observed_lane_rows`` (HBO, runstats history) is a measured fullest-
    lane high-water mark from a previous run of the same structure: it
    replaces the model entirely, with modest padding instead of the blind
    skew headroom."""
    if observed_lane_rows is not None and observed_lane_rows > 0:
        return max(1.0, float(observed_lane_rows) * 1.25)
    if rows <= 0:
        return 1.0
    if n_dev <= 1:
        return max(1.0, rows)
    per_lane = rows / (n_dev * n_dev)
    if key_ndv and key_ndv > 0:
        per_part = (rows / key_ndv) * math.ceil(key_ndv / n_dev)
        per_lane = max(per_lane, per_part / n_dev)
    return max(1.0, per_lane * EXCHANGE_SKEW_HEADROOM)


# ---------------------------------------------------------------------------
# breaker engine choice: sort-based vs Pallas linear-probing hash table
# (ops/pallas_hash). The hash engine wins when the group/build table is
# SMALL and rows hit it repeatedly — each row costs O(probe chain) serial
# work instead of participating in an O((cap + batch) log) sort — and
# loses when the table is large (long kernel, big planes) or barely
# reused. The reference analog is DetermineJoinDistributionType: a
# stats-driven physical-strategy pick recorded on the plan node.

# above this many estimated groups the group table stops being "small":
# the insert kernel's serial row loop dominates and the sort engine's
# O(n log n) batched primitives win
HASH_MAX_GROUPS = 1 << 12
# minimum rows-per-group duplication for keyed aggregation: near-distinct
# keys mean the hash table does no reduction, all insert cost
HASH_MIN_DUPLICATION = 4.0
# join/semijoin build sides larger than this probe too long a chain under
# skew and carry wide slot_row tables
HASH_MAX_BUILD_ROWS = 1 << 13
# each key adds an int64 plane every kernel walks per probe step; wide
# key tuples (and wide agg payloads) favor the sort engine's columnar ops
HASH_MAX_KEY_WIDTH = 6
HASH_MAX_PAYLOAD_STATES = 16


def _observed(node: PlanNode, catalog, site: str):
    """History entry for this node's structural fingerprint, or None.
    Lazy import: obs/runstats imports obs/metrics only, but keep the CBO
    importable even if the observability plane is stripped."""
    try:
        from presto_tpu.obs import runstats
        return runstats.lookup_node(node, catalog, site)
    except Exception:
        return None


def choose_breaker_engine(node: PlanNode, catalog,
                          override: str = "auto", hbo: str = "off"):
    """(engine, why) for a pipeline breaker: ``engine`` ∈ {sort, hash}.

    ``override`` is the ``breaker_engine`` session property: ``sort`` /
    ``hash`` force the engine; ``auto`` asks the stats above. No stats →
    sort (never regress the known-good engine on a blind guess).

    ``hbo="correct"`` consults the runstats history first: a previous run
    of the same structural fingerprint replaces the estimated group /
    build-row counts with observed ones, and the why string carries an
    ``(hbo: observed)`` provenance suffix."""
    if override == "sort":
        return "sort", "session breaker_engine=sort"
    if override == "hash":
        return "hash", "session breaker_engine=hash"
    if isinstance(node, Aggregate):
        if not node.group_keys:
            return "sort", "global aggregate"
        if len(node.group_keys) > HASH_MAX_KEY_WIDTH:
            return "sort", f"{len(node.group_keys)} group keys > {HASH_MAX_KEY_WIDTH}"
        if len(node.aggs) > HASH_MAX_PAYLOAD_STATES:
            return "sort", f"{len(node.aggs)} agg states > {HASH_MAX_PAYLOAD_STATES}"
        groups = None
        src, suffix = "est", ""
        if hbo == "correct":
            h = _observed(node, catalog, "agg_groups")
            if h and h.get("actual"):
                groups = float(h["actual"])
                src, suffix = "observed", " (hbo: observed)"
        st = derive(node, catalog)
        child = derive(node.child, catalog)
        if groups is None:
            if st is None or child is None or not st.rows or not child.rows:
                return "sort", "no stats"
            groups = st.rows
        rows = child.rows if (child is not None and child.rows) else None
        if rows is None:
            # observed groups without an input-row estimate: assume enough
            # duplication that the group-count threshold alone decides
            rows = groups * HASH_MIN_DUPLICATION
        if groups > HASH_MAX_GROUPS:
            return "sort", f"{src} {groups:.3g} groups > {HASH_MAX_GROUPS}{suffix}"
        dup = rows / max(groups, 1.0)
        if dup < HASH_MIN_DUPLICATION:
            return "sort", f"duplication x{dup:.2g} < {HASH_MIN_DUPLICATION:.2g}{suffix}"
        return "hash", f"{src} {groups:.3g} groups, x{dup:.3g} duplication{suffix}"
    if isinstance(node, (HashJoin, SemiJoin)):
        keys = node.right_keys
        if len(keys) > HASH_MAX_KEY_WIDTH:
            return "sort", f"{len(keys)} join keys > {HASH_MAX_KEY_WIDTH}"
        build_rows = None
        src, suffix = "est", ""
        if hbo == "correct":
            h = _observed(node, catalog, "join_build")
            if h and h.get("actual"):
                build_rows = float(h["actual"])
                src, suffix = "observed", " (hbo: observed)"
        if build_rows is None:
            build = derive(node.right, catalog)
            if build is None or not build.rows:
                return "sort", "no build-side stats"
            build_rows = build.rows
        if build_rows > HASH_MAX_BUILD_ROWS:
            return "sort", f"{src} build {build_rows:.3g} rows > {HASH_MAX_BUILD_ROWS}{suffix}"
        return "hash", f"{src} build {build_rows:.3g} rows{suffix}"
    return "sort", "not an engine-dimensioned breaker"


def choose_breaker_engine_observed(node: PlanNode, groups: float,
                                   rows: Optional[float] = None):
    """(engine, why) from OBSERVED telemetry — the in-run adaptive analog
    of ``choose_breaker_engine``. Same sort/hash thresholds, but the
    group count is the replay wave's confirmed ``ng`` and the row count
    is the host-known dispatched-capacity watermark, so the verdict
    reflects what THIS run actually saw instead of derived estimates.
    Structural guards (key width, payload states, global agg) match the
    estimate path — a shape the hash engine cannot take never flips."""
    if isinstance(node, Aggregate):
        if not node.group_keys:
            return "sort", "global aggregate"
        if len(node.group_keys) > HASH_MAX_KEY_WIDTH:
            return "sort", f"{len(node.group_keys)} group keys > {HASH_MAX_KEY_WIDTH}"
        if len(node.aggs) > HASH_MAX_PAYLOAD_STATES:
            return "sort", f"{len(node.aggs)} agg states > {HASH_MAX_PAYLOAD_STATES}"
        groups = float(max(groups, 1.0))
        if groups > HASH_MAX_GROUPS:
            return "sort", (f"observed {groups:.3g} groups > "
                            f"{HASH_MAX_GROUPS} (adaptive: observed)")
        if rows is None:
            rows = groups * HASH_MIN_DUPLICATION
        dup = float(rows) / groups
        if dup < HASH_MIN_DUPLICATION:
            return "sort", (f"observed duplication x{dup:.2g} < "
                            f"{HASH_MIN_DUPLICATION:.2g} (adaptive: observed)")
        return "hash", (f"observed {groups:.3g} groups, x{dup:.3g} "
                        f"duplication (adaptive: observed)")
    return "sort", "not an engine-dimensioned breaker"


# ---------------------------------------------------------------------------
# binary-vs-multiway join chain choice (plan/multiway.py collapse pass).
# Multiway keeps N build tables resident and walks every probe row through
# all N probes in one compiled pass — it wins when the chain's joins are
# not so selective that a binary cascade would shrink the intermediate
# stream early (multiway probes table i for rows a selective join i-1
# would already have dropped), and when the combined builds fit residency.

# combined build rows past which the resident-builds assumption is off —
# the collapse declines and the binary chain keeps its PR 15 spill ladder
MULTIWAY_MAX_BUILD_ROWS = 1 << 22
# non-unique builds probe through the Pallas fanout kernel; past the
# binary hash-engine threshold its serial insert loop dominates
MULTIWAY_MAX_FANOUT_BUILD_ROWS = HASH_MAX_BUILD_ROWS
# observed probe selectivity (output rows / probe rows) of the bottom
# join below which the binary cascade's early filtering wins
MULTIWAY_MIN_SELECTIVITY = 0.02


def choose_join_mode(chain, catalog, override: str = "auto",
                     hbo: str = "off"):
    """(mode, why) for a collapsible left-deep join chain: ``mode`` ∈
    {binary, multiway}. ``chain`` is the eligible HashJoin list bottom-up
    (chain[0] probes the base); ``override`` is the ``join_mode`` session
    property. Mirrors choose_breaker_engine: ``hbo="correct"`` swaps the
    estimated build sizes and bottom-join selectivity for runstats history
    under the joins' structural fingerprints, and the why string carries
    the ``(hbo: observed)`` provenance suffix."""
    n = len(chain)
    if override == "multiway":
        return "multiway", f"session join_mode=multiway ({n} joins)"
    if override in ("binary", "off"):
        return "binary", f"session join_mode={override}"
    total_build = 0.0
    src, suffix = "est", ""
    n_observed = 0
    for j in chain:
        build_rows = None
        if hbo == "correct":
            h = _observed(j, catalog, "join_build")
            if h and h.get("actual"):
                build_rows = float(h["actual"])
                n_observed += 1
                src, suffix = "observed", " (hbo: observed)"
        if build_rows is None:
            build = derive(j.right, catalog)
            if build is None or not build.rows:
                return "binary", "no build-side stats"
            build_rows = build.rows
        if not j.build_unique and build_rows > MULTIWAY_MAX_FANOUT_BUILD_ROWS:
            return "binary", (f"{src} fanout build {build_rows:.3g} rows > "
                              f"{MULTIWAY_MAX_FANOUT_BUILD_ROWS}{suffix}")
        total_build += build_rows
    if total_build > MULTIWAY_MAX_BUILD_ROWS:
        return "binary", (f"{src} combined builds {total_build:.3g} rows > "
                          f"{MULTIWAY_MAX_BUILD_ROWS}{suffix}")
    if n_observed < n:
        # auto fuses only on observed history: a misestimated chain
        # compounds the error N ways and pays every build before the
        # first probe can filter, so estimates alone never flip the
        # plan shape — the binary run itself lands the history
        return "binary", (f"{n - n_observed}/{n} builds lack observed "
                          f"history — binary until hbo=correct repeat")
    sel = None
    sel_src, sel_suffix = "est", ""
    if hbo == "correct":
        h = _observed(chain[0], catalog, "join_probe_sel")
        if h and h.get("actual") is not None:
            sel = float(h["actual"])
            sel_src, sel_suffix = "observed", " (hbo: observed)"
            src, suffix = sel_src, sel_suffix
    if sel is None:
        probe = derive(chain[0].left, catalog)
        out = derive(chain[0], catalog)
        if probe is not None and out is not None and probe.rows:
            sel = out.rows / probe.rows
    if sel is not None and sel < MULTIWAY_MIN_SELECTIVITY and n > 2:
        # deep chain over a near-empty bottom join: the binary cascade
        # filters before paying the upper probes; multiway pays them all
        return "binary", (f"{sel_src} bottom-join selectivity {sel:.3g} < "
                          f"{MULTIWAY_MIN_SELECTIVITY}{sel_suffix}")
    selpart = f", sel {sel:.3g}" if sel is not None else ""
    return "multiway", (f"{n} joins, {src} combined builds "
                        f"{total_build:.3g} rows{selpart}{suffix}")
