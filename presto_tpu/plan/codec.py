"""JSON codec for plan fragments — the wire format of the control plane.

Reference: the coordinator ships TaskUpdateRequest as JSON/Smile DTOs
(server/remotetask/HttpRemoteTask.java + jackson codecs;
InternalCommunicationConfig.java:92 binary option). The round-2 engine
pickled fragments, which makes every secret-bearing client an RCE vector;
this codec encodes the CLOSED plan-node vocabulary explicitly — unknown
node/expression kinds are rejected on decode, and no arbitrary object
construction is reachable from the wire.
"""

from __future__ import annotations

from typing import Any, Dict

from presto_tpu.expr.ir import (
    Call,
    Constant,
    InputRef,
    LambdaExpr,
    Param,
    RowExpression,
)
from presto_tpu.plan.fragmenter import Fragment
from presto_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    HashJoin,
    IndexJoin,
    Limit,
    MultiwayJoin,
    NestedLoopJoin,
    OneRow,
    Output,
    PlanNode,
    Project,
    RemoteSource,
    SemiJoin,
    SetOp,
    Sort,
    SortItem,
    TableScan,
    Unnest,
    Window,
    WindowFunc,
)
from presto_tpu.types import Type, parse_type


class CodecError(ValueError):
    pass


# -- types ------------------------------------------------------------------


def _t(t: Type) -> str:
    return t.name


def _untype(s: str) -> Type:
    return parse_type(s)


# -- expressions ------------------------------------------------------------


def expr_to_json(e: RowExpression) -> Dict[str, Any]:
    if isinstance(e, InputRef):
        return {"k": "ref", "t": _t(e.type), "name": e.name}
    if isinstance(e, Constant):
        v = e.value
        if v is not None and not isinstance(v, (bool, int, float, str)):
            v = v.item() if hasattr(v, "item") else str(v)
        return {"k": "const", "t": _t(e.type), "v": v, "raw": e.raw}
    if isinstance(e, Call):
        return {"k": "call", "t": _t(e.type), "fn": e.fn,
                "args": [expr_to_json(a) for a in e.args]}
    if isinstance(e, Param):
        return {"k": "param", "t": _t(e.type), "name": e.name}
    if isinstance(e, LambdaExpr):
        return {"k": "lambda", "t": _t(e.type),
                "params": [[s, _t(t)] for s, t in e.params],
                "body": expr_to_json(e.body)}
    raise CodecError(f"unencodable expression {type(e).__name__}")


def expr_from_json(d: Dict[str, Any]) -> RowExpression:
    k = d.get("k")
    t = _untype(d["t"])
    if k == "ref":
        return InputRef(t, d["name"])
    if k == "const":
        return Constant(t, d["v"], raw=bool(d.get("raw", False)))
    if k == "call":
        return Call(t, d["fn"], tuple(expr_from_json(a) for a in d["args"]))
    if k == "param":
        return Param(t, d["name"])
    if k == "lambda":
        try:
            params = tuple((s, _untype(ts)) for s, ts in d["params"])
            body = expr_from_json(d["body"])
        except (KeyError, TypeError, ValueError) as e:
            raise CodecError(f"malformed lambda payload: {e}")
        return LambdaExpr(t, params, body)
    raise CodecError(f"unknown expression kind {k!r}")


def _out(node_output) -> list:
    return [[s, _t(t)] for s, t in node_output]


def _unout(lst) -> list:
    return [(s, _untype(t)) for s, t in lst]


# -- plan nodes -------------------------------------------------------------


def node_to_json(n: PlanNode) -> Dict[str, Any]:
    if isinstance(n, TableScan):
        return {"k": "scan", "catalog": n.catalog, "table": n.table,
                "assignments": dict(n.assignments), "output": _out(n.output),
                "constraints": {c: [lo, hi]
                                for c, (lo, hi) in (n.constraints or {}).items()}}
    if isinstance(n, Filter):
        return {"k": "filter", "child": node_to_json(n.child),
                "pred": expr_to_json(n.predicate)}
    if isinstance(n, Project):
        return {"k": "project", "child": node_to_json(n.child),
                "exprs": [[s, expr_to_json(e)] for s, e in n.exprs]}
    if isinstance(n, Aggregate):
        return {"k": "agg", "child": node_to_json(n.child),
                "keys": list(n.group_keys), "step": n.step,
                "aggs": [{"symbol": a.symbol, "fn": a.fn, "arg": a.arg,
                          "t": _t(a.type), "distinct": a.distinct,
                          "arg2": a.arg2, "param": a.param}
                         for a in n.aggs]}
    if isinstance(n, HashJoin):
        return {"k": "join", "kind": n.kind,
                "left": node_to_json(n.left), "right": node_to_json(n.right),
                "lkeys": list(n.left_keys), "rkeys": list(n.right_keys),
                "residual": (expr_to_json(n.residual)
                             if n.residual is not None else None),
                "build_unique": n.build_unique,
                "colocated": n.colocated}
    if isinstance(n, MultiwayJoin):
        return {"k": "mwjoin",
                "probe": node_to_json(n.probe),
                "builds": [node_to_json(b) for b in n.builds],
                "kinds": list(n.kinds),
                "pkeys": [list(ks) for ks in n.probe_keys],
                "bkeys": [list(ks) for ks in n.build_keys],
                "build_unique": [bool(u) for u in n.build_unique]}
    if isinstance(n, NestedLoopJoin):
        return {"k": "nljoin",
                "left": node_to_json(n.left), "right": node_to_json(n.right),
                "residual": (expr_to_json(n.residual)
                             if n.residual is not None else None)}
    if isinstance(n, IndexJoin):
        return {"k": "indexjoin", "kind": n.kind,
                "left": node_to_json(n.left),
                "catalog": n.catalog, "table": n.table,
                "lkeys": list(n.left_keys),
                "index_key_cols": list(n.index_key_cols),
                "assignments": dict(n.assignments),
                "index_output": _out(n.index_output),
                "build_unique": n.build_unique}
    if isinstance(n, SemiJoin):
        return {"k": "semijoin", "negated": n.negated,
                "null_aware": n.null_aware,
                "left": node_to_json(n.left), "right": node_to_json(n.right),
                "lkeys": list(n.left_keys), "rkeys": list(n.right_keys),
                "residual": (expr_to_json(n.residual)
                             if n.residual is not None else None)}
    if isinstance(n, SetOp):
        return {"k": "setop", "kind": n.kind, "all": n.all,
                "left": node_to_json(n.left), "right": node_to_json(n.right),
                "symbols": list(n.symbols), "types": [_t(t) for t in n.types]}
    if isinstance(n, Sort):
        return {"k": "sort", "child": node_to_json(n.child),
                "keys": [[s.symbol, s.ascending, s.nulls_first]
                         for s in n.keys],
                "limit": n.limit}
    if isinstance(n, Window):
        return {"k": "window", "child": node_to_json(n.child),
                "pkeys": list(n.partition_keys),
                "okeys": [[s.symbol, s.ascending, s.nulls_first]
                          for s in n.order_items],
                "funcs": [{"symbol": f.symbol, "fn": f.fn, "t": _t(f.type),
                           "arg": f.arg, "param": f.param, "frame": f.frame,
                           "default": f.default}
                          for f in n.funcs]}
    if isinstance(n, Limit):
        return {"k": "limit", "child": node_to_json(n.child), "count": n.count}
    if isinstance(n, Output):
        return {"k": "output", "child": node_to_json(n.child),
                "names": list(n.names), "symbols": list(n.symbols)}
    if isinstance(n, RemoteSource):
        return {"k": "remote", "fid": n.fragment_id, "output": _out(n.output)}
    if isinstance(n, Unnest):
        return {"k": "unnest", "child": node_to_json(n.child),
                "sources": list(n.sources), "replicate": list(n.replicate),
                "out_syms": [list(s) for s in n.out_syms],
                "out_types": [[_t(t) for t in ts] for ts in n.out_types],
                "ordinality": n.ordinality_sym}
    if isinstance(n, OneRow):
        return {"k": "onerow"}
    from presto_tpu.plan.nodes import HostProject, TableWriter

    if isinstance(n, TableWriter):
        return {"k": "tablewriter", "child": node_to_json(n.child),
                "catalog": n.catalog, "table": n.table,
                "write_id": n.write_id}
    if isinstance(n, HostProject):
        return {"k": "hostproject", "child": node_to_json(n.child),
                "items": [[sym, kind, in_sym, param]
                          for sym, kind, in_sym, param in n.items]}
    raise CodecError(f"unencodable plan node {type(n).__name__}")


def canonical_node_json(n: PlanNode) -> str:
    """Canonical structural serialization of one node's subtree: the wire
    encoding rendered with sorted keys and no whitespace, so it is
    byte-identical for any two nodes that encode to the same logical plan
    — across a codec round trip, across two decodes of one fragment, and
    across processes. strip_runtime_state keeps wire plans free of
    runtime attrs, so nothing execution-dependent can leak in. This is
    the basis of the compile plane's structural program fingerprints
    (exec/programs.py)."""
    import json

    return json.dumps(node_to_json(n), sort_keys=True,
                      separators=(",", ":"), default=str)


def node_fingerprint(n: PlanNode) -> str:
    """sha256 hex digest of canonical_node_json — the structural identity
    under which exec/programs.py shares compiled programs."""
    import hashlib

    return hashlib.sha256(canonical_node_json(n).encode()).hexdigest()


def node_from_json(d: Dict[str, Any]) -> PlanNode:
    k = d.get("k")
    if k == "scan":
        return TableScan(
            catalog=d["catalog"], table=d["table"],
            assignments=dict(d["assignments"]), output=_unout(d["output"]),
            constraints={c: (lo, hi)
                         for c, (lo, hi) in (d.get("constraints") or {}).items()},
        )
    if k == "filter":
        return Filter(node_from_json(d["child"]), expr_from_json(d["pred"]))
    if k == "project":
        return Project(node_from_json(d["child"]),
                       [(s, expr_from_json(e)) for s, e in d["exprs"]])
    if k == "agg":
        return Aggregate(
            node_from_json(d["child"]), list(d["keys"]),
            [AggSpec(a["symbol"], a["fn"], a["arg"], _untype(a["t"]),
                     bool(a.get("distinct", False)), a.get("arg2"),
                     a.get("param")) for a in d["aggs"]],
            step=d.get("step", "single"),
        )
    if k == "join":
        return HashJoin(
            kind=d["kind"], left=node_from_json(d["left"]),
            right=node_from_json(d["right"]),
            left_keys=list(d["lkeys"]), right_keys=list(d["rkeys"]),
            residual=(expr_from_json(d["residual"])
                      if d.get("residual") is not None else None),
            build_unique=bool(d.get("build_unique", False)),
            colocated=int(d.get("colocated", 0)),
        )
    if k == "mwjoin":
        return MultiwayJoin(
            probe=node_from_json(d["probe"]),
            builds=[node_from_json(b) for b in d["builds"]],
            kinds=list(d["kinds"]),
            probe_keys=[list(ks) for ks in d["pkeys"]],
            build_keys=[list(ks) for ks in d["bkeys"]],
            build_unique=[bool(u) for u in d["build_unique"]],
        )
    if k == "nljoin":
        return NestedLoopJoin(
            left=node_from_json(d["left"]), right=node_from_json(d["right"]),
            residual=(expr_from_json(d["residual"])
                      if d.get("residual") is not None else None),
        )
    if k == "indexjoin":
        return IndexJoin(
            kind=d["kind"], left=node_from_json(d["left"]),
            catalog=d["catalog"], table=d["table"],
            left_keys=list(d["lkeys"]),
            index_key_cols=list(d["index_key_cols"]),
            assignments=dict(d["assignments"]),
            index_output=_unout(d["index_output"]),
            build_unique=bool(d.get("build_unique", True)),
        )
    if k == "semijoin":
        return SemiJoin(
            left=node_from_json(d["left"]), right=node_from_json(d["right"]),
            left_keys=list(d["lkeys"]), right_keys=list(d["rkeys"]),
            negated=bool(d.get("negated", False)),
            residual=(expr_from_json(d["residual"])
                      if d.get("residual") is not None else None),
            null_aware=bool(d.get("null_aware", True)),
        )
    if k == "setop":
        return SetOp(d["kind"], bool(d["all"]), node_from_json(d["left"]),
                     node_from_json(d["right"]), list(d["symbols"]),
                     [_untype(t) for t in d["types"]])
    if k == "sort":
        return Sort(node_from_json(d["child"]),
                    [SortItem(s, bool(a), nf) for s, a, nf in d["keys"]],
                    limit=d.get("limit"))
    if k == "window":
        return Window(
            node_from_json(d["child"]), list(d["pkeys"]),
            [SortItem(s, bool(a), nf) for s, a, nf in d["okeys"]],
            [WindowFunc(f["symbol"], f["fn"], _untype(f["t"]), f.get("arg"),
                        f.get("param"), f.get("frame"),
                        default=f.get("default")) for f in d["funcs"]],
        )
    if k == "limit":
        return Limit(node_from_json(d["child"]), int(d["count"]))
    if k == "output":
        return Output(node_from_json(d["child"]), list(d["names"]),
                      list(d["symbols"]))
    if k == "remote":
        return RemoteSource(fragment_id=int(d["fid"]),
                            output=_unout(d["output"]))
    if k == "unnest":
        return Unnest(
            child=node_from_json(d["child"]), sources=list(d["sources"]),
            replicate=list(d["replicate"]),
            out_syms=[list(s) for s in d["out_syms"]],
            out_types=[[_untype(t) for t in ts] for ts in d["out_types"]],
            ordinality_sym=d.get("ordinality"),
        )
    if k == "onerow":
        return OneRow()
    if k == "tablewriter":
        from presto_tpu.plan.nodes import TableWriter

        return TableWriter(node_from_json(d["child"]), d["catalog"],
                           d["table"], d["write_id"])
    if k == "hostproject":
        from presto_tpu.plan.nodes import HostProject

        return HostProject(
            node_from_json(d["child"]),
            [(sym, kind, in_sym, param)
             for sym, kind, in_sym, param in d["items"]])
    raise CodecError(f"unknown plan node kind {k!r}")


# -- fragments + task updates ----------------------------------------------


def fragment_to_json(f: Fragment) -> Dict[str, Any]:
    return {"fid": f.fid, "root": node_to_json(f.root),
            "partitioning": f.partitioning,
            "output_partitioning": f.output_partitioning,
            "output_keys": list(f.output_keys),
            "radix_align": bool(f.radix_align)}


def fragment_from_json(d: Dict[str, Any]) -> Fragment:
    return Fragment(
        fid=int(d["fid"]), root=node_from_json(d["root"]),
        partitioning=d["partitioning"],
        output_partitioning=d["output_partitioning"],
        output_keys=list(d.get("output_keys") or []),
        radix_align=bool(d.get("radix_align") or False),
    )


def task_update_to_json(u) -> Dict[str, Any]:
    out = {"fragment": fragment_to_json(u.fragment),
           "task_index": u.task_index, "n_tasks": u.n_tasks,
           "n_out_partitions": u.n_out_partitions,
           "upstreams": {str(k): list(v) for k, v in u.upstreams.items()},
           "config": dict(u.config), "spool": bool(u.spool)}
    if u.split_assignment is not None:
        out["split_assignment"] = {
            t: list(map(int, idxs)) for t, idxs in u.split_assignment.items()}
    if u.split_counts is not None:
        out["split_counts"] = {t: int(n) for t, n in u.split_counts.items()}
    return out


def task_update_from_json(d: Dict[str, Any]):
    from presto_tpu.server.worker import TaskUpdate

    return TaskUpdate(
        fragment=fragment_from_json(d["fragment"]),
        task_index=int(d["task_index"]), n_tasks=int(d["n_tasks"]),
        n_out_partitions=int(d["n_out_partitions"]),
        upstreams={int(k): list(v) for k, v in d["upstreams"].items()},
        config=dict(d.get("config") or {}),
        spool=bool(d.get("spool", False)),
        split_assignment=(
            {t: [int(i) for i in idxs]
             for t, idxs in d["split_assignment"].items()}
            if d.get("split_assignment") is not None else None),
        split_counts=(
            {t: int(n) for t, n in d["split_counts"].items()}
            if d.get("split_counts") is not None else None),
    )
