"""Logical plan nodes.

Analog of presto-main's PlanNode hierarchy
(sql/planner/plan/*.java — 45 node types) reduced to the executed surface.
Every node exposes `output`: an ordered list of (symbol, Type). Symbols are
unique column names within a plan (Presto's Symbol allocator —
sql/planner/SymbolAllocator.java).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.expr.ir import RowExpression
from presto_tpu.types import Type


class PlanNode:
    output: List[Tuple[str, Type]]

    @property
    def out_names(self) -> List[str]:
        return [n for n, _ in self.output]

    def children(self) -> List["PlanNode"]:
        return []


@dataclasses.dataclass
class TableScan(PlanNode):
    catalog: str
    table: str
    # symbol -> source column name
    assignments: Dict[str, str] = dataclasses.field(default_factory=dict)
    output: List[Tuple[str, Type]] = dataclasses.field(default_factory=list)
    # column-name-keyed (lo, hi) bounds derived from filters above this scan
    # (TupleDomain pushdown; connectors use them to prune splits/row-groups)
    constraints: Dict[str, tuple] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Filter(PlanNode):
    child: PlanNode
    predicate: RowExpression

    @property
    def output(self):
        return self.child.output

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Project(PlanNode):
    child: PlanNode
    # ordered (symbol, expression); identity projections are InputRefs
    exprs: List[Tuple[str, RowExpression]]

    @property
    def output(self):
        return [(n, e.type) for n, e in self.exprs]

    def children(self):
        return [self.child]


@dataclasses.dataclass
class AggSpec:
    symbol: str
    fn: str  # sum|count|count_star|avg|min|max|variance family|covar|corr|
    #          bool_and|bool_or|arbitrary|checksum|count_if|geometric_mean|
    #          approx_percentile|max_by|min_by
    arg: Optional[str]  # input symbol (None for count_star)
    type: Type  # output type
    distinct: bool = False
    arg2: Optional[str] = None  # second input (covar/corr/max_by/min_by)
    param: Optional[float] = None  # constant parameter (approx_percentile p)


@dataclasses.dataclass
class Aggregate(PlanNode):
    child: PlanNode
    group_keys: List[str]  # input symbols
    aggs: List[AggSpec]
    # step mirrors Presto's AggregationNode.Step: SINGLE initially; the
    # fragmenter splits into PARTIAL (emits state columns) / FINAL (merges
    # state columns arriving through the exchange)
    step: str = "single"

    @property
    def output(self):
        if self.step == "partial":
            from presto_tpu.plan.agg_states import partial_output

            return partial_output(self.child.output, self.group_keys, self.aggs)
        key_types = dict(self.child.output)
        return [(k, key_types[k]) for k in self.group_keys] + [
            (a.symbol, a.type) for a in self.aggs
        ]

    def children(self):
        return [self.child]


@dataclasses.dataclass
class TableWriter(PlanNode):
    """Scaled writes: each task writes its stream as one part of the
    target table and emits its row count (reference: TableWriterOperator
    + SystemPartitioningHandle.SCALED_WRITER_DISTRIBUTION; the
    TableFinish sum happens coordinator-side over the gathered counts)."""

    child: PlanNode
    catalog: str
    table: str
    write_id: str  # unique per statement (part-file namespace)

    @property
    def output(self):
        from presto_tpu.types import BIGINT

        return [("rows", BIGINT)]

    def children(self):
        return [self.child]


@dataclasses.dataclass
class OneRow(PlanNode):
    """A single live row with no columns (reference: planner/plan
    ValuesNode's single-row degenerate form) — the child of a top-level
    FROM UNNEST(constant array)."""

    output: List[Tuple[str, Type]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Unnest(PlanNode):
    """Expand ARRAY/MAP columns into rows (operator/unnest/UnnestOperator
    redesigned for the dense padded layout: output row j of input row i
    exists iff j < max over sources of sizes[i] — a static [cap, W] →
    [cap*W] reshape, no per-position offset walking).

    `sources`: child symbols holding the array/map columns to expand.
    `replicate`: child symbols carried through (repeated per element).
    `out_syms[i]`: output symbols for sources[i] — [elem] for arrays,
    [key, value] for maps. `ordinality_sym`: the WITH ORDINALITY column.
    """

    child: PlanNode
    sources: List[str]
    replicate: List[str]
    out_syms: List[List[str]]
    out_types: List[List[Type]]
    ordinality_sym: Optional[str] = None

    @property
    def output(self):
        child_types = dict(self.child.output)
        out = [(s, child_types[s]) for s in self.replicate]
        for syms, types in zip(self.out_syms, self.out_types):
            out.extend(zip(syms, types))
        if self.ordinality_sym:
            from presto_tpu.types import BIGINT

            out.append((self.ordinality_sym, BIGINT))
        return out

    def children(self):
        return [self.child]


@dataclasses.dataclass
class RemoteSource(PlanNode):
    """Leaf reading pages from an upstream fragment through the exchange
    (reference: plan/RemoteSourceNode + operator/ExchangeOperator.java:35)."""

    fragment_id: int
    output: List[Tuple[str, Type]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class HashJoin(PlanNode):
    kind: str  # inner | left
    left: PlanNode  # probe
    right: PlanNode  # build
    left_keys: List[str]
    right_keys: List[str]
    residual: Optional[RowExpression] = None
    # planner hint: build side keys are unique (dimension table)
    build_unique: bool = False
    # colocated bucketed join (ConnectorNodePartitioningProvider /
    # grouped execution): both sides scan tables bucketed on the join
    # keys with this bucket count — no exchange; the runtime drives the
    # join bucket-by-bucket (lifespans). 0 = not colocated.
    colocated: int = 0

    @property
    def output(self):
        return list(self.left.output) + list(self.right.output)

    def children(self):
        return [self.left, self.right]


@dataclasses.dataclass
class MultiwayJoin(PlanNode):
    """N-ary join: one probe child, N resident build children probed in a
    single pass (PAPERS.md 1905.13376). Produced by plan/multiway.py when
    a left-deep chain of inner/left equi-joins shares one probe pipeline
    (the star-schema shape of q3/q5/q9/q64); semantically identical to the
    equivalent left-deep HashJoin nesting, with `builds[i]` the build side
    of the i-th join bottom-up.

    `probe_keys[i]` resolve against the probe output or against the
    payload of an EARLIER build j<i with `build_unique[j]` — a probe row
    has at most one match there, so the key value is well-defined per
    probe row (snowflake chains like lineitem⋈orders⋈customer)."""

    probe: PlanNode
    builds: List[PlanNode]
    kinds: List[str]                 # inner | left, per build
    probe_keys: List[List[str]]
    build_keys: List[List[str]]
    build_unique: List[bool]

    @property
    def output(self):
        out = list(self.probe.output)
        for b in self.builds:
            out.extend(b.output)
        return out

    def children(self):
        return [self.probe] + list(self.builds)


@dataclasses.dataclass
class NestedLoopJoin(PlanNode):
    """Inner join with no equi keys (pure cross product or non-equi ON
    condition). Reference: NestedLoopJoinOperator.java + NestedLoopBuild
    Operator (inner-only there too). Executed as probe×build-chunk
    expansion with the residual fused (exec/runtime._execute_nljoin)."""

    left: PlanNode   # probe (streamed)
    right: PlanNode  # build (collected, broadcast in distributed plans)
    residual: Optional[RowExpression] = None

    @property
    def output(self):
        return list(self.left.output) + list(self.right.output)

    def children(self):
        return [self.left, self.right]


@dataclasses.dataclass
class IndexJoin(PlanNode):
    """Join whose build side is a connector keyed-lookup instead of a scan
    (reference: IndexJoinNode via IndexJoinOptimizer.java + operator/index/
    IndexLoader.java): each probe batch's key values are fed to the
    connector index, which returns only matching rows — no full-table
    build. Planned by plan/optimizer.make_index_joins when the connector
    exposes an index over exactly the join keys."""

    kind: str                      # inner | left
    left: PlanNode                 # probe (streamed)
    catalog: str                   # index-side connector/table
    table: str
    left_keys: List[str] = dataclasses.field(default_factory=list)
    index_key_cols: List[str] = dataclasses.field(default_factory=list)
    # symbol -> source column name for the index-side output (includes keys)
    assignments: Dict[str, str] = dataclasses.field(default_factory=dict)
    index_output: List[Tuple[str, Type]] = dataclasses.field(
        default_factory=list)
    # build-side keys are unique (primary-key index): single-match probe
    build_unique: bool = True

    @property
    def output(self):
        return list(self.left.output) + list(self.index_output)

    def children(self):
        return [self.left]


@dataclasses.dataclass
class SemiJoin(PlanNode):
    """left [NOT] IN (subquery) / [NOT] EXISTS — probe side filtered by
    membership (reference: HashSemiJoinOperator / SemiJoinNode). Multi-key
    with an optional residual predicate over (probe ∪ build) columns covers
    correlated EXISTS with non-equi correlation (TPC-H Q21's
    `l2.l_suppkey <> l1.l_suppkey`)."""

    left: PlanNode
    right: PlanNode
    left_keys: List[str]
    right_keys: List[str]
    negated: bool = False
    residual: Optional[RowExpression] = None
    # True for [NOT] IN (NULL key ⇒ NULL membership), False for [NOT] EXISTS
    # (NULL correlation key simply never matches)
    null_aware: bool = True

    @property
    def output(self):
        return self.left.output

    def children(self):
        return [self.left, self.right]


@dataclasses.dataclass
class SetOp(PlanNode):
    """UNION [ALL] / INTERSECT / EXCEPT (reference: planner/plan/UnionNode,
    IntersectNode, ExceptNode + SetOperationNodeTranslator rewrites).

    Both children produce `arity` columns; the executor renames each
    child's output positionally onto `symbols` (types taken from the left
    child). DISTINCT variants dedup/membership-test with NULLs-equal
    semantics after aligning string dictionaries."""

    kind: str  # 'union' | 'intersect' | 'except'
    all: bool
    left: PlanNode
    right: PlanNode
    symbols: List[str]
    types: List[Type]

    @property
    def output(self):
        return list(zip(self.symbols, self.types))

    def children(self):
        return [self.left, self.right]


@dataclasses.dataclass
class SortItem:
    symbol: str
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclasses.dataclass
class WindowFunc:
    """One window function instance (reference: operator/window/*)."""

    symbol: str
    fn: str                       # row_number|rank|dense_rank|percent_rank|
                                  # cume_dist|ntile|lag|lead|first_value|
                                  # last_value|nth_value|sum|avg|min|max|count
    type: Type
    arg: Optional[str] = None     # input column symbol (value functions/aggs)
    param: Optional[int] = None   # ntile buckets / lag-lead offset / nth n
    # None = default frame (RANGE UNBOUNDED..CURRENT with ORDER BY, whole
    # partition without); "rows_unbounded_current" = explicit ROWS frame
    frame: Optional[str] = None
    # lag/lead third argument: value when the offset leaves the partition
    default: Optional[object] = None


@dataclasses.dataclass
class Window(PlanNode):
    """Window functions over one (PARTITION BY, ORDER BY) spec. Multiple
    specs chain as stacked Window nodes (reference: WindowOperator.java:47;
    the local planner similarly splits by specification)."""

    child: PlanNode
    partition_keys: List[str]
    order_items: List[SortItem]
    funcs: List[WindowFunc]

    @property
    def output(self):
        return list(self.child.output) + [(f.symbol, f.type) for f in self.funcs]

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Sort(PlanNode):
    child: PlanNode
    keys: List[SortItem]
    limit: Optional[int] = None  # TopN fusion (TopNNode)

    @property
    def output(self):
        return self.child.output

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Limit(PlanNode):
    child: PlanNode
    count: int

    @property
    def output(self):
        return self.child.output

    def children(self):
        return [self.child]


@dataclasses.dataclass
class HostProject(PlanNode):
    """Host-side finishing projection at the query root: string-PRODUCING
    functions over unbounded value domains (CAST(numeric AS varchar),
    date_format) cannot be dictionary transforms — there is no input
    dictionary to expand. They run on the host over the (gathered) final
    rows instead, formatting per distinct value and re-encoding
    (reference: these are ordinary scalars in the row-at-a-time JVM
    engine; here they are the one projection class the device cannot
    express, so it executes where the rows already materialize)."""

    child: PlanNode
    # (out_symbol, kind, in_symbol, param): kind ∈ {"varchar_cast",
    # "date_format"}; param is the constant format for date_format
    items: List[tuple]

    @property
    def output(self):
        from presto_tpu.types import VARCHAR

        return list(self.child.output) + [
            (sym, VARCHAR) for sym, _, _, _ in self.items]

    def children(self):
        return [self.child]


@dataclasses.dataclass
class Output(PlanNode):
    child: PlanNode
    names: List[str]  # user-facing column names
    symbols: List[str]

    @property
    def output(self):
        types = dict(self.child.output)
        return [(n, types[s]) for n, s in zip(self.names, self.symbols)]

    def children(self):
        return [self.child]


@dataclasses.dataclass
class QueryPlan:
    root: Output
    # uncorrelated scalar subqueries: symbol -> plan producing 1 row / 1 col;
    # the executor evaluates these first and binds them as constants
    scalar_subqueries: Dict[str, "QueryPlan"] = dataclasses.field(default_factory=dict)
    # False when the plan baked in per-query state (now()/current_date
    # constants): caches must not serve it to later queries
    cacheable: bool = True


def plan_to_string(node: PlanNode, indent: int = 0, node_stats=None,
                   shape_budgets=None) -> str:
    """EXPLAIN-style rendering (reference: sql/planner/planPrinter); with
    node_stats, renders EXPLAIN ANALYZE-style per-operator output rows /
    batches / wall time (ExplainAnalyzeOperator analog). `shape_budgets`
    is an optional (global, scan, breaker) budget triple for the
    headroom rendering; executed nodes always render their worst
    program's compiled-shape count against the node's class budget, so
    how close a plan runs to the bounded-shapes guard is visible in
    EXPLAIN output, not only as a guard failure."""
    pad = "  " * indent
    if isinstance(node, TableScan):
        cols = ", ".join(f"{s}:={c}" for s, c in node.assignments.items())
        s = f"{pad}TableScan[{node.catalog}.{node.table}] {cols}"
    elif isinstance(node, Filter):
        s = f"{pad}Filter[{node.predicate}]"
    elif isinstance(node, Project):
        s = f"{pad}Project[{', '.join(f'{n} := {e}' for n, e in node.exprs)}]"
    elif isinstance(node, Aggregate):
        aggs = ", ".join(f"{a.symbol} := {a.fn}({a.arg or '*'})" for a in node.aggs)
        s = f"{pad}Aggregate[{node.step}; keys={node.group_keys}; {aggs}]"
    elif isinstance(node, HashJoin):
        s = (f"{pad}HashJoin[{node.kind}; {node.left_keys} = "
             f"{node.right_keys}{'; unique' if node.build_unique else ''}"
             f"{f'; colocated={node.colocated} buckets' if node.colocated else ''}]")
    elif isinstance(node, MultiwayJoin):
        legs = "; ".join(
            f"{k}:{pk} = {bk}{'*' if u else ''}"
            for k, pk, bk, u in zip(node.kinds, node.probe_keys,
                                    node.build_keys, node.build_unique))
        s = f"{pad}MultiwayJoin[{len(node.builds)} builds; {legs}]"
    elif isinstance(node, IndexJoin):
        s = (f"{pad}IndexJoin[{node.kind}; {node.left_keys} = "
             f"{node.catalog}.{node.table}({node.index_key_cols})]")
    elif isinstance(node, SemiJoin):
        s = (f"{pad}SemiJoin[{'NOT ' if node.negated else ''}{node.left_keys} IN "
             f"{node.right_keys}{f'; residual={node.residual}' if node.residual else ''}]")
    elif isinstance(node, SetOp):
        s = f"{pad}SetOp[{node.kind}{' all' if node.all else ''}]"
    elif isinstance(node, Sort):
        keys = ", ".join(f"{k.symbol}{'' if k.ascending else ' desc'}" for k in node.keys)
        s = f"{pad}Sort[{keys}{f'; limit={node.limit}' if node.limit else ''}]"
    elif isinstance(node, Window):
        fns = ", ".join(f"{f.symbol} := {f.fn}({f.arg or ''})" for f in node.funcs)
        s = (f"{pad}Window[partition={node.partition_keys}; "
             f"order={[k.symbol for k in node.order_items]}; {fns}]")
    elif isinstance(node, Limit):
        s = f"{pad}Limit[{node.count}]"
    elif isinstance(node, RemoteSource):
        s = f"{pad}RemoteSource[fragment {node.fragment_id}]"
    elif isinstance(node, Output):
        s = f"{pad}Output[{', '.join(node.names)}]"
    else:
        s = f"{pad}{type(node).__name__}"
    beng = node.__dict__.get("_breaker_engine")
    if beng is not None:
        why = node.__dict__.get("_breaker_engine_why")
        s += f"   [engine={beng}{f': {why}' if why else ''}]"
    jm = node.__dict__.get("_join_mode")
    if jm is not None:
        jwhy = node.__dict__.get("_join_mode_why")
        s += f"   [join={jm}{f': {jwhy}' if jwhy else ''}]"
    rs = node.__dict__.get("_runstats")
    if rs is not None and node_stats is not None:
        # estimate-vs-actual drift stamped by obs/runstats observation
        # sites; EXPLAIN ANALYZE only — plain EXPLAIN stays estimate-land
        est, actual = rs.get("est"), rs.get("actual")
        if est and actual:
            s += (f"   [est={est:.3g} actual={actual:.3g} "
                  f"drift={actual / est:.2g}x]")
    aa = node.__dict__.get("_adaptive_actions")
    if aa:
        # in-run adaptation trail (exec/adaptive.py): every decision the
        # adaptive layer took (or, in observe mode, WOULD have taken —
        # prefixed "would") at this node, in decision order
        s += f"   [adaptive: {'; '.join(aa)}]"
    sp = node.__dict__.get("_spill_stats")
    if sp is not None and (sp.get("partitions") or sp.get("repartitions")
                           or sp.get("revocations")):
        # dynamic hybrid hash spill shape stamped by exec/runtime.py's
        # spill drivers: final leaf count, next-hash-bits splits, max
        # recursion depth, role reversals, pool-pressure revocations
        s += (f"   [spill: P={sp['partitions']} "
              f"repartitions={sp['repartitions']} depth={sp['depth']} "
              f"reversed={sp['reversed']} revoked={sp['revocations']} "
              f"bytes={sp['bytes']}]")
    frag = node.__dict__.get("_fragment_fusion")
    if frag is not None:
        fs = node.__dict__.get("_fragment_stats")
        if fs and (fs.get("fragment_dispatches") or fs.get("batch_dispatches")):
            s += (f"   [fragment={frag}; dispatches="
                  f"{fs['fragment_dispatches']}fused"
                  f"({fs['fused_batches']} batches)"
                  f"+{fs['batch_dispatches']}per-batch]")
        else:
            s += f"   [fragment={frag}]"
    jstats = getattr(node, "_jit_stats", None)
    if node_stats and id(node) in node_stats:
        st = node_stats[id(node)]
        s += (f"   [rows={int(st['rows'])}, batches={int(st['batches'])}, "
              f"wall={st['wall_s']*1000:.1f}ms")
        if st.get("bytes"):
            s += f", bytes={int(st['bytes'])}"
        compiles = sum(v["compiles"] for v in jstats.values()) if jstats \
            else 0
        if compiles:
            # split the measured wall into compile vs execute: recompiles
            # (capacity growth, new batch shapes) show up HERE, not as
            # mysteriously slow operators
            cwall = sum(v["compile_wall_s"] for v in jstats.values())
            s += (f", compiles={compiles}, compile={cwall:.2f}s, "
                  f"execute={max(0.0, st['wall_s'] - cwall):.2f}s")
            s += _shape_headroom(node, jstats, shape_budgets)
        s += "]"
        s += _devprof_annotation(jstats)
    elif jstats:
        # an executed node renders its recompile profile even without the
        # EXPLAIN ANALYZE stats map: distinct programs × compiled shapes
        # is the bounded-shapes contract analysis/recompile.py enforces
        compiles = sum(v["compiles"] for v in jstats.values())
        cwall = sum(v["compile_wall_s"] for v in jstats.values())
        if compiles:
            s += (f"   [programs={len(jstats)}, compiles={compiles}, "
                  f"compile_wall={cwall:.2f}s"
                  f"{_shape_headroom(node, jstats, shape_budgets)}]")
        s += _devprof_annotation(jstats)
    return s + "".join(
        "\n" + plan_to_string(c, indent + 1, node_stats, shape_budgets)
        for c in node.children()
    )


def _devprof_annotation(jstats) -> str:
    """'   [peak=… flops=… bytes=… ai=…]' — XLA's own cost/memory analysis
    of the node's compiled programs, stamped into _jit_stats by the
    obs/devprof plane (devprof=on only; off renders nothing, keeping the
    pre-devprof output bit-for-bit). ai = flops per byte accessed — the
    roofline x-axis."""
    if not jstats:
        return ""
    flops = sum(v.get("flops", 0.0) for v in jstats.values())
    byts = sum(v.get("bytes_accessed", 0.0) for v in jstats.values())
    peak = max((v.get("footprint_bytes", 0.0) for v in jstats.values()),
               default=0.0)
    if not (flops or byts or peak):
        return ""
    parts = []
    if peak:
        parts.append(f"peak={int(peak):,}")
    if flops:
        parts.append(f"flops={flops:.4g}")
    if byts:
        parts.append(f"bytes={byts:.4g}")
    if flops and byts:
        parts.append(f"ai={flops / byts:.2f}")
    return "   [" + " ".join(parts) + "]"


def _shape_headroom(node, jstats, shape_budgets) -> str:
    """', shapes=<worst>/<budget>' — the node's worst program's distinct
    compiled shapes against its operator-class budget (scan vs breaker;
    analysis/recompile.py is the source of truth for both the classes
    and the defaults)."""
    try:
        from presto_tpu.analysis.recompile import budget_for, distinct_shapes
    except Exception:
        return ""
    worst = max((distinct_shapes(v) for v in jstats.values()), default=0)
    g, sc, br = shape_budgets or (None, None, None)
    budget = budget_for(node, g, sc, br)
    return f", shapes={worst}/{budget}"
