"""Analyzer + logical planner: AST → typed QueryPlan.

Covers the roles of the reference's sql/analyzer (Analyzer.java:69,
StatementAnalyzer.java:217, ExpressionAnalyzer) and sql/planner
(LogicalPlanner.java:173, QueryPlanner, RelationPlanner, SubqueryPlanner) in
one pass, sized to the executed SQL surface:

- scopes resolve (qualifier, column) → unique plan symbols
- expressions lower to the typed IR with implicit coercions and exact
  decimal scale/precision rules (add/sub align scales via casts; mul adds
  scales; div is exact with Presto's result scale and HALF_UP rounding —
  expr/compile._decimal_div)
- aggregates are extracted and planned as pre-Project → Aggregate →
  post-Project (the reference's QueryPlanner.aggregate path)
- comma-FROM + WHERE equi-conjuncts become a greedy size-heuristic join
  tree (stand-in for ReorderJoins.java:94 + DetermineJoinDistributionType);
  explicit JOIN ... ON trees are kept as written
- IN (subquery) → SemiJoin; uncorrelated scalar subqueries → Param bound
  by pre-executing the subplan
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.connector import Catalog, TableHandle
from presto_tpu.expr.compile import days_from_civil
from presto_tpu.expr.ir import Call, Constant, InputRef, RowExpression, expr_inputs
from presto_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    HashJoin,
    Limit,
    NestedLoopJoin,
    OneRow,
    Output,
    PlanNode,
    Project,
    QueryPlan,
    SemiJoin,
    SetOp,
    Sort,
    SortItem,
    TableScan,
    Unnest,
)
from presto_tpu.sql import ast
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    ArrayType,
    DecimalType,
    GEOMETRY,
    INTEGER,
    IPADDRESS,
    IPPREFIX,
    IpAddressType,
    IpPrefixType,
    MapType,
    TDIGEST,
    TIME,
    TIMESTAMP,
    Type,
    VARBINARY,
    VARCHAR,
    common_super_type,
    is_floating,
    is_integral,
    is_numeric,
    parse_type,
)


class AnalysisError(Exception):
    pass


def _fold_string_call(e):
    """Constant-fold dictionary-transform string functions whose operand
    and arguments are all plan-time constants (to_hex(<literal bytes>),
    upper('x'), …). Without this, such calls reach the compiler with no
    dictionary to transform (reference: these fold in the interpreter,
    ExpressionInterpreter.java)."""
    if not isinstance(e, Call) or not e.args:
        return e
    if not all(isinstance(a, Constant) for a in e.args):
        return e
    from presto_tpu.expr.compile import (
        _STR_INT_NULLABLE,
        _STR_PRED,
        _STR_TO_INT,
        _STR_TO_STR,
        _str_int_pyfn,
        _str_pred_pyfn,
        _str_xform_pyfn,
        _xform_parts,
    )

    fn = e.fn
    if fn not in _STR_TO_STR and fn not in _STR_TO_INT and fn not in _STR_PRED:
        return e
    try:
        operand, cargs = _xform_parts(e)
    except NotImplementedError:
        # all-constant concat never reaches here (folded at analysis);
        # other shapes _xform_parts can't split stay runtime calls
        return e
    value = operand.value
    if value is None:
        return Constant(e.type, None)
    if isinstance(value, (bytes, bytearray)):
        value = value.decode("latin-1")
    try:
        if fn in _STR_TO_STR:
            out = _str_xform_pyfn(fn, cargs)(str(value))
        elif fn in _STR_TO_INT:
            out = _str_int_pyfn(fn, cargs)(str(value))
            if out is not None and fn not in _STR_INT_NULLABLE:
                out = int(out)
        else:
            out = bool(_str_pred_pyfn(fn, cargs)(str(value)))
    except Exception:
        return e  # leave malformed folds to runtime NULL semantics
    return Constant(e.type, out)


# ---------------------------------------------------------------------------
# symbols & scopes


class SymbolAllocator:
    """Also the per-query shared scratch: nested Planners receive the same
    allocator, so query-scoped state (the fixed start instant for niladic
    datetime functions, the plan-volatility flag) lives here."""

    def __init__(self):
        self.used = set()
        self.query_start_s: Optional[float] = None
        self.volatile_plan = False

    def query_start(self) -> float:
        """One instant per query (Session.getStartTime): first call fixes
        it; every niladic datetime function reads the same value. Using
        it makes the plan non-cacheable."""
        if self.query_start_s is None:
            import time as _time

            self.query_start_s = _time.time()
        self.volatile_plan = True
        return self.query_start_s

    def fresh(self, hint: str) -> str:
        base = hint or "expr"
        if base not in self.used:
            self.used.add(base)
            return base
        i = 1
        while f"{base}#{i}" in self.used:
            i += 1
        name = f"{base}#{i}"
        self.used.add(name)
        return name


@dataclasses.dataclass
class Field:
    qualifier: Optional[str]
    name: str
    symbol: str
    type: Type


class Scope:
    def __init__(self, fields: List[Field]):
        self.fields = fields

    def resolve(self, parts: Tuple[str, ...]) -> Field:
        if len(parts) == 1:
            matches = [f for f in self.fields if f.name == parts[0]]
        else:
            q, n = parts[-2], parts[-1]
            matches = [f for f in self.fields if f.qualifier == q and f.name == n]
        if not matches and len(parts) > 1:
            # ROW field access over flattened struct leaves: `r.f` (and
            # `t.r.f`) resolve against the dotted column name "r.f"
            for k in range(len(parts), 1, -1):
                dotted = ".".join(parts[-k:])
                q = parts[-k - 1] if len(parts) > k else None
                matches = [
                    f for f in self.fields
                    if f.name == dotted and (q is None or f.qualifier == q)
                ]
                if matches:
                    break
        if not matches:
            raise AnalysisError(f"column not found: {'.'.join(parts)}")
        symbols = {m.symbol for m in matches}
        if len(symbols) > 1:
            raise AnalysisError(f"ambiguous column: {'.'.join(parts)}")
        return matches[0]

    def __add__(self, other: "Scope") -> "Scope":
        return Scope(self.fields + other.fields)


class LambdaScope(Scope):
    """Lambda parameters SHADOW same-named outer columns (SQL lambda
    scoping) — unlike Scope concatenation, which treats duplicate names
    as ambiguous."""

    def __init__(self, params: List[Field], outer: Scope):
        super().__init__(params + outer.fields)
        self._params = params
        self._outer = outer

    def resolve(self, parts: Tuple[str, ...]) -> Field:
        if len(parts) == 1:
            for f in self._params:
                if f.name == parts[0]:
                    return f
        return self._outer.resolve(parts)


@dataclasses.dataclass
class RelationPlan:
    node: PlanNode
    scope: Scope
    # estimated rows (connector stats; for join ordering heuristic)
    rows: float = 1e6


def ast_key(node) -> str:
    """Canonical structural key for AST expressions (for GROUP BY matching
    and duplicate-aggregate elimination)."""
    if isinstance(node, ast.Identifier):
        return "id:" + ".".join(node.parts)
    if isinstance(node, ast.Literal):
        return f"lit:{node.kind}:{node.value!r}"
    if isinstance(node, ast.IntervalLiteral):
        return f"interval:{node.value}:{node.unit}"
    if isinstance(node, ast.UnaryOp):
        return f"u{node.op}({ast_key(node.operand)})"
    if isinstance(node, ast.BinaryOp):
        return f"({ast_key(node.left)}){node.op}({ast_key(node.right)})"
    if isinstance(node, ast.Between):
        return f"between{node.negated}({ast_key(node.value)},{ast_key(node.low)},{ast_key(node.high)})"
    if isinstance(node, ast.InList):
        return f"in{node.negated}({ast_key(node.value)};{','.join(ast_key(i) for i in node.items)})"
    if isinstance(node, ast.Like):
        return f"like{node.negated}({ast_key(node.value)},{ast_key(node.pattern)})"
    if isinstance(node, ast.IsNull):
        return f"isnull{node.negated}({ast_key(node.value)})"
    if isinstance(node, ast.FunctionCall):
        star = "*" if node.is_star else ""
        return f"fn:{node.name}{'D' if node.distinct else ''}({star}{','.join(ast_key(a) for a in node.args)})"
    if isinstance(node, ast.Cast):
        return f"cast({ast_key(node.value)} as {node.type_name})"
    if isinstance(node, ast.Case):
        op = ast_key(node.operand) if node.operand else ""
        whens = ";".join(f"{ast_key(c)}->{ast_key(v)}" for c, v in node.whens)
        dflt = ast_key(node.default) if node.default else ""
        return f"case({op};{whens};{dflt})"
    if isinstance(node, ast.Extract):
        return f"extract:{node.field}({ast_key(node.value)})"
    if isinstance(node, ast.WindowFunction):
        args = ",".join(ast_key(a) for a in node.args)
        part = ",".join(ast_key(p) for p in node.partition_by)
        order = ",".join(
            f"{ast_key(o.expr)}:{o.ascending}:{o.nulls_first}" for o in node.order_by
        )
        return f"win:{node.name}({'*' if node.is_star else args};{part};{order};{node.frame})"
    return f"?{id(node)}"


_AGG_FUNCS = {
    "sum", "avg", "count", "min", "max",
    # statistics (reference: operator/aggregation/Variance*, Covariance*,
    # CorrelationAggregation, GeometricMeanAggregations)
    "stddev", "stddev_pop", "stddev_samp", "variance", "var_pop", "var_samp",
    "covar_pop", "covar_samp", "corr", "geometric_mean",
    # boolean / misc (BooleanAndAggregation, ArbitraryAggregationFunction,
    # ChecksumAggregationFunction, CountIfAggregation)
    "bool_and", "bool_or", "every", "arbitrary", "any_value", "checksum",
    "count_if",
    # approx family (ApproximateCountDistinct / ApproximateLongPercentile —
    # here computed exactly, which satisfies the approximation contract)
    "approx_distinct", "approx_percentile", "numeric_histogram",
    # sketches as values (TDigestAggregationFunction,
    # ApproximateSetAggregation, MergeAggregation)
    "tdigest_agg", "merge", "approx_set",
    # argmax family (AbstractMinMaxBy)
    "max_by", "min_by",
    # structural (ArrayAggregationFunction / MapAggregation — materialized
    # single-task here)
    "array_agg", "map_agg",
}

# aliases → canonical names
_AGG_CANON = {"every": "bool_and", "any_value": "arbitrary",
              "stddev": "stddev_samp", "variance": "var_samp"}

_TWO_ARG_AGGS = {"covar_pop", "covar_samp", "corr", "max_by", "min_by",
                 "map_agg"}


def _is_agg_fn(name: str) -> bool:
    """Built-in aggregates plus registry-registered ones
    (FunctionManager.resolveFunction consults registered namespaces)."""
    if name in _AGG_FUNCS:
        return True
    from presto_tpu.functions import registry

    return registry().aggregate(name) is not None


# ---------------------------------------------------------------------------
# expression analysis (AST → typed IR)


class ExprAnalyzer:
    def __init__(self, scope: Scope, planner: "Planner",
                 replacements: Optional[Dict[str, Tuple[str, Type]]] = None):
        self.scope = scope
        self.planner = planner
        self.replacements = replacements or {}

    def analyze(self, node) -> RowExpression:
        k = ast_key(node)
        if k in self.replacements:
            sym, t = self.replacements[k]
            return InputRef(t, sym)
        m = getattr(self, f"_an_{type(node).__name__}", None)
        if m is None:
            raise AnalysisError(f"unsupported expression: {type(node).__name__}")
        return _fold_string_call(m(node))

    # -- leaves -----------------------------------------------------------

    def _an_Identifier(self, node: ast.Identifier) -> RowExpression:
        f = self.scope.resolve(node.parts)
        return InputRef(f.type, f.symbol)

    def _an_Literal(self, node: ast.Literal) -> RowExpression:
        if node.kind == "null":
            return Constant(BIGINT, None)
        if node.kind == "integer":
            return Constant(BIGINT, int(node.value))
        if node.kind == "double":
            return Constant(DOUBLE, float(node.value))
        if node.kind == "decimal":
            txt = node.text
            frac = len(txt.split(".")[1]) if "." in txt else 0
            digits = len(txt.replace(".", "").lstrip("0")) or 1
            return Constant(DecimalType(min(18, max(digits, frac)), frac), float(node.value))
        if node.kind == "string":
            return Constant(VARCHAR, str(node.value))
        if node.kind == "boolean":
            return Constant(BOOLEAN, bool(node.value))
        if node.kind == "date":
            y, m, d = map(int, str(node.value).split("-"))
            return Constant(DATE, days_from_civil(y, m, d))
        if node.kind == "time":
            hms, _, frac = str(node.value).partition(".")
            parts = list(map(int, hms.split(":")))
            while len(parts) < 3:
                parts.append(0)
            hh, mm, ss = parts[:3]
            micros = (hh * 3600 + mm * 60 + ss) * 1_000_000
            if frac:
                micros += int(frac[:6].ljust(6, "0"))
            return Constant(TIME, micros, raw=True)
        if node.kind == "timestamp":
            s = str(node.value)
            datepart, _, timepart = s.partition(" ")
            y, m, d = map(int, datepart.split("-"))
            micros = days_from_civil(y, m, d) * 86_400_000_000
            if timepart:
                hms, _, frac = timepart.partition(".")
                parts = list(map(int, hms.split(":")))
                while len(parts) < 3:
                    parts.append(0)
                hh, mm, ss = parts[:3]
                micros += (hh * 3600 + mm * 60 + ss) * 1_000_000
                if frac:
                    micros += int(frac[:6].ljust(6, "0"))
            return Constant(TIMESTAMP, micros, raw=True)
        raise AnalysisError(f"bad literal {node!r}")

    # -- operators --------------------------------------------------------

    def _an_UnaryOp(self, node: ast.UnaryOp) -> RowExpression:
        v = self.analyze(node.operand)
        if node.op == "not":
            return Call(BOOLEAN, "not", (v,))
        if node.op == "-":
            if isinstance(v, Constant) and v.value is not None:
                return Constant(v.type, -v.value)
            return Call(v.type, "neg", (v,))
        return v

    def _an_BinaryOp(self, node: ast.BinaryOp) -> RowExpression:
        op = node.op
        if op in ("and", "or"):
            l = self.analyze(node.left)
            r = self.analyze(node.right)
            return Call(BOOLEAN, op, (l, r))
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            l = self.analyze(node.left)
            r = self.analyze(node.right)
            if isinstance(l.type, (ArrayType, MapType)) or isinstance(
                    r.type, (ArrayType, MapType)):
                raise AnalysisError(
                    "comparisons on ARRAY/MAP values are not supported")
            l, r = self._align_comparable(l, r)
            return Call(BOOLEAN, op, (l, r))
        if op in ("add", "sub", "mul", "div", "mod"):
            return self._arith(op, node.left, node.right)
        if op == "concat":
            l = self.analyze(node.left)
            r = self.analyze(node.right)
            if isinstance(l.type, ArrayType):
                return self._an_structural_fn("concat", (l, r))
            # flatten nested concat so a || b || c becomes one call, and fold
            # all-constant concat to a literal
            args = []
            for a in (l, r):
                if isinstance(a, Call) and a.fn == "concat":
                    args.extend(a.args)
                else:
                    args.append(a)
            if all(isinstance(a, Constant) for a in args):
                if any(a.value is None for a in args):
                    return Constant(VARCHAR, None)  # NULL poisons concat
                return Constant(VARCHAR, "".join(str(a.value) for a in args))
            return Call(VARCHAR, "concat", tuple(args))
        raise AnalysisError(f"unknown operator {op}")

    def _align_comparable(self, l: RowExpression, r: RowExpression):
        ip_types = (IpAddressType, IpPrefixType)
        if (isinstance(l.type, ip_types) or isinstance(r.type, ip_types)) \
                and l.type != r.type:
            # '10.0.0.1' = ip_col: fold the text constant to the canonical
            # entry so it resolves against the ip dictionary. Anything
            # else (ipaddress vs ipprefix, ip vs varchar column) is a
            # type error — byte-comparing 16- against 17-byte entries
            # would be silently always-false
            tgt = l.type if isinstance(l.type, ip_types) else r.type
            if isinstance(l, Constant) and l.type is VARCHAR:
                l = self._ip_cast(l, tgt)
            elif isinstance(r, Constant) and r.type is VARCHAR:
                r = self._ip_cast(r, tgt)
            else:
                raise AnalysisError(
                    f"cannot compare {l.type} with {r.type}")
            return l, r
        if l.type.is_string or r.type.is_string:
            return l, r
        if isinstance(l.type, DecimalType) or isinstance(r.type, DecimalType):
            if is_floating(l.type) or is_floating(r.type):
                return self._to_double(l), self._to_double(r)
            ls = l.type.scale if isinstance(l.type, DecimalType) else 0
            rs = r.type.scale if isinstance(r.type, DecimalType) else 0
            s = max(ls, rs)
            return self._rescale(l, s), self._rescale(r, s)
        return l, r

    def _rescale(self, e: RowExpression, scale: int) -> RowExpression:
        if isinstance(e.type, DecimalType):
            if e.type.scale == scale:
                return e
            t = DecimalType(min(18, e.type.precision + scale - e.type.scale), scale)
            if isinstance(e, Constant) and e.value is not None:
                return Constant(t, e.value)
            return Call(t, "cast", (e,))
        if is_integral(e.type):
            t = DecimalType(18, scale)
            if isinstance(e, Constant) and e.value is not None:
                return Constant(t, e.value)
            return Call(t, "cast", (e,))
        raise AnalysisError(f"cannot rescale {e.type}")

    def _to_double(self, e: RowExpression) -> RowExpression:
        if e.type is DOUBLE:
            return e
        if isinstance(e, Constant) and e.value is not None:
            return Constant(DOUBLE, float(e.value))
        return Call(DOUBLE, "cast", (e,))

    def _arith(self, op: str, last, rast) -> RowExpression:
        # date ± interval
        if isinstance(rast, ast.IntervalLiteral):
            l = self.analyze(last)
            days = rast.value if rast.unit == "day" else None
            if l.type is not DATE:
                raise AnalysisError("interval arithmetic requires a date")
            sign = 1 if op == "add" else -1
            if days is not None:
                if isinstance(l, Constant):
                    return Constant(DATE, l.value + sign * days)
                return Call(DATE, "date_add_days", (l, Constant(INTEGER, sign * days)))
            # month/year intervals: constant-fold only (TPC-H uses literals)
            if isinstance(l, Constant):
                return Constant(DATE, _add_months_days(l.value, sign * rast.value * (12 if rast.unit == "year" else 1)))
            raise AnalysisError("month/year interval on non-constant date")
        l = self.analyze(last)
        r = self.analyze(rast)
        ldec, rdec = isinstance(l.type, DecimalType), isinstance(r.type, DecimalType)
        if l.type is DATE and is_integral(r.type) and op in ("add", "sub"):
            return Call(DATE, "date_add_days", (l, Call(INTEGER, "neg", (r,)) if op == "sub" else r))
        if is_floating(l.type) or is_floating(r.type):
            return Call(DOUBLE, op, (self._to_double(l), self._to_double(r)))
        if ldec or rdec:
            if op in ("add", "sub"):
                s = max(l.type.scale if ldec else 0, r.type.scale if rdec else 0)
                l2, r2 = self._rescale(l, s), self._rescale(r, s)
                return Call(DecimalType(18, s), op, (l2, r2))
            if op == "mul":
                ls = l.type.scale if ldec else 0
                rs = r.type.scale if rdec else 0
                if not ldec:
                    l = self._rescale(l, 0)
                if not rdec:
                    r = self._rescale(r, 0)
                return Call(DecimalType(18, ls + rs), "mul", (l, r))
            if op == "div":
                # Presto DecimalOperators.divideOperator typing: scale =
                # max(s1, s2), precision = p1 - s1 + s2 + scale, ROUND HALF
                # AWAY on the dropped digits. Deviation: result precision
                # caps at 18 (short decimal) — quotients needing 19+ digits
                # fall outside the int64 lane (compile._decimal_div).
                ls = l.type.scale if ldec else 0
                rs = r.type.scale if rdec else 0
                lp = l.type.precision if ldec else 18
                if not ldec:
                    l = self._rescale(l, 0)
                if not rdec:
                    r = self._rescale(r, 0)
                s = max(ls, rs)
                p = max(min(lp - ls + rs + s, 18), 1)
                return Call(DecimalType(p, s), "div", (l, r))
            if op == "mod":
                s = max(l.type.scale if ldec else 0, r.type.scale if rdec else 0)
                return Call(DecimalType(18, s), "mod", (self._rescale(l, s), self._rescale(r, s)))
        t = common_super_type(l.type, r.type)
        return Call(t, op, (l, r))

    # -- predicates -------------------------------------------------------

    def _an_Between(self, node: ast.Between) -> RowExpression:
        v = self.analyze(node.value)
        lo = self.analyze(node.low)
        hi = self.analyze(node.high)
        v1, lo = self._align_comparable(v, lo)
        v2, hi = self._align_comparable(v, hi)
        ge = Call(BOOLEAN, "ge", (v1, lo))
        le = Call(BOOLEAN, "le", (v2, hi))
        e = Call(BOOLEAN, "and", (ge, le))
        return Call(BOOLEAN, "not", (e,)) if node.negated else e

    def _an_InList(self, node: ast.InList) -> RowExpression:
        v = self.analyze(node.value)
        items = []
        for it in node.items:
            c = self.analyze(it)
            if not isinstance(c, Constant):
                raise AnalysisError("IN list items must be literals")
            if not v.type.is_string:
                _, c = self._align_comparable(v, c)
            items.append(c)
        e = Call(BOOLEAN, "in", tuple([v] + items))
        return Call(BOOLEAN, "not", (e,)) if node.negated else e

    def _an_Like(self, node: ast.Like) -> RowExpression:
        v = self.analyze(node.value)
        p = self.analyze(node.pattern)
        if not isinstance(p, Constant):
            raise AnalysisError("LIKE pattern must be a literal")
        args = [v, p]
        if node.escape is not None:
            esc = self.analyze(node.escape)
            if not isinstance(esc, Constant):
                raise AnalysisError("LIKE escape must be a literal")
            args.append(esc)
        e = Call(BOOLEAN, "like", tuple(args))
        return Call(BOOLEAN, "not", (e,)) if node.negated else e

    def _an_IsNull(self, node: ast.IsNull) -> RowExpression:
        v = self.analyze(node.value)
        return Call(BOOLEAN, "is_not_null" if node.negated else "is_null", (v,))

    def _an_Case(self, node: ast.Case) -> RowExpression:
        whens = []
        for cond, val in node.whens:
            if node.operand is not None:
                c = self._an_BinaryOp(ast.BinaryOp("eq", node.operand, cond))
            else:
                c = self.analyze(cond)
            whens.append((c, self.analyze(val)))
        default = self.analyze(node.default) if node.default else None
        # result type: common super type of branches
        branch_types = [v.type for _, v in whens] + ([default.type] if default else [])
        t = branch_types[0]
        for bt in branch_types[1:]:
            t = common_super_type(t, bt)
        # align branch scales for decimals
        def coerce(e):
            if isinstance(t, DecimalType):
                return self._rescale(e, t.scale)
            if t is DOUBLE and e.type is not DOUBLE:
                return self._to_double(e)
            return e
        out = coerce(default) if default else Constant(t, None)
        for c, v in reversed(whens):
            out = Call(t, "if", (c, coerce(v), out))
        return out

    def _an_Cast(self, node: ast.Cast) -> RowExpression:
        t = parse_type(node.type_name)
        if t is GEOMETRY:
            raise AnalysisError(
                "cannot cast to GEOMETRY — use ST_GeometryFromText")
        v = self.analyze(node.value)
        ip_types = (IpAddressType, IpPrefixType)
        if isinstance(t, ip_types) or isinstance(v.type, ip_types):
            return self._ip_cast(v, t)
        if (isinstance(v, Constant) and v.type.is_string
                and not t.is_string and not isinstance(t, (ArrayType,
                                                           MapType))):
            # constant text → value folds at plan time (there is no
            # dictionary to LUT over); unparseable folds to NULL, the
            # engine's documented row-level-cast deviation
            if v.value is None:
                return Constant(t, None)
            from presto_tpu.expr.compile import parse_string_to

            return Constant(t, parse_string_to(t, str(v.value)))
        return Call(t, "cast", (v,))

    def _ip_cast(self, v: RowExpression, t: Type) -> RowExpression:
        """IPADDRESS/IPPREFIX casts are dictionary transforms between
        canonical-byte entries and text/bytes (expr/ip.py; reference
        IpAddressOperators.java / IpPrefixOperators.java). Routed here so
        the generic cast path never passes codes through un-re-encoded."""
        if v.type == t:
            return v
        fn = {
            ("varchar", "ipaddress"): "__to_ipaddress",
            ("varbinary", "ipaddress"): "__vb_to_ipaddress",
            ("ipaddress", "varchar"): "__ip_to_varchar",
            ("ipaddress", "varbinary"): "__ip_to_bytes",
            ("ipaddress", "ipprefix"): "__addr_to_ipprefix",
            ("varchar", "ipprefix"): "__to_ipprefix",
            ("ipprefix", "varchar"): "__ipprefix_to_varchar",
            ("ipprefix", "ipaddress"): "__ipprefix_to_addr",
        }.get((v.type.name, t.name))
        if fn is None:
            raise AnalysisError(f"cannot cast {v.type} to {t}")
        if isinstance(v, Constant):
            if v.value is None:
                return Constant(t, None)
            from presto_tpu.expr.compile import _str_xform_pyfn

            raw = (v.value.decode("latin-1")
                   if isinstance(v.value, (bytes, bytearray))
                   else str(v.value))
            out = _str_xform_pyfn(fn, ())(raw)
            if out is None:
                raise AnalysisError(f"invalid {t.name}: {v.value!r}")
            return Constant(t, out)
        return Call(t, fn, (v,))

    def _an_ip_fn(self, name: str, args) -> RowExpression:
        """IP function family (reference operator/scalar/
        IpPrefixFunctions.java). Operands ride dictionary transforms, so
        every non-operand argument must be a plan-time constant."""
        from presto_tpu.expr import ip as _ip

        def coerce(a, want_prefix=False):
            # bare text constants are a convenience the reference gets via
            # implicit varchar→ipaddress coercion
            if isinstance(a, Constant) and a.type is VARCHAR and a.value is not None:
                t = IPPREFIX if (want_prefix or "/" in str(a.value)) else IPADDRESS
                return self._ip_cast(a, t)
            return a

        if name == "ip_prefix":
            if len(args) != 2:
                raise AnalysisError("ip_prefix(ip, prefix_bits) takes 2 arguments")
            a, bits = args
            if not (isinstance(bits, Constant) and is_integral(bits.type)):
                raise AnalysisError(
                    "ip_prefix: prefix length must be a constant integer")
            if a.type.name not in ("ipaddress", "varchar"):
                raise AnalysisError(f"ip_prefix expects ipaddress, got {a.type}")
            if isinstance(a, Constant):
                if a.value is None or bits.value is None:
                    return Constant(IPPREFIX, None)
                a = coerce(a)
                out = _ip.ip_prefix(str(a.value), int(bits.value))
                if out is None:
                    raise AnalysisError(
                        f"ip_prefix: invalid prefix length {bits.value}")
                return Constant(IPPREFIX, out)
            if a.type is VARCHAR:
                # parse text explicitly — ip_prefix itself takes canonical
                # entries only (a 16-char address TEXT is not 16 bytes)
                a = Call(IPADDRESS, "__to_ipaddress", (a,))
            return Call(IPPREFIX, "ip_prefix", (a, bits))
        if name in ("ip_subnet_min", "ip_subnet_max", "ip_subnet_range"):
            if len(args) != 1:
                raise AnalysisError(f"{name}(prefix) takes 1 argument")
            p = coerce(args[0], want_prefix=True)
            if not isinstance(p.type, IpPrefixType):
                raise AnalysisError(f"{name} expects ipprefix, got {p.type}")
            if name == "ip_subnet_range":
                mn = self._an_ip_fn("ip_subnet_min", (p,))
                mx = self._an_ip_fn("ip_subnet_max", (p,))
                return self._an_structural_fn("array_ctor", (mn, mx))
            if isinstance(p, Constant):
                if p.value is None:
                    return Constant(IPADDRESS, None)
                fn = _ip.subnet_min if name == "ip_subnet_min" else _ip.subnet_max
                return Constant(IPADDRESS, fn(str(p.value)))
            return Call(IPADDRESS, name, (p,))
        # is_subnet_of(prefix, address-or-prefix)
        if len(args) != 2:
            raise AnalysisError("is_subnet_of(prefix, ip) takes 2 arguments")
        p, x = coerce(args[0], want_prefix=True), coerce(args[1])
        if not isinstance(p.type, IpPrefixType):
            raise AnalysisError(f"is_subnet_of expects ipprefix, got {p.type}")
        if not isinstance(x.type, (IpAddressType, IpPrefixType)):
            raise AnalysisError(
                f"is_subnet_of expects ipaddress or ipprefix, got {x.type}")
        if isinstance(p, Constant) and isinstance(x, Constant):
            if p.value is None or x.value is None:
                return Constant(BOOLEAN, None)
            return Constant(BOOLEAN,
                            _ip.is_subnet_of(str(p.value), str(x.value)))
        if isinstance(p, Constant):
            if p.value is None:
                return Constant(BOOLEAN, None)
            return Call(BOOLEAN, "__is_subnet_of_c",
                        (x, Constant(VARCHAR, str(p.value))))
        if isinstance(x, Constant):
            if x.value is None:
                return Constant(BOOLEAN, None)
            return Call(BOOLEAN, "__prefix_contains_c",
                        (p, Constant(VARCHAR, str(x.value))))
        raise AnalysisError(
            "is_subnet_of needs a constant prefix or a constant operand "
            "(two-column containment would need a cross-dictionary product)")

    def _an_tdigest_fn(self, name: str, args) -> RowExpression:
        """TDIGEST scalar family (reference operator/scalar/
        TDigestFunctions.java). Digests are dictionary entries, so these
        evaluate once per distinct digest; the non-digest arguments must
        be plan-time constants."""
        if not args or args[0].type.name != "tdigest(double)":
            got = args[0].type if args else "no arguments"
            raise AnalysisError(f"{name} expects a tdigest, got {got}")
        td = args[0]

        def const_num(a, what):
            if not isinstance(a, Constant) or not is_numeric(a.type):
                raise AnalysisError(f"{name}: {what} must be a numeric constant")
            if a.value is None:
                raise AnalysisError(f"{name}: {what} must not be NULL")
            return float(a.value)

        if name == "value_at_quantile":
            if len(args) != 2:
                raise AnalysisError("value_at_quantile(tdigest, q)")
            q = const_num(args[1], "quantile")
            if not 0.0 <= q <= 1.0:
                raise AnalysisError("quantile must be in [0, 1]")
            return Call(DOUBLE, "value_at_quantile",
                        (td, Constant(DOUBLE, q)))
        if name == "values_at_quantiles":
            if len(args) != 2:
                raise AnalysisError("values_at_quantiles(tdigest, qs)")
            arr = args[1]
            if not (isinstance(arr, Call) and arr.fn == "array_ctor"
                    and all(isinstance(x, Constant)
                            and x.value is not None for x in arr.args)):
                raise AnalysisError(
                    "values_at_quantiles requires a constant array of "
                    "non-null quantiles")
            calls = tuple(
                self._an_tdigest_fn("value_at_quantile",
                                    (td, Constant(DOUBLE, float(x.value))))
                for x in arr.args)
            return self._an_structural_fn("array_ctor", calls)
        if name == "quantile_at_value":
            if len(args) != 2:
                raise AnalysisError("quantile_at_value(tdigest, x)")
            v = const_num(args[1], "value")
            return Call(DOUBLE, "quantile_at_value",
                        (td, Constant(DOUBLE, v)))
        if name == "trimmed_mean":
            if len(args) != 3:
                raise AnalysisError("trimmed_mean(tdigest, lo, hi)")
            lo = const_num(args[1], "low quantile")
            hi = const_num(args[2], "high quantile")
            if not 0.0 <= lo <= hi <= 1.0:
                raise AnalysisError("quantile bounds must satisfy 0<=lo<=hi<=1")
            return Call(DOUBLE, "trimmed_mean",
                        (td, Constant(DOUBLE, lo), Constant(DOUBLE, hi)))
        # scale_tdigest
        if len(args) != 2:
            raise AnalysisError("scale_tdigest(tdigest, factor)")
        f = const_num(args[1], "scale factor")
        if f <= 0:
            raise AnalysisError("scale factor must be positive")
        return Call(TDIGEST, "scale_tdigest", (td, Constant(DOUBLE, f)))

    def _an_Extract(self, node: ast.Extract) -> RowExpression:
        v = self.analyze(node.value)
        if node.field in ("hour", "minute", "second"):
            if v.type not in (TIME, TIMESTAMP):
                raise AnalysisError(
                    f"extract({node.field}) expects time or timestamp, "
                    f"got {v.type}")
            # TIME is micros-of-day; TIMESTAMP micros-since-epoch — the
            # mod-day lowering serves both
            return Call(BIGINT, "__time_" + node.field, (v,))
        if node.field not in ("year", "month", "day"):
            raise AnalysisError(f"extract({node.field}) unsupported")
        return Call(BIGINT, node.field, (v,))

    def _an_FunctionCall(self, node: ast.FunctionCall) -> RowExpression:
        name = node.name.lower()
        if _is_agg_fn(name):
            raise AnalysisError(f"aggregate {name}() not allowed here")
        if name in ("transform", "filter", "reduce", "any_match",
                    "all_match", "none_match", "transform_values",
                    "map_filter", "zip_with"):
            return self._an_higher_order(name, node)
        args = tuple(self.analyze(a) for a in node.args)
        structural = self._an_structural_fn(name, args)
        if structural is not None:
            return structural
        geo = self._an_geo_fn(name, args)
        if geo is not None:
            return geo
        if name == "abs":
            return Call(args[0].type, "abs", args)
        if name in ("sqrt", "exp", "ln", "power", "pow"):
            return Call(DOUBLE, {"pow": "power"}.get(name, name),
                        tuple(self._to_double(a) for a in args))
        if name in ("floor", "ceil", "ceiling"):
            return Call(args[0].type if not is_floating(args[0].type) else DOUBLE,
                        {"ceiling": "ceil"}.get(name, name), args)
        if name == "round":
            return Call(args[0].type, "round", args)
        if name == "try":
            # try(expr): the reference converts row-level errors to NULL;
            # this engine's device computations never raise and its host
            # transforms (string casts etc.) already yield NULL on bad
            # input — try() is the identity, kept for compatibility
            if len(args) != 1:
                raise AnalysisError("try() takes one argument")
            return args[0]
        if name == "coalesce":
            t = args[0].type
            for a in args[1:]:
                t = common_super_type(t, a.type)
            return Call(t, "coalesce", args)
        if name == "nullif":
            return Call(args[0].type, "nullif", args)
        if name in ("year", "month", "day", "quarter", "day_of_week", "dow",
                    "day_of_year", "doy"):
            canon = {"dow": "day_of_week", "doy": "day_of_year"}.get(name, name)
            return Call(BIGINT, canon, args)
        # string functions (dictionary transforms / luts — expr/compile.py)
        if name in ("substr", "substring"):
            return Call(VARCHAR, "substr", args)
        if (name in ("md5", "sha1", "sha256", "sha512", "to_base64")
                and args and args[0].type.name == "varbinary"):
            # VarbinaryFunctions.java: digests of BYTES return varbinary
            # (to_base64 returns varchar); the varchar overloads below
            # hash utf-8 text and return hex — a convenience extension
            out_t = VARCHAR if name == "to_base64" else VARBINARY
            return Call(out_t, "__vb_" + name, args)
        if name in ("to_hex", "from_hex", "to_utf8", "from_utf8"):
            want_vb = name in ("to_hex", "from_utf8")
            got_vb = bool(args) and args[0].type.name == "varbinary"
            if want_vb != got_vb:
                # exact signatures (VarbinaryFunctions.java): to_hex /
                # from_utf8 take varbinary; from_hex / to_utf8 take
                # varchar — silently re-encoding would corrupt bytes
                raise AnalysisError(
                    f"{name}() expects "
                    f"{'varbinary' if want_vb else 'varchar'}")
            out_t = VARCHAR if want_vb else VARBINARY
            return Call(out_t, name, args)
        if name in ("ip_prefix", "ip_subnet_min", "ip_subnet_max",
                    "ip_subnet_range", "is_subnet_of"):
            return self._an_ip_fn(name, args)
        if name in ("value_at_quantile", "values_at_quantiles",
                    "quantile_at_value", "trimmed_mean", "scale_tdigest"):
            return self._an_tdigest_fn(name, args)
        if name == "empty_approx_set":
            if args:
                raise AnalysisError("empty_approx_set() takes no arguments")
            from presto_tpu.expr.hll import empty as _hll_empty
            from presto_tpu.types import HYPERLOGLOG

            return Constant(HYPERLOGLOG, _hll_empty())
        if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse",
                    "replace", "lpad", "rpad", "split_part",
                    "url_extract_host", "url_extract_path",
                    "url_extract_query", "url_extract_protocol",
                    "url_extract_fragment", "url_encode", "url_decode",
                    "md5", "sha1", "sha256", "sha512", "to_base64",
                    "from_base64", "normalize"):
            return Call(VARCHAR, name, args)
        if name == "concat":
            if all(isinstance(a, Constant) for a in args):
                if any(a.value is None for a in args):
                    return Constant(VARCHAR, None)  # NULL poisons concat
                return Constant(VARCHAR, "".join(str(a.value) for a in args))
            return Call(VARCHAR, "concat", args)
        if name in ("length", "strpos", "position", "codepoint"):
            return Call(BIGINT, {"position": "strpos"}.get(name, name), args)
        if name == "bit_length":
            if len(args) != 1 or not args[0].type.is_string:
                raise AnalysisError("bit_length expects a string argument")
            vb = args[0].type.name == "varbinary"
            return Call(BIGINT, "__vb_bit_length" if vb else "bit_length",
                        args)
        if name == "date_parse":
            # date_parse(string, format) — MySQL format vocabulary
            # (DateTimeFunctions.java); format must be a constant
            if len(args) != 2:
                raise AnalysisError("date_parse(string, format)")
            if not (isinstance(args[1], Constant)
                    and args[1].type.is_string and args[1].value is not None):
                raise AnalysisError("date_parse format must be a constant string")
            from presto_tpu.expr.compile import mysql_format_to_strptime

            try:
                mysql_format_to_strptime(str(args[1].value))
            except ValueError as ex:
                raise AnalysisError(f"date_parse: {ex}")
            return Call(TIMESTAMP, "date_parse", args)
        if name == "date_format":
            # date_format(ts, fmt) → varchar: a HOST finishing projection
            # (unbounded output domain — no dictionary to transform); the
            # planner accepts it in the top-level SELECT list only
            if len(args) != 2:
                raise AnalysisError("date_format(timestamp, format)")
            if args[0].type.name not in ("timestamp", "date"):
                raise AnalysisError(
                    f"date_format expects timestamp or date, got {args[0].type}")
            if not (isinstance(args[1], Constant)
                    and args[1].type.is_string and args[1].value is not None):
                raise AnalysisError("date_format format must be a constant string")
            from presto_tpu.expr.compile import mysql_format_to_strptime

            try:
                mysql_format_to_strptime(str(args[1].value))
            except ValueError as ex:
                raise AnalysisError(f"date_format: {ex}")
            return Call(VARCHAR, "__host_date_format", args)
        if name in ("from_iso8601_date", "from_iso8601_timestamp"):
            if len(args) != 1 or not args[0].type.is_string:
                raise AnalysisError(f"{name} expects a string argument")
            out_t = DATE if name == "from_iso8601_date" else TIMESTAMP
            return Call(out_t, name, args)
        if name in ("split", "regexp_split"):
            # split(s, delim[, limit]) / regexp_split(s, pattern) →
            # array(varchar): per-dictionary-entry expansion applied as a
            # 2D gather (StringFunctions.split / RegexpFunctions)
            if not 2 <= len(args) <= (3 if name == "split" else 2):
                raise AnalysisError(f"{name}: wrong argument count")
            if not args[0].type.is_string:
                raise AnalysisError(f"{name} expects a string argument")
            if not (isinstance(args[1], Constant) and args[1].value not in
                    (None, "")):
                raise AnalysisError(
                    f"{name}: delimiter must be a non-empty constant")
            if len(args) == 3 and not (isinstance(args[2], Constant)
                                       and is_integral(args[2].type)
                                       and (args[2].value or 0) >= 1):
                raise AnalysisError("split: limit must be a positive constant")
            if isinstance(args[0], Constant):
                # constant operand: fold to an array constructor (there is
                # no dictionary to expand at runtime)
                if args[0].value is None:
                    return Constant(ArrayType(VARCHAR), None)
                s = str(args[0].value)
                if name == "split":
                    lim = (int(args[2].value) - 1 if len(args) == 3 else -1)
                    pieces = s.split(str(args[1].value), lim)
                else:
                    from presto_tpu.expr.compile import regexp_split_pieces

                    pieces = regexp_split_pieces(str(args[1].value))(s)
                return self._an_structural_fn(
                    "array_ctor",
                    tuple(Constant(VARCHAR, p) for p in pieces))
            return Call(ArrayType(VARCHAR), name, args)
        if name in ("regexp_like", "starts_with", "ends_with", "contains"):
            return Call(BOOLEAN, name, args)
        # math
        if name in ("sin", "cos", "tan", "asin", "acos", "atan", "sinh",
                    "cosh", "tanh", "log2", "log10", "cbrt", "degrees",
                    "radians", "atan2"):
            return Call(DOUBLE, name, tuple(self._to_double(a) for a in args))
        if name == "log":
            # log(base, x) = ln(x)/ln(base)
            b, x = (self._to_double(a) for a in args)
            return Call(DOUBLE, "div",
                        (Call(DOUBLE, "ln", (x,)), Call(DOUBLE, "ln", (b,))))
        if name == "sign":
            return Call(args[0].type, "sign", args)
        if name == "truncate":
            return Call(DOUBLE, "truncate", (self._to_double(args[0]),))
        if name == "mod":
            return self._arith("mod", node.args[0], node.args[1])
        if name in ("current_date", "current_timestamp", "now"):
            # plan-time constants, ONE instant per query
            # (Session.getStartTime); marks the plan non-cacheable
            now_s = self.planner.symbols.query_start()
            if name == "current_date":
                return Constant(DATE, int(now_s // 86400), raw=True)
            return Constant(TIMESTAMP, int(now_s * 1e6), raw=True)
        if name == "typeof":
            if len(args) != 1:
                raise AnalysisError("typeof() takes one argument")
            return Constant(VARCHAR, str(args[0].type))
        if name == "version":
            if args:
                raise AnalysisError("version() takes no arguments")
            import presto_tpu

            return Constant(VARCHAR, f"presto-tpu {presto_tpu.__version__}")
        if name == "pi":
            return Constant(DOUBLE, 3.141592653589793, raw=True)
        if name in ("e",):
            return Constant(DOUBLE, 2.718281828459045, raw=True)
        if name in ("greatest", "least"):
            t = args[0].type
            for a in args[1:]:
                t = common_super_type(t, a.type)
            if isinstance(t, DecimalType):
                args = tuple(self._rescale(a, t.scale) for a in args)
            elif t is DOUBLE:
                args = tuple(self._to_double(a) for a in args)
            return Call(t, name, args)
        if name == "if":
            return self._an_Case(
                ast.Case(None, [(node.args[0], node.args[1])],
                         node.args[2] if len(node.args) > 2 else None)
            )
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_left_shift", "bitwise_right_shift",
                    "bitwise_not"):
            return Call(BIGINT, name, args)
        if name in ("is_nan", "is_finite", "is_infinite"):
            return Call(BOOLEAN, name, args)
        if name == "from_unixtime":
            return Call(TIMESTAMP, name, args)
        if name == "to_unixtime":
            return Call(DOUBLE, name, args)
        if name in ("hour", "minute", "second") and args and args[0].type in (
                TIME, TIMESTAMP):
            return Call(BIGINT, "__time_" + name, args)
        if name == "width_bucket":
            return Call(BIGINT, name, args)
        if name in ("regexp_extract", "regexp_replace", "json_extract_scalar",
                    "json_extract", "json_array_get", "json_format",
                    "json_parse"):
            return Call(VARCHAR, name, args)
        if name in ("json_array_length", "json_size"):
            return Call(BIGINT, name, args)
        if name in ("json_array_contains", "is_json_scalar"):
            return Call(BOOLEAN, name, args)
        if name in ("levenshtein_distance", "hamming_distance"):
            # second operand must be a plan-time constant (dictionary lut)
            return Call(BIGINT, name + "_c", (args[0], args[1]))
        # date
        if name == "date_trunc":
            return Call(DATE, "date_trunc", args)
        if name == "date_diff":
            return Call(BIGINT, "date_diff", args)
        if name == "date_add":
            if len(args) == 2:
                return Call(DATE, "date_add_days", (args[1], args[0]))
            return Call(DATE, "date_add_unit", args)
        # registered (plugin/user) scalars — built-ins above take precedence
        # (FunctionManager: global namespace resolves before plugins)
        from presto_tpu.functions import registry as _freg

        udf = _freg().scalar(name)
        if udf is not None:
            if udf.arity is not None and len(args) != udf.arity:
                raise AnalysisError(
                    f"{name}() takes {udf.arity} arguments, got {len(args)}")
            if udf.coerce_double:
                args = tuple(self._to_double(a) for a in args)
            t = udf.result_type([a.type for a in args])
            return Call(t, "udf:" + udf.name, args)
        raise AnalysisError(f"unknown function {name}")

    def _an_lambda(self, lam, param_types) -> "LambdaExpr":
        """Analyze a lambda body with its params bound in a child scope
        (SqlBase.g4 lambda / ExpressionAnalyzer's lambda scoping)."""
        from presto_tpu.expr.ir import LambdaExpr

        if not isinstance(lam, ast.Lambda):
            raise AnalysisError("expected a lambda argument (x -> ...)")
        if len(lam.params) != len(param_types):
            raise AnalysisError(
                f"lambda takes {len(param_types)} parameters, "
                f"got {len(lam.params)}")
        params = []
        fields = []
        for pname, pt in zip(lam.params, param_types):
            sym = self.planner.symbols.fresh(pname)
            params.append((sym, pt))
            fields.append(Field("", pname, sym, pt))
        sub = ExprAnalyzer(LambdaScope(fields, self.scope), self.planner,
                           self.replacements)
        body = sub.analyze(lam.body)
        return LambdaExpr(body.type, tuple(params), body)

    def _an_higher_order(self, name: str, node: ast.FunctionCall):
        """transform/filter/reduce/…_match over arrays: the lambda body
        vectorizes over the flattened element plane at compile time."""
        if len(node.args) < 2:
            raise AnalysisError(f"{name} expects an array and a lambda")
        arr = self.analyze(node.args[0])
        if name == "zip_with":
            if len(node.args) != 3:
                raise AnalysisError(
                    "zip_with(array, array, (x, y) -> ...) expects 3 "
                    "arguments")
            arr2 = self.analyze(node.args[1])
            if not isinstance(arr.type, ArrayType) or not isinstance(
                    arr2.type, ArrayType):
                raise AnalysisError("zip_with requires two ARRAYs")
            le = self._an_lambda(node.args[2],
                                 [arr.type.element, arr2.type.element])
            return Call(ArrayType(le.type), "zip_with", (arr, arr2, le))
        if name in ("transform_values", "map_filter"):
            if not isinstance(arr.type, MapType):
                raise AnalysisError(f"{name} requires MAP, got {arr.type}")
            le = self._an_lambda(node.args[1],
                                 [arr.type.key, arr.type.value])
            if name == "transform_values":
                return Call(MapType(arr.type.key, le.type),
                            "transform_values", (arr, le))
            if le.type is not BOOLEAN:
                raise AnalysisError("map_filter lambda must return boolean")
            return Call(arr.type, "map_filter", (arr, le))
        if not isinstance(arr.type, ArrayType):
            raise AnalysisError(f"{name} requires ARRAY, got {arr.type}")
        et = arr.type.element
        if name == "reduce":
            if len(node.args) != 3:
                raise AnalysisError(
                    "reduce(array, initial, (state, x) -> ...) expects 3 "
                    "arguments")
            init = self.analyze(node.args[1])
            le = self._an_lambda(node.args[2], [init.type, et])
            return Call(le.type, "reduce", (arr, init, le))
        le = self._an_lambda(node.args[1], [et])
        if name == "transform":
            return Call(ArrayType(le.type), "transform", (arr, le))
        if le.type is not BOOLEAN:
            raise AnalysisError(f"{name} lambda must return boolean")
        if name == "filter":
            return Call(arr.type, "filter", (arr, le))
        return Call(BOOLEAN, name, (arr, le))  # any/all/none_match

    _GEO_ALIASES = {
        "st_geometry_from_text": "st_geometryfromtext",
        "st_geomfromtext": "st_geometryfromtext",
        "st_as_text": "st_astext",
    }

    def _an_geo_fn(self, name: str, args) -> Optional[RowExpression]:
        """Geospatial functions (reference: presto-geospatial
        GeoFunctions.java). GEOMETRY values flow only between geo
        functions — ST_AsText is the way out, ST_GeometryFromText /
        ST_Point the ways in."""
        name = self._GEO_ALIASES.get(name, name)

        def need(n, what):
            if len(args) != n:
                raise AnalysisError(f"{what} takes {n} argument(s)")

        def geom(i):
            if args[i].type is not GEOMETRY:
                raise AnalysisError(
                    f"{name} argument {i + 1} must be a GEOMETRY "
                    f"(got {args[i].type})")

        if name == "st_geometryfromtext":
            need(1, name)
            if not args[0].type.is_string:
                raise AnalysisError(
                    "ST_GeometryFromText takes a varchar WKT argument")
            return Call(GEOMETRY, "st_geometryfromtext", args)
        if name == "st_point":
            need(2, name)
            return Call(GEOMETRY, "st_point",
                        tuple(self._to_double(a) for a in args))
        if name == "st_astext":
            need(1, name)
            geom(0)
            inner = args[0]
            if isinstance(inner, Call) and inner.fn == "st_geometryfromtext":
                return inner.args[0]  # text round-trips unchanged
            raise AnalysisError(
                "ST_AsText is supported only on geometries parsed from "
                "text (derived geometries have no stored representation)")
        if name in ("st_x", "st_y", "st_area", "st_perimeter", "st_length",
                    "st_xmin", "st_xmax", "st_ymin", "st_ymax"):
            need(1, name)
            geom(0)
            return Call(DOUBLE, name, args)
        if name == "st_npoints":
            need(1, name)
            geom(0)
            return Call(BIGINT, name, args)
        if name == "st_centroid":
            need(1, name)
            geom(0)
            return Call(GEOMETRY, name, args)
        if name in ("st_contains", "st_intersects", "st_within"):
            need(2, name)
            geom(0)
            geom(1)
            if name == "st_within":  # within(a, b) == contains(b, a)
                return Call(BOOLEAN, "st_contains", (args[1], args[0]))
            return Call(BOOLEAN, name, args)
        if name == "st_distance":
            need(2, name)
            geom(0)
            geom(1)
            return Call(DOUBLE, name, args)
        if name == "great_circle_distance":
            need(4, name)
            return Call(DOUBLE, name,
                        tuple(self._to_double(a) for a in args))
        return None

    def _an_structural_fn(self, name: str, args) -> Optional[RowExpression]:
        """ARRAY/MAP function typing (spi/type/ArrayType + MapType;
        scalar surface of operator/scalar array/map functions). Returns
        None when `name` is not structural (or is a polymorphic name like
        contains/concat applied to non-structural operands)."""
        t0 = args[0].type if args else None

        if name == "array_ctor":
            et = None
            for a in args:
                if isinstance(a, Constant) and a.value is None:
                    continue
                et = a.type if et is None else common_super_type(et, a.type)
            et = et or BIGINT
            coerced = []
            for a in args:
                if isinstance(a, Constant) and a.value is None:
                    coerced.append(Constant(et, None))
                elif isinstance(et, DecimalType):
                    coerced.append(self._rescale(a, et.scale))
                elif et is DOUBLE and a.type is not DOUBLE:
                    coerced.append(self._to_double(a))
                else:
                    coerced.append(a)
            return Call(ArrayType(et), "array_ctor", tuple(coerced))

        if name == "subscript":
            if isinstance(t0, ArrayType):
                return Call(t0.element, "subscript", args)
            if isinstance(t0, MapType):
                return Call(t0.value, "element_at", args)
            raise AnalysisError(f"[] requires ARRAY or MAP, got {t0}")
        if name == "element_at":
            if isinstance(t0, ArrayType):
                return Call(t0.element, "element_at", args)
            if isinstance(t0, MapType):
                return Call(t0.value, "element_at", args)
            raise AnalysisError(f"element_at requires ARRAY or MAP, got {t0}")
        if name == "cardinality":
            if t0.name == "hyperloglog":
                # HyperLogLogFunctions.cardinality: the sketch estimate,
                # evaluated once per distinct sketch entry
                return Call(BIGINT, "__hll_cardinality", args)
            if not isinstance(t0, (ArrayType, MapType)):
                raise AnalysisError(f"cardinality requires ARRAY or MAP, got {t0}")
            return Call(BIGINT, "cardinality", args)
        if name == "contains" and isinstance(t0, ArrayType):
            return Call(BOOLEAN, "contains", args)
        if name == "array_position":
            return Call(BIGINT, "array_position", args)
        if name == "array_remove":
            if not isinstance(t0, ArrayType):
                raise AnalysisError(f"array_remove requires ARRAY, got {t0}")
            if len(args) != 2:
                raise AnalysisError("array_remove(array, element)")
            et, xt = t0.element, args[1].type
            if not ((is_numeric(et) and is_numeric(xt))
                    or (et.is_string and xt.is_string) or et == xt):
                raise AnalysisError(
                    f"array_remove: cannot match {xt} against array({et})")
            return Call(t0, "array_remove", args)
        if name in ("array_min", "array_max"):
            if not isinstance(t0, ArrayType):
                raise AnalysisError(f"{name} requires ARRAY, got {t0}")
            return Call(t0.element, name, args)
        if name == "array_sum":
            if not isinstance(t0, ArrayType):
                raise AnalysisError(f"array_sum requires ARRAY, got {t0}")
            return Call(
                DOUBLE if is_floating(t0.element) else BIGINT, name, args)
        if name == "array_average":
            return Call(DOUBLE, name, args)
        if name in ("array_distinct", "array_sort"):
            if not isinstance(t0, ArrayType):
                raise AnalysisError(f"{name} requires ARRAY, got {t0}")
            return Call(t0, name, args)
        if name == "slice" and isinstance(t0, ArrayType):
            return Call(t0, "slice", args)
        if name == "sequence":
            for a in args:
                if not isinstance(a, Constant):
                    raise AnalysisError(
                        "sequence bounds must be constants (static array "
                        "width under XLA)")
            return Call(ArrayType(BIGINT), "sequence", args)
        if name == "repeat":
            if not isinstance(args[1], Constant):
                raise AnalysisError("repeat count must be a constant")
            return Call(ArrayType(args[0].type), "repeat", args)
        if name == "map":
            if len(args) != 2 or not all(isinstance(a.type, ArrayType) for a in args):
                raise AnalysisError("map() expects two ARRAY arguments")
            return Call(MapType(args[0].type.element, args[1].type.element),
                        "map", args)
        if name == "map_keys":
            if not isinstance(t0, MapType):
                raise AnalysisError(f"map_keys requires MAP, got {t0}")
            return Call(ArrayType(t0.key), "map_keys", args)
        if name == "map_values":
            if not isinstance(t0, MapType):
                raise AnalysisError(f"map_values requires MAP, got {t0}")
            return Call(ArrayType(t0.value), "map_values", args)
        if name == "concat" and isinstance(t0, ArrayType):
            out = t0
            for a in args[1:]:
                if not isinstance(a.type, ArrayType):
                    raise AnalysisError("concat mixes ARRAY and non-ARRAY")
                out = ArrayType(common_super_type(out.element, a.type.element))
            return Call(out, "concat", args)
        if name in ("array_union", "array_intersect", "array_except"):
            if len(args) != 2 or not all(
                    isinstance(a.type, ArrayType) for a in args):
                raise AnalysisError(f"{name} expects two ARRAY arguments")
            et = common_super_type(args[0].type.element,
                                   args[1].type.element)
            return Call(ArrayType(et), name, args)
        if name == "arrays_overlap":
            if len(args) != 2 or not all(
                    isinstance(a.type, ArrayType) for a in args):
                raise AnalysisError("arrays_overlap expects two ARRAYs")
            return Call(BOOLEAN, name, args)
        if name == "map_concat":
            if len(args) < 2 or not all(
                    isinstance(a.type, MapType) for a in args):
                raise AnalysisError("map_concat expects MAP arguments")
            t = args[0].type
            for a in args[1:]:
                if a.type.key.name != t.key.name:
                    raise AnalysisError("map_concat key types differ")
            if is_floating(t.key):
                raise AnalysisError(
                    "map_concat with floating-point keys is not supported")
            return Call(t, "map_concat", args)
        return None

    def _an_Parameter(self, node: "ast.Parameter") -> RowExpression:
        raise AnalysisError(
            "unbound prepared-statement parameter (use EXECUTE ... USING)")

    def _an_ScalarSubquery(self, node: ast.ScalarSubquery) -> RowExpression:
        return self.planner.plan_scalar_subquery(node.query)

    def _an_IntervalLiteral(self, node):
        raise AnalysisError("interval literal outside date arithmetic")


def _add_months_days(days: int, months: int) -> int:
    """Host-side month arithmetic on days-since-epoch (constant folding)."""
    from presto_tpu.expr.compile import _civil_from_days
    import numpy as np
    import jax.numpy as jnp

    y, m, d = _civil_from_days(jnp.asarray(days, jnp.int32))
    y, m, d = int(y), int(m), int(d)
    m0 = (m - 1) + months
    y += m0 // 12
    m = m0 % 12 + 1
    # clamp day to month length
    mdays = [31, 29 if (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)) else 28,
             31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m - 1]
    return days_from_civil(y, m, min(d, mdays))


# ---------------------------------------------------------------------------
# conjunct utilities


def _resolve_limit(limit) -> Optional[int]:
    """LIMIT is an int after parsing, or an AST node when it came from a
    bound (or unbound) prepared-statement parameter."""
    if limit is None or isinstance(limit, int):
        return limit
    if isinstance(limit, ast.Literal) and limit.kind == "integer":
        return int(limit.value)
    if isinstance(limit, ast.Parameter):
        raise AnalysisError(
            "unbound prepared-statement parameter in LIMIT "
            "(use EXECUTE ... USING)")
    raise AnalysisError("LIMIT must be an integer")


def split_conjuncts(e) -> List:
    if isinstance(e, ast.BinaryOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def combine_conjuncts(es: List[RowExpression]) -> Optional[RowExpression]:
    if not es:
        return None
    out = es[0]
    for e in es[1:]:
        out = Call(BOOLEAN, "and", (out, e))
    return out


# ---------------------------------------------------------------------------
# planner


class Planner:
    def __init__(self, catalog: Catalog, symbols: Optional[SymbolAllocator] = None,
                 ctes: Optional[Dict[str, ast.Query]] = None):
        self.catalog = catalog
        self.symbols = symbols or SymbolAllocator()
        self.ctes = dict(ctes or {})
        self.scalar_subqueries: Dict[str, QueryPlan] = {}

    # -- relations --------------------------------------------------------

    def plan_relation(self, rel) -> RelationPlan:
        if isinstance(rel, ast.Table):
            name = rel.name[-1]
            if len(rel.name) == 1 and name not in self.ctes and (
                    name in self.catalog.views):
                # view expansion: plan the stored query like a subquery
                sub = Planner(self.catalog, self.symbols)
                qp = sub.plan(self.catalog.views[name])
                self.scalar_subqueries.update(sub.scalar_subqueries)
                out = qp.root
                fields = [
                    Field(rel.alias or name, n, s, t)
                    for (n, s), (_, t) in zip(zip(out.names, out.symbols),
                                              out.output)
                ]
                return RelationPlan(out.child, Scope(fields), rows=1e5)
            if len(rel.name) == 1 and name in self.ctes:
                sub = Planner(self.catalog, self.symbols, self.ctes)
                qp = sub.plan(self.ctes[name])
                self.scalar_subqueries.update(sub.scalar_subqueries)
                out = qp.root
                fields = [
                    Field(rel.alias or name, n, s, t)
                    for (n, s), (_, t) in zip(zip(out.names, out.symbols), out.output)
                ]
                return RelationPlan(out.child, Scope(fields), rows=1e6)
            conn, handle = self.catalog.resolve(rel.name)
            qualifier = rel.alias or name
            assignments = {}
            output = []
            fields = []
            for c in handle.columns:
                sym = self.symbols.fresh(c.name)
                assignments[sym] = c.name
                output.append((sym, c.type))
                fields.append(Field(qualifier, c.name, sym, c.type))
            node = TableScan(catalog=conn.name, table=handle.name,
                             assignments=assignments, output=output)
            if handle.primary_key:
                col_to_sym = {c: s for s, c in assignments.items()}
                node.primary_key_symbols = [col_to_sym[c] for c in handle.primary_key]
            rows = handle.row_count or 1e6
            return RelationPlan(node, Scope(fields), rows=rows)
        if isinstance(rel, ast.SubqueryRelation):
            sub = Planner(self.catalog, self.symbols, self.ctes)
            qp = sub.plan(rel.query)
            self.scalar_subqueries.update(sub.scalar_subqueries)
            out = qp.root
            fields = [
                Field(rel.alias, n, s, t)
                for (n, s), (_, t) in zip(zip(out.names, out.symbols), out.output)
            ]
            return RelationPlan(out.child, Scope(fields), rows=1e5)
        if isinstance(rel, ast.ValuesRelation):
            sub = Planner(self.catalog, self.symbols, self.ctes)
            qp = sub.plan(rel.query)
            self.scalar_subqueries.update(sub.scalar_subqueries)
            out = qp.root
            names = list(rel.column_names or out.names)
            if len(names) != len(out.symbols):
                raise AnalysisError(
                    f"VALUES alias declares {len(names)} columns, rows "
                    f"have {len(out.symbols)}")
            fields = [
                Field(rel.alias, n, s, t)
                for (n, s), (_, t) in zip(zip(names, out.symbols), out.output)
            ]
            return RelationPlan(out.child, Scope(fields), rows=4.0)
        if isinstance(rel, ast.Join):
            return self.plan_join(rel)
        if isinstance(rel, ast.UnnestRelation):
            # top-level FROM UNNEST(ARRAY[...]): expand over one synthetic row
            return self.plan_unnest(rel, None)
        raise AnalysisError(f"unsupported relation {type(rel).__name__}")

    def plan_unnest(self, rel: ast.UnnestRelation,
                    left: Optional[RelationPlan]) -> RelationPlan:
        """UNNEST as a (lateral) relation: project the array/map expressions
        onto the input, then expand (reference: RelationPlanner.visitUnnest
        → planner/plan/UnnestNode; lateral column references resolve
        against the left relation like the reference's implicit lateral)."""
        if left is None:
            child: PlanNode = OneRow()
            scope = Scope([])
            rows = 1.0
        else:
            if isinstance(left.node, _PendingCross):
                raise AnalysisError(
                    "UNNEST after a comma-join chain is not supported; use "
                    "explicit CROSS JOIN ordering")
            child, scope, rows = left.node, left.scope, left.rows
        analyzer = ExprAnalyzer(scope, self)
        exprs = [analyzer.analyze(a) for a in rel.exprs]
        for e in exprs:
            if not isinstance(e.type, (ArrayType, MapType)):
                raise AnalysisError(
                    f"UNNEST argument must be ARRAY or MAP, got {e.type}")
        # project sources (keeping all existing columns)
        proj_exprs = [(f.symbol, InputRef(f.type, f.symbol))
                      for f in scope.fields]
        sources = []
        for e in exprs:
            s = self.symbols.fresh("unnest_src")
            proj_exprs.append((s, e))
            sources.append(s)
        proj = Project(child, proj_exprs)

        qualifier = rel.alias or "unnest"
        wanted = list(rel.column_names or [])
        out_syms, out_types, new_fields = [], [], []

        def take_name(default):
            return wanted.pop(0) if wanted else default

        for e, s in zip(exprs, sources):
            if isinstance(e.type, MapType):
                kn, vn = take_name("key"), take_name("value")
                ks = self.symbols.fresh(kn)
                vs = self.symbols.fresh(vn)
                out_syms.append([ks, vs])
                out_types.append([e.type.key, e.type.value])
                new_fields.append(Field(qualifier, kn, ks, e.type.key))
                new_fields.append(Field(qualifier, vn, vs, e.type.value))
            else:
                n = take_name("col")
                s2 = self.symbols.fresh(n)
                out_syms.append([s2])
                out_types.append([e.type.element])
                new_fields.append(Field(qualifier, n, s2, e.type.element))
        ord_sym = None
        if rel.ordinality:
            n = take_name("ordinality")
            ord_sym = self.symbols.fresh(n)
            new_fields.append(Field(qualifier, n, ord_sym, BIGINT))
        node = Unnest(
            child=proj,
            sources=sources,
            replicate=[f.symbol for f in scope.fields],
            out_syms=out_syms,
            out_types=out_types,
            ordinality_sym=ord_sym,
        )
        return RelationPlan(node, Scope(list(scope.fields) + new_fields),
                            rows=rows * 4)

    def plan_join(self, rel: ast.Join) -> RelationPlan:
        if isinstance(rel.right, ast.UnnestRelation):
            if rel.kind not in ("cross", "inner") or rel.condition is not None:
                raise AnalysisError(
                    "UNNEST is only supported with CROSS JOIN")
            return self.plan_unnest(rel.right, self.plan_relation(rel.left))
        # flatten pure cross-join chains into leaves for WHERE-driven ordering
        left = self.plan_relation(rel.left)
        right = self.plan_relation(rel.right)
        scope = left.scope + right.scope
        if rel.kind == "cross":
            # deferred: caller (plan_from_where) orders cross joins by
            # conjunct connectivity. Represent as a pending cross product.
            return RelationPlan(_PendingCross(left, right), scope,
                               rows=left.rows * right.rows)
        cond = ExprAnalyzer(scope, self).analyze(rel.condition) if rel.condition else None
        conjs = _split_ir_conjuncts(cond) if cond is not None else []
        lsyms = {f.symbol for f in left.scope.fields}
        rsyms = {f.symbol for f in right.scope.fields}
        lkeys, rkeys, residual = _extract_equi_keys(conjs, lsyms, rsyms)
        if rel.kind == "right":
            left, right = right, left
            lkeys, rkeys = rkeys, lkeys
            kind = "left"
        else:
            kind = rel.kind
        if not lkeys and kind != "cross":
            if kind != "inner":
                raise AnalysisError(
                    "outer joins require at least one equi-join condition")
            # non-equi INNER join → nested loop with the condition fused
            # (NestedLoopJoinOperator; build = right as written)
            node = NestedLoopJoin(left.node, right.node,
                                  residual=combine_conjuncts(residual) or cond)
            return RelationPlan(node, scope, rows=left.rows * right.rows)
        if kind == "left":
            # push build-side-only residuals into the build side (correct for
            # LEFT: non-matching build rows are dropped pre-join)
            keep = []
            for c in residual:
                syms = expr_inputs(c)
                if syms <= rsyms:
                    right = RelationPlan(Filter(right.node, c), right.scope, right.rows)
                else:
                    raise AnalysisError("left join residual on probe side unsupported")
            residual = keep
        if kind == "full" and residual:
            # an ON residual must not drop unmatched rows on either side;
            # no correct place to evaluate it outside the join yet
            raise AnalysisError("FULL JOIN with non-equi residual not supported")
        node = HashJoin(kind=kind, left=left.node, right=right.node,
                        left_keys=lkeys, right_keys=rkeys,
                        build_unique=_derives_unique(right.node, rkeys))
        out: PlanNode = node
        if residual:
            out = Filter(out, combine_conjuncts(residual))
        return RelationPlan(out, scope, rows=max(left.rows, right.rows))

    # -- set operations ---------------------------------------------------

    def plan_setop(self, q: ast.SetOp) -> QueryPlan:
        """UNION/INTERSECT/EXCEPT: plan both sides independently, align
        arity and types positionally, wrap in a SetOp node; a trailing
        ORDER BY/LIMIT sorts the combined result (reference:
        StatementAnalyzer set-operation analysis + UnionNode planning)."""
        ctes = dict(self.ctes)
        for name, sub in q.ctes:
            ctes[name] = sub

        def plan_side(side):
            sub = Planner(self.catalog, self.symbols, ctes)
            qp = sub.plan(side)
            self.scalar_subqueries.update(sub.scalar_subqueries)
            return qp

        lqp, rqp = plan_side(q.left), plan_side(q.right)
        lout, rout = lqp.root, rqp.root
        self.scalar_subqueries.update(lqp.scalar_subqueries)
        self.scalar_subqueries.update(rqp.scalar_subqueries)
        if len(lout.symbols) != len(rout.symbols):
            raise AnalysisError(
                f"{q.kind.upper()} arity mismatch: {len(lout.symbols)} vs "
                f"{len(rout.symbols)} columns")
        ltypes = [t for _, t in lout.output]
        rtypes = [t for _, t in rout.output]
        for i, (lt, rt) in enumerate(zip(ltypes, rtypes)):
            # exact logical-type compatibility: dtype equality is not
            # enough (decimal scales, dates and bigints all share int64 —
            # mixing them would compare raw representations)
            same = lt.name == rt.name or (
                lt.dtype == rt.dtype
                and not lt.is_string and not rt.is_string
                and not isinstance(lt, DecimalType)
                and not isinstance(rt, DecimalType)
                and lt.name not in ("date", "timestamp", "time")
                and rt.name not in ("date", "timestamp", "time")
            )
            if not same:
                raise AnalysisError(
                    f"{q.kind.upper()} column {i + 1} type mismatch: "
                    f"{lt} vs {rt}")
        symbols = [self.symbols.fresh(n or f"col{i}")
                   for i, n in enumerate(lout.names)]
        node: PlanNode = SetOp(q.kind, q.all, lout, rout, symbols, ltypes)

        # ORDER BY / LIMIT over the combined result (names or ordinals)
        if q.order_by:
            name_to_sym = dict(zip(lout.names, symbols))
            keys = []
            for oi in q.order_by:
                if isinstance(oi.expr, ast.Literal) and oi.expr.kind == "integer":
                    pos = int(oi.expr.value)
                    if not 1 <= pos <= len(symbols):
                        raise AnalysisError(
                            f"ORDER BY position {pos} out of range "
                            f"(1..{len(symbols)})")
                    sym = symbols[pos - 1]
                elif isinstance(oi.expr, ast.Identifier):
                    nm = oi.expr.parts[-1]
                    if nm not in name_to_sym:
                        raise AnalysisError(f"ORDER BY column {nm} not in output")
                    sym = name_to_sym[nm]
                else:
                    raise AnalysisError(
                        "set-operation ORDER BY supports output columns only")
                keys.append(SortItem(sym, oi.ascending, oi.nulls_first))
            node = Sort(node, keys, q.limit)
        elif q.limit is not None:
            node = Limit(node, q.limit)
        root = Output(node, list(lout.names), symbols)
        return QueryPlan(root, self.scalar_subqueries,
                         cacheable=not self.symbols.volatile_plan)

    # -- query ------------------------------------------------------------

    def plan(self, q) -> QueryPlan:
        if isinstance(q, ast.SetOp):
            return self.plan_setop(q)
        q = dataclasses.replace(q, limit=_resolve_limit(q.limit))
        ctes = dict(self.ctes)
        for name, sub in q.ctes:
            ctes[name] = sub
        self.ctes = ctes

        from presto_tpu.plan.decorrelate import decorrelate

        q = decorrelate(q, self.catalog, self.ctes)

        if q.from_ is None:
            # SELECT <exprs> with no FROM: one synthetic row (the
            # reference's ValuesNode single-row plan)
            rp = RelationPlan(OneRow(), Scope([]), rows=1.0)
        else:
            rp = self.plan_relation(q.from_)

        # WHERE: analyze conjuncts; subquery predicates become semi-joins
        where_conjs_ast = split_conjuncts(q.where) if q.where is not None else []
        plain_conjs_ast = []
        semi_asts = []
        for c in where_conjs_ast:
            # NOT EXISTS / NOT IN parse as UnaryOp('not', ...); fold the
            # negation into the subquery predicate node
            if isinstance(c, ast.UnaryOp) and c.op == "not" and isinstance(
                c.operand, (ast.InSubquery, ast.Exists)
            ):
                c = dataclasses.replace(c.operand, negated=not c.operand.negated)
            if isinstance(c, ast.InSubquery):
                semi_asts.append(("in", c))
            elif isinstance(c, ast.Exists):
                semi_asts.append(("exists", c))
            else:
                plain_conjs_ast.append(c)

        node, scope, residuals = self._assemble_joins(rp, plain_conjs_ast)

        for kind, c in semi_asts:
            node = self._plan_semijoin(node, scope, kind, c)

        if residuals:
            node = Filter(node, combine_conjuncts(residuals))

        # aggregation?
        has_group = bool(q.group_by)
        has_aggs = any(_contains_agg(it.expr) for it in q.select) or (
            q.having is not None and _contains_agg(q.having)
        )

        select_items = list(q.select)
        # expand stars
        expanded = []
        for it in select_items:
            if isinstance(it.expr, ast.Star):
                for f in scope.fields:
                    if it.expr.qualifier and f.qualifier != it.expr.qualifier:
                        continue
                    expanded.append(ast.SelectItem(ast.Identifier((f.name,)), None))
            else:
                expanded.append(it)
        select_items = expanded

        # resolve group-by ordinals
        group_by = []
        for g in q.group_by:
            if isinstance(g, ast.Literal) and g.kind == "integer":
                group_by.append(select_items[int(g.value) - 1].expr)
            else:
                group_by.append(g)

        if has_group or has_aggs:
            node, post_scope_repl = self._plan_aggregation(
                node, scope, select_items, group_by, q.having
            )
            analyzer = ExprAnalyzer(scope, self, replacements=post_scope_repl)
            if q.having is not None:
                having_ast = _rewrite_aggs_to_keys(q.having)
                node = Filter(node, analyzer.analyze(having_ast))
        else:
            analyzer = ExprAnalyzer(scope, self)

        # window functions (computed after WHERE/GROUP BY/HAVING, before the
        # select projection — SQL evaluation order)
        windows: List[ast.WindowFunction] = []

        def collect_windows(n):
            if isinstance(n, ast.WindowFunction):
                windows.append(n)
            for ch in _ast_children(n):
                collect_windows(ch)

        for it in select_items:
            collect_windows(it.expr)
        for oi in q.order_by or []:
            collect_windows(oi.expr)
        if windows:
            node = self._plan_windows(node, analyzer, windows)

        if has_group or has_aggs:
            select_exprs = [
                analyzer.analyze(_rewrite_aggs_to_keys(it.expr)) for it in select_items
            ]
        else:
            select_exprs = [analyzer.analyze(it.expr) for it in select_items]

        # select projection
        proj_exprs: List[Tuple[str, RowExpression]] = []
        display_names: List[str] = []
        select_symbols: List[str] = []
        alias_map: Dict[str, Tuple[str, Type]] = {}
        host_items: List[tuple] = []  # HostProject finishing items
        host_syms: set = set()
        # (symbol, type) per SELECT item, aligned with select_items — the
        # ORDER BY resolver must not zip proj_exprs (host items don't
        # always add a projection)
        select_sym_types: List[Tuple[str, Type]] = []
        for it, e in zip(select_items, select_exprs):
            name = it.alias or _derive_name(it.expr)
            if e.type is GEOMETRY:
                raise AnalysisError(
                    "GEOMETRY values cannot be output directly — wrap the "
                    "expression in ST_AsText(...)")
            hs = _host_split(e)
            if hs is not None:
                # string-producing host function (cast-to-varchar /
                # date_format): its DEVICE input rides the projection; the
                # formatting happens in a HostProject above the root
                inner, kind, param = hs
                if isinstance(inner, InputRef):
                    in_sym = inner.name
                else:
                    in_sym = self.symbols.fresh("hostin")
                if not any(s == in_sym for s, _ in proj_exprs):
                    proj_exprs.append((in_sym, inner))
                sym = self.symbols.fresh(it.alias or name)
                host_items.append((sym, kind, in_sym, param))
                host_syms.add(sym)
                display_names.append(name)
                select_symbols.append(sym)
                select_sym_types.append((sym, VARCHAR))
                if it.alias:
                    # ORDER BY <alias> must bind here (and then fail the
                    # host-sym check), not to a same-named table column
                    alias_map[f"id:{it.alias}"] = (sym, VARCHAR)
                continue
            if isinstance(e, InputRef) and it.alias is None:
                sym = e.name
            else:
                sym = self.symbols.fresh(it.alias or name)
            proj_exprs.append((sym, e))
            display_names.append(name)
            select_symbols.append(sym)
            select_sym_types.append((sym, e.type))
            if it.alias:
                alias_map[f"id:{it.alias}"] = (sym, e.type)

        # ORDER BY may reference select aliases, ordinals, or agg exprs
        sort_items: List[SortItem] = []
        extra_order_exprs: List[Tuple[str, RowExpression]] = []
        if q.order_by:
            repl = dict(getattr(analyzer, "replacements", {}))
            repl.update(alias_map)
            # select expressions themselves are available as symbols
            # (aligned per select item — proj_exprs may not be)
            for (sym, ty), it in zip(select_sym_types, select_items):
                repl.setdefault(ast_key(it.expr), (sym, ty))
            order_an = ExprAnalyzer(scope, self, replacements=repl)
            for oi in q.order_by:
                if isinstance(oi.expr, ast.Literal) and oi.expr.kind == "integer":
                    pos = int(oi.expr.value)
                    if not 1 <= pos <= len(select_symbols):
                        raise AnalysisError(
                            f"ORDER BY position {pos} out of range "
                            f"(1..{len(select_symbols)})")
                    sym = select_symbols[pos - 1]
                    if sym in host_syms:
                        raise AnalysisError(
                            "ORDER BY on a host-computed expression "
                            "(cast to varchar / date_format) is not "
                            "supported — order by the underlying value")
                else:
                    e = order_an.analyze(
                        _rewrite_aggs_to_keys(oi.expr) if (has_group or has_aggs) else oi.expr
                    )
                    if isinstance(e, InputRef):
                        sym = e.name
                        if sym in host_syms:
                            raise AnalysisError(
                                "ORDER BY on a host-computed expression "
                                "(cast to varchar / date_format) is not "
                                "supported — order by the underlying value")
                        # ORDER BY a non-selected column: the sort key must
                        # ride through the projection (Output drops it)
                        if not any(s == sym for s, _ in proj_exprs) and not any(
                                s == sym for s, _ in extra_order_exprs):
                            extra_order_exprs.append((sym, e))
                    else:
                        if _host_split(e) is not None:
                            raise AnalysisError(
                                "ORDER BY on a host-computed expression "
                                "(cast to varchar / date_format) is not "
                                "supported — order by the underlying value")
                        sym = self.symbols.fresh("orderkey")
                        extra_order_exprs.append((sym, e))
                sort_items.append(SortItem(sym, oi.ascending, oi.nulls_first))

        node = Project(node, proj_exprs + extra_order_exprs)

        if q.distinct:
            if host_items:
                raise AnalysisError(
                    "SELECT DISTINCT over host-computed expressions "
                    "(cast to varchar / date_format) is not supported")
            node = Aggregate(node, [s for s, _ in proj_exprs], [], step="single")

        if sort_items:
            node = Sort(node, sort_items, limit=q.limit)
        elif q.limit is not None:
            node = Limit(node, q.limit)

        if host_items:
            from presto_tpu.plan.nodes import HostProject

            node = HostProject(node, host_items)

        root = Output(node, display_names, select_symbols)
        return QueryPlan(root, dict(self.scalar_subqueries),
                         cacheable=not self.symbols.volatile_plan)

    # -- join assembly from comma-FROM + WHERE ----------------------------

    def _assemble_joins(self, rp: RelationPlan, conjs_ast) -> Tuple[PlanNode, Scope, List[RowExpression]]:
        scope = rp.scope
        analyzer = ExprAnalyzer(scope, self)
        conjs = [analyzer.analyze(c) for c in conjs_ast]

        leaves: List[RelationPlan] = []
        _collect_cross_leaves(rp, leaves)
        if len(leaves) == 1:
            return rp.node, scope, conjs

        # Stats-driven greedy join ordering (CBO v1 — the role of
        # ReorderJoins.java:94 with JoinStatsRule estimates): each leaf's
        # cardinality is adjusted by the selectivity of its single-leaf
        # WHERE conjuncts; each step joins the connected leaf minimizing the
        # estimated intermediate; the smaller estimated side builds.
        from presto_tpu.plan.stats import NodeStats, derive, filter_selectivity

        def leaf_estimate(leaf: RelationPlan, pending) -> Tuple[float, Optional[NodeStats]]:
            st = derive(leaf.node, self.catalog)
            rows = st.rows if st is not None else leaf.rows
            if st is not None:
                syms = {f.symbol for f in leaf.scope.fields}
                for c in pending:
                    if expr_inputs(c) <= syms:
                        rows *= filter_selectivity(c, st)
            return max(rows, 1.0), st

        def join_out_estimate(a_rows, a_st, a_keys, b_rows, b_st, b_keys) -> float:
            ndvs = []
            for ak, bk in zip(a_keys, b_keys):
                for st, k in ((a_st, ak), (b_st, bk)):
                    cs = st.col(k) if st is not None else None
                    if cs is not None and cs.ndv:
                        ndvs.append(cs.ndv)
            if ndvs:
                return max(1.0, a_rows * b_rows / max(ndvs))
            return max(a_rows, b_rows)

        remaining = list(leaves)
        pending = list(conjs)
        est = {id(l): leaf_estimate(l, pending) for l in remaining}

        # DP plan enumeration (ReorderJoins.java:94 — there a memo over
        # MultiJoinNode partitions, here bushy DP over connected subsets)
        # when the join graph is connected and small enough. Cost model:
        # Σ per join (probe_rows + 2·build_rows + out_rows) — probing is a
        # stream pass, building sorts (≈2×), output rows feed the parent.
        # The greedy below remains the fallback (disconnected graphs, >10
        # relations), deliberately starting from the fact table; DP instead
        # can discover plans like (customer⋈orders)⋈lineitem where the big
        # fact relation flows through ONE join against a pre-reduced build.
        if 2 <= len(leaves) <= 10:
            dp_out = self._dp_join_order(leaves, pending, est,
                                         join_out_estimate)
            if dp_out is not None:
                node, pending = dp_out
                return node, scope, pending

        # start from the largest relation (likely the fact table → probe side)
        remaining.sort(key=lambda r: -est[id(r)][0])
        current = remaining.pop(0)
        cur_rows, cur_st = est[id(current)]
        while remaining:
            cur_syms = {f.symbol for f in current.scope.fields}
            best = None
            for leaf in remaining:
                leaf_syms = {f.symbol for f in leaf.scope.fields}
                lkeys, rkeys, rest = _extract_equi_keys(pending, cur_syms, leaf_syms)
                if not lkeys:
                    continue
                leaf_rows, leaf_st = est[id(leaf)]
                out_rows = join_out_estimate(cur_rows, cur_st, lkeys,
                                             leaf_rows, leaf_st, rkeys)
                if best is None or out_rows < best[0]:
                    best = (out_rows, leaf, lkeys, rkeys, rest, leaf_rows, leaf_st)
            if best is None:
                # disconnected join graph: cross product via nested loop
                # against the smallest remaining leaf (ReorderJoins keeps
                # cross products last for the same reason); conjuncts that
                # span the two sides (non-equi) fuse as the residual
                remaining.sort(key=lambda r: est[id(r)][0])
                leaf = remaining.pop(0)
                leaf_rows, leaf_st = est[id(leaf)]
                cur_syms2 = cur_syms | {f.symbol for f in leaf.scope.fields}
                covered = [c for c in pending if expr_inputs(c) <= cur_syms2]
                pending = [c for c in pending if expr_inputs(c) > cur_syms2]
                node = NestedLoopJoin(current.node, leaf.node,
                                      residual=combine_conjuncts(covered))
                out_rows = max(cur_rows * leaf_rows, 1.0)
                merged_cols = {}
                for st in (cur_st, leaf_st):
                    if st is not None:
                        merged_cols.update(st.columns)
                cur_st = NodeStats(out_rows, merged_cols)
                cur_rows = out_rows
                current = RelationPlan(node, current.scope + leaf.scope,
                                       rows=out_rows)
                continue
            out_rows, leaf, lkeys, rkeys, rest, leaf_rows, leaf_st = best
            remaining.remove(leaf)
            # consumed conjuncts: pending minus rest
            pending = rest
            if leaf_rows <= cur_rows:
                probe, build = current, leaf
                pkeys, bkeys = lkeys, rkeys
            else:
                probe, build = leaf, current
                pkeys, bkeys = rkeys, lkeys
            node = HashJoin(
                kind="inner", left=probe.node, right=build.node,
                left_keys=pkeys, right_keys=bkeys,
                build_unique=_derives_unique(build.node, bkeys),
            )
            merged_cols = {}
            for st in (cur_st, leaf_st):
                if st is not None:
                    merged_cols.update(st.columns)
            cur_st = NodeStats(out_rows, merged_cols)
            cur_rows = out_rows
            current = RelationPlan(node, probe.scope + build.scope,
                                   rows=out_rows)
        # apply any conjunct that is now fully covered; keep the rest as residuals
        return current.node, scope, pending

    def _notnull_side(self, node: PlanNode, keys: List[str]) -> PlanNode:
        """IS NOT NULL inference (reference: the predicate-inference half of
        optimizations/PredicatePushDown — inner-join equi keys can't match
        NULL, so null rows are droppable BEFORE the join). Skipped when
        stats prove the column never null (filter would be a no-op)."""
        from presto_tpu.plan.stats import derive

        try:
            st = derive(node, self.catalog)
        except Exception:
            st = None
        types = dict(node.output)
        conjs = []
        for k in keys:
            cs = st.col(k) if st is not None else None
            if cs is not None and cs.null_fraction == 0.0:
                continue
            conjs.append(Call(BOOLEAN, "is_not_null",
                              (InputRef(types[k], k),)))
        if not conjs:
            return node
        return Filter(node, combine_conjuncts(conjs))

    def _dp_join_order(self, leaves, conjs, est, join_out_estimate):
        """Bushy dynamic-programming join enumeration over connected
        subsets. Returns (root PlanNode, leftover conjuncts) or None when
        the join graph is disconnected (caller falls back to the greedy
        path, which handles cross products)."""
        from presto_tpu.plan.stats import NodeStats

        n = len(leaves)
        syms = [frozenset(f.symbol for f in l.scope.fields) for l in leaves]
        full = (1 << n) - 1

        def mask_syms(mask):
            s = set()
            for i in range(n):
                if mask >> i & 1:
                    s |= syms[i]
            return s

        # connectivity over equi edges (cross-join elimination: DP only
        # combines subsets an equi conjunct connects)
        parent = list(range(n))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for i in range(n):
            for j in range(i + 1, n):
                lk, _, _ = _extract_equi_keys(conjs, syms[i], syms[j])
                if lk:
                    parent[find(i)] = find(j)
        if len({find(i) for i in range(n)}) != 1:
            return None

        # dp[mask] = (cost, rows, stats, repr) where repr is a leaf index
        # or (maskA, maskB) with A the probe (larger) side
        dp = {}
        for i, leaf in enumerate(leaves):
            rows, st = est[id(leaf)]
            dp[1 << i] = (0.0, rows, st, i)
        msyms = {1 << i: syms[i] for i in range(n)}

        for mask in range(3, full + 1):
            if mask in dp or bin(mask).count("1") < 2:
                continue
            best = None
            sub = (mask - 1) & mask
            while sub:
                other = mask ^ sub
                if sub < other:  # each unordered split once
                    a, b = dp.get(sub), dp.get(other)
                    if a is not None and b is not None:
                        sa = msyms.get(sub)
                        if sa is None:
                            sa = msyms[sub] = frozenset(mask_syms(sub))
                        sb = msyms.get(other)
                        if sb is None:
                            sb = msyms[other] = frozenset(mask_syms(other))
                        lk, rk, _ = _extract_equi_keys(conjs, sa, sb)
                        if lk:
                            out = join_out_estimate(a[1], a[2], lk,
                                                    b[1], b[2], rk)
                            probe, build = max(a[1], b[1]), min(a[1], b[1])
                            cost = (a[0] + b[0] + probe + 2.0 * build + out)
                            if best is None or cost < best[0]:
                                pa, pb = ((sub, other) if a[1] >= b[1]
                                          else (other, sub))
                                merged = {}
                                for st in (a[2], b[2]):
                                    if st is not None:
                                        merged.update(st.columns)
                                best = (cost, out,
                                        NodeStats(out, merged), (pa, pb))
                sub = (sub - 1) & mask
            if best is not None:
                dp[mask] = best
        if full not in dp:
            return None

        pending = list(conjs)

        def build_tree(mask):
            entry = dp[mask]
            if isinstance(entry[3], int):
                leaf = leaves[entry[3]]
                return leaf.node, msyms[mask]
            pa, pb = entry[3]
            lnode, lsyms = build_tree(pa)
            rnode, rsyms = build_tree(pb)
            nonlocal pending
            lk, rk, pending = _extract_equi_keys(pending, lsyms, rsyms)
            node = HashJoin(
                kind="inner",
                left=self._notnull_side(lnode, lk),
                right=self._notnull_side(rnode, rk),
                left_keys=lk, right_keys=rk,
                build_unique=_derives_unique(rnode, rk),
            )
            return node, msyms.setdefault(mask, frozenset(mask_syms(mask)))

        root, _ = build_tree(full)
        return root, pending

    # -- semi joins -------------------------------------------------------

    def _plan_semijoin(self, node: PlanNode, scope: Scope, kind: str, c) -> PlanNode:
        sub = Planner(self.catalog, self.symbols, self.ctes)
        if kind == "in":
            qp = sub.plan(c.query)
            self.scalar_subqueries.update(sub.scalar_subqueries)
            out = qp.root
            if len(out.symbols) != 1:
                raise AnalysisError("IN subquery must produce one column")
            left_e = ExprAnalyzer(scope, self).analyze(c.value)
            if not isinstance(left_e, InputRef):
                raise AnalysisError("IN subquery LHS must be a column")
            return SemiJoin(node, out.child, [left_e.name], [out.symbols[0]], c.negated)
        # correlated [NOT] EXISTS (reference: TransformExistsApplyToLateralNode
        # + PlanNodeDecorrelator → SemiJoinNode). The subquery's WHERE is split
        # into pure-inner conjuncts (stay inside the build plan), equi
        # correlation pairs (become semi-join keys), and residual correlated
        # conjuncts (become the semi-join residual, evaluated over probe∪build
        # pairs — covers Q21's `l2.l_suppkey <> l1.l_suppkey`).
        sq = c.query
        if sq.group_by or sq.having or sq.order_by or sq.limit:
            raise AnalysisError("EXISTS subquery with group/order/limit unsupported")
        for name, cq in sq.ctes:
            sub.ctes[name] = cq
        rel = sub.plan_relation(sq.from_)
        inner_scope = rel.scope
        inner_syms = {f.symbol for f in inner_scope.fields}
        combined = scope + inner_scope
        combined_an = ExprAnalyzer(combined, self)
        inner_an = ExprAnalyzer(inner_scope, sub)
        pure_inner: List[RowExpression] = []
        correlated: List[RowExpression] = []
        for conj in split_conjuncts(sq.where) if sq.where is not None else []:
            try:
                pure_inner.append(inner_an.analyze(conj))
            except AnalysisError:
                correlated.append(combined_an.analyze(conj))
        # after the conjunct loop: scalar subqueries inside the EXISTS WHERE
        # register params on the sub-planner during analysis above
        self.scalar_subqueries.update(sub.scalar_subqueries)
        outer_syms = {f.symbol for f in scope.fields}
        lkeys, rkeys, residual = _extract_equi_keys(correlated, outer_syms, inner_syms)
        if not lkeys:
            raise AnalysisError("uncorrelated / non-equi-correlated EXISTS unsupported")
        build = rel.node
        if pure_inner:
            build = Filter(build, combine_conjuncts(pure_inner))
        return SemiJoin(node, build, lkeys, rkeys, c.negated,
                        residual=combine_conjuncts(residual), null_aware=False)

    # -- window functions -------------------------------------------------

    def _plan_windows(self, node: PlanNode, analyzer: "ExprAnalyzer",
                      windows: List[ast.WindowFunction]) -> PlanNode:
        """Lower window function instances onto the plan: pre-project any
        computed inputs, group instances by (partition, order) spec, stack a
        Window node per spec, and register replacements so the select/order
        analyzers resolve each OVER() expression to its output symbol
        (reference: sql/planner/QueryPlanner.window + WindowNode)."""
        from presto_tpu.plan.nodes import Window, WindowFunc

        pre_exprs: List[Tuple[str, RowExpression]] = [
            (s, InputRef(t, s)) for s, t in node.output
        ]
        added = False

        def to_symbol(e_ast) -> Tuple[str, Type]:
            nonlocal added
            e = analyzer.analyze(_rewrite_aggs_to_keys(e_ast))
            if isinstance(e, InputRef):
                return e.name, e.type
            sym = self.symbols.fresh("winexpr")
            pre_exprs.append((sym, e))
            added = True
            return sym, e.type

        def const_int(e_ast, what: str) -> int:
            e = analyzer.analyze(e_ast)
            if not isinstance(e, Constant) or e.value is None:
                raise AnalysisError(f"{what} must be an integer literal")
            return int(e.value)

        specs: Dict[tuple, tuple] = {}
        for w in windows:
            key = ast_key(w)
            if key in analyzer.replacements:
                continue
            part_syms = [to_symbol(p)[0] for p in w.partition_by]
            order_pairs = [to_symbol(oi.expr) for oi in w.order_by]
            order_items = [
                SortItem(sym, oi.ascending, oi.nulls_first)
                for (sym, _), oi in zip(order_pairs, w.order_by)
            ]
            if (w.frame and w.frame.startswith("range:")
                    and any(b[0] in "pf" for b in w.frame.split(":")[1:])):
                # value-offset RANGE frame: one numeric/temporal sort key
                # (reference: WindowFrameTypeCheck in sql/analyzer)
                if len(order_pairs) != 1:
                    raise AnalysisError(
                        "RANGE frame with value offsets requires exactly "
                        "one ORDER BY key")
                ot = order_pairs[0][1]
                if ot is TIMESTAMP:
                    # bare integer offsets would silently mean microseconds;
                    # reject until INTERVAL offsets exist (cast to date)
                    raise AnalysisError(
                        "RANGE frame offsets over a timestamp ORDER BY key "
                        "are not supported (cast the key to date — offsets "
                        "are then in days)")
                if not (is_integral(ot) or is_floating(ot)
                        or isinstance(ot, DecimalType) or ot is DATE):
                    raise AnalysisError(
                        "RANGE frame offsets require a numeric or date "
                        f"ORDER BY key (date offsets are in days), got {ot}")
                if isinstance(ot, DecimalType) and ot.precision > 18:
                    # two-limb int128 decimals: only the low limb reaches
                    # the frame binary search, so comparisons would lie
                    raise AnalysisError(
                        "RANGE frame offsets over decimal keys wider than "
                        "18 digits are not supported")
            name = w.name.lower()
            arg_sym: Optional[str] = None
            param: Optional[int] = None
            default: Optional[object] = None
            if name in ("row_number", "rank", "dense_rank"):
                t: Type = BIGINT
            elif name in ("percent_rank", "cume_dist"):
                t = DOUBLE
            elif name == "ntile":
                param = const_int(w.args[0], "ntile buckets")
                t = BIGINT
            elif name in ("lag", "lead"):
                arg_sym, t = to_symbol(w.args[0])
                param = const_int(w.args[1], f"{name} offset") if len(w.args) > 1 else 1
                if len(w.args) > 2:
                    de = analyzer.analyze(w.args[2])
                    if not isinstance(de, Constant):
                        raise AnalysisError(
                            f"{name} default must be a literal")
                    if de.value is None:
                        pass  # NULL default == no default
                    elif t.is_string or de.type.is_string:
                        raise AnalysisError(
                            f"{name} default on string columns is not "
                            "supported")
                    elif t is BOOLEAN:
                        if de.type is not BOOLEAN:
                            raise AnalysisError(
                                f"{name} default must be boolean for a "
                                "boolean column")
                        default = bool(de.value)
                    elif isinstance(t, DecimalType):
                        # store in the column's unscaled representation
                        default = int(round(float(de.value) * 10 ** t.scale))
                    elif is_integral(t):
                        if float(de.value) != int(float(de.value)):
                            raise AnalysisError(
                                f"{name} default {de.value} does not fit "
                                f"the {t} column (would truncate)")
                        default = int(de.value)
                    elif is_floating(t):
                        default = float(de.value)
                    elif t is DATE or t is TIMESTAMP:
                        default = int(de.value)
                    else:
                        raise AnalysisError(
                            f"{name} default unsupported for {t}")
            elif name in ("first_value", "last_value"):
                arg_sym, t = to_symbol(w.args[0])
            elif name == "nth_value":
                arg_sym, t = to_symbol(w.args[0])
                param = const_int(w.args[1], "nth_value n")
            elif name in _AGG_FUNCS:
                if w.is_star or (name == "count" and not w.args):
                    name, t = "count", BIGINT
                else:
                    arg_sym, arg_t = to_symbol(w.args[0])
                    t = _agg_output_type(name, arg_t, False)
            else:
                raise AnalysisError(f"unknown window function {name}")
            if name in ("row_number", "rank", "dense_rank", "percent_rank",
                        "cume_dist", "ntile", "lag", "lead") and not w.order_by:
                raise AnalysisError(f"{name}() requires ORDER BY in its OVER clause")
            wsym = self.symbols.fresh(name)
            skey = (
                tuple(part_syms),
                tuple((o.symbol, o.ascending, o.nulls_first) for o in order_items),
            )
            if skey not in specs:
                specs[skey] = (part_syms, order_items, [])
            specs[skey][2].append(
                WindowFunc(wsym, name, t, arg_sym, param, frame=w.frame,
                           default=default)
            )
            analyzer.replacements[key] = (wsym, t)

        if added:
            node = Project(node, pre_exprs)
        for part_syms, order_items, funcs in specs.values():
            node = Window(node, part_syms, order_items, funcs)
        return node

    # -- scalar subqueries ------------------------------------------------

    def plan_scalar_subquery(self, q: ast.Query) -> RowExpression:
        sub = Planner(self.catalog, self.symbols, self.ctes)
        qp = sub.plan(q)
        self.scalar_subqueries.update(sub.scalar_subqueries)
        out = qp.root
        if len(out.symbols) != 1:
            raise AnalysisError("scalar subquery must produce one column")
        sym = self.symbols.fresh("param")
        t = out.output[0][1]
        self.scalar_subqueries[sym] = qp
        from presto_tpu.expr.ir import Param

        return Param(t, sym)

    # -- aggregation ------------------------------------------------------

    def _plan_aggregation(self, node, scope, select_items, group_by, having):
        analyzer = ExprAnalyzer(scope, self)

        # collect aggregates from select + having
        aggs_by_key: Dict[str, ast.FunctionCall] = {}
        grouping_calls: Dict[str, ast.FunctionCall] = {}

        def collect(n):
            if isinstance(n, ast.FunctionCall) and _is_agg_fn(n.name.lower()):
                aggs_by_key.setdefault("agg:" + ast_key(n), n)
                return
            if isinstance(n, ast.FunctionCall) and n.name.lower() == "grouping":
                grouping_calls.setdefault(ast_key(n), n)
                return
            for child in _ast_children(n):
                collect(child)

        for it in select_items:
            collect(it.expr)
        if having is not None:
            collect(having)

        # GROUPING SETS / ROLLUP / CUBE: the full key list is the ordered
        # union of all sets; each set plans its own aggregate below
        grouping_sets: Optional[List[List[str]]] = None
        set_asts: Optional[list] = None
        if len(group_by) == 1 and isinstance(group_by[0], ast.GroupingSets):
            set_asts = group_by[0].sets
            seen_keys: Dict[str, ast.Node] = {}
            for s in set_asts:
                for g in s:
                    seen_keys.setdefault(ast_key(g), g)
            group_by = list(seen_keys.values())

        # pre-projection: group keys + agg args
        pre_exprs: List[Tuple[str, RowExpression]] = []
        group_syms: List[str] = []
        repl: Dict[str, Tuple[str, Type]] = {}
        for g in group_by:
            e = analyzer.analyze(g)
            if isinstance(e.type, (ArrayType, MapType)):
                raise AnalysisError("GROUP BY on ARRAY/MAP is not supported")
            if isinstance(e, InputRef):
                sym = e.name
            else:
                sym = self.symbols.fresh("groupkey")
            pre_exprs.append((sym, e))
            group_syms.append(sym)
            repl["id:" + sym] = (sym, e.type)
            repl[ast_key(g)] = (sym, e.type)

        agg_specs: List[AggSpec] = []
        for key, fc in aggs_by_key.items():
            fn = _AGG_CANON.get(fc.name.lower(), fc.name.lower())
            distinct = fc.distinct
            arg2_sym = None
            param = None
            if fc.is_star:
                arg_sym = None
                arg_t = BIGINT
            else:
                if fn == "numeric_histogram":
                    # numeric_histogram(buckets, x) — buckets is the
                    # leading CONSTANT (NumericHistogramAggregation)
                    if len(fc.args) != 2:
                        raise AnalysisError(
                            "numeric_histogram(buckets, x) takes two "
                            "arguments")
                    be = analyzer.analyze(fc.args[0])
                    from presto_tpu.expr.ir import Constant as _Const

                    if not isinstance(be, _Const) or be.value is None:
                        raise AnalysisError(
                            "numeric_histogram bucket count must be a "
                            "constant")
                    param = float(int(be.value))
                    if param < 2:
                        raise AnalysisError("bucket count must be >= 2")
                    ae = analyzer._to_double(analyzer.analyze(fc.args[1]))
                elif fn == "tdigest_agg":
                    # tdigest_agg(x[, w][, compression]) — weight is a
                    # column, compression a constant (reference:
                    # TDigestAggregationFunction signatures)
                    if not 1 <= len(fc.args) <= 3:
                        raise AnalysisError(
                            "tdigest_agg(x[, w][, compression]) takes "
                            "1-3 arguments")
                    ae = analyzer._to_double(analyzer.analyze(fc.args[0]))
                    if len(fc.args) == 3:
                        from presto_tpu.expr.ir import Constant as _Const

                        ce = analyzer.analyze(fc.args[2])
                        if not isinstance(ce, _Const) or ce.value is None:
                            raise AnalysisError(
                                "tdigest_agg compression must be a constant")
                        param = float(ce.value)
                        if param < 10:
                            raise AnalysisError("compression must be >= 10")
                elif fn == "merge":
                    if len(fc.args) != 1:
                        raise AnalysisError("merge(sketch) takes one argument")
                    ae = analyzer.analyze(fc.args[0])
                    if ae.type.name not in ("tdigest(double)",
                                            "hyperloglog"):
                        raise AnalysisError(
                            f"merge expects tdigest or hyperloglog, "
                            f"got {ae.type}")
                else:
                    ae = analyzer.analyze(fc.args[0])
                if isinstance(ae, InputRef):
                    arg_sym = ae.name
                else:
                    arg_sym = self.symbols.fresh(f"{fn}_arg")
                if not any(s == arg_sym for s, _ in pre_exprs):
                    pre_exprs.append((arg_sym, ae))
                arg_t = ae.type
                if fn in _TWO_ARG_AGGS:
                    if len(fc.args) < 2:
                        raise AnalysisError(f"{fn} takes two arguments")
                    ae2 = analyzer.analyze(fc.args[1])
                    arg2_t = ae2.type
                    if isinstance(ae2, InputRef):
                        arg2_sym = ae2.name
                    else:
                        arg2_sym = self.symbols.fresh(f"{fn}_arg2")
                    if not any(s == arg2_sym for s, _ in pre_exprs):
                        pre_exprs.append((arg2_sym, ae2))
                elif fn == "tdigest_agg" and len(fc.args) >= 2:
                    ae2 = analyzer._to_double(analyzer.analyze(fc.args[1]))
                    if isinstance(ae2, InputRef):
                        arg2_sym = ae2.name
                    else:
                        arg2_sym = self.symbols.fresh(f"{fn}_arg2")
                    if not any(s == arg2_sym for s, _ in pre_exprs):
                        pre_exprs.append((arg2_sym, ae2))
                elif fn == "approx_percentile":
                    if len(fc.args) < 2:
                        raise AnalysisError("approx_percentile(x, p) takes two arguments")
                    pe = analyzer.analyze(fc.args[1])
                    from presto_tpu.expr.ir import Constant as _Const

                    if not isinstance(pe, _Const) or pe.value is None:
                        raise AnalysisError("approx_percentile percentile must be a constant")
                    param = float(pe.value)
                    if not 0.0 <= param <= 1.0:
                        raise AnalysisError("percentile must be in [0, 1]")
            if fn == "map_agg":
                if arg_t.is_string is False and is_floating(arg_t):
                    raise AnalysisError(
                        "map_agg with floating-point keys is not supported")
                out_t = MapType(arg_t, arg2_t)
            elif fn == "numeric_histogram":
                out_t = MapType(DOUBLE, DOUBLE)
            elif fn == "tdigest_agg":
                out_t = TDIGEST
            elif fn == "approx_set":
                from presto_tpu.types import HYPERLOGLOG

                out_t = HYPERLOGLOG
            elif fn == "merge":
                out_t = arg_t  # tdigest or hyperloglog, checked above
            else:
                out_t = _agg_output_type(fn, arg_t, fc.is_star)
            sym = self.symbols.fresh(fn)
            agg_specs.append(AggSpec(sym, "count_star" if fc.is_star else fn,
                                     arg_sym, out_t, distinct,
                                     arg2=arg2_sym, param=param))
            repl[key.replace("agg:", "", 1)] = (sym, out_t)

        # ensure group key InputRef identities present
        seen = {s for s, _ in pre_exprs}
        pre = Project(node, pre_exprs) if pre_exprs else node

        def plan_one(gsyms: List[str], pre: PlanNode) -> PlanNode:
            hll_aggs = [a for a in agg_specs if a.fn == "approx_distinct"]
            pct_aggs = [a for a in agg_specs if a.fn == "approx_percentile"]
            distinct_aggs = [a for a in agg_specs if a.distinct]
            if hll_aggs:
                if len(agg_specs) == 1:
                    return self._plan_hll(pre, gsyms, agg_specs[0],
                                          pre_exprs, node)
                # mixed with other aggregates: the HLL lowering reshapes
                # the whole plan (registers become group rows), so fall
                # back to EXACT count-distinct on the sorted materialized
                # path — exactness trivially satisfies the approximation
                # contract; only the mergeable-sketch scaling is lost
                agg_specs_local = [
                    (AggSpec(a.symbol, "count_distinct", a.arg, a.type,
                             False) if a.fn == "approx_distinct" else a)
                    for a in agg_specs
                ]
                return Aggregate(pre, gsyms, agg_specs_local, step="single")
            if (pct_aggs and len(agg_specs) == len(pct_aggs)
                    and len({a.arg for a in pct_aggs}) == 1
                    and not any(a.distinct for a in pct_aggs)):
                # all aggregates are approx_percentile over one column → the
                # mergeable quantized-histogram sketch (distributable); mixed
                # forms fall back to the materialized exact path below
                return self._plan_qsketch(pre, gsyms, pct_aggs)
            if distinct_aggs:
                if len(agg_specs) == 1 and agg_specs[0].fn == "count":
                    # sole COUNT(DISTINCT x): two-phase dedup-then-count —
                    # both phases decomposable, so it distributes
                    a = agg_specs[0]
                    inner = Aggregate(pre, gsyms + [a.arg], [], step="single")
                    return Aggregate(
                        inner, gsyms,
                        [AggSpec(a.symbol, "count", a.arg, a.type, False)],
                        step="single",
                    )
                # mixed forms (count/sum/avg DISTINCT alongside other
                # aggregates): rewrite each DISTINCT spec to its sorted
                # order-dependent form — the materialized single-task path
                # computes decomposable and sorted aggregates in one pass
                # (reference: MarkDistinct + masked accumulators;
                # DistinctingGroupedAccumulator)
                rewritten = []
                for a in agg_specs:
                    if not a.distinct:
                        rewritten.append(a)
                        continue
                    if a.fn in ("min", "max"):  # DISTINCT is a no-op
                        rewritten.append(AggSpec(a.symbol, a.fn, a.arg,
                                                 a.type, False))
                        continue
                    if a.fn not in ("count", "sum", "avg"):
                        raise AnalysisError(
                            f"{a.fn}(DISTINCT) not supported (count/sum/avg"
                            " are)")
                    rewritten.append(AggSpec(
                        a.symbol, f"{a.fn}_distinct", a.arg, a.type, False,
                        arg2=a.arg2, param=a.param))
                return Aggregate(pre, gsyms, rewritten, step="single")
            return Aggregate(pre, gsyms, agg_specs, step="single")

        if set_asts is None:
            if grouping_calls:
                raise AnalysisError(
                    "grouping() requires GROUPING SETS / ROLLUP / CUBE")
            return plan_one(group_syms, pre), repl

        # grouping(c1, ..) → per-branch constant bitmask (bit i set when
        # ci is NOT aggregated in that branch's set — Presto semantics)
        sym_of = {ast_key(g): s for g, s in zip(group_by, group_syms)}
        grouping_syms: List[Tuple[str, List[str]]] = []
        for gkey, gc in grouping_calls.items():
            arg_syms = []
            for a in gc.args:
                k = ast_key(a)
                if k not in sym_of:
                    raise AnalysisError(
                        "grouping() arguments must be grouping columns")
                arg_syms.append(sym_of[k])
            sym = self.symbols.fresh("grouping")
            grouping_syms.append((sym, arg_syms))
            repl[gkey] = (sym, BIGINT)

        # GROUPING SETS: one aggregate per set over the shared
        # pre-projection, keys absent from a set pad as typed NULLs, then
        # UNION ALL (reference: GroupIdNode + a single multi-set
        # aggregation; the union-of-aggregates shape computes the same
        # rows and distributes through the existing set-op machinery)
        key_types = {s: e.type for s, e in pre_exprs if s in group_syms}
        out_syms = (list(group_syms) + [a.symbol for a in agg_specs]
                    + [s for s, _ in grouping_syms])
        out_types = ([key_types[s] for s in group_syms]
                     + [a.type for a in agg_specs]
                     + [BIGINT] * len(grouping_syms))
        import copy as _copy

        branches = []
        for i, s_ast in enumerate(set_asts):
            gsyms = [sym_of[ast_key(g)] for g in s_ast]
            # each branch owns its subtree: optimizer passes mutate nodes
            # in place (pruning one branch's copy of the shared
            # pre-projection must not strip columns another branch needs)
            agg_i = plan_one(gsyms, pre if i == 0 else _copy.deepcopy(pre))
            pad = []
            for sym in group_syms:
                if sym in gsyms:
                    pad.append((sym, InputRef(key_types[sym], sym)))
                else:
                    pad.append((sym, Constant(key_types[sym], None)))
            pad.extend((a.symbol, InputRef(a.type, a.symbol))
                       for a in agg_specs)
            for gsym, arg_syms in grouping_syms:
                mask = 0
                for bit, s in enumerate(arg_syms):
                    if s not in gsyms:
                        mask |= 1 << (len(arg_syms) - 1 - bit)
                pad.append((gsym, Constant(BIGINT, mask)))
            branches.append(Project(agg_i, pad))
        agg_node = branches[0]
        for b in branches[1:]:
            agg_node = SetOp("union", True, agg_node, b,
                             list(out_syms), list(out_types))
        return agg_node, repl

    def _plan_qsketch(self, pre: PlanNode, group_syms,
                      pct_aggs: List[AggSpec]) -> PlanNode:
        """Lower approx_percentile(x, p) into a mergeable value-space
        sketch (reference: ApproximateLongPercentileAggregations over
        qdigest — here a quantized histogram over the static float64
        universe, riding the ordinary partial → exchange → final path):

          Project    qb = __qsk_bucket(x)   (order-preserving top-24-bit
                                             quantization of the monotone
                                             IEEE-754 encoding)
          Aggregate  group (keys…, qb):  cnt := count(x), mn := min(x)
                     -- decomposable: distributes and merges exactly
          Aggregate  group (keys…):  p-quantile := __approx_percentile_w
                     -- weighted-rank selection over ≤ occupied-bucket
                        rows (order-dependent, runs at the gathered task
                        like the reference's final qdigest.valueAt)

        Value-space relative error ≤ 2⁻¹² per bucket (12 mantissa bits);
        the returned value is a real data value (a bucket minimum)."""
        a0 = pct_aggs[0]
        in_types = dict(pre.output)
        arg_t = in_types[a0.arg]
        arg_ref = InputRef(arg_t, a0.arg)
        qb = self.symbols.fresh("qsk_bucket")
        lower = Project(pre, [(s, InputRef(t, s)) for s, t in pre.output] + [
            (qb, Call(BIGINT, "__qsk_bucket", (arg_ref,))),
        ])
        cnt = self.symbols.fresh("qsk_cnt")
        mn = self.symbols.fresh("qsk_min")
        inner = Aggregate(lower, group_syms + [qb], [
            AggSpec(cnt, "count", a0.arg, BIGINT),
            AggSpec(mn, "min", a0.arg, arg_t),
        ], step="single")
        outer_specs = [
            AggSpec(a.symbol, "__approx_percentile_w", mn, a.type,
                    arg2=cnt, param=a.param)
            for a in pct_aggs
        ]
        return Aggregate(inner, group_syms, outer_specs, step="single")

    def _plan_hll(self, pre: PlanNode, group_syms, a: AggSpec, pre_exprs,
                  raw_input: PlanNode) -> PlanNode:
        """Lower approx_distinct(x) into HyperLogLog over existing plan
        machinery (reference: ApproximateCountDistinctAggregations +
        HyperLogLogState — but here registers ARE group-table rows, so the
        sketch is mergeable/distributable through the ordinary partial →
        exchange → final aggregate path with a fixed m-row footprint):

          Project    reg  = __hll_reg(x)   (low bits of content hash)
                     rank = __hll_rank(x)  (1 + clz of top hash bits)
          Aggregate  group (keys…, reg):  r := max(rank)
          Project    e := 2^-r
          Aggregate  group (keys…):  c := count(r), s := sum(e)
          Project    estimate := bias-corrected harmonic mean over m
                     registers, with the small-range linear-counting
                     correction (zeros = m - c).
        """
        from presto_tpu.expr.compile import HLL_M

        if a.arg is None:
            raise AnalysisError("approx_distinct requires an argument")
        in_types = dict(pre.output)
        arg_ref = InputRef(in_types[a.arg], a.arg)
        reg = self.symbols.fresh("hll_reg")
        rank = self.symbols.fresh("hll_rank")
        lower = Project(pre, [(s, InputRef(t, s)) for s, t in pre.output] + [
            (reg, Call(BIGINT, "__hll_reg", (arg_ref,))),
            (rank, Call(BIGINT, "__hll_rank", (arg_ref,))),
        ])
        rmax = self.symbols.fresh("hll_r")
        inner = Aggregate(lower, group_syms + [reg],
                          [AggSpec(rmax, "max", rank, BIGINT)], step="single")
        e_sym = self.symbols.fresh("hll_e")
        inner_types = dict(inner.output)
        mid = Project(inner, [(s, InputRef(inner_types[s], s))
                              for s in group_syms + [rmax]] + [
            (e_sym, Call(DOUBLE, "power",
                         (Constant(DOUBLE, 2.0),
                          Call(DOUBLE, "neg",
                               (Call(DOUBLE, "cast",
                                     (InputRef(BIGINT, rmax),)),))))),
        ])
        c_sym = self.symbols.fresh("hll_c")
        s_sym = self.symbols.fresh("hll_s")
        outer = Aggregate(mid, group_syms, [
            AggSpec(c_sym, "count", rmax, BIGINT),
            AggSpec(s_sym, "sum", e_sym, DOUBLE),
        ], step="single")
        # estimator: zeros = m - c; S = s + zeros; raw = α·m²/S;
        # small range (raw ≤ 2.5m, zeros > 0): m·ln(m/zeros)
        m = float(HLL_M)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        c_ref = Call(DOUBLE, "cast", (InputRef(BIGINT, c_sym),))
        zeros = Call(DOUBLE, "sub", (Constant(DOUBLE, m), c_ref))
        # empty input: sum over zero rows is SQL NULL but approx_distinct
        # must return 0 — coalesce keeps the estimator defined (all-zero
        # registers → linear counting → m·ln(m/m) = 0)
        s_safe = Call(DOUBLE, "coalesce",
                      (InputRef(DOUBLE, s_sym), Constant(DOUBLE, 0.0)))
        S = Call(DOUBLE, "add", (s_safe, zeros))
        raw = Call(DOUBLE, "div",
                   (Constant(DOUBLE, alpha * m * m), S))
        small = Call(DOUBLE, "mul",
                     (Constant(DOUBLE, m),
                      Call(DOUBLE, "ln",
                           (Call(DOUBLE, "div",
                                 (Constant(DOUBLE, m), zeros)),))))
        use_small = Call(BOOLEAN, "and", (
            Call(BOOLEAN, "le", (raw, Constant(DOUBLE, 2.5 * m))),
            Call(BOOLEAN, "gt", (zeros, Constant(DOUBLE, 0.0))),
        ))
        est = Call(BIGINT, "cast", (
            Call(DOUBLE, "round",
                 (Call(DOUBLE, "if", (use_small, small, raw)),)),))
        outer_types = dict(outer.output)
        return Project(outer, [(s, InputRef(outer_types[s], s))
                               for s in group_syms] + [(a.symbol, est)])


def _host_split(e: RowExpression):
    """Top-level host-only call → (device_input_expr, kind, param), else
    None. These produce strings over unbounded value domains, so they
    cannot be dictionary transforms; the planner runs them in a
    HostProject at the query root (plan/nodes.HostProject)."""
    if not isinstance(e, Call):
        return None
    if (e.fn == "cast" and e.type is VARCHAR and e.args
            and not e.args[0].type.is_string
            and not isinstance(e.args[0].type, (ArrayType, MapType))):
        return e.args[0], "varchar_cast", None
    if e.fn == "__host_date_format":
        return e.args[0], "date_format", str(e.args[1].value)
    return None


class _PendingCross(PlanNode):
    """Marker node: cross product whose ordering is decided by WHERE
    conjuncts in _assemble_joins. Never reaches execution."""

    def __init__(self, left: RelationPlan, right: RelationPlan):
        self.left = left
        self.right = right
        self.output = list(left.node.output) + list(right.node.output)

    def children(self):
        return [self.left.node, self.right.node]


def _collect_cross_leaves(rp: RelationPlan, out: List[RelationPlan]):
    if isinstance(rp.node, _PendingCross):
        _collect_cross_leaves(rp.node.left, out)
        _collect_cross_leaves(rp.node.right, out)
    else:
        out.append(rp)


def _split_ir_conjuncts(e: RowExpression) -> List[RowExpression]:
    if isinstance(e, Call) and e.fn == "and":
        out = []
        for a in e.args:
            out.extend(_split_ir_conjuncts(a))
        return out
    return [e]


def _extract_equi_keys(conjs, lsyms, rsyms):
    lkeys, rkeys, rest = [], [], []
    for c in conjs:
        if isinstance(c, Call) and c.fn == "eq":
            a, b = c.args
            if isinstance(a, InputRef) and isinstance(b, InputRef):
                if a.name in lsyms and b.name in rsyms:
                    lkeys.append(a.name)
                    rkeys.append(b.name)
                    continue
                if b.name in lsyms and a.name in rsyms:
                    lkeys.append(b.name)
                    rkeys.append(a.name)
                    continue
        rest.append(c)
    return lkeys, rkeys, rest


def _derives_unique(node: PlanNode, keys: List[str]) -> bool:
    """True if `keys` are unique on node's output (primary key of a scan,
    or grouping keys of an aggregation) — enables the single-match probe
    fast path (analog of knowing the build has no PositionLinks chains)."""
    if isinstance(node, Aggregate):
        return set(node.group_keys) <= set(keys)
    if isinstance(node, Filter):
        return _derives_unique(node.child, keys)
    if isinstance(node, Project):
        # identity-projected symbols only
        ident = {s for s, e in node.exprs if isinstance(e, InputRef) and e.name == s}
        if set(keys) <= ident:
            return _derives_unique(node.child, keys)
        return False
    if isinstance(node, TableScan):
        pk = getattr(node, "primary_key_symbols", None)
        if pk is None:
            return False
        return set(pk) <= set(keys)
    return False


def _contains_agg(n) -> bool:
    if isinstance(n, ast.FunctionCall) and _is_agg_fn(n.name.lower()):
        return True
    return any(_contains_agg(c) for c in _ast_children(n))


def _rewrite_aggs_to_keys(n):
    """Aggregate calls inside post-agg expressions are replaced at analysis
    time via the replacements map (keyed by ast_key); nothing to rewrite
    structurally."""
    return n


def _ast_children(n):
    if isinstance(n, ast.UnaryOp):
        return [n.operand]
    if isinstance(n, ast.BinaryOp):
        return [n.left, n.right]
    if isinstance(n, ast.Between):
        return [n.value, n.low, n.high]
    if isinstance(n, ast.InList):
        return [n.value] + n.items
    if isinstance(n, ast.Like):
        return [n.value, n.pattern]
    if isinstance(n, ast.IsNull):
        return [n.value]
    if isinstance(n, ast.FunctionCall):
        return n.args
    if isinstance(n, ast.Cast):
        return [n.value]
    if isinstance(n, ast.Case):
        out = []
        if n.operand:
            out.append(n.operand)
        for c, v in n.whens:
            out.extend([c, v])
        if n.default:
            out.append(n.default)
        return out
    if isinstance(n, ast.Extract):
        return [n.value]
    if isinstance(n, ast.WindowFunction):
        return list(n.args) + list(n.partition_by) + [o.expr for o in n.order_by]
    return []


def _derive_name(e) -> str:
    if isinstance(e, ast.Identifier):
        return e.parts[-1]
    if isinstance(e, ast.FunctionCall):
        return e.name.lower()
    if isinstance(e, ast.Extract):
        return e.field
    return "_col"


def _agg_output_type(fn: str, arg_t: Type, is_star: bool) -> Type:
    if fn in ("count", "count_if") or is_star:
        return BIGINT
    if fn == "sum":
        if isinstance(arg_t, DecimalType):
            # Presto: sum(decimal(p,s)) -> decimal(38,s), int128-backed
            return DecimalType(38, arg_t.scale)
        if is_integral(arg_t):
            return BIGINT
        return DOUBLE
    if fn == "avg":
        return DOUBLE  # deviation: Presto returns decimal for decimal args
    if fn in ("min", "max", "arbitrary", "max_by", "min_by",
              "approx_percentile"):
        if isinstance(arg_t, DecimalType) and arg_t.is_long:
            # long-decimal extremes compare on the combined float64 value
            # (deviation: Presto keeps decimal(38); exactness is preserved
            # for sums, which is where int128 matters)
            return DOUBLE
        return arg_t
    if fn in ("stddev_pop", "stddev_samp", "var_pop", "var_samp",
              "covar_pop", "covar_samp", "corr", "geometric_mean"):
        return DOUBLE
    if fn in ("bool_and", "bool_or"):
        return BOOLEAN
    if fn in ("checksum", "approx_distinct"):
        return BIGINT
    if fn == "array_agg":
        return ArrayType(arg_t)
    from presto_tpu.functions import registry as _freg

    udf = _freg().aggregate(fn)
    if udf is not None:
        return udf.result_type(arg_t)
    raise AnalysisError(f"unknown aggregate {fn}")


def plan_query(sql_or_ast, catalog: Catalog) -> QueryPlan:
    """Parse (if needed), analyze and plan a query (reference path:
    SqlQueryExecution.doAnalyzeQuery → LogicalPlanner.plan)."""
    from presto_tpu.sql.parser import parse_sql

    q = (sql_or_ast if isinstance(sql_or_ast, (ast.Query, ast.SetOp))
         else parse_sql(sql_or_ast))
    return Planner(catalog).plan(q)
