"""Logical plan optimization passes.

Analog of sql/planner/PlanOptimizers.java (76 passes) reduced to the ones
that matter for this execution model:

- PredicatePushdown (optimizations/PredicatePushDown.java): split conjuncts,
  push each to the deepest node whose output covers its inputs — through
  Projects (with substitution), past Joins into the covering side, below
  Aggregates when the conjunct only references group keys.
- PruneUnreferencedOutputs / PushdownSubfields-style column pruning: trim
  Project expressions and TableScan assignments to what the query needs.
  On this engine column pruning is the *scan pushdown* — the parquet reader
  only materializes referenced columns (the moral of the Aria selective
  reader's column skipping).
- Cleanup: merge adjacent Filters, drop identity Projects.

Join ordering happens at plan-build time (builder._assemble_joins) with
connector row counts — the stand-in for the cost-based ReorderJoins.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from presto_tpu.expr.ir import (
    Call,
    InputRef,
    RowExpression,
    expr_inputs,
    substitute_refs,
)
from presto_tpu.plan.nodes import (
    Aggregate,
    Filter,
    HashJoin,
    Limit,
    Output,
    PlanNode,
    Project,
    QueryPlan,
    SemiJoin,
    Sort,
    TableScan,
    Unnest,
    Window,
)
from presto_tpu.types import BOOLEAN


def _conjuncts(e: RowExpression) -> List[RowExpression]:
    if isinstance(e, Call) and e.fn == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _combine(es: List[RowExpression]) -> Optional[RowExpression]:
    if not es:
        return None
    out = es[0]
    for e in es[1:]:
        out = Call(BOOLEAN, "and", (out, e))
    return out


def push_filters(node: PlanNode) -> PlanNode:
    """Recursively push filter conjuncts toward the leaves."""
    if isinstance(node, Filter):
        child = push_filters(node.child)
        conjs = _conjuncts(node.predicate)
        return _push_into(child, conjs)
    for attr in ("child", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, push_filters(getattr(node, attr)))
    return node


def _push_into(node: PlanNode, conjs: List[RowExpression]) -> PlanNode:
    if not conjs:
        return node
    if isinstance(node, Filter):
        return _push_into(node.child, conjs + _conjuncts(node.predicate))
    if isinstance(node, Project):
        mapping = {s: e for s, e in node.exprs}
        pushable, kept = [], []
        for c in conjs:
            # only substitute through cheap expressions (refs / arithmetic);
            # always safe since Project is stateless and deterministic
            pushable.append(substitute_refs(c, mapping))
        node.child = _push_into(node.child, pushable)
        return node
    if isinstance(node, HashJoin):
        lsyms = {n for n, _ in node.left.output}
        rsyms = {n for n, _ in node.right.output}
        lpush, rpush, kept = [], [], []
        for c in conjs:
            ins = expr_inputs(c)
            if ins <= lsyms and node.kind != "full":
                # probe-side push is fine for INNER and LEFT (probe rows
                # keep their own values); NOT for FULL — the build
                # remainder's NULL probe columns must be filtered
                # post-join, and pre-join evaluation can't see them
                lpush.append(c)
            elif ins <= rsyms and node.kind == "inner":
                rpush.append(c)
            else:
                # NOTE: a WHERE conjunct on build-side columns above a LEFT
                # join must NOT be pushed below it — it filters the
                # NULL-extended post-join rows (pushing it would resurrect
                # non-matching probe rows). ON-clause residuals are pushed at
                # plan-build time instead (builder.plan_join).
                kept.append(c)
        if lpush:
            node.left = _push_into(node.left, lpush)
        if rpush:
            node.right = _push_into(node.right, rpush)
        node.left = push_filters(node.left)
        node.right = push_filters(node.right)
        if kept:
            if node.kind == "inner":
                return Filter(node, _combine(kept))
            return Filter(node, _combine(kept))
        return node
    if isinstance(node, SemiJoin):
        lsyms = {n for n, _ in node.left.output}
        lpush, kept = [], []
        for c in conjs:
            (lpush if expr_inputs(c) <= lsyms else kept).append(c)
        if lpush:
            node.left = _push_into(node.left, lpush)
        node.left = push_filters(node.left)
        node.right = push_filters(node.right)
        return Filter(node, _combine(kept)) if kept else node
    from presto_tpu.plan.nodes import NestedLoopJoin as _NLJ

    if isinstance(node, _NLJ):
        # inner semantics: single-side conjuncts push through freely
        lsyms = {n for n, _ in node.left.output}
        rsyms = {n for n, _ in node.right.output}
        lpush, rpush, kept = [], [], []
        for c in conjs:
            ins = expr_inputs(c)
            if ins <= lsyms:
                lpush.append(c)
            elif ins <= rsyms:
                rpush.append(c)
            else:
                kept.append(c)
        if lpush:
            node.left = _push_into(node.left, lpush)
        if rpush:
            node.right = _push_into(node.right, rpush)
        node.left = push_filters(node.left)
        node.right = push_filters(node.right)
        return Filter(node, _combine(kept)) if kept else node
    if isinstance(node, Aggregate):
        keys = set(node.group_keys)
        below, above = [], []
        for c in conjs:
            (below if expr_inputs(c) <= keys else above).append(c)
        if below:
            node.child = _push_into(node.child, below)
        node.child = push_filters(node.child)
        return Filter(node, _combine(above)) if above else node
    if isinstance(node, (Sort, Limit)):
        # filters commute with sort/limit only if limit absent
        if isinstance(node, Sort) and node.limit is None:
            node.child = _push_into(node.child, conjs)
            return node
        node.child = push_filters(node.child)
        return Filter(node, _combine(conjs))
    # TableScan and everything else: stop here
    if isinstance(node, TableScan):
        _derive_scan_constraints(node, conjs)
    node2 = push_filters(node) if node.children() else node
    return Filter(node2, _combine(conjs))


def _derive_scan_constraints(scan: TableScan, conjs: List[RowExpression]):
    """Extract per-column (lo, hi) bounds from simple comparison conjuncts
    for connector split pruning (coarse TupleDomain pushdown — the IO-level
    slice of the reference's selective-reader filter pushdown). The exact
    filter still runs on-device; this only skips row groups."""
    from presto_tpu.expr.ir import Constant

    sym_to_col = {s: c for s, c in scan.assignments.items()}
    for c in conjs:
        if not (isinstance(c, Call) and c.fn in ("lt", "le", "gt", "ge", "eq")):
            continue
        a, b = c.args
        if isinstance(a, InputRef) and isinstance(b, Constant) and b.value is not None:
            ref, const, op = a, b, c.fn
        elif isinstance(b, InputRef) and isinstance(a, Constant) and a.value is not None:
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
            ref, const, op = b, a, flip[c.fn]
        else:
            continue
        if ref.name not in sym_to_col:
            continue
        if const.type.is_string and not isinstance(const.value, str):
            # string bounds feed dictionary-code filters downstream; only
            # plain python-str constants have a well-defined order there
            continue
        col = sym_to_col[ref.name]
        lo, hi = scan.constraints.get(col, (None, None))
        v = const.value
        t = const.type
        from presto_tpu.types import DecimalType as _Dec

        if isinstance(t, _Dec) and not const.raw:
            v = int(round(float(v) * 10 ** t.scale))
        if op in ("gt", "ge"):
            lo = v if lo is None else max(lo, v)
        elif op in ("lt", "le"):
            hi = v if hi is None else min(hi, v)
        else:  # eq
            lo = v if lo is None else max(lo, v)
            hi = v if hi is None else min(hi, v)
        scan.constraints[col] = (lo, hi)


# ---------------------------------------------------------------------------
# column pruning


def prune_columns(node: PlanNode, required: Set[str]) -> PlanNode:
    if isinstance(node, Output):
        node.child = prune_columns(node.child, set(node.symbols))
        return node
    if isinstance(node, TableScan):
        node.assignments = {s: c for s, c in node.assignments.items() if s in required}
        node.output = [(s, t) for s, t in node.output if s in required]
        return node
    if isinstance(node, Filter):
        need = required | expr_inputs(node.predicate)
        node.child = prune_columns(node.child, need)
        return node
    if isinstance(node, Project):
        node.exprs = [(s, e) for s, e in node.exprs if s in required]
        need = set()
        for _, e in node.exprs:
            need |= expr_inputs(e)
        node.child = prune_columns(node.child, need)
        return node
    if isinstance(node, Aggregate):
        node.aggs = [a for a in node.aggs if a.symbol in required]
        need = set(node.group_keys) | {a.arg for a in node.aggs if a.arg}
        need |= {a.arg2 for a in node.aggs if a.arg2}
        node.child = prune_columns(node.child, need)
        return node
    if isinstance(node, HashJoin):
        need = required | set(node.left_keys) | set(node.right_keys)
        if node.residual is not None:
            need |= expr_inputs(node.residual)
        lsyms = {n for n, _ in node.left.output}
        rsyms = {n for n, _ in node.right.output}
        node.left = prune_columns(node.left, need & lsyms)
        node.right = prune_columns(node.right, need & rsyms)
        return node
    if isinstance(node, SemiJoin):
        res_syms = expr_inputs(node.residual) if node.residual is not None else set()
        rsyms = {n for n, _ in node.right.output}
        node.left = prune_columns(
            node.left, required | set(node.left_keys) | (res_syms - rsyms)
        )
        node.right = prune_columns(
            node.right, set(node.right_keys) | (res_syms & rsyms)
        )
        return node
    if isinstance(node, Window):
        need = set(required) - {f.symbol for f in node.funcs}
        need |= set(node.partition_keys)
        need |= {k.symbol for k in node.order_items}
        need |= {f.arg for f in node.funcs if f.arg}
        node.child = prune_columns(node.child, need)
        return node
    if isinstance(node, Sort):
        need = required | {k.symbol for k in node.keys}
        node.child = prune_columns(node.child, need)
        return node
    if isinstance(node, Limit):
        node.child = prune_columns(node.child, required)
        return node
    from presto_tpu.plan.nodes import HostProject as _HP

    if isinstance(node, _HP):
        # host outputs resolve to their device inputs below this node
        need = (required - {s for s, _, _, _ in node.items}) | {
            in_s for _, _, in_s, _ in node.items}
        node.child = prune_columns(node.child, need)
        return node
    if isinstance(node, Unnest):
        node.replicate = [s for s in node.replicate if s in required]
        node.child = prune_columns(
            node.child, set(node.replicate) | set(node.sources))
        return node
    from presto_tpu.plan.nodes import NestedLoopJoin as _NLJ

    if isinstance(node, _NLJ):
        need = set(required)
        if node.residual is not None:
            need |= expr_inputs(node.residual)
        lsyms = {n for n, _ in node.left.output}
        rsyms = {n for n, _ in node.right.output}
        node.left = prune_columns(node.left, need & lsyms)
        node.right = prune_columns(node.right, need & rsyms)
        return node
    for c in node.children():
        prune_columns(c, required)
    return node


def cleanup(node: PlanNode) -> PlanNode:
    """Merge adjacent filters; drop empty/identity projects."""
    for attr in ("child", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, cleanup(getattr(node, attr)))
    if isinstance(node, Filter) and isinstance(node.child, Filter):
        inner = node.child
        return cleanup(Filter(inner.child, _combine(_conjuncts(node.predicate) + _conjuncts(inner.predicate))))
    if isinstance(node, Project):
        child_names = [n for n, _ in node.child.output]
        if (
            len(node.exprs) == len(child_names)
            and all(
                isinstance(e, InputRef) and e.name == s and s == cn
                for (s, e), cn in zip(node.exprs, child_names)
            )
        ):
            return node.child
    return node


def make_index_joins(node: PlanNode, catalog) -> PlanNode:
    """Rewrite HashJoins whose build side is a bare scan of a table whose
    connector exposes a ConnectorIndex over exactly the join keys
    (reference: IndexJoinOptimizer.java — the source side collapses into
    an IndexSourceNode driven by probe keys)."""
    from presto_tpu.plan.nodes import IndexJoin

    for attr in ("child", "left", "right"):
        if hasattr(node, attr):
            setattr(node, attr, make_index_joins(getattr(node, attr), catalog))
    if (isinstance(node, HashJoin) and node.kind in ("inner", "left")
            and node.residual is None and not node.colocated
            and isinstance(node.right, TableScan)):
        scan = node.right
        try:
            conn = catalog.connectors[scan.catalog]
            handle = conn.get_table(scan.table)
        except Exception:
            return node
        key_cols = [scan.assignments.get(k) for k in node.right_keys]
        if None in key_cols:
            return node
        if conn.get_index(handle, key_cols) is None:
            return node
        from presto_tpu.plan.builder import _derives_unique

        return IndexJoin(
            kind=node.kind, left=node.left,
            catalog=scan.catalog, table=scan.table,
            left_keys=list(node.left_keys), index_key_cols=key_cols,
            assignments=dict(scan.assignments),
            index_output=list(scan.output),
            build_unique=_derives_unique(scan, node.right_keys),
        )
    return node


def _debug_checks_enabled() -> bool:
    import os

    return os.environ.get("PRESTO_TPU_PLAN_CHECK", "") not in ("", "0")


def optimize(plan: QueryPlan, catalog=None,
             debug_checks: Optional[bool] = None) -> QueryPlan:
    """Run the pass pipeline (reference: PlanOptimizers.java:146 ordering).

    With `debug_checks` (or env PRESTO_TPU_PLAN_CHECK=1), the plan-IR
    invariant checker (analysis/plan_check.py) re-runs after every pass,
    so a violation is attributed to the rewrite rule that introduced it
    instead of surfacing as a KeyError three layers later — the
    PlanSanityChecker-between-optimizers discipline of the reference."""
    from presto_tpu.plan.stats import invalidate

    from presto_tpu.plan.rules import IterativeOptimizer

    if debug_checks is None:
        debug_checks = _debug_checks_enabled()

    def checked(pass_name: str):
        if not debug_checks:
            return
        from presto_tpu.analysis.plan_check import (
            PlanInvariantError,
            check_plan,
        )

        findings = check_plan(plan.root)
        if findings:
            raise PlanInvariantError(pass_name, findings)

    root = plan.root
    checked("input (builder output)")
    root.child = push_filters(root.child)
    checked("push_filters")
    prune_columns(root, set(root.symbols))
    checked("prune_columns")
    root.child = cleanup(root.child)
    checked("cleanup")
    # iterative pattern rules (merge filters/projects/limits, TopN
    # formation) run after the big passes, to fixpoint
    root.child = IterativeOptimizer().optimize(root.child)
    checked("IterativeOptimizer")
    if catalog is not None:
        root.child = make_index_joins(root.child, catalog)
        checked("make_index_joins")
    # builder-time stats memos are stale once filters/pruning rewrote the
    # tree; later consumers (fragmenter, capacity planner) re-derive
    invalidate(root)
    for sub in plan.scalar_subqueries.values():
        optimize(sub, catalog, debug_checks=debug_checks)
    return plan
