"""Within-worker radix partitioning for pipeline breakers.

Reference: the partitioned-hash-join literature (Design Trade-offs for a
Robust Dynamic Hybrid Hash Join, arXiv:2112.02480; Global Hash Tables
Strike Back!, arXiv:2505.04153) — split both sides of a breaker by a few
high bits of the join hash so every per-partition build/probe (or
group-by merge) runs at a small fixed capacity. On XLA that bounds the
set of compiled program shapes: instead of one giant sort/searchsorted
over a query-size-dependent capacity, P independent kernels over the
same handful of power-of-two buckets.

TPU-native design: scatter-free. Routing is `lax.sort` by partition id
(stable, so row order within a partition is preserved), partition
extents come from a segment-sum pulled to the host (a P-element
transfer), and per-partition sub-batches are gathered out of the sorted
batch by a `start + iota(bucket)` window gather whose bucket size is a
static power of two — the only shape-keying quantities are
(input capacity, bucket), both from small closed sets.

Partition id = TOP bits of the shared 63-bit content hash
(ops/partition.py:partition_hash). The exchange routes by `hash %
n_out`; using the high bits here keeps the two decompositions
independent, so radix refines an already hash-partitioned stream instead
of degenerating to one resident partition per task. The same ids are
reused by the partition-aligned exchange sink (server/worker.py): a page
tagged with its radix id skips the sort entirely on the consumer side.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch
from presto_tpu.ops.partition import partition_hash

_HASH_BITS = 63  # hash_columns masks the sign bit


def radix_bits(num_partitions: int) -> int:
    """log2(P); P must be a power of two."""
    if num_partitions <= 0 or num_partitions & (num_partitions - 1):
        raise ValueError(
            f"radix partition count must be a power of two, got "
            f"{num_partitions}")
    return num_partitions.bit_length() - 1


def slot_hash(h: jnp.ndarray, tcap: int) -> jnp.ndarray:
    """Initial probe slot for the Pallas hash-table engine
    (ops/pallas_hash): the LOW log2(tcap) bits of the shared 63-bit
    content hash. Partition ids (radix_ids, above) take the TOP bits of
    the same hash, so under radix every per-partition hash table still
    sees fully mixed slot bits — the breaker-engine dimension composes
    with radix partitioning without hash-bit reuse (table capacities stay
    far below 2^(63 - log2(P)))."""
    if tcap <= 0 or tcap & (tcap - 1):
        raise ValueError(
            f"slot table capacity must be a power of two, got {tcap}")
    return (h & jnp.int64(tcap - 1)).astype(jnp.int32)


def radix_ids(batch: Batch, key_names: Sequence[str],
              num_partitions: int) -> jnp.ndarray:
    """Row → radix partition id: top `log2(P)` bits of the content hash."""
    bits = radix_bits(num_partitions)
    if bits == 0:
        return jnp.zeros(batch.capacity, dtype=jnp.int32)
    h = partition_hash(batch, key_names)
    return jnp.right_shift(h, _HASH_BITS - bits).astype(jnp.int32)


def radix_sort(batch: Batch, key_names: Sequence[str],
               num_partitions: int) -> Tuple[Batch, jnp.ndarray]:
    """Stable-sort rows by radix id, dead rows last.

    Returns (sorted batch — its live mask marks exactly the routed rows,
    in partition order — and per-partition live counts int32[P]). Meant
    to be jitted once per (plan node, input capacity).
    """
    n = batch.capacity
    pid = radix_ids(batch, key_names, num_partitions)
    pid = jnp.where(batch.live, pid, num_partitions)  # dead rows sink
    perm = jnp.arange(n, dtype=jnp.int32)
    spid, sperm = jax.lax.sort([pid, perm], num_keys=1, is_stable=True)
    cols = [c.gather(sperm) for c in batch.columns]
    out = Batch(batch.names, batch.types, cols, spid < num_partitions,
                batch.dicts)
    counts = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), spid, num_segments=num_partitions + 1
    )[:num_partitions]
    return out, counts


def radix_perm(batch: Batch, key_names: Sequence[str],
               num_partitions: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable argsort by radix id WITHOUT materializing the sorted batch.

    Returns (sperm int32[capacity] — row indices in partition order, dead
    rows last — and per-partition live counts int32[P]). The runtime
    splitter pairs this with `radix_window_perm`, which gathers each
    window's columns straight out of the ORIGINAL batch through the
    permutation — every payload byte moves once (in its window) instead
    of twice (sorted copy + window copy); the sort itself only touches
    two int32 planes.
    """
    n = batch.capacity
    pid = radix_ids(batch, key_names, num_partitions)
    pid = jnp.where(batch.live, pid, num_partitions)  # dead rows sink
    perm = jnp.arange(n, dtype=jnp.int32)
    spid, sperm = jax.lax.sort([pid, perm], num_keys=1, is_stable=True)
    counts = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), spid, num_segments=num_partitions + 1
    )[:num_partitions]
    return sperm, counts


def radix_window_perm(batch: Batch, perm, start, count,
                      bucket: int) -> Batch:
    """`radix_window` through a `radix_perm` permutation: gather `bucket`
    rows whose partition-order ranks begin at (traced) `start` directly
    from the unsorted batch. Same clamp-and-mask contract as
    `radix_window`; `bucket` is the only static shape key."""
    cap = batch.capacity
    lane = jnp.arange(bucket, dtype=jnp.int32)
    idx = perm[jnp.clip(start.astype(jnp.int32) + lane, 0, cap - 1)]
    cols = [c.gather(idx) for c in batch.columns]
    live = lane < count.astype(jnp.int32)
    return Batch(batch.names, batch.types, cols, live, batch.dicts)


def radix_child_ids(batch: Batch, key_names: Sequence[str],
                    parent_partitions: int, fanout: int) -> jnp.ndarray:
    """Row → child index within its parent radix partition: the next
    ``log2(fanout)`` hash bits BELOW the parent's top ``log2(P)`` bits.

    The adaptive device-side analog of the host spiller's
    ``grow_partition`` (spiller.py): a partition whose observed footprint
    blows its budget splits by fresh hash entropy, so skewed-but-distinct
    keys do separate while the parent decomposition (and any
    partition-aligned exchange tags at the parent P) stays valid — a
    child id refines its parent id exactly like a deeper radix pass."""
    pbits = radix_bits(parent_partitions)
    fbits = radix_bits(fanout)
    if pbits + fbits > _HASH_BITS:
        raise ValueError("radix growth exhausted the hash bits")
    h = partition_hash(batch, key_names)
    shifted = jnp.right_shift(h, _HASH_BITS - pbits - fbits)
    return (shifted & jnp.int64(fanout - 1)).astype(jnp.int32)


def radix_child_perm(batch: Batch, key_names: Sequence[str],
                     parent_partitions: int,
                     fanout: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``radix_perm`` over the CHILD ids of one grown partition: stable
    argsort by the next hash bits down, dead rows last, per-child live
    counts. The caller guarantees every live row of ``batch`` belongs to
    the same parent partition (it came out of the parent's splitter), so
    only the child bits discriminate. Same scatter-free shape contract
    as ``radix_perm`` — one ``lax.sort`` of two int32 planes plus a
    ``fanout``-element count transfer, jitted once per input capacity."""
    n = batch.capacity
    cid = radix_child_ids(batch, key_names, parent_partitions, fanout)
    cid = jnp.where(batch.live, cid, fanout)  # dead rows sink
    perm = jnp.arange(n, dtype=jnp.int32)
    scid, sperm = jax.lax.sort([cid, perm], num_keys=1, is_stable=True)
    counts = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), scid, num_segments=fanout + 1
    )[:fanout]
    return sperm, counts


def radix_window(sorted_batch: Batch, start, count, bucket: int) -> Batch:
    """Gather `bucket` rows beginning at (traced) `start` out of a sorted
    batch; rows at rank >= `count` are marked dead.

    A gather (not dynamic_slice) so out-of-range lanes clamp harmlessly —
    they are masked dead by `count` regardless of what they read. `bucket`
    is static: jit once per (input capacity, bucket).
    """
    cap = sorted_batch.capacity
    lane = jnp.arange(bucket, dtype=jnp.int32)
    idx = jnp.clip(start.astype(jnp.int32) + lane, 0, cap - 1)
    cols = [c.gather(idx) for c in sorted_batch.columns]
    live = lane < count.astype(jnp.int32)
    return Batch(sorted_batch.names, sorted_batch.types, cols, live,
                 sorted_batch.dicts)
