"""Sort-based grouped aggregation — the GROUP BY kernel.

Reference: operator/MultiChannelGroupByHash.java:54 (open-addressing table
over flat long[] with codegen'd hash strategies) feeding
InMemoryHashAggregationBuilder.

TPU-native redesign: scatter-with-conflicts is hostile to XLA, so grouping is
a *sort*: lexicographic `lax.sort` over (deadness, per-key null bit, key
value)*, boundary detection, then `segment_sum/min/max` into a fixed-capacity
group table. Everything is static-shape; the only dynamic quantity (group
count) is returned as a device scalar so the driver can detect capacity
overflow and recompile with a bigger bucket.

The same kernel does partial aggregation, state merging, and final
aggregation: inputs are "state columns" each with a merge op
(sum/min/max/count-add), exactly like the reference's
partial/intermediate/final accumulator phases
(operator/aggregation/builder/InMemoryHashAggregationBuilder.java:160).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class StateCol(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]  # None = all valid
    op: str  # 'sum' | 'min' | 'max' | 'count_add' (values are counts)


class KeyCol(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]
    # Exclusive upper bound of non-null values when statically known (values
    # in [0, domain): dictionary codes, booleans). Lets grouped_merge take
    # the direct-indexed path (group id = mixed-radix key digits — no sort),
    # the analog of the reference's BigintGroupByHash small-range fast path
    # (operator/BigintGroupByHash.java). None = unbounded.
    domain: Optional[int] = None


def _minmax_identity(dtype, op):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if op == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if op == "min" else info.min, dtype)


def grouped_merge(
    keys: Sequence[KeyCol],
    states: Sequence[StateCol],
    live: jnp.ndarray,
    num_groups_cap: int,
) -> Tuple[list, list, jnp.ndarray, jnp.ndarray]:
    """Group rows by `keys`, merging `states` within each group.

    Returns (key_cols_out, state_cols_out, out_live, n_groups) where all
    output arrays have length num_groups_cap and rows beyond n_groups are
    dead. NULL key values form their own group (SQL GROUP BY semantics).
    Rows with live=False are ignored. If n_groups > num_groups_cap the
    caller must retry with a bigger capacity (groups beyond cap are dropped
    deterministically — the driver checks).
    """
    if keys and all(k.domain is not None for k in keys):
        dom_slots = [
            (k.domain + 1) if k.validity is not None else max(k.domain, 1)
            for k in keys
        ]
        total = 1
        for ds in dom_slots:
            total *= ds
        if 0 < total <= num_groups_cap:
            return _direct_grouped_merge(
                keys, states, live, num_groups_cap, dom_slots
            )

    n = live.shape[0]
    dead = (~live).astype(jnp.int32)

    operands = [dead]
    for k in keys:
        if k.validity is not None:
            operands.append((~k.validity).astype(jnp.int32))
            operands.append(jnp.where(k.validity, k.values, jnp.zeros_like(k.values)))
        else:
            operands.append(k.values)
    num_keys = len(operands)
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=num_keys)
    sorted_keys = sorted_ops[:num_keys]
    sperm = sorted_ops[-1]
    sdead = sorted_keys[0]

    # boundary where any sort key changes (first row is always a boundary)
    change = jnp.zeros(n, dtype=bool).at[0].set(True)
    for sk in sorted_keys:
        change = change.at[1:].set(change[1:] | (sk[1:] != sk[:-1]))
    seg = jnp.cumsum(change.astype(jnp.int32)) - 1
    # dead rows sort last; push their segment out of range so segment ops drop them
    seg = jnp.where(sdead == 1, num_groups_cap, seg)
    n_groups = jnp.max(jnp.where(sdead == 1, -1, seg)) + 1

    # materialize group keys: first (any) row of each segment
    key_out = []
    ki = 1
    for k in keys:
        if k.validity is not None:
            nullbit = sorted_keys[ki]
            vals = sorted_keys[ki + 1]
            ki += 2
            kv = jnp.zeros(num_groups_cap, dtype=vals.dtype).at[seg].set(vals, mode="drop")
            kvd = jnp.zeros(num_groups_cap, dtype=bool).at[seg].set(nullbit == 0, mode="drop")
            key_out.append(KeyCol(kv, kvd))
        else:
            vals = sorted_keys[ki]
            ki += 1
            kv = jnp.zeros(num_groups_cap, dtype=vals.dtype).at[seg].set(vals, mode="drop")
            key_out.append(KeyCol(kv, None))

    state_out = []
    for s in states:
        sv = s.values[sperm]
        svalid = s.validity[sperm] if s.validity is not None else None
        state_out.append(_state_merge(sv, svalid, s.op, seg, n, num_groups_cap))

    out_live = jnp.arange(num_groups_cap) < n_groups
    return key_out, state_out, out_live, n_groups


def _state_merge(sv, svalid, op, seg, n, num_groups_cap):
    """One state column → per-segment aggregate (+ validity). Shared by the
    sort path (seg = dense rank over permuted rows) and the direct path
    (seg = mixed-radix key digits over input order)."""
    if op in ("sum", "count_add"):
        contrib = sv if svalid is None else jnp.where(svalid, sv, jnp.zeros_like(sv))
        agg = jax.ops.segment_sum(contrib, seg, num_segments=num_groups_cap)
        if op == "count_add":
            return StateCol(agg, None, op)
        if svalid is None:
            nvalid = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg,
                                         num_segments=num_groups_cap)
        else:
            nvalid = jax.ops.segment_sum(svalid.astype(jnp.int32), seg,
                                         num_segments=num_groups_cap)
        return StateCol(agg, nvalid > 0, op)
    if op in ("min", "max"):
        ident = _minmax_identity(sv.dtype, op)
        contrib = sv if svalid is None else jnp.where(svalid, sv, ident)
        segop = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        agg = segop(contrib, seg, num_segments=num_groups_cap)
        if svalid is None:
            nvalid = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg,
                                         num_segments=num_groups_cap)
        else:
            nvalid = jax.ops.segment_sum(svalid.astype(jnp.int32), seg,
                                         num_segments=num_groups_cap)
        return StateCol(agg, nvalid > 0, op)
    raise ValueError(f"unknown merge op {op}")


def _direct_grouped_merge(
    keys: Sequence[KeyCol],
    states: Sequence[StateCol],
    live: jnp.ndarray,
    num_groups_cap: int,
    dom_slots: Sequence[int],
) -> Tuple[list, list, jnp.ndarray, jnp.ndarray]:
    """Small-key-domain GROUP BY: the group id IS the mixed-radix number of
    the key digits (nullable keys reserve digit 0 for NULL), so states
    segment-reduce directly on input order — no sort, no permutation. The
    group table is sparse: out_live marks occupied slots and key columns are
    decoded from the slot index itself. Because Π dom_slots ≤ cap, overflow
    is impossible (n_groups counts occupied slots).

    Reference analog: BigintGroupByHash's dense small-range path; here it
    also covers multi-key dictionary-coded GROUP BY (TPC-H Q1's
    returnflag×linestatus), which the reference would route through
    MultiChannelGroupByHash."""
    n = live.shape[0]
    gid = jnp.zeros(n, dtype=jnp.int32)
    for k, ds in zip(keys, dom_slots):
        v = k.values.astype(jnp.int32)
        if k.validity is not None:
            slot = jnp.where(k.validity, jnp.clip(v, 0, ds - 2) + 1, 0)
        else:
            slot = jnp.clip(v, 0, ds - 1)
        gid = gid * ds + slot
    gid = jnp.where(live, gid, num_groups_cap)  # dead rows dropped

    counts = jax.ops.segment_sum(
        live.astype(jnp.int32), gid, num_segments=num_groups_cap
    )
    out_live = counts > 0
    n_groups = jnp.sum(out_live.astype(jnp.int32))

    # decode key values straight from the slot index (O(cap), no scatter)
    g = jnp.arange(num_groups_cap, dtype=jnp.int32)
    digits = []
    rem = g
    for ds in reversed(dom_slots):
        digits.append(rem % ds)
        rem = rem // ds
    digits.reverse()
    key_out = []
    for k, d, ds in zip(keys, digits, dom_slots):
        if k.validity is not None:
            kvd = d > 0
            kv = jnp.where(kvd, d - 1, 0).astype(k.values.dtype)
            key_out.append(KeyCol(kv, kvd, k.domain))
        else:
            key_out.append(KeyCol(d.astype(k.values.dtype), None, k.domain))

    state_out = [
        _state_merge(s.values, s.validity, s.op, gid, n, num_groups_cap)
        for s in states
    ]
    return key_out, state_out, out_live, n_groups
