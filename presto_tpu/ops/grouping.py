"""Sort-based grouped aggregation — the GROUP BY kernel.

Reference: operator/MultiChannelGroupByHash.java:54 (open-addressing table
over flat long[] with codegen'd hash strategies) feeding
InMemoryHashAggregationBuilder.

TPU-native redesign: scatter-with-conflicts is hostile to XLA, so grouping is
a *sort*: lexicographic `lax.sort` over (deadness, per-key null bit, key
value)*, boundary detection, then `segment_sum/min/max` into a fixed-capacity
group table. Everything is static-shape; the only dynamic quantity (group
count) is returned as a device scalar so the driver can detect capacity
overflow and recompile with a bigger bucket.

The same kernel does partial aggregation, state merging, and final
aggregation: inputs are "state columns" each with a merge op
(sum/min/max/count-add), exactly like the reference's
partial/intermediate/final accumulator phases
(operator/aggregation/builder/InMemoryHashAggregationBuilder.java:160).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class StateCol(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]  # None = all valid
    op: str  # 'sum' | 'min' | 'max' | 'count_add' (values are counts)


class KeyCol(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]


def _minmax_identity(dtype, op):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if op == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if op == "min" else info.min, dtype)


def grouped_merge(
    keys: Sequence[KeyCol],
    states: Sequence[StateCol],
    live: jnp.ndarray,
    num_groups_cap: int,
) -> Tuple[list, list, jnp.ndarray, jnp.ndarray]:
    """Group rows by `keys`, merging `states` within each group.

    Returns (key_cols_out, state_cols_out, out_live, n_groups) where all
    output arrays have length num_groups_cap and rows beyond n_groups are
    dead. NULL key values form their own group (SQL GROUP BY semantics).
    Rows with live=False are ignored. If n_groups > num_groups_cap the
    caller must retry with a bigger capacity (groups beyond cap are dropped
    deterministically — the driver checks).
    """
    n = live.shape[0]
    dead = (~live).astype(jnp.int32)

    operands = [dead]
    for k in keys:
        if k.validity is not None:
            operands.append((~k.validity).astype(jnp.int32))
            operands.append(jnp.where(k.validity, k.values, jnp.zeros_like(k.values)))
        else:
            operands.append(k.values)
    num_keys = len(operands)
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=num_keys)
    sorted_keys = sorted_ops[:num_keys]
    sperm = sorted_ops[-1]
    sdead = sorted_keys[0]

    # boundary where any sort key changes (first row is always a boundary)
    change = jnp.zeros(n, dtype=bool).at[0].set(True)
    for sk in sorted_keys:
        change = change.at[1:].set(change[1:] | (sk[1:] != sk[:-1]))
    seg = jnp.cumsum(change.astype(jnp.int32)) - 1
    # dead rows sort last; push their segment out of range so segment ops drop them
    seg = jnp.where(sdead == 1, num_groups_cap, seg)
    n_groups = jnp.max(jnp.where(sdead == 1, -1, seg)) + 1

    # materialize group keys: first (any) row of each segment
    key_out = []
    ki = 1
    for k in keys:
        if k.validity is not None:
            nullbit = sorted_keys[ki]
            vals = sorted_keys[ki + 1]
            ki += 2
            kv = jnp.zeros(num_groups_cap, dtype=vals.dtype).at[seg].set(vals, mode="drop")
            kvd = jnp.zeros(num_groups_cap, dtype=bool).at[seg].set(nullbit == 0, mode="drop")
            key_out.append(KeyCol(kv, kvd))
        else:
            vals = sorted_keys[ki]
            ki += 1
            kv = jnp.zeros(num_groups_cap, dtype=vals.dtype).at[seg].set(vals, mode="drop")
            key_out.append(KeyCol(kv, None))

    state_out = []
    for s in states:
        sv = s.values[sperm]
        svalid = s.validity[sperm] if s.validity is not None else None
        if s.op in ("sum", "count_add"):
            contrib = sv if svalid is None else jnp.where(svalid, sv, jnp.zeros_like(sv))
            agg = jax.ops.segment_sum(contrib, seg, num_segments=num_groups_cap)
            if s.op == "count_add":
                state_out.append(StateCol(agg, None, s.op))
            else:
                if svalid is None:
                    nvalid = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg, num_segments=num_groups_cap)
                else:
                    nvalid = jax.ops.segment_sum(svalid.astype(jnp.int32), seg, num_segments=num_groups_cap)
                state_out.append(StateCol(agg, nvalid > 0, s.op))
        elif s.op in ("min", "max"):
            ident = _minmax_identity(sv.dtype, s.op)
            contrib = sv if svalid is None else jnp.where(svalid, sv, ident)
            segop = jax.ops.segment_min if s.op == "min" else jax.ops.segment_max
            agg = segop(contrib, seg, num_segments=num_groups_cap)
            if svalid is None:
                nvalid = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg, num_segments=num_groups_cap)
            else:
                nvalid = jax.ops.segment_sum(svalid.astype(jnp.int32), seg, num_segments=num_groups_cap)
            state_out.append(StateCol(agg, nvalid > 0, s.op))
        else:
            raise ValueError(f"unknown merge op {s.op}")

    out_live = jnp.arange(num_groups_cap) < n_groups
    return key_out, state_out, out_live, n_groups
