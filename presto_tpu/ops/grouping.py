"""Sort-based grouped aggregation — the GROUP BY kernel.

Reference: operator/MultiChannelGroupByHash.java:54 (open-addressing table
over flat long[] with codegen'd hash strategies) feeding
InMemoryHashAggregationBuilder.

TPU-native redesign: scatter-with-conflicts is hostile to XLA, so grouping is
a *sort*: lexicographic `lax.sort` over (deadness, per-key null bit, key
value)*, boundary detection, then a segmented reduction into a
fixed-capacity group table. Everything is static-shape; the only dynamic
quantity (group count) is returned as a device scalar so the driver can
detect capacity overflow and recompile with a bigger bucket.

Scatter avoidance (the load-bearing perf property): XLA lowers
`segment_sum` to HLO scatter, which TPU executes as a serialized
read-modify-write loop (~95 GB of HBM traffic for a 1M-row batch at
cap=1024 — measured ~0.8 s/batch). Three scatter-free strategies instead:

- **no keys** (global aggregate): one masked reduction per state.
- **small static key domain** (dictionary/boolean keys, ≤ _MASK_SLOTS
  slots): the group id is the mixed-radix number of the key digits and
  states reduce via a [G, n] masked-broadcast reduction — no sort, no
  scatter (BigintGroupByHash's dense small-range analog).
- **general**: lexicographic sort, then per-segment reduction by
  *segmented associative scan* (log-depth, elementwise) and a gather at
  segment ends; group keys materialize with a searchsorted + gather.
  Per-segment scans also keep float sums exact per group (no
  prefix-difference cancellation).

The same kernel does partial aggregation, state merging, and final
aggregation: inputs are "state columns" each with a merge op
(sum/min/max/count-add), exactly like the reference's
partial/intermediate/final accumulator phases
(operator/aggregation/builder/InMemoryHashAggregationBuilder.java:160).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class StateCol(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]  # None = all valid
    op: str  # 'sum' | 'min' | 'max' | 'count_add' (values are counts)


class KeyCol(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]
    # Exclusive upper bound of non-null values when statically known (values
    # in [0, domain): dictionary codes, booleans). Lets grouped_merge take
    # the direct-indexed path (group id = mixed-radix key digits — no sort),
    # the analog of the reference's BigintGroupByHash small-range fast path
    # (operator/BigintGroupByHash.java). None = unbounded.
    domain: Optional[int] = None


def _minmax_identity(dtype, op):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if op == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if op == "min" else info.min, dtype)


# Masked-broadcast reduction is O(G·n); past this many slots the sorted
# segmented-scan path (O(n log n) but G-independent) wins.
_MASK_SLOTS = 128


def grouped_merge(
    keys: Sequence[KeyCol],
    states: Sequence[StateCol],
    live: jnp.ndarray,
    num_groups_cap: int,
    engine: str = "sort",
) -> Tuple[list, list, jnp.ndarray, jnp.ndarray]:
    """Group rows by `keys`, merging `states` within each group.

    Returns (key_cols_out, state_cols_out, out_live, n_groups) where all
    output arrays share one capacity (num_groups_cap on the sort path;
    the pow2 hash-table capacity on the hash path — drivers must size off
    the returned arrays, not the requested cap) and slots with
    out_live=False are dead. NULL key values form their own group (SQL
    GROUP BY semantics). Rows with live=False are ignored. If
    n_groups > num_groups_cap the caller must retry with a bigger
    capacity (groups beyond cap are dropped deterministically — the
    driver checks; on the hash engine n_groups then upper-bounds the true
    distinct count instead of equaling it).

    engine: "sort" (lexicographic sort + segmented scan — the default) or
    "hash" (ops/pallas_hash linear probing; chosen per breaker by
    plan/stats.choose_breaker_engine). Both engines produce the same
    group multiset; group ORDER differs (sorted by key vs. hash slot).
    """
    if not keys:
        return _global_merge(states, live, num_groups_cap)

    if all(k.domain is not None for k in keys):
        dom_slots = [
            (k.domain + 1) if k.validity is not None else max(k.domain, 1)
            for k in keys
        ]
        total = 1
        for ds in dom_slots:
            total *= ds
        if 0 < total <= min(num_groups_cap, _MASK_SLOTS):
            return _direct_grouped_merge(
                keys, states, live, num_groups_cap, dom_slots, engine
            )

    if engine == "hash":
        return _hash_grouped_merge(keys, states, live, num_groups_cap)

    n = live.shape[0]
    dead = (~live).astype(jnp.int32)

    operands = [dead]
    for k in keys:
        if k.validity is not None:
            operands.append((~k.validity).astype(jnp.int32))
            operands.append(jnp.where(k.validity, k.values, jnp.zeros_like(k.values)))
        else:
            operands.append(k.values)
    num_keys = len(operands)
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=num_keys)
    sorted_keys = sorted_ops[:num_keys]
    sperm = sorted_ops[-1]
    sdead = sorted_keys[0]

    # boundary where any sort key changes (first row is always a boundary)
    change = jnp.zeros(n, dtype=bool).at[0].set(True)
    for sk in sorted_keys:
        change = change.at[1:].set(change[1:] | (sk[1:] != sk[:-1]))
    seg = jnp.cumsum(change.astype(jnp.int32)) - 1
    # dead rows sort last; push their segment out of range so lookups miss
    seg = jnp.where(sdead == 1, num_groups_cap, seg)
    n_groups = jnp.max(jnp.where(sdead == 1, -1, seg)) + 1

    # per-group first/last row positions in sorted order (gather, no scatter)
    gids = jnp.arange(num_groups_cap, dtype=seg.dtype)
    starts = jnp.searchsorted(seg, gids, side="left")        # [cap] in [0, n]
    ends = jnp.searchsorted(seg, gids, side="right") - 1     # [cap] in [-1, n-1]
    has = ends >= starts
    starts_c = jnp.clip(starts, 0, n - 1).astype(jnp.int32)
    ends_c = jnp.clip(ends, 0, n - 1).astype(jnp.int32)

    # materialize group keys: first row of each segment
    key_out = []
    ki = 1
    for k in keys:
        if k.validity is not None:
            nullbit = sorted_keys[ki]
            vals = sorted_keys[ki + 1]
            ki += 2
            kv = jnp.where(has, vals[starts_c], jnp.zeros((), vals.dtype))
            kvd = has & (nullbit[starts_c] == 0)
            key_out.append(KeyCol(kv, kvd))
        else:
            vals = sorted_keys[ki]
            ki += 1
            kv = jnp.where(has, vals[starts_c], jnp.zeros((), vals.dtype))
            key_out.append(KeyCol(kv, None))

    state_out = []
    for s in states:
        sv = s.values[sperm]
        svalid = s.validity[sperm] if s.validity is not None else None
        state_out.append(
            _state_merge_sorted(sv, svalid, s.op, change, ends_c, has)
        )

    out_live = jnp.arange(num_groups_cap) < n_groups
    return key_out, state_out, out_live, n_groups


def _segmented_scan(vals, first_flag, op: str):
    """Inclusive segmented scan: within each run started by first_flag,
    combine with `op`. Log-depth associative scan, pure elementwise —
    the scatter-free backbone of the sorted reduction."""

    def combine(a, b):
        fa, va = a
        fb, vb = b
        if op == "sum":
            v = jnp.where(fb, vb, va + vb)
        elif op == "min":
            v = jnp.where(fb, vb, jnp.minimum(va, vb))
        else:
            v = jnp.where(fb, vb, jnp.maximum(va, vb))
        return fa | fb, v

    _, scanned = jax.lax.associative_scan(combine, (first_flag, vals))
    return scanned


def _state_merge_sorted(sv, svalid, op, change, ends_c, has):
    """One permuted state column → per-segment aggregate via segmented scan
    + gather at segment ends. Exact per group (no prefix-difference
    cancellation for floats; int sums are plain adds)."""
    base_op = "sum" if op == "count_add" else op
    if op in ("sum", "count_add"):
        contrib = sv if svalid is None else jnp.where(svalid, sv, jnp.zeros_like(sv))
    else:
        ident = _minmax_identity(sv.dtype, op)
        contrib = sv if svalid is None else jnp.where(svalid, sv, ident)
    scanned = _segmented_scan(contrib, change, base_op)
    agg = jnp.where(has, scanned[ends_c], jnp.zeros((), sv.dtype))
    if op == "count_add":
        return StateCol(agg, None, op)
    if svalid is None:
        return StateCol(agg, has, op)
    vscan = _segmented_scan(svalid.astype(jnp.int32), change, "sum")
    nvalid = jnp.where(has, vscan[ends_c], 0)
    return StateCol(agg, nvalid > 0, op)


def _global_merge(states, live, num_groups_cap):
    """No GROUP BY keys: one masked reduction per state into slot 0.
    (The sort path would scatter; a global aggregate needs neither.)"""
    any_live = jnp.any(live)
    out_live = (jnp.arange(num_groups_cap) == 0) & any_live
    n_groups = any_live.astype(jnp.int64)
    state_out = []
    for s in states:
        sv, svalid = s.values, s.validity
        valid = live if svalid is None else (live & svalid)
        if s.op in ("sum", "count_add"):
            total = jnp.sum(jnp.where(valid, sv, jnp.zeros_like(sv)))
        elif s.op == "min":
            total = jnp.min(jnp.where(valid, sv, _minmax_identity(sv.dtype, "min")))
        else:
            total = jnp.max(jnp.where(valid, sv, _minmax_identity(sv.dtype, "max")))
        agg = jnp.zeros(num_groups_cap, sv.dtype).at[0].set(total)
        if s.op == "count_add":
            state_out.append(StateCol(agg, None, s.op))
        else:
            nvalid = jnp.sum(valid.astype(jnp.int32))
            v0 = (jnp.arange(num_groups_cap) == 0) & (nvalid > 0)
            state_out.append(StateCol(agg, v0, s.op))
    return [], state_out, out_live, n_groups


def _direct_grouped_merge(
    keys: Sequence[KeyCol],
    states: Sequence[StateCol],
    live: jnp.ndarray,
    num_groups_cap: int,
    dom_slots: Sequence[int],
    engine: str = "sort",
) -> Tuple[list, list, jnp.ndarray, jnp.ndarray]:
    """Small-key-domain GROUP BY: the group id IS the mixed-radix number of
    the key digits (nullable keys reserve digit 0 for NULL), so states
    reduce by a [G, n] masked-broadcast reduction on input order — no sort,
    no permutation, no scatter. The group table is sparse: out_live marks
    occupied slots and key columns are decoded from the slot index itself.
    Because Π dom_slots ≤ min(cap, _MASK_SLOTS), overflow is impossible
    (n_groups counts occupied slots).

    Reference analog: BigintGroupByHash's dense small-range path; here it
    also covers multi-key dictionary-coded GROUP BY (TPC-H Q1's
    returnflag×linestatus), which the reference would route through
    MultiChannelGroupByHash."""
    n = live.shape[0]
    total = 1
    for ds in dom_slots:
        total *= ds
    gid = jnp.zeros(n, dtype=jnp.int32)
    for k, ds in zip(keys, dom_slots):
        v = k.values.astype(jnp.int32)
        if k.validity is not None:
            slot = jnp.where(k.validity, jnp.clip(v, 0, ds - 2) + 1, 0)
        else:
            slot = jnp.clip(v, 0, ds - 1)
        gid = gid * ds + slot
    gid = jnp.where(live, gid, total)  # dead rows match no slot

    from presto_tpu.ops import pallas_groupby as _pg
    from presto_tpu.ops import pallas_hash as _ph

    if engine == "hash" or _pg.enabled():
        return _pallas_direct_merge(keys, states, live, num_groups_cap,
                                    dom_slots, gid, total,
                                    interpret=_ph.use_interpret())

    # [G, n] group-membership mask, reused across all states
    eq = gid[None, :] == jnp.arange(total, dtype=jnp.int32)[:, None]

    counts_g = jnp.sum(eq, axis=1, dtype=jnp.int32)  # [G]
    counts = jnp.zeros(num_groups_cap, jnp.int32).at[:total].set(counts_g)
    out_live = counts > 0
    n_groups = jnp.sum(out_live.astype(jnp.int32))

    # decode key values straight from the slot index (O(cap), no scatter)
    g = jnp.arange(num_groups_cap, dtype=jnp.int32)
    digits = []
    rem = g
    for ds in reversed(dom_slots):
        digits.append(rem % ds)
        rem = rem // ds
    digits.reverse()
    key_out = []
    for k, d, ds in zip(keys, digits, dom_slots):
        if k.validity is not None:
            kvd = d > 0
            kv = jnp.where(kvd, d - 1, 0).astype(k.values.dtype)
            key_out.append(KeyCol(kv, kvd, k.domain))
        else:
            key_out.append(KeyCol(d.astype(k.values.dtype), None, k.domain))

    state_out = [
        _state_merge_masked(s, eq, total, num_groups_cap) for s in states
    ]
    return key_out, state_out, out_live, n_groups


def _decode_direct_keys(keys, dom_slots, num_groups_cap):
    """Key columns decoded from the slot index (shared by the mask and
    Pallas direct paths)."""
    g = jnp.arange(num_groups_cap, dtype=jnp.int32)
    digits = []
    rem = g
    for ds in reversed(dom_slots):
        digits.append(rem % ds)
        rem = rem // ds
    digits.reverse()
    key_out = []
    for k, d, ds in zip(keys, digits, dom_slots):
        if k.validity is not None:
            kvd = d > 0
            kv = jnp.where(kvd, d - 1, 0).astype(k.values.dtype)
            key_out.append(KeyCol(kv, kvd, k.domain))
        else:
            key_out.append(KeyCol(d.astype(k.values.dtype), None, k.domain))
    return key_out


def _pallas_direct_merge(keys, states, live, num_groups_cap, dom_slots,
                         gid, total, interpret: bool = False):
    """Direct small-domain path on the MXU (ops/pallas_groupby): integer
    sums (decimal money, counts) and validity counts fuse into ONE exact
    kernel pass; float sums and min/max states keep the portable masked
    reduction (f32 MACs cannot deliver f64 sums — see the kernel's
    docstring)."""
    from presto_tpu.ops import pallas_groupby as _pg

    int_states, plan = [], []
    # group occupancy ride-along: one all-ones int state
    int_states.append(live.astype(jnp.int64))
    for s in states:
        valid = live if s.validity is None else (live & s.validity)
        int_sum = (s.op in ("sum", "count_add")
                   and not jnp.issubdtype(s.values.dtype, jnp.floating))
        if int_sum:
            contrib = jnp.where(valid, s.values, jnp.zeros_like(s.values))
            main = ("int", len(int_states))
            int_states.append(contrib.astype(jnp.int64))
        else:
            main = ("masked", None)
        if int_sum and s.op != "count_add":
            plan.append((main, len(int_states)))
            int_states.append(valid.astype(jnp.int64))
        else:
            plan.append((main, None))
    iouts = _pg.grouped_sums(gid, int_states, total, interpret=interpret)

    def widen(arr, dtype):
        return jnp.zeros(num_groups_cap, dtype).at[:total].set(
            arr.astype(dtype))

    counts = widen(iouts[0], jnp.int32)
    out_live = counts > 0
    n_groups = jnp.sum(out_live.astype(jnp.int32))
    key_out = _decode_direct_keys(keys, dom_slots, num_groups_cap)

    eq = None
    state_out = []
    for s, ((kind, idx), nv_idx) in zip(states, plan):
        if kind == "masked":
            if eq is None:
                eq = (gid[None, :]
                      == jnp.arange(total, dtype=jnp.int32)[:, None])
            state_out.append(_state_merge_masked(s, eq, total,
                                                 num_groups_cap))
            continue
        agg = widen(iouts[idx], s.values.dtype)
        if s.op == "count_add":
            state_out.append(StateCol(agg, None, s.op))
            continue
        nvalid = widen(iouts[nv_idx], jnp.int32)
        state_out.append(StateCol(agg, nvalid > 0, s.op))
    return key_out, state_out, out_live, n_groups


# One-hot [B, G] MXU membership is O(B·G); past this many physical slots
# the gid-sorted segmented-scan reduction (G-independent) wins.
_HASH_MXU_SLOTS = 512


def _hash_grouped_merge(
    keys: Sequence[KeyCol],
    states: Sequence[StateCol],
    live: jnp.ndarray,
    num_groups_cap: int,
) -> Tuple[list, list, jnp.ndarray, jnp.ndarray]:
    """General GROUP BY on the Pallas linear-probing table
    (ops/pallas_hash): encode keys into int64 planes, assign group ids by
    hash-table insert, then reduce states by gid — via the exact
    limb-split MXU kernel (ops/pallas_groupby.grouped_sums) when every
    state is an integer sum and the table is small, else via a gid sort
    feeding the same segmented-scan reduction the sort engine uses
    (stable sort, so per-group float addition order matches input order).

    The group table is sparse over the physical capacity (2× the pow2
    logical cap): out_live marks occupied slots, keys decode from the
    stored planes. Overflow reports n_groups > num_groups_cap so the
    driver's regrow-replay fires on the existing contract."""
    from presto_tpu.ops import pallas_groupby as _pg
    from presto_tpu.ops import pallas_hash as _ph
    from presto_tpu.ops import radix as _radix
    from presto_tpu.ops.hashing import hash_columns

    interpret = _ph.use_interpret()
    cap = 1
    while cap < num_groups_cap:
        cap *= 2
    tcap = 2 * cap

    planes, has_nulls = _ph.encode_group_keys(
        [(k.values, k.validity) for k in keys])
    h = hash_columns(list(planes))
    slot0 = _radix.slot_hash(h, tcap)
    gid, table, occ, ngroups, ovf = _ph.group_insert(
        planes, slot0, live, cap, interpret=interpret)
    out_live = occ > 0

    # On overflow report > cap so the driver regrows; ovf counts unplaced
    # ROWS (an upper bound on the missing distinct keys), so clamp the
    # overshoot to keep the regrow ladder geometric, not row-count-sized.
    ng = jnp.where(
        ovf > 0,
        jnp.int64(cap) + jnp.minimum(ovf.astype(jnp.int64),
                                     jnp.int64(3 * cap)),
        ngroups.astype(jnp.int64))

    nullplane = table[len(keys)] if has_nulls else None
    key_out = []
    for j, k in enumerate(keys):
        kv = _ph.decode_plane(table[j], k.values.dtype)
        if k.validity is not None:
            nbit = (nullplane >> jnp.int64(j)) & jnp.int64(1)
            key_out.append(KeyCol(kv, out_live & (nbit == 0), k.domain))
        else:
            key_out.append(KeyCol(kv, None, k.domain))

    if not states:
        return key_out, [], out_live, ng
    all_int_sums = all(
        s.op in ("sum", "count_add")
        and not jnp.issubdtype(s.values.dtype, jnp.floating)
        for s in states)
    if all_int_sums and tcap <= _HASH_MXU_SLOTS:
        state_out = _hash_states_mxu(states, live, gid, tcap, interpret)
    else:
        state_out = _hash_states_sorted(states, gid, tcap)
    return key_out, state_out, out_live, ng


def _hash_states_mxu(states, live, gid, tcap: int, interpret: bool):
    """All-integer-sum states reduce on the MXU limb-split kernel: one
    fused pass, exact int64 sums (gid >= tcap marks dead/unplaced rows)."""
    from presto_tpu.ops import pallas_groupby as _pg

    int_states, plan = [], []
    for s in states:
        valid = live if s.validity is None else (live & s.validity)
        contrib = jnp.where(valid, s.values, jnp.zeros_like(s.values))
        main = len(int_states)
        int_states.append(contrib.astype(jnp.int64))
        if s.op != "count_add":
            plan.append((main, len(int_states)))
            int_states.append(valid.astype(jnp.int64))
        else:
            plan.append((main, None))
    iouts = _pg.grouped_sums(gid, int_states, tcap, interpret=interpret)
    state_out = []
    for s, (mi, ni) in zip(states, plan):
        agg = iouts[mi].astype(s.values.dtype)
        if s.op == "count_add":
            state_out.append(StateCol(agg, None, s.op))
        else:
            state_out.append(StateCol(agg, iouts[ni] > 0, s.op))
    return state_out


def _hash_states_sorted(states, gid, tcap: int):
    """General states reduce by a stable sort on gid feeding the same
    segmented-scan machinery as the sort engine — per-group combine order
    is input row order on both engines. Dead/unplaced rows (gid == tcap)
    sink past every slot's segment."""
    n = gid.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    sgid, sperm = jax.lax.sort([gid, perm], num_keys=1, is_stable=True)
    change = jnp.zeros(n, dtype=bool).at[0].set(True)
    change = change.at[1:].set(sgid[1:] != sgid[:-1])
    slots = jnp.arange(tcap, dtype=sgid.dtype)
    starts = jnp.searchsorted(sgid, slots, side="left")
    ends = jnp.searchsorted(sgid, slots, side="right") - 1
    has = ends >= starts
    ends_c = jnp.clip(ends, 0, n - 1).astype(jnp.int32)
    out = []
    for s in states:
        sv = s.values[sperm]
        svalid = s.validity[sperm] if s.validity is not None else None
        out.append(_state_merge_sorted(sv, svalid, s.op, change, ends_c, has))
    return out


def _state_merge_masked(s: StateCol, eq, total: int, num_groups_cap: int):
    """One state column → per-slot aggregate via the [G, n] mask."""
    sv, svalid = s.values, s.validity
    if s.op in ("sum", "count_add"):
        contrib = sv if svalid is None else jnp.where(svalid, sv, jnp.zeros_like(sv))
        agg_g = jnp.sum(jnp.where(eq, contrib[None, :], jnp.zeros((), sv.dtype)),
                        axis=1)
    else:
        ident = _minmax_identity(sv.dtype, s.op)
        contrib = sv if svalid is None else jnp.where(svalid, sv, ident)
        masked = jnp.where(eq, contrib[None, :], ident)
        agg_g = jnp.min(masked, axis=1) if s.op == "min" else jnp.max(masked, axis=1)
    agg = jnp.zeros(num_groups_cap, sv.dtype).at[:total].set(agg_g)
    if s.op == "count_add":
        return StateCol(agg, None, s.op)
    if svalid is None:
        nvalid_g = jnp.sum(eq, axis=1, dtype=jnp.int32)
    else:
        nvalid_g = jnp.sum(eq & svalid[None, :], axis=1, dtype=jnp.int32)
    nvalid = jnp.zeros(num_groups_cap, jnp.int32).at[:total].set(nvalid_g)
    return StateCol(agg, nvalid > 0, s.op)


def partition_skew(rows) -> float:
    """Skew factor of a per-partition row distribution: fullest partition
    over the mean of the non-empty ones (1.0 = perfectly balanced). Host
    math over already-synced ints — the radix drivers feed it the
    partition row counters they hold anyway, and obs/runstats stores it
    as the observed-skew input to future presize decisions."""
    live = [int(r) for r in rows if int(r) > 0]  # lint: allow(host-sync)
    if not live:
        return 1.0
    return max(live) * len(live) / float(sum(live))  # lint: allow(host-sync)
