"""Hash-repartitioning kernel — the device side of the exchange.

Reference: operator/PartitionedOutputOperator.java:48 (PagePartitioner
.partitionPage:377) which routes each row to a per-consumer OutputBuffer for
the HTTP pull shuffle.

TPU-native redesign: repartitioning across chips is a *collective*, not a
buffer + RPC. This kernel scatters rows of a batch into a dense
(num_partitions, per_partition_capacity) layout that feeds
`jax.lax.all_to_all` under shard_map (see presto_tpu.parallel.exchange).
Routing = sort by partition id; slot within partition = rank - partition
start (both from the same sort) — no atomics, no conflicts, static shapes.

Overflow (a skewed partition exceeding per-partition capacity) is detected
and returned as a count so the driver can re-run with a bigger bucket —
the moral analog of exchange back-pressure.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch, Column
from presto_tpu.ops.hashing import hash_columns


def partition_hash(batch: Batch, key_names: Sequence[str]) -> jnp.ndarray:
    """Content-equality 63-bit hash of the key columns (int64, non-negative).

    String keys are remapped through the dictionary's content-hash LUT
    before hashing: partitioning must agree on the string VALUE, not the
    per-batch dictionary code, or equal keys encoded against different
    dictionaries land on different partitions (reference
    InterpretedHashGenerator hashes value bytes). The LUT is a trace-time
    constant — batch dicts are static pytree aux, so each dictionary keys
    its own compiled program.

    Both the exchange (`h % num_partitions`) and the within-worker radix
    partitioner (top bits, ops/radix.py) derive from this same hash so a
    sink that already routed by it can tag pages with their radix id.
    """
    vals, valids = [], []
    for k in key_names:
        c = batch.column(k)
        v = c.values
        d = batch.dicts.get(k)
        if d is not None:
            lut = jnp.asarray(d.content_hash_lut())
            v = jnp.take(lut, v.astype(jnp.int32) + 1, mode="clip")
        vals.append(v)
        valids.append(c.validity)
    return hash_columns(vals, valids)


def partition_ids(batch: Batch, key_names: Sequence[str], num_partitions: int):
    """Row → partition id by hash(keys) mod num_partitions."""
    h = partition_hash(batch, key_names)
    return (h % num_partitions).astype(jnp.int32)


def partition_layout(
    batch: Batch,
    key_names: Sequence[str],
    num_partitions: int,
    per_partition_capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Routing layout shared by the per-column and the packed (fused-lane)
    exchange paths: sort rows by partition id once, derive every plane's
    scatter from that single sort.

    Returns (sperm, dest, counts, routed, overflow):
    - sperm int32[n]: source row for each sorted position,
    - dest  int32[n]: output slot (partition * C + rank) for each sorted
      position; the value ``P*C`` marks dead/overflow rows (scatter with
      mode="drop" discards them),
    - counts int32[P]: live rows per partition (uncapped — overflow rows
      included, so lane-utilization accounting sees true demand),
    - routed bool[n]: sorted-order mask of rows that landed in a lane
      (live and within capacity) — the source plane for `live`,
    - overflow: scalar count of live rows beyond per-partition capacity.
    """
    n = batch.capacity
    pid = partition_ids(batch, key_names, num_partitions)
    pid = jnp.where(batch.live, pid, num_partitions)  # dead rows last
    perm = jnp.arange(n, dtype=jnp.int32)
    spid, sperm = jax.lax.sort([pid, perm], num_keys=1, is_stable=True)
    # rank within partition: global rank minus partition start
    start = jnp.searchsorted(spid, jnp.arange(num_partitions + 1, dtype=spid.dtype))
    rank = jnp.arange(n, dtype=jnp.int32)
    pstart = start[jnp.clip(spid, 0, num_partitions)]
    slot = rank - pstart.astype(jnp.int32)
    live_sorted = spid < num_partitions
    in_cap = slot < per_partition_capacity
    dest = jnp.clip(spid, 0, num_partitions - 1) * per_partition_capacity + slot
    dest = jnp.where(live_sorted & in_cap, dest, num_partitions * per_partition_capacity)
    counts = jax.ops.segment_sum(
        live_sorted.astype(jnp.int32),
        jnp.clip(spid, 0, num_partitions),
        num_segments=num_partitions + 1,
    )[:num_partitions]
    overflow = jnp.sum(live_sorted & ~in_cap)
    return sperm, dest, counts, live_sorted & in_cap, overflow


def partition_for_exchange(
    batch: Batch,
    key_names: Sequence[str],
    num_partitions: int,
    per_partition_capacity: int,
) -> Tuple[Batch, jnp.ndarray, jnp.ndarray]:
    """Scatter rows into (P, C) per-partition lanes.

    Returns (out_batch with leading partition axis folded as P*C rows,
    per-partition live counts int32[P], overflow_count scalar).
    The out batch's arrays are reshaped by the exchange into (P, C) and fed
    to all_to_all; row order within a partition follows input order.
    """
    sperm, dest, counts, routed, overflow = partition_layout(
        batch, key_names, num_partitions, per_partition_capacity)
    out_n = num_partitions * per_partition_capacity
    cols = []
    for c in batch.columns:
        sv = c.values[sperm]
        ov = jnp.zeros(out_n, dtype=sv.dtype).at[dest].set(sv, mode="drop")
        if c.validity is not None:
            sval = c.validity[sperm]
            oval = jnp.zeros(out_n, dtype=bool).at[dest].set(sval, mode="drop")
        else:
            oval = None
        if c.hi is not None:
            shi = c.hi[sperm]
            ohi = jnp.zeros(out_n, dtype=shi.dtype).at[dest].set(shi, mode="drop")
        else:
            ohi = None
        cols.append(Column(ov, oval, ohi))
    out_live = jnp.zeros(out_n, dtype=bool).at[dest].set(routed, mode="drop")
    out = Batch(batch.names, batch.types, cols, out_live, batch.dicts)
    return out, counts, overflow
