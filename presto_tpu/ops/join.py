"""Hash join kernels: sorted build + searchsorted probe.

Reference: operator/HashBuilderOperator.java (build), PagesHash.java:34,152 /
JoinHash + PositionLinks chains (probe), LookupJoinOperator.java:392-460
(probe loop with yielding output builder).

TPU-native redesign: no pointer chains. The build side is *sorted by a
64-bit key hash*; a probe is two vectorized binary searches
(searchsorted left/right) giving each probe row its candidate range
[lo, hi). Range semantics replace PositionLinks. Because we join on the
hash, candidates are verified against the actual key columns (exact
semantics even under hash collisions).

Fanout handling (the LookupJoinPageBuilder analog): a counts pass computes
per-probe match counts and a prefix sum; materialization maps each output
slot i back to (probe_row, ordinal) with one searchsorted over the prefix
sums — fully vectorized, chunked by the driver when total matches exceed the
output capacity.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch, Column, round_up_capacity
from presto_tpu.ops import pallas_hash
from presto_tpu.ops.hashing import hash_columns
from presto_tpu.ops.radix import slot_hash
from presto_tpu.ops.sort import permute_batch


class BuildTable(NamedTuple):
    """Sorted-by-hash build side. `batch` holds payload + key columns,
    compacted so live rows occupy [0, n_rows); `hashes` aligned with it.
    `orig_live` preserves input liveness BEFORE NULL-key rows were killed —
    FULL OUTER must still emit those rows in its build remainder (a NULL
    key never matches, but the row exists)."""

    hashes: jnp.ndarray  # int64[cap], sorted; dead lanes = int64.max
    batch: Batch
    n_rows: jnp.ndarray  # device scalar
    orig_live: jnp.ndarray  # bool[cap], aligned with batch


_SENTINEL = jnp.iinfo(jnp.int64).max


def join_hash(batch: Batch, key_names: Sequence[str]) -> jnp.ndarray:
    cols = [batch.column(k).values for k in key_names]
    valids = [batch.column(k).validity for k in key_names]
    return hash_columns(cols, valids)


def align_probe_strings(
    probe: Batch, probe_keys: Sequence[str], table: "BuildTable",
    build_keys: Sequence[str],
) -> Batch:
    """Equi-join on varchar compares dictionary codes, so probe-side codes
    must be remapped into the build side's dictionary code space (analog of
    DictionaryBlock id canonicalization before PagesHash compare). Codes with
    no build-side entry become -1, which never equals a valid build code.
    Host builds the remap table at trace time; device does one gather."""
    out = probe
    for pk, bk in zip(probe_keys, build_keys):
        if not probe.type_of(pk).is_string:
            continue
        pd_ = probe.dict_of(pk)
        bd = table.batch.dict_of(bk)
        if pd_ is None or bd is None or pd_ is bd:
            continue
        remap = jnp.asarray(pd_.map_to(bd))
        c = out.column(pk)
        from presto_tpu.batch import Column

        out = out.with_column(
            pk, probe.type_of(pk), Column(remap[c.values + 1], c.validity),
            dictionary=bd,
        )
    return out


def build_side(batch: Batch, key_names: Sequence[str]) -> BuildTable:
    """Sort the (concatenated, still masked) build input by key hash; dead
    rows sink to the end via a sentinel hash."""
    h = join_hash(batch, key_names)
    # rows with NULL in any key never match an equi-join: kill them now
    live = batch.live
    for k in key_names:
        v = batch.column(k).validity
        if v is not None:
            live = live & v
    h = jnp.where(live, h, _SENTINEL)
    perm = jnp.arange(batch.capacity, dtype=jnp.int32)
    sorted_h, sperm = jax.lax.sort([h, perm], num_keys=1)
    sorted_batch = permute_batch(batch.with_live(live), sperm)
    n = jnp.sum(live.astype(jnp.int64))
    return BuildTable(sorted_h, sorted_batch, n, batch.live[sperm])


def _probe_ranges(table: BuildTable, probe: Batch, key_names: Sequence[str]):
    h = join_hash(probe, key_names)
    live = probe.live
    for k in key_names:
        v = probe.column(k).validity
        if v is not None:
            live = live & v
    h = jnp.where(live, h, _SENTINEL - 1)  # never matches a real hash*
    lo = jnp.searchsorted(table.hashes, h, side="left")
    hi = jnp.searchsorted(table.hashes, h, side="right")
    return h, lo, hi, live


def _keys_equal(table: BuildTable, build_idx, probe: Batch,
                probe_keys: Sequence[str], build_keys: Sequence[str]):
    """Verify actual key equality at gathered build positions."""
    ok = jnp.ones(build_idx.shape, dtype=bool)
    for pk, bk in zip(probe_keys, build_keys):
        pv = probe.column(pk).values
        bv = table.batch.column(bk).values[build_idx]
        if pv.dtype != bv.dtype:
            t = jnp.result_type(pv.dtype, bv.dtype)
            pv, bv = pv.astype(t), bv.astype(t)
        ok = ok & (pv == bv)
    return ok


def probe_unique(
    table: BuildTable,
    probe: Batch,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    collision_scan: int = 4,
):
    """Fast path: build keys are unique (dimension tables — the dominant
    TPC-H shape). Each probe row matches <= 1 build row.

    A range [lo, hi) wider than 1 can only come from distinct build keys
    sharing a 64-bit hash; `collision_scan` candidates are verified so the
    exactness guarantee survives collisions (beyond-scan collisions of 4+
    distinct keys on one hash are beyond astronomically unlikely, but are
    counted and surfaced by callers that care via hi-lo).

    Returns (build_idx int32[cap], matched bool[cap]).
    """
    _, lo, hi, live = _probe_ranges(table, probe, probe_keys)
    cap = table.hashes.shape[0]
    width = hi - lo
    idx = jnp.clip(lo, 0, cap - 1).astype(jnp.int32)
    matched = jnp.zeros(lo.shape, dtype=bool)
    for j in range(collision_scan):
        cand = jnp.clip(lo + j, 0, cap - 1).astype(jnp.int32)
        ok = (
            (j < width)
            & ~matched
            & _keys_equal(table, cand, probe, probe_keys, build_keys)
        )
        idx = jnp.where(ok, cand, idx)
        matched = matched | ok
    return idx, matched & live


def probe_counts(
    table: BuildTable,
    probe: Batch,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    max_fanout_scan: int = 8,
):
    """General path, pass 1: per-probe-row candidate ranges and counts.

    Hash-collision verification for the counting pass scans up to
    `max_fanout_scan` candidates vectorized; ranges wider than that fall
    back to counting hash matches (superset — rows are still verified and
    masked at expand time, so correctness holds; only capacity estimation
    widens). The number of probe rows that hit this widening is returned
    as `overflow` so drivers can surface it as a counter instead of the
    estimate silently inflating output capacity.

    Returns (lo int32[cap], counts, offsets, total, live, overflow).
    """
    _, lo, hi, live = _probe_ranges(table, probe, probe_keys)
    width = hi - lo
    counts = jnp.zeros(width.shape, dtype=jnp.int64)
    cap = table.hashes.shape[0]
    for j in range(max_fanout_scan):
        idx = jnp.clip(lo + j, 0, cap - 1).astype(jnp.int32)
        ok = (j < width) & _keys_equal(table, idx, probe, probe_keys, build_keys)
        counts = counts + ok.astype(jnp.int64)
    # A range can verify NON-contiguously when distinct keys share a hash
    # (float keys hash by integer truncation, so every value with the same
    # integer part collides). probe_expand assumes verified matches start
    # at lo, so emit the whole range in that case and let expand's key
    # verification mask the non-matches — capacity widens, results don't.
    counts = jnp.where(counts == width, counts, width)
    widened = live & (width > max_fanout_scan)
    counts = jnp.where(width > max_fanout_scan, width, counts)
    counts = jnp.where(live, counts, 0)
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix sum
    total = jnp.sum(counts)
    overflow = jnp.sum(widened.astype(jnp.int64))
    return lo.astype(jnp.int32), counts, offsets, total, live, overflow


def probe_expand(
    table: BuildTable,
    probe: Batch,
    probe_keys: Sequence[str],
    build_keys: Sequence[str],
    lo: jnp.ndarray,
    counts: jnp.ndarray,
    offsets: jnp.ndarray,
    chunk_base,
    out_capacity: int,
):
    """General path, pass 2: materialize output slots
    [chunk_base, chunk_base + out_capacity).

    Each output slot i maps to probe_row = searchsorted(offsets_end, i,
    'right') and ordinal = i - offsets[probe_row]; the build row is
    lo[probe_row] + ordinal, verified against real keys.

    Returns (probe_idx int32[out_capacity], build_idx int32[out_capacity],
    out_live bool[out_capacity]).
    """
    total = offsets + counts  # inclusive ends
    i = jnp.arange(out_capacity, dtype=jnp.int64) + chunk_base
    probe_row = jnp.searchsorted(total, i, side="right").astype(jnp.int32)
    pcap = counts.shape[0]
    probe_row = jnp.clip(probe_row, 0, pcap - 1)
    ordinal = i - offsets[probe_row]
    in_range = (i < total[-1]) & (ordinal >= 0) & (ordinal < counts[probe_row])
    build_idx = (lo[probe_row] + ordinal).astype(jnp.int32)
    build_idx = jnp.clip(build_idx, 0, table.hashes.shape[0] - 1)
    # verify real keys at the expanded pairs (covers hash collisions and the
    # wide-range counting fallback)
    pk_ok = jnp.ones(out_capacity, dtype=bool)
    for pk, bk in zip(probe_keys, build_keys):
        pv = probe.column(pk).values[probe_row]
        bv = table.batch.column(bk).values[build_idx]
        if pv.dtype != bv.dtype:
            t = jnp.result_type(pv.dtype, bv.dtype)
            pv, bv = pv.astype(t), bv.astype(t)
        pk_ok = pk_ok & (pv == bv)
    return probe_row, build_idx, in_range & pk_ok


# ---------------------------------------------------------------------------
# linear-probing hash-table engine (ops/pallas_hash) — the alternative to the
# sorted build above, selected per breaker by plan/stats.choose_breaker_engine


class HashJoinTable(NamedTuple):
    """Linear-probing build side. Unlike BuildTable there is NO sort: the
    build batch keeps input row order and `slot_row` maps probe-chain
    slots to build ROW indices (-1 = empty); duplicate keys occupy
    consecutive chain slots. `planes` are the pairwise-promoted encoded
    key planes (pallas_hash.encode_plane), reused by every probe batch.
    `hashes`/`orig_live` keep BuildTable's shape contract so the FULL
    OUTER remainder path is engine-agnostic."""

    hashes: jnp.ndarray       # int64[cap_b], per-row content hash
    batch: Batch              # NULL-key rows live-killed, input order
    n_rows: jnp.ndarray       # device scalar
    orig_live: jnp.ndarray    # bool[cap_b]
    slot_row: jnp.ndarray     # int32[tcap], tcap = 2 * pow2(cap_b)
    planes: jnp.ndarray       # int64[K, cap_b]


def join_compare_dtypes(build_batch: Batch, build_keys: Sequence[str],
                        probe_dtypes: Sequence) -> tuple:
    """Pairwise-promoted compare dtype per key position — the dtype at
    which _keys_equal would compare, applied at ENCODE time so plane
    equality matches the sort engine's `==` (identical rounding for
    int→float promotions)."""
    return tuple(
        jnp.result_type(build_batch.column(k).values.dtype, jnp.dtype(d))
        for k, d in zip(build_keys, probe_dtypes))


def _encode_join_planes(batch: Batch, key_names: Sequence[str],
                        compare_dtypes: Sequence):
    """Encode one side's key columns at the promoted compare dtypes.

    Returns (planes int64[K, cap], live, matchable): `live` kills
    NULL-key rows (an equi-join never matches NULL — same as
    build_side/_probe_ranges); `matchable` additionally excludes rows
    with a NaN float key, because the hash table would make equal NaN
    bit patterns match while IEEE `==` (the sort engine) never does."""
    planes = []
    live = batch.live
    matchable = batch.live
    for k, dt in zip(key_names, compare_dtypes):
        c = batch.column(k)
        if c.validity is not None:
            live = live & c.validity
        v = c.values
        dt = jnp.dtype(dt)
        if v.dtype != dt:
            v = v.astype(dt)
        if jnp.issubdtype(dt, jnp.floating):
            matchable = matchable & jnp.logical_not(jnp.isnan(v))
        planes.append(pallas_hash.encode_plane(v, canonicalize_nan=False))
    return jnp.stack(planes), live, live & matchable


def hash_build_side(batch: Batch, key_names: Sequence[str],
                    probe_dtypes: Sequence) -> HashJoinTable:
    """Build-side insert on the Pallas linear-probing kernel. The table
    holds 2× the batch capacity (load ≤ 50%), so every live row claims a
    slot. `probe_dtypes` are the probe side's key dtypes (from the plan),
    fixing the pairwise-promoted encoding before any probe batch exists."""
    compare = join_compare_dtypes(batch, key_names, probe_dtypes)
    planes, live, ins_live = _encode_join_planes(batch, key_names, compare)
    h = hash_columns(list(planes))
    tcap = 2 * round_up_capacity(batch.capacity, minimum=64)
    slot_row = pallas_hash.join_insert(
        slot_hash(h, tcap), ins_live, tcap,
        interpret=pallas_hash.use_interpret())
    n = jnp.sum(live.astype(jnp.int64))
    return HashJoinTable(h, batch.with_live(live), n, batch.live,
                         slot_row, planes)


def _hash_probe(table: HashJoinTable, probe: Batch,
                probe_keys: Sequence[str], compare_dtypes: Sequence,
                fanout: int):
    planes, live, matchable = _encode_join_planes(
        probe, probe_keys, compare_dtypes)
    h = hash_columns(list(planes))
    slot0 = slot_hash(h, table.slot_row.shape[0])
    mm, cnt, ovf = pallas_hash.join_probe(
        slot0, planes, matchable, table.slot_row, table.planes, fanout,
        interpret=pallas_hash.use_interpret())
    return mm, cnt, ovf, live


def hash_probe_unique(table: HashJoinTable, probe: Batch,
                      probe_keys: Sequence[str], compare_dtypes: Sequence):
    """Unique-build fast path: first (only) match per probe row.
    Same contract as probe_unique: (build_idx int32[cap], matched)."""
    mm, cnt, _ovf, _live = _hash_probe(
        table, probe, probe_keys, compare_dtypes, 1)
    idx = jnp.clip(mm[:, 0], 0, table.batch.capacity - 1).astype(jnp.int32)
    return idx, cnt > 0


def hash_probe_counts(table: HashJoinTable, probe: Batch,
                      probe_keys: Sequence[str], compare_dtypes: Sequence,
                      max_fanout_scan: int = 8):
    """General path, pass 1. Counts are EXACT (the kernel keeps counting
    past the match-matrix width), so offsets/total never inflate;
    overflow = #rows with more matches than the matrix holds — the
    driver re-runs ONLY this probe with the fanout doubled.

    Returns (mm int32[n, F], counts int64, offsets, total, live,
    overflow)."""
    mm, cnt, ovf, live = _hash_probe(
        table, probe, probe_keys, compare_dtypes, max_fanout_scan)
    counts = cnt.astype(jnp.int64)
    offsets = jnp.cumsum(counts) - counts
    total = jnp.sum(counts)
    return mm, counts, offsets, total, live, ovf.astype(jnp.int64)


def hash_probe_expand(table: HashJoinTable, mm: jnp.ndarray,
                      counts: jnp.ndarray, offsets: jnp.ndarray,
                      chunk_base, out_capacity: int):
    """General path, pass 2 — pure XLA (no kernel): slot i maps back to
    (probe_row, ordinal) by one searchsorted over the inclusive ends and
    the build row is mm[probe_row, ordinal]. Precondition: counts <= F
    everywhere (the driver widened the probe on overflow), so no key
    re-verification is needed — the kernel matched exact planes.

    Returns (probe_idx, build_idx, out_live), like probe_expand."""
    ends = offsets + counts
    i = jnp.arange(out_capacity, dtype=jnp.int64) + chunk_base
    pcap = counts.shape[0]
    probe_row = jnp.searchsorted(ends, i, side="right").astype(jnp.int32)
    probe_row = jnp.clip(probe_row, 0, pcap - 1)
    ordinal = i - offsets[probe_row]
    in_range = (i < ends[-1]) & (ordinal >= 0) & (ordinal < counts[probe_row])
    fanout = mm.shape[1]
    oc = jnp.clip(ordinal, 0, fanout - 1).astype(jnp.int32)
    build_idx = mm[probe_row, oc]
    out_live = in_range & (build_idx >= 0)
    build_idx = jnp.clip(build_idx, 0, table.batch.capacity - 1)
    return probe_row, build_idx, out_live


# ---------------------------------------------------------------------------
# N-ary multiway probe (plan/nodes.MultiwayJoin): N resident build tables,
# one probe batch walked through all N probes in a single traced pass —
# no intermediate batch materialization between legs (PAPERS.md
# 1905.13376). Output row = probe row × one (match | left-null) per leg,
# decomposed mixed-radix over the per-leg match counts.


class MwSpec(NamedTuple):
    """Static description of one leg of a multiway probe. Drivers close
    over it (it is NOT a traced value), so every field must be hashable.
    `sources[k]` locates probe-side key k: -1 = the probe batch itself,
    j >= 0 = the payload of earlier UNIQUE build j, gathered at that
    leg's matched row (snowflake chains). Non-unique legs probe through
    the pallas kernel (`hash_engine`, exact counts) or the sorted engine
    (counts may widen — inner kinds only; expand re-verifies keys)."""

    probe_keys: tuple
    build_keys: tuple
    sources: tuple
    kind: str                # inner | left
    unique: bool             # single-match sorted-engine probe
    hash_engine: bool        # fanout leg probes through the pallas kernel
    compare_dtypes: tuple    # hash-engine encode dtypes (else ())


def _mw_key_batch(probe: Batch, tables, spec: "MwSpec", idxs, matcheds):
    """Key batch for one leg: key columns assembled from the probe batch
    and/or earlier unique legs' payloads, with rows unmatched in the
    source leg invalidated — a NULL key never equi-matches, which is
    exactly the binary chain's semantics for that row."""
    names, types, cols, dicts = [], [], [], {}
    for sym, src in zip(spec.probe_keys, spec.sources):
        if src < 0:
            c = probe.column(sym)
            t = probe.type_of(sym)
            d = probe.dicts.get(sym)
        else:
            tb = tables[src].batch
            c = tb.column(sym).gather(idxs[src])
            v = matcheds[src] if c.validity is None else \
                (c.validity & matcheds[src])
            c = Column(c.values, v, c.hi, c.sizes, c.evalid, c.keys)
            t = tb.type_of(sym)
            d = tb.dicts.get(sym)
        names.append(sym)
        types.append(t)
        cols.append(c)
        if d is not None:
            dicts[sym] = d
    return Batch(names, types, cols, probe.live, dicts)


def _mw_unique_state(specs, state):
    """(idxs, matcheds) maps for the unique legs — key sources for later
    snowflake legs."""
    idxs, matcheds = {}, {}
    for i, spec in enumerate(specs):
        if spec.unique:
            idxs[i], matcheds[i] = state[i]
    return idxs, matcheds


def multiway_counts(tables, probe: Batch, specs, fanouts):
    """Pass 1 of the N-ary probe: per-leg match state, per-leg effective
    counts (left legs floor at 1 — the null-extension row), the combined
    per-probe-row product T and its exclusive prefix sum. Counts are
    exact for unique and hash legs; sorted-engine fanout legs may widen
    (probe_counts contract) — expand re-verifies keys, so only capacity
    inflates. ``ovfs[i]`` > 0 means hash leg i truncated its match
    matrix: the driver doubles that leg's fanout and re-runs (the
    widening-replay ladder).

    Returns (state, chats, offsets, T, total, ovfs)."""
    state, chats, ovfs = [], [], []
    idxs, matcheds = {}, {}
    for i, spec in enumerate(specs):
        kb = _mw_key_batch(probe, tables, spec, idxs, matcheds)
        kb = align_probe_strings(kb, spec.probe_keys, tables[i],
                                 spec.build_keys)
        if spec.unique:
            idx, matched = probe_unique(tables[i], kb, spec.probe_keys,
                                        spec.build_keys)
            idxs[i], matcheds[i] = idx, matched
            c = matched.astype(jnp.int64)
            state.append((idx, matched))
            ovfs.append(jnp.zeros((), jnp.int64))
        elif spec.hash_engine:
            mm, c, _off, _tot, _live, ovf = hash_probe_counts(
                tables[i], kb, spec.probe_keys, spec.compare_dtypes,
                fanouts[i])
            state.append((mm, c))
            ovfs.append(ovf)
        else:
            lo, c, _off, _tot, _live, ovf = probe_counts(
                tables[i], kb, spec.probe_keys, spec.build_keys,
                fanouts[i])
            state.append((lo, c))
            ovfs.append(jnp.zeros((), jnp.int64))
        chats.append(jnp.maximum(c, 1) if spec.kind == "left" else c)
    T = probe.live.astype(jnp.int64)
    for chat in chats:
        T = T * chat
    offsets = jnp.cumsum(T) - T
    total = jnp.sum(T)
    return (tuple(state), tuple(chats), offsets, T, total,
            jnp.stack(ovfs))


def multiway_expand(tables, probe: Batch, specs, state, chats, offsets,
                    T, chunk_base, out_capacity: int, probe_cols,
                    build_cols):
    """Pass 2: materialize output slots [chunk_base, chunk_base +
    out_capacity). One searchsorted over the inclusive ends of T maps a
    slot to its probe row; the residual ordinal decomposes mixed-radix
    across legs (last leg fastest). Left legs emit their null-extension
    at digit 0 when unmatched. ``build_cols[i]`` are leg i's payload
    symbols; probe columns gather at probe_row."""
    N = len(specs)
    ends = offsets + T
    i = jnp.arange(out_capacity, dtype=jnp.int64) + chunk_base
    pcap = T.shape[0]
    probe_row = jnp.searchsorted(ends, i, side="right").astype(jnp.int32)
    probe_row = jnp.clip(probe_row, 0, pcap - 1)
    r = i - offsets[probe_row]
    in_range = (i < ends[-1]) & (r >= 0) & (r < T[probe_row])
    digits = [None] * N
    for t in range(N - 1, -1, -1):
        c = jnp.maximum(chats[t][probe_row], 1)
        digits[t] = r % c
        r = r // c
    idxs, matcheds = _mw_unique_state(specs, state)
    out_live = in_range
    bidx, bvalid = [], []
    for t, spec in enumerate(specs):
        d = digits[t]
        if spec.unique:
            idx, matched = state[t]
            bi = idx[probe_row]
            ok = matched[probe_row]
        elif spec.hash_engine:
            mm, c = state[t]
            oc = jnp.clip(d, 0, mm.shape[1] - 1).astype(jnp.int32)
            bi = mm[probe_row, oc]
            ok = (d < c[probe_row]) & (bi >= 0)
            bi = jnp.clip(bi, 0,
                          tables[t].batch.capacity - 1).astype(jnp.int32)
        else:
            lo, c = state[t]
            bi = (lo[probe_row] + d).astype(jnp.int32)
            bi = jnp.clip(bi, 0, tables[t].hashes.shape[0] - 1)
            ok = d < c[probe_row]
            # re-verify real keys in the leg's aligned code space (covers
            # collisions and the widened counting fallback)
            kb = align_probe_strings(
                _mw_key_batch(probe, tables, spec, idxs, matcheds),
                spec.probe_keys, tables[t], spec.build_keys)
            for pk, bk in zip(spec.probe_keys, spec.build_keys):
                pv = kb.column(pk).values[probe_row]
                bv = tables[t].batch.column(bk).values[bi]
                if pv.dtype != bv.dtype:
                    pt = jnp.result_type(pv.dtype, bv.dtype)
                    pv, bv = pv.astype(pt), bv.astype(pt)
                ok = ok & (pv == bv)
        if spec.kind == "inner":
            out_live = out_live & ok
        bidx.append(bi)
        bvalid.append(ok)
    names, types, cols, dicts = [], [], [], {}
    for sym in probe_cols:
        names.append(sym)
        types.append(probe.type_of(sym))
        cols.append(probe.column(sym).gather(probe_row))
        if sym in probe.dicts:
            dicts[sym] = probe.dicts[sym]
    for t in range(N):
        tb = tables[t].batch
        for sym in build_cols[t]:
            names.append(sym)
            types.append(tb.type_of(sym))
            c = tb.column(sym).gather(bidx[t])
            v = bvalid[t] if c.validity is None else \
                (c.validity & bvalid[t])
            cols.append(Column(c.values, v, c.hi, c.sizes, c.evalid,
                               c.keys))
            if sym in tb.dicts:
                dicts[sym] = tb.dicts[sym]
    return Batch(names, types, cols, out_live, dicts)


def multiway_probe_unique(tables, probe: Batch, specs, probe_cols,
                          build_cols):
    """All-unique fast path — the dominant star-schema shape: every leg
    matches at most one build row, so the output is row-aligned with the
    probe batch. Probe columns pass through untouched, each leg costs
    one probe + one payload gather, and the whole N-way join is ONE
    compiled program with no expansion pass.

    Returns (out, n_probe, n_leg0): the probe's live row count and leg
    0's binary-equivalent output row count ride along for the HBO
    probe-selectivity observation (one extra reduction each, no extra
    program)."""
    out_live = probe.live
    idxs, matcheds = {}, {}
    for i, spec in enumerate(specs):
        kb = _mw_key_batch(probe, tables, spec, idxs, matcheds)
        kb = align_probe_strings(kb, spec.probe_keys, tables[i],
                                 spec.build_keys)
        idx, matched = probe_unique(tables[i], kb, spec.probe_keys,
                                    spec.build_keys)
        idxs[i], matcheds[i] = idx, matched
        if spec.kind == "inner":
            out_live = out_live & matched
    n_probe = jnp.sum(probe.live).astype(jnp.int64)
    if specs[0].kind == "inner":
        n_leg0 = jnp.sum(probe.live & matcheds[0]).astype(jnp.int64)
    else:
        n_leg0 = n_probe
    names, types, cols, dicts = [], [], [], {}
    for sym in probe_cols:
        names.append(sym)
        types.append(probe.type_of(sym))
        cols.append(probe.column(sym))
        if sym in probe.dicts:
            dicts[sym] = probe.dicts[sym]
    for t in range(len(specs)):
        tb = tables[t].batch
        for sym in build_cols[t]:
            names.append(sym)
            types.append(tb.type_of(sym))
            c = tb.column(sym).gather(idxs[t])
            v = matcheds[t] if c.validity is None else \
                (c.validity & matcheds[t])
            cols.append(Column(c.values, v, c.hi, c.sizes, c.evalid,
                               c.keys))
            if sym in tb.dicts:
                dicts[sym] = tb.dicts[sym]
    return Batch(names, types, cols, out_live, dicts), n_probe, n_leg0


def gather_join_output(
    probe: Batch,
    table: BuildTable,
    probe_row: jnp.ndarray,
    build_idx: jnp.ndarray,
    out_live: jnp.ndarray,
    probe_cols: Sequence[str],
    build_cols: Sequence[str],
    build_prefix: str = "",
) -> Batch:
    """Materialize an inner-join output batch from index vectors."""
    names, types, cols = [], [], []
    dicts = {}
    for c in probe_cols:
        names.append(c)
        types.append(probe.type_of(c))
        # Column.gather preserves validity AND the long-decimal hi limb
        cols.append(probe.column(c).gather(probe_row))
        if c in probe.dicts:
            dicts[c] = probe.dicts[c]
    for c in build_cols:
        out_name = build_prefix + c
        names.append(out_name)
        types.append(table.batch.type_of(c))
        cols.append(table.batch.column(c).gather(build_idx))
        if c in table.batch.dicts:
            dicts[out_name] = table.batch.dicts[c]
    return Batch(names, types, cols, out_live, dicts)


def table_rows(table) -> int:
    """Host-synced live row count of a built join table (BuildTable or
    HashJoinTable — both carry ``n_rows`` as a device scalar). One sync;
    the HBO observation path calls it after the build phase has already
    materialized the table, so the transfer is of a ready scalar."""
    return int(table.n_rows)  # lint: allow(host-sync)
