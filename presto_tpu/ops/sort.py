"""ORDER BY / compaction kernels.

Reference: operator/OrderByOperator.java + PagesIndex.java:75 with codegen'd
OrderingCompiler comparators; TopNOperator.java:35.

TPU-native: `lax.sort` (XLA's sort, efficient on TPU) over monotone-encoded
sort keys with a permutation payload, then gather every column through the
permutation. Descending order uses bitwise/arithmetic negation of the
encoding rather than a custom comparator. Compaction (live rows to the
front, original order preserved) is a stable sort on the dead bit — the
batch-world analog of copying selected positions into a new Page.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch, Column


class SortKey(NamedTuple):
    values: jnp.ndarray
    validity: Optional[jnp.ndarray]
    descending: bool = False
    nulls_first: bool = False


def _encode_key(k: SortKey):
    """Monotone int/float encoding such that ascending lax.sort yields the
    requested order. Returns (null_rank, value_key)."""
    v = k.values
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int32)
    if k.descending:
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = -v
        else:
            v = ~v  # two's complement bitwise-not: strictly order-reversing
    if k.validity is None:
        null_rank = None
    else:
        # nulls first → null rank 0; nulls last → null rank 1
        null_rank = jnp.where(k.validity, 1, 0) if k.nulls_first else jnp.where(k.validity, 0, 1)
        null_rank = null_rank.astype(jnp.int32)
        v = jnp.where(k.validity, v, jnp.zeros_like(v))
    return null_rank, v


def sort_permutation(keys: Sequence[SortKey], live: jnp.ndarray) -> jnp.ndarray:
    """Stable permutation ordering live rows by keys, dead rows last."""
    n = live.shape[0]
    operands = [(~live).astype(jnp.int32)]
    for k in keys:
        null_rank, v = _encode_key(k)
        if null_rank is not None:
            operands.append(null_rank)
        operands.append(v)
    perm = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort(operands + [perm], num_keys=len(operands), is_stable=True)
    return out[-1]


def permute_batch(b: Batch, perm: jnp.ndarray) -> Batch:
    return Batch(b.names, b.types, [c.gather(perm) for c in b.columns],
                 b.live[perm], b.dicts)


def sort_batch(b: Batch, keys: Sequence[SortKey], limit: Optional[int] = None) -> Batch:
    perm = sort_permutation(keys, b.live)
    out = permute_batch(b, perm)
    if limit is not None:
        keep = jnp.arange(out.capacity) < limit
        out = out.with_live(out.live & keep)
    return out


def compact(b: Batch) -> Batch:
    """Move live rows to the front (stable). Dead lanes become trailing."""
    n = b.capacity
    perm_in = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort(
        [(~b.live).astype(jnp.int32), perm_in], num_keys=1, is_stable=True
    )
    return permute_batch(b, out[-1])


def limit_batch(b: Batch, n: int) -> Batch:
    """LIMIT without ordering: keep the first n live rows."""
    rank = jnp.cumsum(b.live.astype(jnp.int64)) - 1
    return b.with_live(b.live & (rank < n))
