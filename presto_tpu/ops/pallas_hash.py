"""Pallas linear-probing hash tables — the second breaker engine.

Reference hot loops: operator/MultiChannelGroupByHash.java:228 (group-by
open addressing over flat long[]) and PagesHash.java:34 (join build/probe
with PositionLinks chains). The sort engine (ops/grouping.py, ops/join.py)
replaced both with argsort + searchsorted; this module puts the hash table
back as a *selectable* engine, because neither wins everywhere — group
count, skew, and payload width set the crossover ("Global Hash Tables
Strike Back", arXiv 2505.04153; the hash-vs-sort group-by study,
arXiv 2411.13245). plan/stats.choose_breaker_engine makes the call.

Design:

- Keys are pre-encoded into int64 *planes* (`encode_plane`): plane
  equality ⇔ SQL group/join-key equality. Floats are bit-cast with
  -0.0 → +0.0 canonicalized; GROUP BY additionally canonicalizes NaN so
  all NaNs form ONE group (Presto semantics; the sort engine's `!=`
  boundary detection gives each NaN row its own group — a documented
  deviation, irrelevant to equi-joins where NaN keys are excluded from
  matching on both sides, mirroring the sort engine's IEEE `==`).
- The physical table is 2× the logical capacity (load factor ≤ 50%), so
  probe chains stay short even when the logical table is full and the
  overflow signal stays *exact*: inserts stop at `cap` distinct keys, so
  overflow > 0 ⇔ the input holds more than `cap` distinct keys — the
  same n_groups > cap contract the sort engine's drivers already replay
  on (capacity-growth replay, ops/grouping.grouped_merge docstring).
- Kernels are serial per-row loops (grid=(1,)) — the table lives in one
  ref and rows chain through `lax.while_loop` probes. On CPU they run
  under the Pallas interpreter (`use_interpret()`), so tier-1 and the
  verifier sweeps execute the same kernel logic bit-for-bit.
- Join probe returns a bounded-fanout match matrix mm[n, F] plus EXACT
  per-row match counts; rows with more than F matches set the overflow
  counter and the driver re-probes with F doubled (counts, offsets and
  totals are already exact, so only the probe kernel reruns).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def use_interpret() -> bool:
    """Interpret kernels off-TPU: tier-1/CI and the verifier sweeps then
    exercise the hash engine on CPU with the exact kernel semantics."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# key-plane encoding

_NAN64_BITS = 0x7FF8000000000000  # canonical quiet-NaN bit patterns
_NAN32_BITS = 0x7FC00000


def encode_plane(values: jnp.ndarray,
                 target_dtype=None,
                 canonicalize_nan: bool = True) -> jnp.ndarray:
    """One key column → an int64 plane where plane equality matches SQL
    equality under `target_dtype` (the pairwise-promoted compare dtype for
    joins; the column's own dtype for GROUP BY).

    Floats bit-cast (f32 via its int32 pattern — reversible); -0.0 is
    canonicalized to +0.0 first so `-0.0 = 0.0` holds like the sort
    engine's `==`. With canonicalize_nan all NaNs share one plane value
    (GROUP BY); join callers exclude NaN-key rows instead."""
    v = values
    if target_dtype is not None and v.dtype != jnp.dtype(target_dtype):
        v = v.astype(target_dtype)
    if v.dtype == jnp.bool_:
        return v.astype(jnp.int64)
    if jnp.issubdtype(v.dtype, jnp.floating):
        if v.dtype != jnp.float32:
            v = v.astype(jnp.float64)
        v = v + jnp.zeros((), v.dtype)  # -0.0 + 0.0 == +0.0
        if v.dtype == jnp.float32:
            bits = jax.lax.bitcast_convert_type(v, jnp.int32).astype(jnp.int64)
            nan = jnp.int64(_NAN32_BITS)
        else:
            bits = jax.lax.bitcast_convert_type(v, jnp.int64)
            nan = jnp.int64(_NAN64_BITS)
        if canonicalize_nan:
            bits = jnp.where(jnp.isnan(v), nan, bits)
        return bits
    return v.astype(jnp.int64)


def decode_plane(plane: jnp.ndarray, dtype) -> jnp.ndarray:
    """Reverse `encode_plane` for GROUP BY key materialization."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.bool_:
        return plane != 0
    if jnp.issubdtype(dtype, jnp.floating):
        if dtype == jnp.dtype(jnp.float32):
            return jax.lax.bitcast_convert_type(
                plane.astype(jnp.int32), jnp.float32)
        return jax.lax.bitcast_convert_type(
            plane, jnp.float64).astype(dtype)
    return plane.astype(dtype)


def encode_group_keys(
    cols: Sequence[Tuple[jnp.ndarray, Optional[jnp.ndarray]]],
) -> Tuple[jnp.ndarray, bool]:
    """GROUP BY keys → stacked planes [K', n]. Nullable keys zero their
    plane on NULL and set a bit in a shared trailing nullbits plane, so
    (NULL group) ≠ (value-0 group) and NULLs form one group per key —
    exactly the sort engine's (nullbit, zeroed value) operand pair.

    Returns (planes, has_null_plane)."""
    planes = []
    nullbits = None
    for j, (v, valid) in enumerate(cols):
        p = encode_plane(v)
        if valid is not None:
            p = jnp.where(valid, p, jnp.int64(0))
            nb = jnp.where(valid, jnp.int64(0), jnp.int64(1) << jnp.int64(j))
            nullbits = nb if nullbits is None else nullbits | nb
        planes.append(p)
    if nullbits is not None:
        planes.append(nullbits)
    return jnp.stack(planes), nullbits is not None


# ---------------------------------------------------------------------------
# group-by insert kernel


def _group_insert_kernel(slot0_ref, keys_ref, live_ref,
                         gid_ref, table_ref, occ_ref, stat_ref,
                         *, tcap: int, fill_max: int):
    """Serial linear-probing insert: one pass over the rows, table state
    in refs. Probe walks (slot0 + j) & (tcap - 1) until it sees the key
    (match) or an empty slot (claim, while under fill_max distinct)."""
    n = slot0_ref.shape[0]
    occ_ref[...] = jnp.zeros_like(occ_ref)
    table_ref[...] = jnp.zeros_like(table_ref)
    gid_ref[...] = jnp.full_like(gid_ref, tcap)
    mask = tcap - 1

    def row(i, carry):
        ngroups, ovf = carry
        lv = live_ref[i]
        s0 = slot0_ref[i]
        ki = keys_ref[:, i]

        # kind: 0 = searching, 1 = key found at slot, 2 = empty at slot
        def cond(st):
            j, kind, _slot = st
            return (kind == 0) & (j < tcap)

        def body(st):
            j, _kind, _slot = st
            s = (s0 + j) & mask
            o = occ_ref[s]
            stored = table_ref[:, s]
            is_empty = o == 0
            is_match = jnp.logical_not(is_empty) & jnp.all(stored == ki)
            kind = jnp.where(is_match, 1, jnp.where(is_empty, 2, 0))
            return j + 1, kind, s

        init_kind = jnp.where(lv, 0, 1)  # dead rows skip the probe
        _, kind, slot = jax.lax.while_loop(
            cond, body, (jnp.int32(0), init_kind, jnp.int32(0)))

        do_insert = lv & (kind == 2) & (ngroups < fill_max)
        cur = table_ref[:, slot]
        table_ref[:, slot] = jnp.where(do_insert, ki, cur)
        occ_ref[slot] = jnp.where(do_insert, 1, occ_ref[slot])
        placed = lv & ((kind == 1) | do_insert)
        gid_ref[i] = jnp.where(placed, slot, tcap)
        ovf_inc = (lv & jnp.logical_not(placed)).astype(jnp.int32)
        return ngroups + do_insert.astype(jnp.int32), ovf + ovf_inc

    ngroups, ovf = jax.lax.fori_loop(
        0, n, row, (jnp.int32(0), jnp.int32(0)))
    stat_ref[0] = ngroups
    stat_ref[1] = ovf


def group_insert(planes: jnp.ndarray, slot0: jnp.ndarray,
                 live: jnp.ndarray, cap: int,
                 interpret: bool = False):
    """Assign linear-probing group ids for GROUP BY.

    planes: int64[K, n] encoded key planes; slot0: int32[n] initial probe
    slot in [0, 2*cap) (low bits of the key hash — see radix.slot_hash for
    the top-bits/low-bits disjointness contract under radix); cap: the
    driver's logical pow2 group budget. The physical table is tcap=2*cap.

    Returns (gid int32[n], table int64[K, tcap], occ int32[tcap],
    n_groups int32, overflow int32). gid == tcap marks dead or unplaced
    rows. Inserts stop at cap distinct keys, so overflow > 0 ⇔ more than
    cap distinct keys — the driver's regrow-replay trigger; unplaced rows
    each count once, so cap + overflow upper-bounds the true distinct
    count (callers clamp before feeding round_up_capacity)."""
    if cap <= 0 or cap & (cap - 1):
        raise ValueError(f"cap must be a positive power of two, got {cap}")
    K, n = planes.shape
    tcap = 2 * cap
    gid, table, occ, stat = pl.pallas_call(
        functools.partial(_group_insert_kernel, tcap=tcap, fill_max=cap),
        out_shape=(
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((K, tcap), jnp.int64),
            jax.ShapeDtypeStruct((tcap,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        ),
        interpret=interpret,
    )(slot0.astype(jnp.int32), planes, live)
    return gid, table, occ, stat[0], stat[1]


# ---------------------------------------------------------------------------
# join build insert kernel


def _join_insert_kernel(slot0_ref, live_ref, slot_row_ref, *, tcap: int):
    """Claim one slot per live build row (duplicate keys occupy separate
    slots along the probe chain; the probe kernel walks to the first empty
    slot, collecting every row whose key verifies)."""
    n = slot0_ref.shape[0]
    slot_row_ref[...] = jnp.full_like(slot_row_ref, -1)
    mask = tcap - 1

    def row(i, _):
        lv = live_ref[i]
        s0 = slot0_ref[i]

        def cond(st):
            j, done, _slot = st
            return jnp.logical_not(done) & (j < tcap)

        def body(st):
            j, _done, _slot = st
            s = (s0 + j) & mask
            done = slot_row_ref[s] < 0
            return j + 1, done, s

        init_done = jnp.logical_not(lv)
        _, done, slot = jax.lax.while_loop(
            cond, body, (jnp.int32(0), init_done, jnp.int32(0)))
        claim = lv & done
        cur = slot_row_ref[slot]
        slot_row_ref[slot] = jnp.where(claim, i, cur)
        return 0

    jax.lax.fori_loop(0, n, row, 0)


def join_insert(slot0: jnp.ndarray, live: jnp.ndarray, tcap: int,
                interpret: bool = False) -> jnp.ndarray:
    """Build-side insert: → slot_row int32[tcap], the build ROW index
    occupying each slot (-1 = empty). tcap must be a pow2 ≥ 2× the live
    row count so the load factor stays ≤ 50% and every row finds a slot."""
    if tcap <= 0 or tcap & (tcap - 1):
        raise ValueError(f"tcap must be a positive power of two, got {tcap}")
    return pl.pallas_call(
        functools.partial(_join_insert_kernel, tcap=tcap),
        out_shape=jax.ShapeDtypeStruct((tcap,), jnp.int32),
        interpret=interpret,
    )(slot0.astype(jnp.int32), live)


# ---------------------------------------------------------------------------
# join probe kernel


def _join_probe_kernel(slot0_ref, pkeys_ref, plive_ref, slot_row_ref,
                       bkeys_ref, mm_ref, cnt_ref, stat_ref,
                       *, tcap: int, fanout: int):
    """Walk each probe row's chain to the first empty slot, verifying the
    stored row's key planes. The first `fanout` matching build rows land
    in mm[i, :]; the count keeps going past fanout so counts/offsets stay
    exact and stat[0] reports rows needing a wider matrix."""
    n = slot0_ref.shape[0]
    mask = tcap - 1

    def row(i, ovf):
        lv = plive_ref[i]
        s0 = slot0_ref[i]
        ki = pkeys_ref[:, i]

        def cond(st):
            j, cont, _cnt, _mm = st
            return cont & (j < tcap)

        def body(st):
            j, _cont, cnt, mmrow = st
            s = (s0 + j) & mask
            r = slot_row_ref[s]
            occupied = r >= 0
            rc = jnp.maximum(r, 0)
            stored = bkeys_ref[:, rc]
            m = occupied & jnp.all(stored == ki)
            rec = m & (cnt < fanout)
            pos = jnp.minimum(cnt, fanout - 1)
            mmrow = mmrow.at[pos].set(jnp.where(rec, r, mmrow[pos]))
            return j + 1, occupied, cnt + m.astype(jnp.int32), mmrow

        init = (jnp.int32(0), lv, jnp.int32(0),
                jnp.full((fanout,), -1, jnp.int32))
        _, _, cnt, mmrow = jax.lax.while_loop(cond, body, init)
        mm_ref[i, :] = mmrow
        cnt_ref[i] = cnt
        return ovf + (cnt > fanout).astype(jnp.int32)

    ovf = jax.lax.fori_loop(0, n, row, jnp.int32(0))
    stat_ref[0] = ovf


def join_probe(slot0: jnp.ndarray, pkeys: jnp.ndarray, plive: jnp.ndarray,
               slot_row: jnp.ndarray, bkeys: jnp.ndarray, fanout: int,
               interpret: bool = False):
    """Probe-side lookup.

    slot0: int32[n] initial probe slots; pkeys: int64[K, n] probe planes;
    bkeys: int64[K, cap_b] build planes indexed by build ROW; slot_row:
    int32[tcap] from join_insert. Returns (mm int32[n, fanout] build rows
    of the first `fanout` matches (-1 padded), counts int32[n] EXACT match
    counts, overflow int32 scalar = rows with counts > fanout)."""
    if fanout <= 0 or fanout & (fanout - 1):
        raise ValueError(
            f"fanout must be a positive power of two, got {fanout}")
    n = slot0.shape[0]
    tcap = slot_row.shape[0]
    mm, cnt, stat = pl.pallas_call(
        functools.partial(_join_probe_kernel, tcap=tcap, fanout=fanout),
        out_shape=(
            jax.ShapeDtypeStruct((n, fanout), jnp.int32),
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
        interpret=interpret,
    )(slot0.astype(jnp.int32), pkeys, plive, slot_row, bkeys)
    return mm, cnt, stat[0]
