"""64-bit vectorized hashing for partitioning and join keys.

Reference: the engine-internal XXHash64-based CombineHashFunction /
InterpretedHashGenerator used by HashGenerationOptimizer and
PartitionedOutputOperator. We use splitmix64 finalization — cheap integer
mixing that vectorizes on the VPU (int64 is emulated as int32 pairs on TPU
but this is far from the bottleneck).
"""

from __future__ import annotations

import jax.numpy as jnp


_M1 = jnp.uint64(0xBF58476D1CE4E5B9)
_M2 = jnp.uint64(0x94D049BB133111EB)
_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)


def splitmix64(x):
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * _M1
    x = (x ^ (x >> 27)) * _M2
    return x ^ (x >> 31)


def hash_columns(cols, valids=None) -> jnp.ndarray:
    """Combined 64-bit hash of one or more key columns (int-ish values).

    NULLs hash as a distinct fixed value so NULL keys co-partition.
    Returns int64 (non-negative after masking the sign bit, so callers can
    take `% num_partitions` safely).
    """
    h = jnp.uint64(0)
    for i, v in enumerate(cols):
        x = v.astype(jnp.int64).astype(jnp.uint64)
        if valids is not None and valids[i] is not None:
            x = jnp.where(valids[i], x, jnp.uint64(0x9E3779B97F4A7C15))
        hv = splitmix64(x + _GOLDEN * jnp.uint64(i + 1))
        h = splitmix64(h ^ hv)
    out = h & jnp.uint64(0x7FFFFFFFFFFFFFFF)
    return out.astype(jnp.int64)
