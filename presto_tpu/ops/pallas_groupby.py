"""Pallas TPU kernel: fused small-domain grouped aggregation on the MXU.

Reference hot loop: MultiChannelGroupByHash.java:228 + the per-function
accumulators of InMemoryHashAggregationBuilder — a row-at-a-time
open-addressing hash table. The engine's portable path
(ops/grouping._direct_grouped_merge) replaces that with a [G, n]
masked-broadcast reduction per state on the VPU: O(G·n·S) elementwise work
and one pass over the batch per state.

This kernel instead feeds the MXU: per 256-row block, build a one-hot
[B, G] group-membership matrix once and compute ALL state partials as one
[G, B] × [B, S'] matmul — the systolic array does the segmented reduction.
One pass over the input, S-independent membership cost, 128×128 MAC
throughput.

Exactness (the engine's aggregates are money sums — lossy f32 MACs are
not acceptable):
- int64 states (decimal unscaled values, counts) split into four 16-bit
  limbs of the two's-complement bits. A limb is < 2¹⁶ and a 256-row block
  keeps each per-block limb partial < 2²⁴ — exactly representable in f32,
  so the MXU matmul is exact. Each block writes its OWN output slot (no
  cross-block f32 accumulation); the final reduction runs outside the
  kernel in int64, and Σ limbsum_k · 2¹⁶ᵏ in wrapping int64 arithmetic
  equals the true int64 sum for ANY inputs (mod-2⁶⁴ congruence).
- float64 states stay OFF this kernel: the MXU's f32 MACs round each
  accumulation step (~1e-6 relative after 256 addends — measured), and
  no splitting trick fixes rounding inside the systolic array. Float
  sums keep the portable f64 VPU path; the kernel covers the integer
  states (decimal money sums, counts, validity counts) where exactness
  is achievable AND required.

The kernel runs when PRESTO_TPU_PALLAS=1 on a TPU backend (the portable
XLA path stays the default); unit tests validate it bit-for-bit against
numpy in interpreter mode on CPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_ROWS = 256  # keeps 16-bit limb block-partials exact in f32 (< 2^24)
_LIMB = 4         # 4 × 16-bit limbs cover int64


def enabled() -> bool:
    return (os.environ.get("PRESTO_TPU_PALLAS", "0") == "1"
            and jax.default_backend() == "tpu")


def _kernel(gid_ref, vals_ref, out_ref, *, n_groups: int):
    """One grid step = one row block → one [G, S] output slot.

    gid_ref:  [B] int32 group ids (>= n_groups → masked/dead row)
    vals_ref: [B, S] f32 state contributions (limbs already split)
    out_ref:  [1, G, S] this block's partials
    """
    gid = gid_ref[...]
    onehot = (gid[:, None]
              == jax.lax.broadcasted_iota(jnp.int32, (1, n_groups), 1)
              ).astype(jnp.float32)                       # [B, G]
    vals = vals_ref[...]                                  # [B, S]
    out_ref[0, :, :] = jax.lax.dot_general(
        onehot, vals, (((0,), (0,)), ((), ())),            # [G, S]
        preferred_element_type=jnp.float32,
    )


def _blocked_call(gid: jnp.ndarray, vals: jnp.ndarray, n_groups: int,
                  interpret: bool) -> jnp.ndarray:
    """→ [nb, G, S] per-block partials (reduced by the caller)."""
    n, s = vals.shape
    nb = -(-n // BLOCK_ROWS)
    pad = nb * BLOCK_ROWS - n
    if pad:
        gid = jnp.pad(gid, (0, pad), constant_values=n_groups)
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    return pl.pallas_call(
        functools.partial(_kernel, n_groups=n_groups),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS,), lambda i: (i,)),
            pl.BlockSpec((BLOCK_ROWS, s), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_groups, s), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, n_groups, s), jnp.float32),
        interpret=interpret,
    )(gid, vals)


def grouped_sums(gid, int_states, n_groups: int,
                 interpret: bool = False):
    """Fused multi-state EXACT grouped int64 sums.

    gid:        int32[n]; values >= n_groups are ignored (dead rows)
    int_states: list of int64[n] (masked to 0 on dead rows by caller)
    Returns a list of int64[G], exact for any inputs.
    """
    planes = []
    for v in int_states:
        u = v.astype(jnp.uint64)
        for k in range(_LIMB):
            planes.append(((u >> jnp.uint64(16 * k))
                           & jnp.uint64(0xFFFF)).astype(jnp.float32))
    if not planes:
        return []
    vals = jnp.stack(planes, axis=1)  # [n, S']
    out = _blocked_call(gid.astype(jnp.int32), vals, n_groups, interpret)

    int_out = []
    col = 0
    for _ in int_states:
        total = jnp.zeros(n_groups, jnp.int64)
        for k in range(_LIMB):
            # per-block limb partials are exact integers in f32; sum across
            # blocks in int64, then the shifted wrapping-int64 combine is
            # congruent mod 2^64 to the true sum — i.e. the exact int64 sum
            limb_sum = jnp.sum(
                jnp.round(out[:, :, col + k]).astype(jnp.int64), axis=0)
            total = total + (limb_sum << jnp.int64(16 * k))
        int_out.append(total)
        col += _LIMB
    return int_out
