"""Relational kernels on fixed-capacity batches.

The analog of presto-main's hot operator internals (MultiChannelGroupByHash,
PagesHash/JoinHash, PagesIndex sort, PartitionedOutputOperator.partitionPage),
re-expressed as static-shape XLA programs: sorting + segment ops instead of
pointer-chasing hash tables, searchsorted probes instead of bucket chains,
masks instead of selection vectors.
"""

from presto_tpu.ops.hashing import hash_columns
from presto_tpu.ops.grouping import grouped_merge
from presto_tpu.ops.sort import sort_batch, compact
from presto_tpu.ops.join import build_side, probe_unique, probe_counts, probe_expand
from presto_tpu.ops.partition import partition_for_exchange

__all__ = [
    "hash_columns",
    "grouped_merge",
    "sort_batch",
    "compact",
    "build_side",
    "probe_unique",
    "probe_counts",
    "probe_expand",
    "partition_for_exchange",
]
