"""Window function kernels.

Reference: operator/WindowOperator.java:47 + operator/window/* (21 files:
RowNumberFunction, RankFunction, DenseRankFunction, NtileFunction,
LagFunction, LeadFunction, FirstValueFunction, LastValueFunction,
PercentRankFunction, CumeDistFunction, aggregate window frames).

TPU-native redesign: the reference walks each partition row-by-row with
per-function accumulators over a PagesIndex. Here the whole input is sorted
once by (partition keys, order keys) via lax.sort, then every window value
is a closed-form vectorized computation over the sorted array:

- partition/peer boundaries  → adjacent-row key-change masks
- segment start index        → cummax of boundary-marked iota
- segment id / sizes         → cumsum of boundaries + one scatter-add
- running (frame) aggregates → cumsum minus its value at segment start
- RANGE CURRENT ROW frames   → gather the running value at the last peer row
- lag/lead                   → shifted gathers with same-partition masking

No sequential per-partition loops anywhere — one O(n log n) sort plus O(n)
vector ops, all on the VPU.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch, Column


class WindowKeys(NamedTuple):
    """Sorted-order boundary structure shared by every function over one
    (partition_by, order_by) spec."""

    is_start: jnp.ndarray      # partition boundary at i
    seg_start: jnp.ndarray     # index of partition start, per row
    seg_id: jnp.ndarray        # partition ordinal, per row
    seg_size: jnp.ndarray      # partition row count, per row
    peer_start: jnp.ndarray    # index of first peer (same order keys), per row
    peer_last: jnp.ndarray     # index of last peer, per row
    row_number: jnp.ndarray    # 1-based position within partition
    live: jnp.ndarray
    n_live: jnp.ndarray


def _change_mask(cols, live):
    """True at i where any key column differs from row i-1 (or i == 0)."""
    n = live.shape[0]
    iota = jnp.arange(n)
    change = iota == 0
    for values, validity in cols:
        prev = jnp.roll(values, 1)
        diff = values != prev
        if jnp.issubdtype(values.dtype, jnp.floating):
            # SQL total order: NaN equals NaN for grouping/peers
            diff = diff & ~(jnp.isnan(values) & jnp.isnan(prev))
        if validity is not None:
            pv = jnp.roll(validity, 1)
            # null vs null is "same" for partitioning/peers (SQL grouping
            # semantics); null vs value differs
            diff = jnp.where(validity & pv, diff, validity != pv)
        change = change | diff
    return change


def window_keys(
    part_cols: Sequence[tuple], order_cols: Sequence[tuple], live: jnp.ndarray
) -> WindowKeys:
    """All boundary structure for one spec, over batch-sorted rows (live rows
    first — sort_permutation puts dead rows last)."""
    n = live.shape[0]
    iota = jnp.arange(n)
    is_start = _change_mask(part_cols, live)
    seg_start = jax.lax.cummax(jnp.where(is_start, iota, 0))
    seg_id = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    ones = live.astype(jnp.int64)
    sizes = jnp.zeros(n, dtype=jnp.int64).at[seg_id].add(ones, mode="drop")
    seg_size = sizes[seg_id]
    peer_change = is_start | _change_mask(order_cols, live) if order_cols else is_start
    if not order_cols:
        # no ORDER BY: every partition row is a peer of every other
        peer_start = seg_start
        peer_last = seg_start + jnp.maximum(seg_size - 1, 0)
    else:
        peer_start = jax.lax.cummax(jnp.where(peer_change, iota, 0))
        peer_id = jnp.cumsum(peer_change.astype(jnp.int32)) - 1
        last = jnp.zeros(n, dtype=jnp.int64).at[peer_id].max(
            jnp.where(live, iota, 0), mode="drop"
        )
        peer_last = last[peer_id]
    row_number = iota - seg_start + 1
    return WindowKeys(
        is_start, seg_start, seg_id, seg_size, peer_start,
        peer_last.astype(jnp.int32), row_number.astype(jnp.int64),
        live, jnp.sum(ones),
    )


# ---------------------------------------------------------------------------
# ranking functions


def row_number(k: WindowKeys):
    return k.row_number, None


def rank(k: WindowKeys):
    return (k.peer_start - k.seg_start + 1).astype(jnp.int64), None


def dense_rank(k: WindowKeys):
    n = k.live.shape[0]
    iota = jnp.arange(n)
    peer_change = iota == k.peer_start  # first row of each peer group
    cnt = jnp.cumsum(peer_change.astype(jnp.int64))
    return cnt - cnt[k.seg_start] + 1, None


def percent_rank(k: WindowKeys):
    r = (k.peer_start - k.seg_start + 1).astype(jnp.float64)
    denom = jnp.maximum(k.seg_size - 1, 1).astype(jnp.float64)
    out = jnp.where(k.seg_size > 1, (r - 1) / denom, 0.0)
    return out, None


def cume_dist(k: WindowKeys):
    covered = (k.peer_last - k.seg_start + 1).astype(jnp.float64)
    return covered / jnp.maximum(k.seg_size, 1).astype(jnp.float64), None


def ntile(k: WindowKeys, buckets: int):
    """SQL NTILE: first (size % n) buckets get one extra row."""
    size = k.seg_size
    n = jnp.asarray(buckets, dtype=jnp.int64)
    q = size // n
    r = size % n
    rn0 = k.row_number - 1
    big = r * (q + 1)  # rows covered by the larger buckets
    in_big = rn0 < big
    b = jnp.where(
        in_big,
        rn0 // jnp.maximum(q + 1, 1),
        r + (rn0 - big) // jnp.maximum(q, 1),
    )
    # more buckets than rows: bucket == row_number
    b = jnp.where(size < n, rn0, b)
    return b + 1, None


# ---------------------------------------------------------------------------
# value functions


def _shift_gather(values, validity, idx, ok, live, default=None):
    """Gather values[idx] where `ok`; out-of-frame rows are NULL, or
    `default` (lag/lead 3-arg form) when given."""
    n = values.shape[0]
    idx = jnp.clip(idx, 0, n - 1)
    v = values[idx]
    valid = jnp.ones(n, dtype=bool) if validity is None else validity[idx]
    valid = valid & ok & live
    if default is not None:
        v = jnp.where(ok, v, jnp.asarray(default, v.dtype))
        valid = valid | (~ok & live)
    return v, valid


def lag(k: WindowKeys, values, validity, offset: int = 1, default=None):
    n = values.shape[0]
    iota = jnp.arange(n)
    idx = iota - offset
    ok = idx >= k.seg_start
    return _shift_gather(values, validity, idx, ok, k.live, default)


def lead(k: WindowKeys, values, validity, offset: int = 1, default=None):
    n = values.shape[0]
    iota = jnp.arange(n)
    idx = iota + offset
    seg_end = k.seg_start + k.seg_size - 1
    ok = idx <= seg_end
    return _shift_gather(values, validity, idx, ok, k.live, default)


def first_value(k: WindowKeys, values, validity):
    return _shift_gather(values, validity, k.seg_start,
                         jnp.ones_like(k.live), k.live)


def last_value(k: WindowKeys, values, validity):
    # default frame = RANGE UNBOUNDED PRECEDING .. CURRENT ROW → last peer
    return _shift_gather(values, validity, k.peer_last,
                         jnp.ones_like(k.live), k.live)


def nth_value(k: WindowKeys, values, validity, n: int):
    idx = k.seg_start + (n - 1)
    ok = (n >= 1) & (idx <= k.peer_last)
    return _shift_gather(values, validity, idx, ok, k.live)


# ---------------------------------------------------------------------------
# aggregate window functions (default frame: whole partition without ORDER BY,
# RANGE UNBOUNDED PRECEDING..CURRENT ROW with ORDER BY)


def _running_at_peer_last(cum, k: WindowKeys):
    """Frame-inclusive value: the running total at the last peer row."""
    return cum[k.peer_last]


def agg_window(
    k: WindowKeys, fn: str, values, validity, frame: str,
    is_float: bool,
):
    """sum/avg/min/max/count over the window. frame: "whole" = whole
    partition (no ORDER BY), "range" = RANGE UNBOUNDED..CURRENT (default with
    ORDER BY — peer rows included), "rows" = ROWS UNBOUNDED..CURRENT."""
    n = values.shape[0]
    valid = k.live if validity is None else (k.live & validity)
    framed = frame in ("range", "rows")

    def frame_value(run):
        return run if frame == "rows" else _running_at_peer_last(run, k)

    if fn == "count":
        c = jnp.cumsum(valid.astype(jnp.int64))
        run = c - c[k.seg_start] + valid[k.seg_start].astype(jnp.int64)
        if framed:
            return frame_value(run), None
        total = jnp.zeros(n, jnp.int64).at[k.seg_id].add(
            valid.astype(jnp.int64), mode="drop"
        )
        return total[k.seg_id], None

    if fn in ("sum", "avg"):
        acc_dtype = values.dtype if is_float else jnp.int64
        v = jnp.where(valid, values.astype(acc_dtype), 0)
        cs = jnp.cumsum(v)
        run = cs - cs[k.seg_start] + v[k.seg_start]
        cv = jnp.cumsum(valid.astype(jnp.int64))
        runc = cv - cv[k.seg_start] + valid[k.seg_start].astype(jnp.int64)
        if framed:
            s = frame_value(run)
            c = frame_value(runc)
        else:
            s = jnp.zeros(n, acc_dtype).at[k.seg_id].add(v, mode="drop")[k.seg_id]
            c = jnp.zeros(n, jnp.int64).at[k.seg_id].add(
                valid.astype(jnp.int64), mode="drop"
            )[k.seg_id]
        out_valid = c > 0
        if fn == "sum":
            return s, out_valid
        if is_float:
            return s / jnp.maximum(c, 1).astype(s.dtype), out_valid
        # integer/decimal avg: round half away from zero, like the
        # aggregation finalizer
        av = jnp.abs(s)
        cden = jnp.maximum(c, 1)
        q = jnp.sign(s) * ((av + cden // 2) // cden)
        return q, out_valid

    if fn in ("min", "max"):
        if is_float:
            sent = jnp.inf if fn == "min" else -jnp.inf
        else:
            info = jnp.iinfo(values.dtype)
            sent = info.max if fn == "min" else info.min
        v = jnp.where(valid, values, jnp.asarray(sent, values.dtype))
        if fn == "min":
            cm = _segmented_cummin(v, k)
        else:
            cm = -_segmented_cummin(-v, k)
        cnt = jnp.cumsum(valid.astype(jnp.int64))
        runc = cnt - cnt[k.seg_start] + valid[k.seg_start].astype(jnp.int64)
        if framed:
            out = frame_value(cm)
            c = frame_value(runc)
            return out, c > 0
        total = (
            jnp.full(n, sent, dtype=v.dtype).at[k.seg_id].min(v, mode="drop")
            if fn == "min"
            else jnp.full(n, sent, dtype=v.dtype).at[k.seg_id].max(v, mode="drop")
        )
        ctot = jnp.zeros(n, jnp.int64).at[k.seg_id].add(
            valid.astype(jnp.int64), mode="drop"
        )
        return total[k.seg_id], ctot[k.seg_id] > 0

    raise NotImplementedError(f"window aggregate {fn}")


# ---------------------------------------------------------------------------
# bounded ROWS frames (ROWS BETWEEN <bound> AND <bound>)


def parse_frame_bound(tok: str):
    """'up' | 'uf' | 'cur' | 'pN' | 'fN' → (kind, offset)."""
    if tok in ("up", "uf", "cur"):
        return tok, 0
    if tok[0] == "p":
        return "p", int(tok[1:])  # lint: allow(host-sync)
    if tok[0] == "f":
        return "f", int(tok[1:])  # lint: allow(host-sync)
    raise ValueError(f"bad frame bound {tok!r}")


def frame_bounds(k: WindowKeys, frame: str):
    """'rows:<s>:<e>' → (start_idx, end_idx, nonempty) per sorted row.
    Bounds clamp to the partition; an inverted frame is empty (SQL: the
    aggregate over an empty frame is NULL / count 0)."""
    _, s_tok, e_tok = frame.split(":")
    sk, so = parse_frame_bound(s_tok)
    ek, eo = parse_frame_bound(e_tok)
    n = k.live.shape[0]
    iota = jnp.arange(n)
    seg_end = k.seg_start + jnp.maximum(k.seg_size - 1, 0)
    start = {
        "up": k.seg_start,
        "cur": iota,
        "p": iota - so,
        "f": iota + so,
        "uf": seg_end,
    }[sk]
    end = {
        "up": k.seg_start,
        "cur": iota,
        "p": iota - eo,
        "f": iota + eo,
        "uf": seg_end,
    }[ek]
    nonempty = (jnp.maximum(start, k.seg_start)
                <= jnp.minimum(end, seg_end)) & k.live
    start_c = jnp.clip(start, k.seg_start, seg_end)
    end_c = jnp.clip(end, k.seg_start, seg_end)
    return start_c.astype(jnp.int32), end_c.astype(jnp.int32), nonempty


def _range_min_table(v):
    """Sparse table for O(1) range-min queries: levels[j][i] = min over
    [i, i + 2^j). O(n log n) build, pure elementwise shifts — the
    vectorized substitute for the reference's per-row frame walk."""
    n = v.shape[0]
    levels = [v]
    j = 0
    while (1 << (j + 1)) <= n:
        prev = levels[-1]
        half = 1 << j
        shifted = jnp.concatenate([prev[half:], prev[-1:].repeat(half)])
        levels.append(jnp.minimum(prev, shifted))
        j += 1
    return jnp.stack(levels)  # [L, n]


def _range_min_query(table, start, end):
    """min over [start, end] (inclusive, start<=end) via two overlapping
    power-of-two windows."""
    n = table.shape[1]
    span = (end - start + 1).astype(jnp.int32)
    # floor(log2(span)): span >= 1
    j = (31 - jax.lax.clz(span.astype(jnp.int32))).astype(jnp.int32)
    j = jnp.clip(j, 0, table.shape[0] - 1)
    second = jnp.clip(end - (1 << j) + 1, 0, n - 1)
    a = table[j, start]
    b = table[j, second]
    return jnp.minimum(a, b)


def range_frame_bounds(k: WindowKeys, order_vals, frame: str,
                       order_valid=None, nulls_first: bool = False,
                       offset_scale: int = 1):
    """'range:<s>:<e>' with VALUE offsets over ONE ascending-ized numeric
    order key: per-row frame bounds by vectorized binary search (log n
    elementwise gather steps — no per-row loops). order_vals are the
    partition-sorted key values in their NATIVE domain (int64 for
    integral/decimal/date keys — exact past 2^53 — float64 for doubles);
    offsets are scaled by offset_scale (10^scale for decimals) so the
    comparison happens in the exact unscaled domain. NULL keys
    (order_valid False) and NaN keys are excluded from the searchable
    span; their offset bounds resolve to their peer-group edges while
    non-offset bounds (UNBOUNDED / CURRENT ROW) keep their meaning."""
    _, s_tok, e_tok = frame.split(":")
    sk, so = parse_frame_bound(s_tok)
    ek, eo = parse_frame_bound(e_tok)
    seg_end = (k.seg_start + jnp.maximum(k.seg_size - 1, 0)).astype(jnp.int32)
    seg_start = k.seg_start.astype(jnp.int32)
    v = order_vals
    iters = max(1, int(k.live.shape[0] - 1).bit_length()) + 1

    # NULL keys sit at one contiguous end of each partition (per
    # nulls_first); NaN keys always sort at the tail of the non-null run
    # (lax.sort totals NaN greatest in both directions — DESC negates,
    # and -NaN is still NaN). Shrink the searchable span so no finite
    # target ever absorbs either group — this also keeps genuine +inf
    # keys distinct from NaN keys.
    def segcount(mask):
        c = jnp.cumsum(mask.astype(jnp.int32))
        return c[seg_end] - c[seg_start] + mask[seg_start].astype(jnp.int32)

    nan_mask = (jnp.isnan(v) & k.live
                if v is not None and jnp.issubdtype(v.dtype, jnp.floating)
                else None)
    null_mask = ((~order_valid) & k.live) if order_valid is not None else None
    lo0, hi0 = seg_start, seg_end
    if null_mask is not None and nulls_first:
        lo0 = jnp.minimum(seg_start + segcount(null_mask), seg_end)
    tail = nan_mask
    if null_mask is not None and not nulls_first:
        tail = null_mask if tail is None else (tail | null_mask)
    if tail is not None:
        hi0 = jnp.maximum(seg_end - segcount(tail), seg_start)
    # rows whose key can't anchor a value search get their PEER GROUP as
    # the result of any offset bound (SQL: a NULL/NaN row's offset frame
    # edge is its peers); non-offset bounds keep their normal meaning
    over = null_mask
    if nan_mask is not None:
        over = nan_mask if over is None else (over | nan_mask)

    def shift(delta: int):
        """v + delta with saturation (int keys must not wrap past the
        extremes; float +/-inf saturates on its own)."""
        if jnp.issubdtype(v.dtype, jnp.floating):
            return v + float(delta)  # lint: allow(host-sync)
        t = v + jnp.asarray(delta, v.dtype)
        if delta > 0:
            t = jnp.where(t < v, jnp.iinfo(v.dtype).max, t)
        elif delta < 0:
            t = jnp.where(t > v, jnp.iinfo(v.dtype).min, t)
        return t

    def lower_bound(target):
        """Smallest index in [lo0, hi0] whose key >= target (keys ascend
        within the partition); hi0+1 when none."""
        lo, hi = lo0, hi0
        for _ in range(iters):
            mid = (lo + hi) // 2
            ok = v[mid] >= target
            hi = jnp.where(ok, mid, hi)
            lo = jnp.where(ok, lo, jnp.minimum(mid + 1, hi0))
        return jnp.where(v[hi] >= target, hi, hi0 + 1)

    def upper_bound(target):
        """Largest index in [lo0, hi0] whose key <= target; lo0-1 when
        none."""
        lo, hi = lo0, hi0
        for _ in range(iters):
            mid = (lo + hi + 1) // 2
            ok = v[mid] <= target
            lo = jnp.where(ok, mid, lo)
            hi = jnp.where(ok, hi, jnp.maximum(mid - 1, lo0))
        return jnp.where(v[lo] <= target, lo, lo0 - 1)

    if sk == "up":
        start = seg_start
    elif sk == "cur":
        # RANGE start at CURRENT ROW includes preceding PEERS
        start = k.peer_start.astype(jnp.int32)
    else:
        start = lower_bound(shift((-so if sk == "p" else so) * offset_scale))
        if over is not None:
            start = jnp.where(over, k.peer_start.astype(jnp.int32), start)
    if ek == "uf":
        end = seg_end
    elif ek == "cur":
        end = k.peer_last.astype(jnp.int32)
    else:
        end = upper_bound(shift((eo if ek == "f" else -eo) * offset_scale))
        if over is not None:
            end = jnp.where(over, k.peer_last.astype(jnp.int32), end)
    nonempty = (start <= end) & k.live
    start = jnp.clip(start, seg_start, seg_end)
    end = jnp.clip(end, seg_start, seg_end)
    return start, end, nonempty


def agg_window_bounded(k: WindowKeys, fn: str, values, validity,
                       frame: str, is_float: bool, order_vals=None,
                       order_valid=None, nulls_first: bool = False,
                       offset_scale: int = 1):
    """sum/avg/min/max/count over an explicit ROWS or RANGE frame.
    Prefix-sum differences for sum/count (both gather indices stay
    inside one partition, so cross-partition terms cancel); sparse-table
    range min/max for extremes."""
    if frame.startswith("range:"):
        start, end, nonempty = range_frame_bounds(
            k, order_vals, frame, order_valid, nulls_first, offset_scale)
    else:
        start, end, nonempty = frame_bounds(k, frame)
    valid = k.live if validity is None else (k.live & validity)

    def windowed_sum(x, dtype):
        xv = jnp.where(valid, x.astype(dtype), jnp.zeros((), dtype))
        cs = jnp.cumsum(xv)
        lo = jnp.where(start > 0, cs[jnp.maximum(start - 1, 0)],
                       jnp.zeros((), dtype))
        return cs[end] - lo

    cnt = windowed_sum(jnp.ones_like(k.live, dtype=jnp.int64), jnp.int64)
    cnt = jnp.where(nonempty, cnt, 0)
    if fn == "count":
        return cnt, None
    if fn in ("sum", "avg"):
        acc_dtype = values.dtype if is_float else jnp.int64
        s = jnp.where(nonempty, windowed_sum(values, acc_dtype),
                      jnp.zeros((), acc_dtype))
        out_valid = nonempty & (cnt > 0)
        if fn == "sum":
            return s, out_valid
        if is_float:
            return s / jnp.maximum(cnt, 1).astype(s.dtype), out_valid
        av = jnp.abs(s)
        cden = jnp.maximum(cnt, 1)
        return jnp.sign(s) * ((av + cden // 2) // cden), out_valid
    if fn in ("min", "max"):
        if is_float:
            sent = jnp.inf if fn == "min" else -jnp.inf
        else:
            info = jnp.iinfo(values.dtype)
            sent = info.max if fn == "min" else info.min
        v = jnp.where(valid, values, jnp.asarray(sent, values.dtype))
        if fn == "max":
            v = -v
        table = _range_min_table(v)
        out = _range_min_query(table, start, end)
        if fn == "max":
            out = -out
        return out, nonempty & (cnt > 0)
    raise NotImplementedError(f"bounded window aggregate {fn}")


def value_over_frame(k: WindowKeys, fn: str, values, validity, frame: str,
                     nth: int = 1, order_vals=None, order_valid=None,
                     nulls_first: bool = False, offset_scale: int = 1):
    """first_value/last_value/nth_value over an explicit ROWS or RANGE
    frame."""
    if frame.startswith("range:"):
        start, end, nonempty = range_frame_bounds(
            k, order_vals, frame, order_valid, nulls_first, offset_scale)
    else:
        start, end, nonempty = frame_bounds(k, frame)
    if fn == "first_value":
        idx = start
        ok = nonempty
    elif fn == "last_value":
        idx = end
        ok = nonempty
    else:
        idx = start + (nth - 1)
        ok = nonempty & (nth >= 1) & (idx <= end)
    return _shift_gather(values, validity, idx, ok, k.live)


def _segmented_cummin(v, k: WindowKeys):
    """Running minimum that resets at partition boundaries.

    Trick: order-encode (seg_id, v) into a single monotone key so a global
    cummin over the pair key restricted to the segment prefix is exact —
    implemented as an associative scan over (seg_id, v) pairs whose combine
    keeps the right-hand segment and min-merges only within a segment.
    """

    def combine(a, b):
        sa, va = a
        sb, vb = b
        take_b_only = sb != sa
        return sb, jnp.where(take_b_only, vb, jnp.minimum(va, vb))

    _, out = jax.lax.associative_scan(
        combine, (k.seg_id.astype(jnp.int32), v)
    )
    return out
