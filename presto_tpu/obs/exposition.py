"""Prometheus text-exposition validator.

Lints the /v1/metrics documents this engine renders (and anything else in
text format 0.0.4): HELP/TYPE declared at most once per family and before
samples, sample names consistent with the declared type (histogram series
must be `_bucket`/`_sum`/`_count`), label syntax with proper escaping,
`le` bucket bounds sorted with cumulative counts monotone, `+Inf` bucket
present and equal to `_count`.

Usable as a library (`lint_exposition(text) -> [errors]`) and as a CLI for
CI smoke steps: `python -m presto_tpu.obs.exposition [file]` (stdin when no
file) exits 1 and prints one error per line when the document is invalid.
"""

from __future__ import annotations

import re
import sys
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_VALID_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")
_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_labels(s: str) -> Tuple[Optional[Dict[str, str]], Optional[str]]:
    """Parse `a="b",c="d\\""` respecting \\\\, \\", \\n escapes. Returns
    (labels, error)."""
    labels: Dict[str, str] = {}
    i = 0
    n = len(s)
    while i < n:
        m = re.match(r"[a-zA-Z_][a-zA-Z0-9_]*", s[i:])
        if not m:
            return None, f"bad label name at ...{s[i:i+20]!r}"
        name = m.group(0)
        i += len(name)
        if i >= n or s[i] != "=":
            return None, f"expected '=' after label {name!r}"
        i += 1
        if i >= n or s[i] != '"':
            return None, f"label {name!r} value not quoted"
        i += 1
        val = []
        while i < n and s[i] != '"':
            if s[i] == "\\":
                if i + 1 >= n:
                    return None, f"dangling escape in label {name!r}"
                esc = s[i + 1]
                if esc not in ('"', "\\", "n"):
                    return None, (f"invalid escape \\{esc} in label "
                                  f"{name!r}")
                val.append("\n" if esc == "n" else esc)
                i += 2
            else:
                val.append(s[i])
                i += 1
        if i >= n:
            return None, f"unterminated label value for {name!r}"
        i += 1  # closing quote
        labels[name] = "".join(val)
        if i < n:
            if s[i] != ",":
                return None, f"expected ',' between labels at ...{s[i:]!r}"
            i += 1
    return labels, None


def _split_sample(line: str):
    """'name{labels} value' | 'name value' -> (name, labelstr, value)."""
    if "{" in line:
        m = re.match(r"^(\S+?)\{(.*)\}\s+(\S+)(?:\s+-?\d+)?$", line)
        if not m:
            return None
        return m.group(1), m.group(2), m.group(3)
    m = re.match(r"^(\S+)\s+(\S+)(?:\s+-?\d+)?$", line)
    if not m:
        return None
    return m.group(1), "", m.group(2)


def _family_of(sample_name: str, histogram_families: set) -> str:
    for suf in _HISTOGRAM_SUFFIXES:
        if sample_name.endswith(suf) and sample_name[:-len(suf)] \
                in histogram_families:
            return sample_name[:-len(suf)]
    return sample_name


def lint_exposition(text: str) -> List[str]:
    errors: List[str] = []
    helps: set = set()
    types: Dict[str, str] = {}
    sampled: set = set()  # families with at least one sample seen
    # histogram series: (family, labels-minus-le) -> list of (le, value)
    hist_buckets: Dict[tuple, List[Tuple[float, float]]] = {}
    hist_counts: Dict[tuple, float] = {}
    hist_sums: set = set()

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# (HELP|TYPE) (\S+)(?: (.*))?$", line)
            if not m:
                if re.match(r"^# ?(HELP|TYPE)\b", line):
                    errors.append(f"line {lineno}: malformed comment: {line}")
                continue  # plain comment
            kind, fam = m.group(1), m.group(2)
            if not _NAME_RE.match(fam):
                errors.append(f"line {lineno}: invalid metric name {fam!r}")
                continue
            if kind == "HELP":
                if fam in helps:
                    errors.append(
                        f"line {lineno}: duplicate HELP for family {fam}")
                helps.add(fam)
            else:
                if fam in types:
                    errors.append(
                        f"line {lineno}: duplicate TYPE for family {fam}")
                if fam in sampled:
                    errors.append(
                        f"line {lineno}: TYPE for {fam} after its samples")
                t = (m.group(3) or "").strip()
                if t not in _VALID_TYPES:
                    errors.append(
                        f"line {lineno}: invalid type {t!r} for {fam}")
                types[fam] = t
            continue
        parsed = _split_sample(line)
        if parsed is None:
            errors.append(f"line {lineno}: unparseable sample: {line}")
            continue
        name, labelstr, value = parsed
        if not _NAME_RE.match(name):
            errors.append(f"line {lineno}: invalid sample name {name!r}")
            continue
        labels: Dict[str, str] = {}
        if labelstr:
            labels, err = _parse_labels(labelstr)
            if err:
                errors.append(f"line {lineno}: {err}")
                continue
        for ln in labels:
            if not _LABEL_NAME_RE.match(ln):
                errors.append(f"line {lineno}: invalid label name {ln!r}")
        try:
            fval = float(value)
        except ValueError:
            if value not in ("+Inf", "-Inf", "NaN"):
                errors.append(
                    f"line {lineno}: non-numeric value {value!r}")
                continue
            fval = float(value.replace("Inf", "inf"))
        histogram_families = {f for f, t in types.items() if t == "histogram"}
        fam = _family_of(name, histogram_families)
        if fam not in types:
            errors.append(
                f"line {lineno}: sample {name} has no # TYPE declaration")
            sampled.add(fam)
            continue
        sampled.add(fam)
        ftype = types[fam]
        if ftype == "histogram":
            if name == fam + "_bucket":
                if "le" not in labels:
                    errors.append(
                        f"line {lineno}: histogram bucket without le label")
                    continue
                le = labels["le"]
                lef = float("inf") if le == "+Inf" else None
                if lef is None:
                    try:
                        lef = float(le)
                    except ValueError:
                        errors.append(
                            f"line {lineno}: unparseable le bound {le!r}")
                        continue
                key = (fam, tuple(sorted((k, v) for k, v in labels.items()
                                         if k != "le")))
                hist_buckets.setdefault(key, []).append((lef, fval))
            elif name == fam + "_count":
                key = (fam, tuple(sorted(labels.items())))
                hist_counts[key] = fval
            elif name == fam + "_sum":
                hist_sums.add((fam, tuple(sorted(labels.items()))))
            else:
                errors.append(
                    f"line {lineno}: sample {name} invalid for histogram "
                    f"family {fam}")
        else:
            if name != fam:
                errors.append(
                    f"line {lineno}: sample {name} does not match declared "
                    f"family {fam} of type {ftype}")

    for fam in sampled:
        if fam not in helps:
            errors.append(f"family {fam}: missing # HELP")
    for (fam, lkey), buckets in hist_buckets.items():
        series = f"{fam}{{{','.join(f'{k}={v}' for k, v in lkey)}}}"
        in_order = sorted(buckets, key=lambda b: b[0])
        if [b[0] for b in buckets] != [b[0] for b in in_order]:
            errors.append(f"{series}: le bounds not sorted ascending")
        counts = [b[1] for b in in_order]
        if any(counts[i] > counts[i + 1] for i in range(len(counts) - 1)):
            errors.append(f"{series}: bucket counts not monotone "
                          f"non-decreasing")
        if not in_order or in_order[-1][0] != float("inf"):
            errors.append(f"{series}: missing le=\"+Inf\" bucket")
        else:
            cnt = hist_counts.get((fam, lkey))
            if cnt is None:
                errors.append(f"{series}: missing _count sample")
            elif cnt != in_order[-1][1]:
                errors.append(
                    f"{series}: _count {cnt} != +Inf bucket "
                    f"{in_order[-1][1]}")
        if (fam, lkey) not in hist_sums:
            errors.append(f"{series}: missing _sum sample")
    return errors


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        with open(argv[0]) as fh:
            text = fh.read()
    else:
        text = sys.stdin.read()
    errors = lint_exposition(text)
    for e in errors:
        print(e, file=sys.stderr)
    if not errors:
        print("exposition OK "
              f"({len([l for l in text.splitlines() if l and not l.startswith('#')])} samples)")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
