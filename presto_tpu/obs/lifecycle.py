"""Serving-plane SLO telemetry: query lifecycle timelines + live progress.

Three planes, all keyed on the serving query id (== trace id for traced
queries, so every record correlates with PR 2 spans):

1. **Lifecycle timeline** — monotonic timestamps for every state
   transition (``created -> queued -> admitted -> planning -> compiling
   -> executing -> draining -> finished|failed|canceled|expired``),
   decomposed into five segments that ALWAYS sum exactly to the e2e
   wall: a boundary that was never reached resolves to the next boundary
   on its right, so a query that dies while queued books its whole life
   to ``queue_wait`` and an immediate coordinator statement books its
   execute lambda to ``plan``. Segments feed per-resource-group
   log-bucket histograms (``presto_tpu_query_{queue_wait,compile,exec,
   e2e}_seconds{group=...}``) and the ``slo_objectives=`` violation
   counters.

2. **Live progress** — ``progress_doc`` estimates fraction-complete from
   HBO history (PR 10): the fingerprint's recorded output rows / sink
   rows / wall vs. what the coordinator root stream and worker
   heartbeats have observed so far (provenance ``"hbo"``), falling back
   to fragments-done/fragments-total from heartbeats (provenance
   ``"fragments"``). The reported fraction is a running max, so it is
   monotone nondecreasing by construction, and pins to 1.0 on any
   terminal state.

3. **Latency regression** — at completion the pre-run HBO baseline wall
   for the query's fingerprint is compared against the actual e2e; a
   wall >= factor x baseline increments
   ``presto_tpu_latency_regression_total``, lands in the cluster event
   stream, and annotates the slow-query JSONL record.

Everything here is dormant until :func:`register` first runs — the
``lifecycle`` session property gates registration, and the metric
families render on ``/v1/metrics`` only once :func:`armed` is true, so
``lifecycle=off`` sessions leave the scrape (and the serving path)
bit-for-bit pre-PR.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from presto_tpu.obs.metrics import Histogram, log_buckets
from presto_tpu.obs import events as _obs_events
from presto_tpu.obs import runstats as _runstats

# ---------------------------------------------------------------------------
# vocabulary

#: ordered non-terminal marks; ``created`` is stamped at construction
MARKS: Tuple[str, ...] = ("created", "queued", "admitted", "planning",
                          "compiling", "executing", "draining")
TERMINAL_MARKS: Tuple[str, ...] = ("finished", "failed", "canceled",
                                   "expired")
#: wall-clock decomposition; the five sum exactly to ``e2e``
SEGMENTS: Tuple[str, ...] = ("queue_wait", "plan", "compile", "exec",
                             "drain")
#: segment boundaries, left to right (the implicit 6th boundary is the
#: terminal timestamp / now)
_BOUNDARIES: Tuple[str, ...] = ("created", "planning", "compiling",
                                "executing", "draining")

#: HBO site under which completed-query profiles are recorded (wall,
#: output rows, sink rows) and regression baselines are looked up
HBO_SITE = _runstats.QUERY_SITE

_CANON_ORDER = {name: i for i, name in enumerate(MARKS + ("terminal",))}

# QueryManager state -> timeline mark (None = no mark for this state:
# QUEUED is covered by ``created``, RUNNING is refined into
# compiling/executing by the coordinator's own marks)
_STATE_MAP = {
    "QUEUED": None, "PLANNING": "planning", "RUNNING": None,
    "FINISHING": "draining", "FINISHED": "finished", "FAILED": "failed",
    "CANCELED": "canceled", "EXPIRED": "expired",
}


def parse_objectives(spec: str) -> Dict[str, float]:
    """Parse an ``slo_objectives`` spec: ``"e2e=1.5,queue_wait=0.25"``.

    Keys are segment names (or ``e2e``); values are seconds. Raises
    ValueError on unknown segments or non-numeric bounds so the session
    property validator can reject bad specs at SET time.
    """
    out: Dict[str, float] = {}
    allowed = set(SEGMENTS) | {"e2e"}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"slo_objectives entry {part!r} is not segment=seconds")
        key, _, val = part.partition("=")
        key = key.strip().lower()
        if key not in allowed:
            raise ValueError(
                f"unknown slo_objectives segment {key!r} "
                f"(allowed: {', '.join(sorted(allowed))})")
        limit = float(val)
        if limit <= 0:
            raise ValueError(f"slo_objectives bound for {key!r} must be > 0")
        out[key] = limit
    return out


# ---------------------------------------------------------------------------
# timeline

class Timeline:
    """Monotonic per-query state-transition timestamps.

    First mark wins (replay waves re-enter ``executing``; only the first
    entry is the segment boundary). ``finish`` closes the timeline; late
    marks after a terminal state are dropped.
    """

    def __init__(self, created: Optional[float] = None):
        self.created = time.time() if created is None else created
        self._lock = threading.Lock()
        self.marks: Dict[str, float] = {"created": self.created}
        #: transition log in arrival order: [(name, ts), ...]
        self.order: List[Tuple[str, float]] = [("created", self.created)]
        self.terminal: Optional[str] = None
        self.end: Optional[float] = None

    def mark(self, name: str, ts: Optional[float] = None) -> bool:
        now = time.time() if ts is None else ts
        with self._lock:
            if self.terminal is not None or name in self.marks:
                return False
            self.marks[name] = now
            self.order.append((name, now))
            return True

    def finish(self, terminal: str, ts: Optional[float] = None) -> bool:
        now = time.time() if ts is None else ts
        with self._lock:
            if self.terminal is not None:
                return False
            self.terminal = terminal
            self.end = now
            self.marks[terminal] = now
            self.order.append((terminal, now))
            return True

    def segments(self, now: Optional[float] = None) -> Dict[str, float]:
        """queue/plan/compile/exec/drain + e2e, in seconds.

        A boundary that was never stamped resolves to the next boundary
        on its right (terminal/now as the last resort), which keeps every
        segment nonnegative and makes the five segments sum exactly to
        ``e2e`` regardless of which states the query actually visited.
        """
        with self._lock:
            end = self.end if self.end is not None else (
                time.time() if now is None else now)
            bounds: List[Optional[float]] = [
                self.marks.get(n) for n in _BOUNDARIES]
        bounds.append(end)
        for i in range(len(bounds) - 2, -1, -1):
            if bounds[i] is None:
                bounds[i] = bounds[i + 1]
        return {
            "queue_wait": bounds[1] - bounds[0],
            "plan": bounds[2] - bounds[1],
            "compile": bounds[3] - bounds[2],
            "exec": bounds[4] - bounds[3],
            "drain": bounds[5] - bounds[4],
            "e2e": bounds[5] - bounds[0],
        }

    def doc(self) -> Dict[str, Any]:
        with self._lock:
            order = list(self.order)
            terminal = self.terminal
        return {
            "transitions": [
                {"state": n, "ts": round(ts, 6)} for n, ts in order],
            "terminal": terminal,
            "segments": {k: round(v, 6)
                         for k, v in self.segments().items()},
        }


# ---------------------------------------------------------------------------
# metric families — NOT in obs.metrics.ALL_HISTOGRAMS: they render on the
# scrape only once the plane is armed (first lifecycle-on query), so a
# never-armed process exposes the exact pre-PR family set.

QUERY_QUEUE_WAIT = Histogram(
    "presto_tpu_query_queue_wait_seconds",
    "query creation to planning start, per resource group",
    log_buckets(0.001, 600.0))
QUERY_COMPILE = Histogram(
    "presto_tpu_query_compile_seconds",
    "distributed plan ready to first root-stream output, per resource group",
    log_buckets(0.001, 600.0))
QUERY_EXEC = Histogram(
    "presto_tpu_query_exec_seconds",
    "first root-stream output to result drain start, per resource group",
    log_buckets(0.001, 600.0))
QUERY_E2E = Histogram(
    "presto_tpu_query_e2e_seconds",
    "query creation to terminal state, per resource group",
    log_buckets(0.001, 600.0))

SLO_HISTOGRAMS: Tuple[Histogram, ...] = (
    QUERY_QUEUE_WAIT, QUERY_COMPILE, QUERY_EXEC, QUERY_E2E)

_SEGMENT_HISTOGRAMS = {
    "queue_wait": QUERY_QUEUE_WAIT, "compile": QUERY_COMPILE,
    "exec": QUERY_EXEC, "e2e": QUERY_E2E,
}

_counter_lock = threading.Lock()
_slo_violations: Dict[Tuple[str, str], int] = {}   # (group, segment) -> n
# regressions attributed to the lifecycle segment that moved most vs the
# group's running baseline: (group, segment) -> n
_latency_regressions: Dict[Tuple[str, str], int] = {}
# per-(group, segment) running mean of completed-query segment walls:
# (group, segment) -> (sum_s, n). Folded AFTER each query's regression
# check, so attribution always compares against prior completions only.
_segment_baselines: Dict[Tuple[str, str], Tuple[float, int]] = {}
_REGRESSION_SEGMENTS = ("queue_wait", "plan", "compile", "exec", "drain")

_armed = False


def arm() -> None:
    global _armed
    with _counter_lock:
        _armed = True


def armed() -> bool:
    return _armed


def metric_rows(labels: Dict[str, str]) -> List[tuple]:
    """Counter rows for server.metrics.render_metrics (call when armed)."""
    rows: List[tuple] = []
    with _counter_lock:
        viol = dict(_slo_violations)
        regr = dict(_latency_regressions)
    help_v = "queries that missed a configured per-segment latency objective"
    help_r = "completed queries whose wall exceeded factor x HBO baseline"
    if viol:
        for (group, seg), n in sorted(viol.items()):
            rows.append(("presto_tpu_slo_violations_total", help_v, n,
                         {**labels, "group": group, "segment": seg},
                         "counter"))
    else:
        rows.append(("presto_tpu_slo_violations_total", help_v, 0,
                     dict(labels), "counter"))
    if regr:
        for (group, seg), n in sorted(regr.items()):
            rows.append(("presto_tpu_latency_regression_total", help_r, n,
                         {**labels, "group": group, "segment": seg},
                         "counter"))
    else:
        rows.append(("presto_tpu_latency_regression_total", help_r, 0,
                     dict(labels), "counter"))
    return rows


def render_slo_histograms(plane: str) -> str:
    lines: List[str] = []
    for h in SLO_HISTOGRAMS:
        lines.extend(h.render(plane))
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# registry

class QueryLifecycle:
    """Registry entry: timeline + live progress state for one query."""

    def __init__(self, query_id: str, group: Optional[str] = None,
                 objectives: Optional[Dict[str, float]] = None,
                 regression_factor: float = 0.0):
        self.query_id = query_id
        self.timeline = Timeline()
        self.group = group or "none"
        self.objectives = dict(objectives or {})
        self.regression_factor = float(regression_factor or 0.0)
        self.fingerprint: Optional[str] = None
        #: HBO entry for the fingerprint as of plan time (pre-run)
        self.predicted: Optional[Dict[str, Any]] = None
        # live observations
        self.rows = 0            # root-stream output rows (coordinator)
        self.batches = 0         # root-stream batches ingested
        self.replay_waves = 0    # overflow replay waves (from spans)
        #: (node_id, attempt_query_id) -> latest heartbeat progress doc
        self.worker_progress: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.regression: Optional[Dict[str, Any]] = None
        #: result-cache provenance doc (server/result_cache.py): set on a
        #: cache hit; surfaces in stats.resultCache and the slow-query log
        self.cache_info: Optional[Dict[str, Any]] = None
        #: compile-farm attribution doc (exec/farm.py): whether this
        #: query's programs were farm-warmed (armed/live) before it ran
        self.farm_info: Optional[Dict[str, Any]] = None
        self._max_fraction = 0.0
        self._lock = threading.Lock()

    # -- live counting ----------------------------------------------------

    def observe_batch(self, rows: int) -> None:
        with self._lock:
            self.rows += int(rows)
            self.batches += 1

    def worker_rows(self) -> Tuple[int, int]:
        """(sink rows, batches) summed over worker heartbeat docs."""
        with self._lock:
            docs = list(self.worker_progress.values())
        return (sum(int(d.get("rows", 0)) for d in docs),
                sum(int(d.get("batches", 0)) for d in docs))

    def fragment_fraction(self) -> Tuple[float, int, int]:
        """(done/total over tasks, fragmentsDone, fragmentsTotal)."""
        with self._lock:
            docs = list(self.worker_progress.values())
        done = sum(int(d.get("tasksDone", 0)) for d in docs)
        total = sum(int(d.get("tasksTotal", 0)) for d in docs)
        fdone = sum(int(d.get("fragmentsDone", 0)) for d in docs)
        ftotal = sum(int(d.get("fragmentsTotal", 0)) for d in docs)
        return ((done / total) if total else 0.0, fdone, ftotal)


_lock = threading.RLock()
_entries: "OrderedDict[str, QueryLifecycle]" = OrderedDict()
_aliases: Dict[str, str] = {}
_MAX_ENTRIES = 512


def register(query_id: str, group: Optional[str] = None,
             objectives: Optional[Dict[str, float]] = None,
             regression_factor: float = 0.0) -> QueryLifecycle:
    """Create (and arm) the lifecycle entry for a query; emits the
    ``created`` event."""
    entry = QueryLifecycle(query_id, group=group, objectives=objectives,
                           regression_factor=regression_factor)
    with _lock:
        arm()
        _entries[query_id] = entry
        while len(_entries) > _MAX_ENTRIES:
            old_id, _ = _entries.popitem(last=False)
            for a in [a for a, q in _aliases.items() if q == old_id]:
                del _aliases[a]
    _obs_events.EVENTS.emit("lifecycle", query_id=query_id,
                            state="created", group=entry.group)
    return entry


def alias(attempt_id: str, query_id: str) -> None:
    """Map a scheduler attempt query id onto the serving query id, so
    worker heartbeats (keyed by attempt) reach the right entry."""
    if attempt_id == query_id:
        return
    with _lock:
        if query_id in _entries:
            _aliases[attempt_id] = query_id


def get(query_id: str) -> Optional[QueryLifecycle]:
    with _lock:
        qid = _aliases.get(query_id, query_id)
        return _entries.get(qid)


def mark(query_id: str, name: str, **attrs) -> bool:
    """Stamp a timeline mark; emits the matching lifecycle event on the
    first stamp only. No-op (False) for unregistered queries, so callers
    never need their own lifecycle-enabled check."""
    entry = get(query_id)
    if entry is None or not entry.timeline.mark(name):
        return False
    _obs_events.EVENTS.emit("lifecycle", query_id=entry.query_id,
                            state=name, group=entry.group, **attrs)
    return True


def transition(query_id: str, state: str, **attrs) -> bool:
    """Record a QueryManager state transition (called from
    ``QueryExecution._transition``)."""
    entry = get(query_id)
    if entry is None:
        return False
    mapped = _STATE_MAP.get(state, None)
    if mapped is None:
        return False
    if mapped in TERMINAL_MARKS:
        ok = entry.timeline.finish(mapped)
    else:
        ok = entry.timeline.mark(mapped)
    if ok:
        _obs_events.EVENTS.emit("lifecycle", query_id=entry.query_id,
                                state=mapped, group=entry.group, **attrs)
    return ok


def set_fingerprint(query_id: str, fingerprint: str) -> None:
    """Stamp the plan fingerprint and snapshot the pre-run HBO baseline
    (prediction for progress, baseline for regression)."""
    entry = get(query_id)
    if entry is None:
        return
    entry.fingerprint = fingerprint
    ent = _runstats.lookup(fingerprint, HBO_SITE)
    if ent:
        entry.predicted = dict(ent)


def observe_batch(query_id: str, rows: int) -> None:
    entry = get(query_id)
    if entry is not None:
        entry.observe_batch(rows)


def merge_worker_progress(node_id: str, doc: Dict[str, Any]) -> None:
    """Fold one worker heartbeat ``queryProgress`` doc (keyed by attempt
    query id) into the registry."""
    for attempt_id, stats in (doc or {}).items():
        entry = get(attempt_id)
        if entry is None or not isinstance(stats, dict):
            continue
        with entry._lock:
            entry.worker_progress[(node_id, attempt_id)] = dict(stats)


def note_cache(query_id: str, doc: Dict[str, Any]) -> None:
    """Attach a result-cache provenance doc to the query's lifecycle
    entry (no-op for unregistered queries, preserving off-discipline)."""
    entry = get(query_id)
    if entry is not None:
        entry.cache_info = dict(doc)


def note_farm(query_id: str, doc: Dict[str, Any]) -> None:
    """Attach a compile-farm attribution doc to the query's lifecycle
    entry (no-op for unregistered queries, preserving off-discipline) —
    a farm-warmed query's compile segment ≈ 0 needs a WHY on record."""
    entry = get(query_id)
    if entry is not None:
        entry.farm_info = dict(doc)


def slow_log_annotation(query_id: str) -> Optional[Dict[str, Any]]:
    """Extra fields for the slow-query JSONL record (regression flag,
    result-cache provenance, compile-farm attribution)."""
    entry = get(query_id)
    if entry is None:
        return None
    extra: Dict[str, Any] = {}
    if entry.regression is not None:
        extra["latencyRegression"] = dict(entry.regression)
    if entry.cache_info is not None:
        extra["cacheHit"] = dict(entry.cache_info)
    if entry.farm_info is not None:
        extra["farm"] = dict(entry.farm_info)
    return extra or None


# ---------------------------------------------------------------------------
# progress

def progress_doc(query_id: str,
                 state: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The ``GET /v1/query/{id}/progress`` document, or None when the
    query never registered (lifecycle off / unknown id)."""
    entry = get(query_id)
    if entry is None:
        return None
    terminal = entry.timeline.terminal
    segments = entry.timeline.segments()
    w_rows, w_batches = entry.worker_rows()
    frag_frac, fdone, ftotal = entry.fragment_fraction()
    with entry._lock:
        root_rows, root_batches = entry.rows, entry.batches
        predicted = dict(entry.predicted) if entry.predicted else None
        waves = entry.replay_waves
        cache_hit = entry.cache_info is not None
    provenance = "fragments"
    fraction = min(frag_frac, 0.95)
    if cache_hit:
        # result-cache short circuit: the query never executes, so HBO's
        # row/wall estimates would pin the fraction below 1.0 forever —
        # a cache hit IS completion
        provenance = "cache"
        fraction = 1.0
    elif predicted:
        provenance = "hbo"
        estimates = [fraction]
        p_rows = float(predicted.get("rows", 0) or 0)
        if p_rows > 0:
            estimates.append(root_rows / p_rows)
        p_sink = float(predicted.get("sink_rows", 0) or 0)
        if p_sink > 0 and w_rows:
            estimates.append(w_rows / p_sink)
        p_wall = float(predicted.get("wall_s", 0) or 0)
        if p_wall > 0:
            estimates.append(segments["e2e"] / p_wall)
        fraction = min(0.99, max(estimates))
    elif terminal is not None:
        provenance = "terminal"
    if terminal is not None:
        fraction = 1.0
    with entry._lock:
        entry._max_fraction = max(entry._max_fraction, fraction)
        fraction = entry._max_fraction
    doc: Dict[str, Any] = {
        "queryId": entry.query_id,
        "state": state or (terminal or "running"),
        "fraction": round(fraction, 6),
        "provenance": provenance,
        "elapsedS": round(segments["e2e"], 6),
        "segments": {k: round(v, 6) for k, v in segments.items()},
        "rows": root_rows,
        "batches": root_batches,
        "workerRows": w_rows,
        "workerBatches": w_batches,
        "fragments": {"done": fdone, "total": ftotal},
        "replayWaves": waves,
        "group": entry.group,
        "traceToken": entry.query_id,
    }
    if predicted:
        doc["predicted"] = {
            "rows": predicted.get("rows"),
            "sinkRows": predicted.get("sink_rows"),
            "wallS": predicted.get("wall_s"),
        }
    return doc


# ---------------------------------------------------------------------------
# completion

def complete(info, spans: Optional[list] = None) -> None:
    """Terminal-state hook (runs first in the queryCompleted listener
    chain): observes SLO histograms, checks objectives, flags latency
    regressions against the pre-run HBO baseline, derives memory/replay
    events from the query's trace spans, and records the completed
    profile back into HBO for the next run's prediction.
    """
    entry = get(info.query_id)
    if entry is None:
        return
    segments = entry.timeline.segments()
    group = entry.group
    state = entry.timeline.terminal or str(
        getattr(info, "state", "")).lower()

    for seg, hist in _SEGMENT_HISTOGRAMS.items():
        hist.observe(segments[seg], plane="coordinator", group=group)

    for seg, limit in entry.objectives.items():
        actual = segments.get(seg)
        if actual is not None and actual > limit:
            with _counter_lock:
                key = (group, seg)
                _slo_violations[key] = _slo_violations.get(key, 0) + 1
            _obs_events.EVENTS.emit(
                "slo_violation", query_id=entry.query_id, group=group,
                segment=seg, limitS=limit, actualS=round(actual, 6))

    if spans:
        _span_events(entry, spans)

    if state == "finished" and entry.fingerprint:
        baseline = _runstats.lookup(entry.fingerprint, HBO_SITE)
        wall = segments["e2e"]
        factor = entry.regression_factor
        base_wall = float((baseline or {}).get("wall_s", 0) or 0)
        if factor > 0 and base_wall > 0 and wall >= factor * base_wall:
            seg_attr = _attribute_regression(group, segments)
            entry.regression = {
                "wallS": round(wall, 6),
                "baselineWallS": round(base_wall, 6),
                "factor": factor,
                "fingerprint": entry.fingerprint,
                "segment": seg_attr,
            }
            with _counter_lock:
                key = (group, seg_attr)
                _latency_regressions[key] = (
                    _latency_regressions.get(key, 0) + 1)
            _obs_events.EVENTS.emit(
                "latency_regression", query_id=entry.query_id, group=group,
                **entry.regression)
        w_rows, _ = entry.worker_rows()
        _runstats.note(entry.fingerprint, HBO_SITE,
                       wall_s=wall, rows=entry.rows, sink_rows=w_rows)
    if state == "finished":
        # fold AFTER the regression check: baselines are means over prior
        # completions, never contaminated by the run being judged
        with _counter_lock:
            for seg in _REGRESSION_SEGMENTS:
                s, n = _segment_baselines.get((group, seg), (0.0, 0))
                _segment_baselines[(group, seg)] = (
                    s + float(segments.get(seg, 0.0) or 0.0), n + 1)


def _attribute_regression(group: str, segments: Dict[str, float]) -> str:
    """Name the lifecycle segment that regressed most vs the group's
    running baseline (largest actual/mean ratio over prior completions).
    Falls back to ``e2e`` when no baseline exists yet — the first slow
    query in a group has nothing to compare segments against."""
    best, best_ratio = "e2e", 0.0
    with _counter_lock:
        for seg in _REGRESSION_SEGMENTS:
            s, n = _segment_baselines.get((group, seg), (0.0, 0))
            if n == 0:
                continue
            mean = s / n
            if mean <= 1e-6:
                continue
            ratio = float(segments.get(seg, 0.0) or 0.0) / mean
            if ratio > best_ratio:
                best, best_ratio = seg, ratio
    return best


def _span_events(entry: QueryLifecycle, spans: list) -> None:
    """Unify memory revokes/kills and overflow-replay waves (already
    traced as spans) into the cluster event stream."""
    waves = 0
    for sp in spans:
        kind = getattr(sp, "kind", None)
        attrs = dict(getattr(sp, "attrs", {}) or {})
        if kind == "overflow_replay":
            waves += 1
            _obs_events.EVENTS.emit(
                "overflow_replay", query_id=entry.query_id,
                group=entry.group, site=getattr(sp, "name", ""), **attrs)
        elif kind == "memory_revoke":
            _obs_events.EVENTS.emit(
                "memory_revoke", query_id=entry.query_id,
                group=entry.group, **attrs)
        elif kind == "memory_kill":
            _obs_events.EVENTS.emit(
                "memory_kill", query_id=entry.query_id,
                group=entry.group, **attrs)
    if waves:
        with entry._lock:
            entry.replay_waves += waves


# ---------------------------------------------------------------------------

def reset() -> None:
    """Test hook: drop all entries, counters, samples, and disarm."""
    global _armed
    with _lock:
        _entries.clear()
        _aliases.clear()
    with _counter_lock:
        _slo_violations.clear()
        _latency_regressions.clear()
        _segment_baselines.clear()
        _armed = False
    for h in SLO_HISTOGRAMS:
        h.reset()
