"""Query-lifecycle span tracing.

Analog of airlift's trace-token propagation + the reference's per-operator
OperatorStats tree: the coordinator mints one trace per query, every
coordinator↔worker HTTP call carries the token in the `X-Presto-Tpu-Trace`
header, and each worker records its task's spans locally. After the result
stream completes the coordinator pulls every task's span dump and stitches
one query → stage → task → operator tree, served at
`/v1/query/{id}/trace`.

Span kinds:
  query            the coordinator-side root (covers plan + execute + merge)
  stage            synthesized per fragment (envelope of its task spans)
  task             one worker task execution
  operator         one plan node's aggregate batch-production wall
  compile          one XLA compile event inside a jitted program
  host_decode      one split's host-side decode (incl. selective cascade)
  device_transfer  host→device upload + readiness of one split's batch
  exchange_wait    time a consumer spent blocked on a pull exchange; on
                   the mesh path, one per fused-collective exchange site
                   with lane occupancy attrs (fid/bytes/lanes_used/util)
  lane_pack        zero-width marker describing a mesh exchange's packed
                   lane layout (dtype buckets, collectives, payload bytes)
  mesh_program     wall time of one fused mesh device program dispatch
                   (covers every exchange + breaker inside the shard_map)
  breaker_engine   zero-width marker: the CBO's hash-vs-sort verdict for
                   one breaker (attrs carry engine + why, incl. HBO
                   provenance)
  overflow_replay  zero-width marker: one capacity-regrow / fanout-widen
                   replay wave a breaker executed (the runtime cost of
                   estimate error; obs/runstats drives it to zero)
  memory_revoke    one memory-pressure event: a pool reserve() crossed
                   the revoke threshold and drove revokers toward the
                   target (attrs: reserved before/after, request, limit)
  memory_kill      zero-width marker on the victim query's trace: the
                   cluster low-memory killer failed it with
                   CLUSTER_OUT_OF_MEMORY (attrs point at the forensics
                   snapshot dumped by server/cluster_memory.py)
  hbm_sample       zero-width device memory watermark sample at a span
                   boundary (obs/devprof.py; attrs carry bytes_in_use /
                   peak or an honest available=false reason on CPU)

Everything is allocation-light: tracing disabled means every call site
talks to the module NOOP singleton (`enabled=False` short-circuits before
any work), so `ExecConfig.tracing=False` costs one attribute check.

Correlation with the serving-plane telemetry (obs/lifecycle.py): the
trace id IS the serving query id, so every record on the cluster event
stream (`/v1/events`) carries it as `traceToken` — a lifecycle
transition, admission rejection, SLO violation, or latency-regression
flag joins back to this span tree by token equality. obs/lifecycle's
`complete()` also walks the finished tree's span kinds
(overflow_replay / memory_revoke / memory_kill) to republish those
incidents on the event stream with the same token.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

# header carried on every coordinator↔worker HTTP call; value is
# "{trace_id}:{parent_span_id}" (parent = the coordinator's root span)
TRACE_HEADER = "X-Presto-Tpu-Trace"

_span_seq = itertools.count(1)
_trace_seq = itertools.count(1)
_PID = f"{os.getpid() & 0xFFFF:04x}"


def _new_span_id() -> str:
    return f"{_PID}-{next(_span_seq):x}"


def new_trace_id() -> str:
    return f"trace_{_PID}_{next(_trace_seq)}"


def format_token(trace_id: str, parent_span_id: Optional[str]) -> str:
    return f"{trace_id}:{parent_span_id or ''}"


def parse_token(token: str) -> Tuple[str, Optional[str]]:
    trace_id, _, parent = token.partition(":")
    return trace_id, (parent or None)


class Span:
    """One timed event. `end is None` means still open (never serialized
    that way by Tracer — spans are appended on close)."""

    __slots__ = ("span_id", "parent_id", "name", "kind", "start", "end",
                 "attrs")

    def __init__(self, span_id: str, parent_id: Optional[str], name: str,
                 kind: str, start: float, end: Optional[float] = None,
                 attrs: Optional[dict] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.start = start
        self.end = end
        self.attrs = attrs

    @property
    def duration_s(self) -> float:
        return max(0.0, (self.end if self.end is not None else self.start)
                   - self.start)

    def to_dict(self) -> dict:
        d = {"spanId": self.span_id, "parentId": self.parent_id,
             "name": self.name, "kind": self.kind,
             "start": self.start, "end": self.end,
             "durationS": round(self.duration_s, 6)}
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _NoopSpan:
    span_id = None
    parent_id = None
    name = kind = ""
    start = end = 0.0
    duration_s = 0.0
    attrs = None


_NOOP_SPAN = _NoopSpan()


class Tracer:
    """Thread-safe span sink for one trace. A per-thread span stack gives
    `span()` contexts their default parent; threads that never opened a
    span (prefetch producers, exchange pullers) parent to the trace root."""

    enabled = True

    def __init__(self, trace_id: Optional[str] = None, max_spans: int = 8192):
        self.trace_id = trace_id or new_trace_id()
        self.max_spans = max_spans
        self.root_id: Optional[str] = None
        self.dropped = 0
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_parent(self) -> Optional[str]:
        st = self._stack()
        return st[-1] if st else self.root_id

    @contextlib.contextmanager
    def span(self, name: str, kind: str, parent_id: Optional[str] = None,
             **attrs):
        sid = _new_span_id()
        st = self._stack()
        pid = parent_id if parent_id is not None else (
            st[-1] if st else self.root_id)
        if self.root_id is None:
            self.root_id = sid
        sp = Span(sid, pid, name, kind, time.time(), None, attrs or None)
        st.append(sid)
        try:
            yield sp
        finally:
            st.pop()
            sp.end = time.time()
            self._add(sp)

    def record(self, name: str, kind: str, start: float, end: float,
               parent_id: Optional[str] = None, **attrs) -> Span:
        """Append an already-completed span (no stack interaction beyond
        default parenting)."""
        pid = parent_id if parent_id is not None else self.current_parent()
        sp = Span(_new_span_id(), pid, name, kind, start, end, attrs or None)
        self._add(sp)
        return sp

    def _add(self, sp: Span):
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return
            self._spans.append(sp)

    def absorb(self, span_dicts: List[dict],
               parent_map: Optional[Dict[str, str]] = None):
        """Adopt spans serialized by another tracer (a worker task's dump).
        `parent_map` re-parents specific spans by their own span id —
        the coordinator uses it to hang task roots under synthesized
        stage spans."""
        for d in span_dicts or []:
            pid = d.get("parentId")
            sid = d.get("spanId") or _new_span_id()
            if parent_map and sid in parent_map:
                pid = parent_map[sid]
            self._add(Span(sid, pid, d.get("name") or "?",
                           d.get("kind") or "?",
                           float(d.get("start") or 0.0), d.get("end"),
                           d.get("attrs")))

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def token(self, parent_id: Optional[str] = None) -> str:
        return format_token(self.trace_id,
                            parent_id if parent_id is not None
                            else self.current_parent())

    def to_json(self) -> dict:
        spans = self.spans()
        return {
            "traceId": self.trace_id,
            "rootSpanId": self.root_id,
            "dropped": self.dropped,
            "spans": [s.to_dict() for s in spans],
            "tree": build_tree(spans),
        }


class NoopTracer:
    """`enabled=False` lets hot paths skip instrumentation entirely; the
    methods still exist so cold call sites need no branches."""

    enabled = False
    trace_id = ""
    root_id = None
    dropped = 0

    @contextlib.contextmanager
    def span(self, name, kind, parent_id=None, **attrs):
        yield _NOOP_SPAN

    def record(self, name, kind, start, end, parent_id=None, **attrs):
        return _NOOP_SPAN

    def absorb(self, span_dicts, parent_map=None):
        pass

    def current_parent(self):
        return None

    def spans(self):
        return []

    def token(self, parent_id=None):
        return ""

    def to_json(self):
        return {"traceId": "", "rootSpanId": None, "dropped": 0,
                "spans": [], "tree": []}


NOOP = NoopTracer()

# thread-local "current tracer" — lets deeply-buried code (jit compile
# detection, the selective-scan cascade) record spans without threading a
# tracer through every signature
_current = threading.local()


def current():
    return getattr(_current, "tracer", None) or NOOP


def set_current(tracer) -> None:
    _current.tracer = tracer


@contextlib.contextmanager
def use(tracer):
    prev = getattr(_current, "tracer", None)
    _current.tracer = tracer
    try:
        yield tracer
    finally:
        _current.tracer = prev


def build_tree(spans: List[Span]) -> List[dict]:
    """Nest spans by parent id; spans whose parent is unknown (foreign
    coordinator ids inside a worker dump, or None) become roots. Children
    sort by start time."""
    dicts = [s.to_dict() for s in spans]
    by_id = {d["spanId"]: d for d in dicts}
    roots: List[dict] = []
    for d in dicts:
        d.setdefault("children", [])
    for d in dicts:
        parent = by_id.get(d.get("parentId"))
        if parent is not None and parent is not d:
            parent["children"].append(d)
        else:
            roots.append(d)
    for d in dicts:
        d["children"].sort(key=lambda c: c["start"])
    roots.sort(key=lambda c: c["start"])
    return roots


class TraceRegistry:
    """Bounded query-id → Tracer map on the coordinator. Aliases let the
    session-level query id (what /v1/query serves) and the scheduler's
    internal per-attempt id (what task ids embed) resolve to one trace."""

    def __init__(self, max_traces: int = 200):
        self.max_traces = max_traces
        self._by_id: "OrderedDict[str, Tracer]" = OrderedDict()
        self._alias: Dict[str, str] = {}
        self._lock = threading.Lock()

    def register(self, tracer: Tracer, *aliases: str) -> None:
        with self._lock:
            self._by_id[tracer.trace_id] = tracer
            for a in aliases:
                self._alias[a] = tracer.trace_id
            while len(self._by_id) > self.max_traces:
                old, _ = self._by_id.popitem(last=False)
                self._alias = {a: t for a, t in self._alias.items()
                               if t != old}

    def alias(self, alias_id: str, trace_id: str) -> None:
        with self._lock:
            if trace_id in self._by_id:
                self._alias[alias_id] = trace_id

    def get(self, query_id: str) -> Optional[Tracer]:
        with self._lock:
            t = self._by_id.get(query_id)
            if t is not None:
                return t
            target = self._alias.get(query_id)
            return self._by_id.get(target) if target else None

    def latest(self) -> Optional[Tracer]:
        with self._lock:
            return next(reversed(self._by_id.values()), None) \
                if self._by_id else None
