"""Histogram metric families for the /v1/metrics plane.

The gauges/counters in server/metrics.py are derived on demand from status
structures; latencies need real distributions, so these families accumulate
process-wide with fixed log-spaced buckets (the airlift DistributionStat /
TimeStat analog, rendered as proper Prometheus `histogram` types).

Process-global on purpose: the in-process cluster runs coordinator and
workers in ONE process, so every observation carries a `plane` label and
each endpoint renders ONLY its own plane's series — a scraper reading both
endpoints never double-counts (same discipline as the plane-labeled scan
counters).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple


def log_buckets(lo: float, hi: float, per_decade: int = 3) -> List[float]:
    """Fixed log-spaced bucket bounds from lo to >= hi (3 significant
    digits so the rendered `le` values are stable and readable)."""
    out: List[float] = []
    ratio = 10.0 ** (1.0 / per_decade)
    v = float(lo)
    while v < hi * 1.0000001:
        b = float(f"{v:.3g}")
        if not out or b > out[-1]:
            out.append(b)
        v *= ratio
    return out


def _fmt_bound(v: float) -> str:
    s = f"{v:.12g}"
    return s


class Histogram:
    """One metric family; per-labelset cumulative-bucket series."""

    def __init__(self, name: str, help_text: str, buckets: List[float]):
        self.name = name
        self.help_text = help_text
        self.buckets = sorted(buckets)
        self._lock = threading.Lock()
        # labels tuple -> {"counts": per-bucket (+inf last), "sum", "count"}
        self._series: Dict[tuple, dict] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0,
                }
            s["counts"][bisect.bisect_left(self.buckets, value)] += 1
            s["sum"] += float(value)
            s["count"] += 1

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self, plane: Optional[str] = None) -> Dict[tuple, dict]:
        with self._lock:
            out = {}
            for key, s in self._series.items():
                if plane is not None and dict(key).get("plane") != plane:
                    continue
                out[key] = {"counts": list(s["counts"]), "sum": s["sum"],
                            "count": s["count"]}
            return out

    def render(self, plane: Optional[str] = None) -> List[str]:
        """Exposition lines for one plane (declares the family even when it
        has no samples yet, as a zeroed series, so scrapers see stable
        families)."""
        from presto_tpu.server.metrics import _fmt

        series = self.snapshot(plane)
        if not series and plane is not None:
            series = {(("plane", plane),): {
                "counts": [0] * (len(self.buckets) + 1),
                "sum": 0.0, "count": 0}}
        lines = [f"# HELP {self.name} {self.help_text}",
                 f"# TYPE {self.name} histogram"]
        for key in sorted(series):
            s = series[key]
            labels = dict(key)
            cum = 0
            for bound, n in zip(self.buckets, s["counts"]):
                cum += n
                lines.append(_fmt(f"{self.name}_bucket", cum,
                                  {**labels, "le": _fmt_bound(bound)}))
            lines.append(_fmt(f"{self.name}_bucket", s["count"],
                              {**labels, "le": "+Inf"}))
            lines.append(_fmt(f"{self.name}_sum", f"{s['sum']:.9g}", labels))
            lines.append(_fmt(f"{self.name}_count", s["count"], labels))
        return lines


QUERY_LATENCY = Histogram(
    "presto_tpu_query_latency_seconds",
    "end-to-end query wall time (create to terminal state)",
    log_buckets(0.01, 600.0))
TASK_SCHEDULE_DELAY = Histogram(
    "presto_tpu_task_schedule_delay_seconds",
    "delay between task creation on the worker and execution start",
    log_buckets(0.0001, 60.0))
BATCH_KERNEL_WALL = Histogram(
    "presto_tpu_batch_kernel_wall_seconds",
    "wall time producing one operator output batch",
    log_buckets(0.0001, 60.0))
EXCHANGE_WAIT = Histogram(
    "presto_tpu_exchange_wait_seconds",
    "time a consumer spent blocked waiting on a pull-exchange page",
    log_buckets(0.0001, 60.0))
RADIX_PARTITION_ROWS = Histogram(
    "presto_tpu_radix_partition_rows",
    "rows per radix partition at a partitioned breaker (skew shows as a "
    "wide spread)",
    log_buckets(1.0, 1e8))
COMPILE_TRACE_WALL = Histogram(
    "presto_tpu_compile_trace_wall_seconds",
    "wall time of one XLA trace+compile event observed by the program "
    "cache (exec/programs.py)",
    log_buckets(0.001, 600.0))
STATS_DRIFT = Histogram(
    "presto_tpu_stats_drift_ratio",
    "observed/estimated ratio at a stats-driven decision site "
    "(obs/runstats.py; 1.0 = perfect estimate, labeled by operator "
    "class and decision site)",
    log_buckets(0.01, 100.0))
LEDGER_DRIFT = Histogram(
    "presto_tpu_memory_ledger_drift_ratio",
    "device-reported peak HBM bytes over the MemoryPool ledger's "
    "self-reported peak (obs/devprof.py reconciliation; 1.0 = the "
    "accounting matches the hardware, labeled by reconciliation site)",
    log_buckets(0.01, 100.0))
SPILLED_BYTES = Histogram(
    "presto_tpu_spilled_bytes",
    "bytes written to host spill per spilling operator (hybrid hash "
    "join builds/probes and grace-agg partitions, labeled by operator "
    "side; heavy right tails mean partition budgets are mis-sized)",
    log_buckets(1024.0, 1e12))
FARM_WARM_WALL = Histogram(
    "presto_tpu_farm_warm_wall_seconds",
    "wall time of one compile-farm warm task (boot arming or queue-wait "
    "speculation; exec/farm.py — compile cost the farm absorbed off the "
    "query critical path)",
    log_buckets(0.001, 600.0))

ALL_HISTOGRAMS: Tuple[Histogram, ...] = (
    QUERY_LATENCY, TASK_SCHEDULE_DELAY, BATCH_KERNEL_WALL, EXCHANGE_WAIT,
    RADIX_PARTITION_ROWS, COMPILE_TRACE_WALL, STATS_DRIFT, LEDGER_DRIFT,
    SPILLED_BYTES)

# rendered only once the compile farm has done anything, so an unarmed
# scrape's family set stays bit-for-bit pre-farm
_ARMED_HISTOGRAMS: Tuple[Histogram, ...] = (FARM_WARM_WALL,)


def render_histograms(plane: str) -> str:
    """All histogram families for one plane ('coordinator' | 'worker'),
    ready to append to a render_metrics document."""
    lines: List[str] = []
    for h in ALL_HISTOGRAMS:
        lines.extend(h.render(plane))
    try:
        from presto_tpu.exec import farm as _farm

        if _farm.armed():
            for h in _ARMED_HISTOGRAMS:
                lines.extend(h.render(plane))
    except Exception:
        pass
    return "\n".join(lines) + "\n"


def reset() -> None:
    """Test hook — zero every histogram family."""
    for h in ALL_HISTOGRAMS + _ARMED_HISTOGRAMS:
        h.reset()
