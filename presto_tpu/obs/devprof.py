"""Device cost & HBM accounting plane (devprof).

Everything the engine knew about memory was self-reported (memory.py
pool reservations) and everything it knew about hardware efficiency was
hand-derived offline (BENCH_NOTES utilization math). This module is the
device-side truth plane:

  * **per-program cost/memory analysis** — every program the structural
    cache (exec/programs.py) compiles is lowered once more and asked for
    its XLA ``cost_analysis()`` (FLOPs, bytes accessed) and
    ``memory_analysis()`` (argument / output / temp / generated-code
    bytes), recorded here keyed on the PR 5 structural fingerprint. Span
    wall times from the tracer turn those into achieved-FLOP/s,
    achieved-bytes/s and arithmetic intensity (roofline) per operator
    and per query;
  * **HBM watermark sampling** — ``device.memory_stats()`` at span
    boundaries plus a background cadence, with honest ``unavailable``
    labeling when the backend has no device memory introspection (CPU
    fallback — the same policy bench.py applies to its device probe);
  * **ledger-vs-device reconciliation** — the sampled device watermark
    against the MemoryPool ledger's own peak, exported as the
    ``presto_tpu_memory_ledger_drift_ratio`` histogram: it catches
    accounting bugs the way the stats-drift histogram catches
    cardinality bugs;
  * **on-demand ``jax.profiler`` captures** — a per-query registry of
    profile dumps (the ``profile`` session property), surfaced as
    ``profileUri`` next to ``traceUri`` on ``/v1/statement``.

Process-global like the compile plane it mirrors, and strictly opt-in:
until :func:`activate` runs (the ``devprof`` ExecConfig field /
session property is ``"on"``), every hook is a single boolean check and
the engine behaves bit-for-bit as if this module did not exist. The
latch is sticky for the process once requested — same lifecycle as the
program cache — and :func:`deactivate` is the test hook that re-arms
the strict no-op contract. The provider behind HBM sampling is
pluggable (:func:`set_provider`) so reconciliation is unit-testable
off-device.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from presto_tpu.obs import metrics as _obs_metrics
from presto_tpu.obs import trace as _obs_trace

_LOCK = threading.Lock()
_ACTIVE = False

# structural fingerprint -> one program's device profile:
# {"flops", "bytes_accessed", "argument_bytes", "output_bytes",
#  "temp_bytes", "generated_code_bytes", "footprint_bytes", "calls",
#  "kind", "key"}  (numeric fields max-merge across recompiles — the
#  worst compiled shape is the capacity-relevant one)
_programs: Dict[str, Dict[str, Any]] = {}

_counters: Dict[str, int] = {
    # programs whose lowering yielded at least a cost or memory analysis
    "programs_analyzed": 0,
    # lowering/analysis attempts the backend could not answer
    "analysis_unavailable": 0,
    # HBM watermark samples taken (background cadence + span boundaries)
    "hbm_samples": 0,
    # samples answered with "no device memory introspection here"
    "hbm_unavailable": 0,
    # ledger-vs-device reconciliations performed
    "reconciliations": 0,
    # fused-window stagings accounted through note_staging()
    "staging_windows": 0,
}

# device watermark state (high-water across samples since activate/reset)
_hbm: Dict[str, Any] = {
    "available": None,          # None = never sampled, False = no device
    "reason": None,             # why unavailable, honest label
    "platform": None,
    "bytes_in_use": 0,
    "peak_bytes_in_use": 0,
    "bytes_limit": 0,
}

# fused-window device staging (fragment_jit) high-water accounting
_staging: Dict[str, float] = {"bytes_total": 0.0, "peak_window_bytes": 0.0}

# fingerprints whose lazy analysis came back empty — never retried (a
# backend that can't answer once won't answer on the next dispatch either,
# and the lowering attempt is not free)
_analysis_failed: set = set()

# fingerprints whose lazy analysis is running RIGHT NOW on some thread:
# on_call claims the fingerprint under _LOCK before lowering, so N
# concurrent dispatches of a never-seen program lower it exactly once
# instead of N times (lowering is the expensive step)
_analysis_inflight: set = set()

# query_id -> jax.profiler dump directory (profile session property)
_query_profiles: Dict[str, str] = {}

# pluggable memory_stats source: () -> Optional[dict]; None = default
_provider: Optional[Callable[[], Optional[dict]]] = None

_sampler_thread: Optional[threading.Thread] = None
_sampler_stop = threading.Event()
_SAMPLE_PERIOD_S = float(os.environ.get("PRESTO_TPU_DEVPROF_SAMPLE_S",
                                        "0.5"))


def active() -> bool:
    """The one check every hot-path hook performs. False = strict no-op."""
    return _ACTIVE


def activate() -> None:
    """Arm the plane (devprof=on saw a plan install). Sticky for the
    process, like the program cache; starts the background HBM sampler."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE:
            return
        _ACTIVE = True
    _start_sampler()


def deactivate() -> None:
    """Test hook: disarm and stop the sampler so a later devprof=off run
    can assert the strict no-op contract."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = False
    _stop_sampler()


def reset() -> None:
    """Test hook: deactivate and clear all recorded state."""
    deactivate()
    with _LOCK:
        _programs.clear()
        _analysis_failed.clear()
        _analysis_inflight.clear()
        _query_profiles.clear()
        for k in _counters:
            _counters[k] = 0
        _hbm.update(available=None, reason=None, platform=None,
                    bytes_in_use=0, peak_bytes_in_use=0, bytes_limit=0)
        _staging.update(bytes_total=0.0, peak_window_bytes=0.0)


# -- HBM watermark sampling ---------------------------------------------------


def set_provider(fn: Optional[Callable[[], Optional[dict]]]) -> None:
    """Override the device memory_stats source (tests: a fake provider
    makes reconciliation deterministic off-device). None restores the
    real ``jax.local_devices()[0].memory_stats()``."""
    global _provider
    with _LOCK:
        _provider = fn
        # a new source invalidates the old watermark + availability label
        _hbm.update(available=None, reason=None,
                    bytes_in_use=0, peak_bytes_in_use=0, bytes_limit=0)


def _default_provider() -> Optional[dict]:
    import jax

    dev = jax.local_devices()[0]
    platform = getattr(dev, "platform", None)
    # runs outside sample_hbm's critical section (providers are called
    # unlocked so a slow backend can't stall readers), so the label
    # write takes the lock itself
    with _LOCK:
        _hbm["platform"] = platform
    return dev.memory_stats()


def sample_hbm(tag: Optional[str] = None) -> Dict[str, Any]:
    """Take one device memory sample, fold it into the watermark, and —
    when a tracer is live and a tag names the boundary — record an
    ``hbm_sample`` span so the sample lands in the query timeline.
    Honest on CPU: a backend without memory_stats() yields an
    ``available: false`` doc with the reason, never fabricated zeros."""
    now = time.time()
    prov = _provider or _default_provider
    try:
        stats = prov()
        err = None
    except Exception as e:  # no devices / backend without introspection
        stats, err = None, f"{type(e).__name__}: {e}"
    with _LOCK:
        _counters["hbm_samples"] += 1
        if not stats:
            _counters["hbm_unavailable"] += 1
            if _hbm["available"] is None:
                _hbm["available"] = False
                _hbm["reason"] = (err or "backend reports no memory_stats "
                                  "(CPU fallback)")
        else:
            in_use = int(stats.get("bytes_in_use", 0) or 0)
            peak = int(stats.get("peak_bytes_in_use", in_use) or in_use)
            _hbm["available"] = True
            _hbm["reason"] = None
            _hbm["bytes_in_use"] = in_use
            _hbm["peak_bytes_in_use"] = max(
                int(_hbm["peak_bytes_in_use"]), peak, in_use)
            _hbm["bytes_limit"] = int(stats.get(
                "bytes_limit", _hbm["bytes_limit"]) or _hbm["bytes_limit"])
        doc = _hbm_doc_locked()
    if tag is not None:
        tr = _obs_trace.current()
        if tr.enabled:
            tr.record("hbm_sample", "hbm_sample", now, now, tag=tag, **{
                k: v for k, v in doc.items() if v is not None})
    return doc


def _hbm_doc_locked() -> Dict[str, Any]:
    if _hbm["available"]:
        return {"available": True, "platform": _hbm["platform"],
                "bytesInUse": _hbm["bytes_in_use"],
                "peakBytesInUse": _hbm["peak_bytes_in_use"],
                "bytesLimit": _hbm["bytes_limit"] or None}
    return {"available": False, "platform": _hbm["platform"],
            "reason": _hbm["reason"] or "never sampled"}


def device_memory_doc() -> Dict[str, Any]:
    """The current device memory document for status/heartbeat payloads
    (worker /v1/status → cluster heartbeat → /v1/memory rollup)."""
    with _LOCK:
        return _hbm_doc_locked()


def _start_sampler() -> None:
    global _sampler_thread
    if _SAMPLE_PERIOD_S <= 0:
        return
    _sampler_stop.clear()

    def loop():
        while not _sampler_stop.wait(_SAMPLE_PERIOD_S):
            if not _ACTIVE:
                break
            doc = sample_hbm()
            if not doc.get("available"):
                # no introspection on this backend: one honest sample is
                # the whole story, polling it again is pure overhead
                break

    t = threading.Thread(target=loop, daemon=True, name="devprof-hbm")
    with _LOCK:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return
        _sampler_thread = t
    t.start()


def _stop_sampler() -> None:
    global _sampler_thread
    _sampler_stop.set()
    with _LOCK:
        _sampler_thread = None


# -- per-program XLA cost / memory analysis ----------------------------------


def _first_dict(obj) -> Optional[dict]:
    """cost_analysis() is a dict on Lowered and a list of dicts on
    Compiled across jax versions — accept both shapes."""
    if isinstance(obj, dict):
        return obj
    if isinstance(obj, (list, tuple)) and obj and isinstance(obj[0], dict):
        return obj[0]
    return None


def analyze_lowered(lowered) -> Dict[str, Any]:
    """Cost + memory analysis of one jax Lowered. The cost side is free;
    the memory side pays one ``.compile()`` (served by the persistent
    XLA cache when PRESTO_TPU_CACHE_DIR is set) — acceptable because the
    whole plane is opt-in. Missing pieces are recorded as absent, never
    guessed."""
    rec: Dict[str, Any] = {}
    try:
        ca = _first_dict(lowered.cost_analysis())
        if ca:
            if ca.get("flops") is not None:
                rec["flops"] = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                rec["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = lowered.compile().memory_analysis()
        if ma is not None:
            arg = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
            out = float(getattr(ma, "output_size_in_bytes", 0) or 0)
            tmp = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
            rec["argument_bytes"] = arg
            rec["output_bytes"] = out
            rec["temp_bytes"] = tmp
            rec["generated_code_bytes"] = float(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0)
            # the program's device-resident footprint while it runs
            rec["footprint_bytes"] = arg + out + tmp
    except Exception:
        pass
    return rec


def record_program(fp: str, rec: Dict[str, Any], kind: str = "",
                   key: str = "") -> Optional[Dict[str, Any]]:
    """Merge one program's analysis into the store (numerics max-merge:
    across recompiles the worst shape is the one capacity planning must
    survive). Returns the merged record, or None for an empty analysis."""
    if not rec:
        with _LOCK:
            _counters["analysis_unavailable"] += 1
        return None
    with _LOCK:
        ent = _programs.get(fp)
        if ent is None:
            ent = _programs[fp] = {"kind": kind, "key": key, "calls": 0}
            _counters["programs_analyzed"] += 1
        for k, v in rec.items():
            if isinstance(v, (int, float)):
                ent[k] = max(float(ent.get(k) or 0.0), float(v))
            else:
                ent[k] = v
        return dict(ent)


def on_compile(entry, node_kind: str, key: str, args, kw,
               node_stats: Optional[Dict[str, float]] = None) -> None:
    """Compile-plane hook (exec/programs.wrap, delta>0 branch): the
    program just compiled for these concrete args — lower it once more
    and record its XLA cost/memory analysis. Also stamps the calling
    node's ``_jit_stats`` view so EXPLAIN ANALYZE and the worker stats
    rows can attribute device numbers per operator."""
    if not _ACTIVE:
        return
    fp = getattr(entry, "fp", None) or f"private|{node_kind}|{key}"
    try:
        rec = analyze_lowered(entry.jfn.lower(*args, **kw))
    except Exception:
        rec = {}
    merged = record_program(fp, rec, kind=node_kind, key=key)
    if merged and node_stats is not None:
        for k in ("flops", "bytes_accessed", "footprint_bytes"):
            if merged.get(k) is not None:
                node_stats[k] = max(float(node_stats.get(k) or 0.0),
                                    float(merged[k]))


def on_call(entry, node_kind: str = "", key: str = "", args=(), kw=None,
            node_stats: Optional[Dict[str, float]] = None) -> None:
    """Per-call hook (every wrapped dispatch while active): count calls
    per program so roofline totals weight each program by how often it
    actually ran. A fingerprint never seen before is analyzed lazily —
    the program may have compiled before the plane activated (the cache
    deliberately does not fork on the devprof knob), and its analysis
    must not be lost to activation order."""
    if not _ACTIVE:
        return
    fp = getattr(entry, "fp", None) or (f"private|{node_kind}|{key}"
                                        if node_kind else None)
    if fp is None:
        return
    with _LOCK:
        ent = _programs.get(fp)
        if ent is not None:
            ent["calls"] = int(ent.get("calls") or 0) + 1
            merged = dict(ent)
        elif fp in _analysis_failed or fp in _analysis_inflight:
            # failed: never retried. inflight: another dispatch claimed
            # the lowering in this same critical section — its record
            # (or failure mark) will land; duplicating the work here is
            # exactly the check-then-act race this claim closes
            return
        else:
            _analysis_inflight.add(fp)
            merged = None
    if merged is None:
        try:
            try:
                rec = analyze_lowered(entry.jfn.lower(*args, **(kw or {})))
            except Exception:
                rec = {}
            merged = record_program(fp, rec, kind=node_kind, key=key)
        finally:
            with _LOCK:
                # only the thread that claimed fp in the first critical
                # section reaches this discard — the claim protocol, not
                # the lock scope, closes the window
                _analysis_inflight.discard(fp)  # lint: allow(check-then-act)
        if merged is None:
            with _LOCK:
                # safe outside the claiming section: only the thread
                # holding the in-flight claim for fp can reach this add
                _analysis_failed.add(fp)  # lint: allow(check-then-act)
            return
        with _LOCK:
            ent = _programs.get(fp)
            if ent is not None:
                ent["calls"] = int(ent.get("calls") or 0) + 1
    if node_stats is not None:
        # stamp the calling node's stats view every dispatch, not only on
        # first analysis — EXPLAIN ANALYZE task nodes are fresh instances
        # per run while the program record is process-wide
        for k in ("flops", "bytes_accessed", "footprint_bytes"):
            if merged.get(k) is not None:
                node_stats[k] = max(float(node_stats.get(k) or 0.0),
                                    float(merged[k]))


def note_staging(window_bytes: float) -> None:
    """fragment_jit hook: one fused window's stacked batches are about to
    stage onto the device — account the bytes (total shipped + worst
    single window, the fused path's device-residency high-water)."""
    if not _ACTIVE:
        return
    with _LOCK:
        _counters["staging_windows"] += 1
        _staging["bytes_total"] += float(window_bytes)
        _staging["peak_window_bytes"] = max(
            _staging["peak_window_bytes"], float(window_bytes))


# -- ledger-vs-device reconciliation -----------------------------------------


def reconcile(pool, plane: str = "worker",
              site: str = "query") -> Optional[Dict[str, Any]]:
    """Compare the device HBM watermark against the MemoryPool ledger's
    self-reported peak and feed the drift histogram. Returns the
    reconciliation doc, or None when either side has nothing to say
    (no device introspection, or a ledger that never reserved)."""
    if not _ACTIVE or pool is None:
        return None
    doc = sample_hbm()
    ledger_peak = float(getattr(pool, "peak", 0) or 0)
    if not doc.get("available") or ledger_peak <= 0:
        return None
    device_peak = float(doc.get("peakBytesInUse") or 0)
    if device_peak <= 0:
        return None
    ratio = device_peak / ledger_peak
    with _LOCK:
        _counters["reconciliations"] += 1
    _obs_metrics.LEDGER_DRIFT.observe(ratio, plane=plane, site=site)
    return {"devicePeakBytes": device_peak, "ledgerPeakBytes": ledger_peak,
            "driftRatio": ratio}


# -- per-query jax.profiler captures -----------------------------------------


def register_profile(query_id: str, path: str) -> None:
    with _LOCK:
        _query_profiles[query_id] = path
        # bounded like the trace registry — oldest captures age out
        while len(_query_profiles) > 200:
            _query_profiles.pop(next(iter(_query_profiles)))


def profile_for(query_id: str) -> Optional[str]:
    with _LOCK:
        return _query_profiles.get(query_id)


# -- exposure: summaries, metrics, rollups -----------------------------------


def programs_profile() -> Dict[str, Dict[str, Any]]:
    """Copy of the per-fingerprint program store (tests/bench)."""
    with _LOCK:
        return {fp: dict(ent) for fp, ent in _programs.items()}


def snapshot() -> Dict[str, Any]:
    with _LOCK:
        return {"active": _ACTIVE, "counters": dict(_counters),
                "hbm": _hbm_doc_locked(), "staging": dict(_staging),
                "programs": {fp: dict(e) for fp, e in _programs.items()}}


def summary(wall_s: Optional[float] = None) -> Dict[str, Any]:
    """Roofline rollup over every analyzed program, call-weighted: total
    device FLOPs and bytes actually dispatched, arithmetic intensity,
    and — given a wall time — achieved FLOP/s and bytes/s. This is what
    bench.py emits instead of hand-derived utilization numbers."""
    with _LOCK:
        n = len(_programs)
        flops = sum((e.get("flops") or 0.0) * max(int(e.get("calls") or 0), 1)
                    for e in _programs.values())
        byts = sum((e.get("bytes_accessed") or 0.0)
                   * max(int(e.get("calls") or 0), 1)
                   for e in _programs.values())
        peak_fp = max((e.get("footprint_bytes") or 0.0
                       for e in _programs.values()), default=0.0)
        calls = sum(int(e.get("calls") or 0) for e in _programs.values())
        hbm = _hbm_doc_locked()
        staging = dict(_staging)
        counters = dict(_counters)
    out: Dict[str, Any] = {
        "programs": n, "calls": calls,
        "total_flops": flops, "total_bytes_accessed": byts,
        "arithmetic_intensity": (flops / byts) if byts else None,
        "peak_program_footprint_bytes": peak_fp,
        "staging": staging, "device": hbm,
        "analysis_unavailable": counters["analysis_unavailable"],
    }
    if wall_s and wall_s > 0:
        out["achieved_flops_per_s"] = flops / wall_s
        out["achieved_bytes_per_s"] = byts / wall_s
    return out


_HELP = {
    "presto_tpu_devprof_programs_analyzed":
        "compiled programs with a recorded XLA cost/memory analysis",
    "presto_tpu_devprof_analysis_unavailable_total":
        "program analyses the backend could not answer",
    "presto_tpu_devprof_hbm_samples_total":
        "device memory_stats() watermark samples taken",
    "presto_tpu_devprof_hbm_unavailable_total":
        "samples where the backend had no device memory introspection",
    "presto_tpu_devprof_reconciliations_total":
        "ledger-vs-device peak reconciliations performed",
    "presto_tpu_devprof_total_flops":
        "call-weighted XLA-analyzed FLOPs across all recorded programs",
    "presto_tpu_devprof_total_bytes_accessed":
        "call-weighted XLA-analyzed bytes accessed across all programs",
    "presto_tpu_devprof_peak_program_footprint_bytes":
        "largest single-program device footprint (args+outputs+temps)",
    "presto_tpu_devprof_hbm_peak_bytes":
        "device-reported peak bytes in use (0 when unavailable)",
}


def metric_rows(labels: Dict[str, str]) -> List[Tuple]:
    """Rows for server.metrics.render_metrics on both /v1/metrics planes.
    Empty until the plane activates — the families appear only once
    devprof=on has run, keeping devprof=off scrapes byte-identical."""
    with _LOCK:
        if not _ACTIVE and not _counters["programs_analyzed"] \
                and not _counters["hbm_samples"]:
            return []
        c = dict(_counters)
    s = summary()
    rows: List[Tuple] = [
        ("presto_tpu_devprof_programs_analyzed",
         _HELP["presto_tpu_devprof_programs_analyzed"],
         s["programs"], dict(labels), "gauge"),
        ("presto_tpu_devprof_analysis_unavailable_total",
         _HELP["presto_tpu_devprof_analysis_unavailable_total"],
         c["analysis_unavailable"], dict(labels), "counter"),
        ("presto_tpu_devprof_hbm_samples_total",
         _HELP["presto_tpu_devprof_hbm_samples_total"],
         c["hbm_samples"], dict(labels), "counter"),
        ("presto_tpu_devprof_hbm_unavailable_total",
         _HELP["presto_tpu_devprof_hbm_unavailable_total"],
         c["hbm_unavailable"], dict(labels), "counter"),
        ("presto_tpu_devprof_reconciliations_total",
         _HELP["presto_tpu_devprof_reconciliations_total"],
         c["reconciliations"], dict(labels), "counter"),
        ("presto_tpu_devprof_total_flops",
         _HELP["presto_tpu_devprof_total_flops"],
         s["total_flops"], dict(labels), "gauge"),
        ("presto_tpu_devprof_total_bytes_accessed",
         _HELP["presto_tpu_devprof_total_bytes_accessed"],
         s["total_bytes_accessed"], dict(labels), "gauge"),
        ("presto_tpu_devprof_peak_program_footprint_bytes",
         _HELP["presto_tpu_devprof_peak_program_footprint_bytes"],
         s["peak_program_footprint_bytes"], dict(labels), "gauge"),
        ("presto_tpu_devprof_hbm_peak_bytes",
         _HELP["presto_tpu_devprof_hbm_peak_bytes"],
         (s["device"].get("peakBytesInUse") or 0)
         if s["device"].get("available") else 0,
         {**labels, "available": str(bool(
             s["device"].get("available"))).lower()}, "gauge"),
    ]
    return rows
