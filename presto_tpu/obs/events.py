"""Query-event listener sinks + the unified cluster event stream.

The QueryManager fires `(event, QueryInfo)` listeners (the EventListener
SPI's QueryCompletedEvent analog). This module's SlowQueryLogger is the
standard sink: a structured JSONL stream of completed queries over a
latency threshold, each record carrying the top-k most expensive spans
inline so a slow query is diagnosable from the log alone — no trace
endpoint round trip.

ClusterEventStream is the serving-plane's unified feed (`GET /v1/events`):
a bounded in-memory ring buffer — lifecycle transitions, admission
rejections, memory revokes/kills, overflow-replay waves, SLO violations,
and latency-regression flags — with an optional JSONL sink. Every record
carries the query's trace token for span correlation.

Both JSONL sinks append with a single `os.write` to an `O_APPEND` fd
under the shared `fcntl` flock from obs.runstats, so multiple server
processes can share one file without torn lines.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from presto_tpu.obs.runstats import _flock, _funlock


def _append_line(path: str, line: str) -> None:
    """Cross-process-safe JSONL append: one `os.write` of the whole
    record to an `O_APPEND` fd while holding the shared flock (the HBO
    compactor takes it exclusively, so appends never interleave with a
    rewrite)."""
    lock_fd = _flock(path, exclusive=False)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            os.write(fd, (line + "\n").encode("utf-8"))
        finally:
            os.close(fd)
    finally:
        _funlock(lock_fd)


class SlowQueryLogger:
    """Append one JSONL record per completed query whose wall time crossed
    `threshold_s` (0.0 = log every completion)."""

    def __init__(self, path: str, threshold_s: float = 0.0, top_k: int = 5):
        self.path = path
        self.threshold_s = threshold_s
        self.top_k = top_k
        self._lock = threading.Lock()

    def log(self, info, spans: Optional[list] = None,
            memory: Optional[dict] = None,
            extra: Optional[dict] = None) -> None:
        """`info` is a querymanager.QueryInfo; `spans` the query's trace
        spans (obs.trace.Span), when tracing captured any; `memory` an
        optional devprof-plane doc (per-query peak/footprint bytes +
        device stats) folded into the record; `extra` optional top-level
        annotations (e.g. the lifecycle plane's latency-regression
        flag)."""
        elapsed = max(0.0, (info.end_time or time.time()) - info.create_time)
        if elapsed < self.threshold_s:
            return
        top: List[dict] = []
        engines: List[dict] = []
        lane_util: List[dict] = []
        revokes = 0
        revoked_bytes = 0
        kills: List[dict] = []
        replays = 0
        boosts = 0
        spill_repartitions = 0
        spill_revokes = 0
        spill_reversals = 0
        if spans:
            closed = [s for s in spans if s.end is not None]
            closed.sort(key=lambda s: s.duration_s, reverse=True)
            for s in closed[:self.top_k]:
                d = {"name": s.name, "kind": s.kind,
                     "durationS": round(s.duration_s, 6)}
                if s.attrs:
                    d["attrs"] = s.attrs
                top.append(d)
            # breaker/exchange verdict markers (obs/runstats plane): the
            # CBO choices and replay waves behind a slow query, inline
            for s in spans:
                a = s.attrs or {}
                if s.kind == "breaker_engine":
                    engines.append({"node": a.get("node"),
                                    "engine": a.get("engine"),
                                    "why": a.get("why")})
                elif s.kind == "exchange_wait" and "util" in a:
                    lane_util.append({"fid": a.get("fid"),
                                      "lanesUsed": a.get("lanes_used"),
                                      "lanesTotal": a.get("lanes_total"),
                                      "util": a.get("util")})
                elif s.kind == "overflow_replay":
                    replays += 1
                    if a.get("cap_to"):
                        boosts += 1
                elif s.kind == "memory_revoke":
                    # devprof plane: memory pressure behind a slow query
                    revokes += 1
                    before = a.get("reserved_before") or 0
                    after = a.get("reserved_after") or 0
                    revoked_bytes += max(0, int(before) - int(after))
                elif s.kind == "memory_kill":
                    kills.append({"reason": a.get("reason"),
                                  "forensics": a.get("forensics")})
                elif s.kind == "spill_repartition":
                    # dynamic hybrid hash plane: a slow query that spent
                    # its time splitting skewed spill partitions says so
                    # from the log alone
                    spill_repartitions += 1
                elif s.kind == "spill_revoke":
                    spill_revokes += 1
                elif s.kind == "spill_role_reversal":
                    spill_reversals += 1
        rec = {
            "event": "queryCompleted",
            "ts": time.time(),
            "queryId": info.query_id,
            "state": info.state,
            "user": info.user,
            "sql": info.sql,
            "elapsedS": round(elapsed, 6),
            "error": info.error,
            "topSpans": top,
        }
        if engines:
            rec["breakerEngines"] = engines
        if lane_util:
            rec["laneUtil"] = lane_util
        if replays:
            rec["overflowReplays"] = replays
            rec["overflowBoosts"] = boosts
        if revokes:
            rec["memoryRevokes"] = revokes
            rec["memoryRevokedBytes"] = revoked_bytes
        if kills:
            rec["memoryKills"] = kills
        if spill_repartitions or spill_revokes or spill_reversals:
            rec["spill"] = {"repartitions": spill_repartitions,
                            "revocations": spill_revokes,
                            "roleReversals": spill_reversals}
        if memory:
            # peak/footprint fields from the devprof memory rollup
            rec["memory"] = memory
        if extra:
            rec.update(extra)
        line = json.dumps(rec, default=str)
        with self._lock:
            _append_line(self.path, line)


class ClusterEventStream:
    """Bounded ring buffer of cluster events + optional JSONL sink.

    `emit` is cheap and never raises toward the serving path: sink IO
    errors are swallowed (the in-memory ring still gets the record).
    Sequence numbers are monotonically increasing for the process
    lifetime, so `events(since=seq)` is a stable resume cursor.
    """

    def __init__(self, capacity: int = 2048, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._buf: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=capacity)
        self._seq = 0
        self.path = path

    def configure(self, path: Optional[str] = None,
                  capacity: Optional[int] = None) -> None:
        with self._lock:
            if path is not None:
                self.path = path
            if capacity is not None and capacity != self._buf.maxlen:
                self._buf = collections.deque(self._buf, maxlen=capacity)

    def emit(self, kind: str, query_id: Optional[str] = None,
             **attrs) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"ts": round(time.time(), 6), "kind": kind}
        if query_id is not None:
            rec["queryId"] = query_id
            # trace ids are minted as the serving query id, so the query
            # id doubles as the trace token for span correlation
            rec["traceToken"] = query_id
        rec.update(attrs)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._buf.append(rec)
            path = self.path
        if path:
            try:
                _append_line(path, json.dumps(rec, default=str))
            except OSError:
                pass
        return rec

    def events(self, since: int = 0, query_id: Optional[str] = None,
               kind: Optional[str] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
        """Events with seq > ``since``, oldest first, at most ``limit`` —
        a full page means more may follow, so advancing ``since`` to the
        page's last seq never skips events the ring still holds."""
        with self._lock:
            out = [dict(r) for r in self._buf if r["seq"] > since]
        if query_id is not None:
            out = [r for r in out if r.get("queryId") == query_id]
        if kind is not None:
            out = [r for r in out if r.get("kind") == kind]
        return out[:limit]

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        """Test hook: drop buffered events (seq keeps counting)."""
        with self._lock:
            self._buf.clear()


#: process-global stream — one serving plane per process; the coordinator
#: configures the JSONL sink at construction when `events_log=` is set
EVENTS = ClusterEventStream()
