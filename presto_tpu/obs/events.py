"""Query-event listener sinks.

The QueryManager fires `(event, QueryInfo)` listeners (the EventListener
SPI's QueryCompletedEvent analog). This module's SlowQueryLogger is the
standard sink: a structured JSONL stream of completed queries over a
latency threshold, each record carrying the top-k most expensive spans
inline so a slow query is diagnosable from the log alone — no trace
endpoint round trip.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional


class SlowQueryLogger:
    """Append one JSONL record per completed query whose wall time crossed
    `threshold_s` (0.0 = log every completion)."""

    def __init__(self, path: str, threshold_s: float = 0.0, top_k: int = 5):
        self.path = path
        self.threshold_s = threshold_s
        self.top_k = top_k
        self._lock = threading.Lock()

    def log(self, info, spans: Optional[list] = None,
            memory: Optional[dict] = None) -> None:
        """`info` is a querymanager.QueryInfo; `spans` the query's trace
        spans (obs.trace.Span), when tracing captured any; `memory` an
        optional devprof-plane doc (per-query peak/footprint bytes +
        device stats) folded into the record."""
        elapsed = max(0.0, (info.end_time or time.time()) - info.create_time)
        if elapsed < self.threshold_s:
            return
        top: List[dict] = []
        engines: List[dict] = []
        lane_util: List[dict] = []
        revokes = 0
        revoked_bytes = 0
        kills: List[dict] = []
        replays = 0
        boosts = 0
        if spans:
            closed = [s for s in spans if s.end is not None]
            closed.sort(key=lambda s: s.duration_s, reverse=True)
            for s in closed[:self.top_k]:
                d = {"name": s.name, "kind": s.kind,
                     "durationS": round(s.duration_s, 6)}
                if s.attrs:
                    d["attrs"] = s.attrs
                top.append(d)
            # breaker/exchange verdict markers (obs/runstats plane): the
            # CBO choices and replay waves behind a slow query, inline
            for s in spans:
                a = s.attrs or {}
                if s.kind == "breaker_engine":
                    engines.append({"node": a.get("node"),
                                    "engine": a.get("engine"),
                                    "why": a.get("why")})
                elif s.kind == "exchange_wait" and "util" in a:
                    lane_util.append({"fid": a.get("fid"),
                                      "lanesUsed": a.get("lanes_used"),
                                      "lanesTotal": a.get("lanes_total"),
                                      "util": a.get("util")})
                elif s.kind == "overflow_replay":
                    replays += 1
                    if a.get("cap_to"):
                        boosts += 1
                elif s.kind == "memory_revoke":
                    # devprof plane: memory pressure behind a slow query
                    revokes += 1
                    before = a.get("reserved_before") or 0
                    after = a.get("reserved_after") or 0
                    revoked_bytes += max(0, int(before) - int(after))
                elif s.kind == "memory_kill":
                    kills.append({"reason": a.get("reason"),
                                  "forensics": a.get("forensics")})
        rec = {
            "event": "queryCompleted",
            "ts": time.time(),
            "queryId": info.query_id,
            "state": info.state,
            "user": info.user,
            "sql": info.sql,
            "elapsedS": round(elapsed, 6),
            "error": info.error,
            "topSpans": top,
        }
        if engines:
            rec["breakerEngines"] = engines
        if lane_util:
            rec["laneUtil"] = lane_util
        if replays:
            rec["overflowReplays"] = replays
            rec["overflowBoosts"] = boosts
        if revokes:
            rec["memoryRevokes"] = revokes
            rec["memoryRevokedBytes"] = revoked_bytes
        if kills:
            rec["memoryKills"] = kills
        if memory:
            # peak/footprint fields from the devprof memory rollup
            rec["memory"] = memory
        line = json.dumps(rec, default=str)
        with self._lock:
            with open(self.path, "a") as fh:
                fh.write(line + "\n")
