"""Mid-flight telemetry plane: live operator watermarks + bottleneck doctor.

Every other telemetry plane (HBO, devprof, lifecycle) reports after an
operator or query finishes; this one is readable WHILE a run is in
flight, which is what ROADMAP item 3 (adaptive mid-query execution)
needs to react to. Operators publish into a per-query store at
wave/window boundaries only — counts the host already holds (rows
in/out, windows dispatched, overflow caps, spill depth/repartitions,
exchange lane utilization), never a fresh device sync — and everything
downstream is derived from those watermarks:

- ``GET /v1/query/{id}/inflight`` — merged per-fragment snapshot on the
  coordinator. Worker heartbeats carry per-task docs (`queryInflight`),
  merged idempotently by per-operator sequence number so the in-process
  cluster (workers publishing directly into the same registry their
  heartbeats also report) never double-counts.
- **Stall detector** — a coordinator-side watcher thread flags queries
  whose executing segment advances but whose row watermarks have not
  moved for ``stall_threshold_s``: emits a throttled ``stall_detected``
  event naming the stalled operator and appends a forensic JSONL record
  (last N window snapshots per operator, pool reservations, open span
  stack) analogous to the PR 11 OOM forensics.
- **Straggler detector** — compares per-site window watermarks across a
  fragment's tasks; a site > ``straggler_factor``x behind its siblings
  emits ``straggler_detected`` and a slow-log doc.
- **Query doctor** — :func:`analyze` stitches lifecycle segments,
  inflight watermarks, trace spans, HBO drift, spill and farm markers
  into one ranked verdict ("62% of wall in exchange_wait on fragment 3;
  lane util 0.11"), surfaced on EXPLAIN ANALYZE,
  ``GET /v1/query/{id}/doctor``, and the slow-query log.

Off-discipline matches every sibling plane: nothing registers, arms, or
starts the watcher until the ``inflight`` session property is on, so
``inflight=off`` sessions leave the serving path and the ``/v1/metrics``
scrape bit-for-bit identical (the ``presto_tpu_inflight_*`` /
``presto_tpu_stalls_total`` / ``presto_tpu_stragglers_total`` families
render only once :func:`armed`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from presto_tpu.obs import events as _obs_events

#: window snapshots retained per operator (the forensic ring)
SNAPSHOT_DEPTH = 8

#: gauge keys an operator may publish (overwrite semantics; anything the
#: driver observed at the window boundary — never a fresh device sync)
GAUGE_KEYS = ("overflow", "cap", "spillDepth", "repartitions",
              "spilledBytes", "laneUtil", "lanesUsed", "lanesTotal",
              "wave", "stagedWindows", "site", "adaptiveActions",
              "adaptiveLast")


# ---------------------------------------------------------------------------
# per-task publisher

class TaskInflight:
    """The handle operators publish through (``ctx.inflight``). One per
    task execution; owns its operator docs, feeds the worker heartbeat
    (`doc()`), and — when the query is registered in this process —
    mirrors straight into the coordinator registry entry."""

    def __init__(self, query_id: str, task_id: str, fragment: int = 0):
        self.query_id = query_id
        self.task_id = task_id
        self.fragment = int(fragment)
        self.finished = False
        self._lock = threading.Lock()
        #: op name -> {seq, ts, windows, batches, rowsIn, rowsOut,
        #:             <gauges>, snapshots: deque}
        self.ops: Dict[str, Dict[str, Any]] = {}
        self._entry: Optional["QueryInflight"] = None

    def publish(self, op: str, rows_in: int = 0, rows_out: int = 0,
                windows: int = 0, batches: int = 0, **gauges) -> None:
        """One window-boundary heartbeat for operator ``op``: counters
        accumulate, gauges overwrite, and a snapshot lands in the
        forensic ring. Host-only arithmetic — callers pass counts they
        already computed; this never touches the device."""
        now = time.time()
        with self._lock:
            d = self.ops.get(op)
            if d is None:
                d = {"seq": 0, "ts": now, "windows": 0, "batches": 0,
                     "rowsIn": 0, "rowsOut": 0,
                     "snapshots": deque(maxlen=SNAPSHOT_DEPTH)}
                self.ops[op] = d
            d["seq"] += 1
            d["ts"] = now
            d["windows"] += int(windows)
            d["batches"] += int(batches)
            d["rowsIn"] += int(rows_in)
            d["rowsOut"] += int(rows_out)
            for k, v in gauges.items():
                if k in GAUGE_KEYS and v is not None:
                    d[k] = v
            snap = {"seq": d["seq"], "ts": round(now, 6),
                    "windows": d["windows"], "batches": d["batches"],
                    "rowsIn": d["rowsIn"], "rowsOut": d["rowsOut"]}
            for k in GAUGE_KEYS:
                if k in d:
                    snap[k] = d[k]
            d["snapshots"].append(snap)
        entry = self._entry
        if entry is not None:
            entry._note_publish(op, now)
        _count_publish()

    def finish(self) -> None:
        self.finished = True

    def windows_watermark(self) -> int:
        """The task's progress watermark: max windows over its ops, or —
        for fragments whose operators never dispatch fused windows (pure
        scan/project pipelines) — max batches, so sibling sites stay
        comparable for the straggler detector."""
        with self._lock:
            w = max((d["windows"] for d in self.ops.values()), default=0)
            if w:
                return w
            return max((d["batches"] for d in self.ops.values()), default=0)

    def doc(self) -> Dict[str, Any]:
        """Serializable per-task doc for the worker heartbeat."""
        with self._lock:
            ops = {op: {**{k: v for k, v in d.items() if k != "snapshots"},
                        "snapshots": list(d["snapshots"])}
                   for op, d in self.ops.items()}
        return {"taskId": self.task_id, "fragment": self.fragment,
                "finished": self.finished, "ops": ops}


# ---------------------------------------------------------------------------
# registry entry

class QueryInflight:
    """Coordinator-side entry: the per-task publishers (or their merged
    heartbeat images), stall/straggler episode state, and thresholds."""

    def __init__(self, query_id: str, group: Optional[str] = None,
                 stall_threshold_s: float = 2.0,
                 straggler_factor: float = 4.0):
        self.query_id = query_id
        self.group = group or "none"
        self.stall_threshold_s = float(stall_threshold_s or 2.0)
        self.straggler_factor = float(straggler_factor or 4.0)
        self.created = time.time()
        self.finished = False
        self._lock = threading.Lock()
        self.tasks: Dict[str, TaskInflight] = {}
        self.publishes = 0
        self.last_publish_ts: Optional[float] = None
        # stall episode state (watcher-owned except episode close)
        self._stall_since: Optional[float] = None
        self._stall_op: Optional[Tuple[str, str]] = None  # (task, op)
        #: op name -> accumulated stalled seconds over closed episodes
        self.stall_seconds: Dict[str, float] = {}
        self.stalls = 0
        #: straggler docs already flagged (one event per (fragment, task))
        self.stragglers: List[Dict[str, Any]] = []
        self._straggler_flagged: set = set()
        #: next observed/predicted rows ratio that fires inflight_drift
        #: (doubles each firing — the event-stream throttle)
        self._next_drift_ratio = 2.0

    # -- publish-side hooks -----------------------------------------------

    def _note_publish(self, op: str, now: float) -> None:
        with self._lock:
            self.publishes += 1
            self.last_publish_ts = now
            if self._stall_since is not None:
                # the watermark moved: close the stall episode and book
                # its wall to the operator that was stuck
                stuck = self._stall_op[1] if self._stall_op else op
                self.stall_seconds[stuck] = (
                    self.stall_seconds.get(stuck, 0.0)
                    + max(0.0, now - self._stall_since))
                self._stall_since = None
                self._stall_op = None

    def attach(self, task: TaskInflight) -> None:
        with self._lock:
            self.tasks[task.task_id] = task
        task._entry = self

    # -- derived watermarks -----------------------------------------------

    def total_rows_out(self) -> int:
        with self._lock:
            tasks = list(self.tasks.values())
        total = 0
        for t in tasks:
            with t._lock:
                total += sum(int(d.get("rowsOut", 0))
                             for d in t.ops.values())
        return total

    def stall_wall_s(self, now: Optional[float] = None) -> float:
        """Stalled seconds booked so far (closed episodes + the open
        one) — the doctor's stall score numerator."""
        now = time.time() if now is None else now
        with self._lock:
            total = sum(self.stall_seconds.values())
            if self._stall_since is not None:
                total += max(0.0, now - self._stall_since)
        return total


_lock = threading.RLock()
_entries: "OrderedDict[str, QueryInflight]" = OrderedDict()
_aliases: Dict[str, str] = {}
_MAX_ENTRIES = 256

_counter_lock = threading.Lock()
_publishes_total = 0
_stalls_total = 0
_stragglers_total = 0

_armed = False

# coordinator-configured context providers (best-effort, forensics only)
_forensics_dir: Optional[str] = None
_span_provider: Optional[Callable[[str], Optional[list]]] = None
_pool_provider: Optional[Callable[[], Optional[dict]]] = None


def _count_publish() -> None:
    global _publishes_total
    with _counter_lock:
        _publishes_total += 1


def arm() -> None:
    global _armed
    with _counter_lock:
        _armed = True


def armed() -> bool:
    return _armed


def configure(forensics_dir: Optional[str] = None,
              span_provider: Optional[Callable] = None,
              pool_provider: Optional[Callable] = None) -> None:
    """Wire coordinator context into forensic dumps. Configuring does
    NOT arm the plane — off sessions stay bit-for-bit."""
    global _forensics_dir, _span_provider, _pool_provider
    with _lock:
        if forensics_dir is not None:
            _forensics_dir = forensics_dir
        if span_provider is not None:
            _span_provider = span_provider
        if pool_provider is not None:
            _pool_provider = pool_provider


# ---------------------------------------------------------------------------
# registry

def register(query_id: str, group: Optional[str] = None,
             stall_threshold_s: float = 2.0,
             straggler_factor: float = 4.0) -> QueryInflight:
    """Create (and arm) the inflight entry for a query; starts the
    watcher thread on first use. Gated by the ``inflight`` session
    property at the call site — never reached for off sessions."""
    entry = QueryInflight(query_id, group=group,
                          stall_threshold_s=stall_threshold_s,
                          straggler_factor=straggler_factor)
    with _lock:
        arm()
        _entries[query_id] = entry
        while len(_entries) > _MAX_ENTRIES:
            old_id, _ = _entries.popitem(last=False)
            for a in [a for a, q in _aliases.items() if q == old_id]:
                del _aliases[a]
    _ensure_watcher()
    return entry


def alias(attempt_id: str, query_id: str) -> None:
    """Map a scheduler attempt query id onto the serving query id, so
    task publishers and heartbeat docs (keyed by attempt) reach the
    right entry."""
    if attempt_id == query_id:
        return
    with _lock:
        if query_id in _entries:
            _aliases[attempt_id] = query_id


def get(query_id: str) -> Optional[QueryInflight]:
    with _lock:
        qid = _aliases.get(query_id, query_id)
        return _entries.get(qid)


def task(query_id: str, task_id: str,
         fragment: int = 0) -> TaskInflight:
    """Worker-side publisher factory. Attaches to the registry entry
    when the query is registered in this process (in-process cluster);
    standalone otherwise — the doc still flows via the heartbeat."""
    t = TaskInflight(query_id, task_id, fragment=fragment)
    entry = get(query_id)
    if entry is not None:
        entry.attach(t)
    return t


def publish(query_id: str, op: str, task_id: str = "mesh",
            fragment: int = 0, **kw) -> None:
    """Registry-direct publish for drivers without a per-task publisher
    (the mesh data plane runs in the coordinator process). No-op when
    the query never registered — off-discipline preserved."""
    entry = get(query_id)
    if entry is None:
        return
    with entry._lock:
        t = entry.tasks.get(task_id)
        if t is None:
            t = TaskInflight(entry.query_id, task_id, fragment=fragment)
            t._entry = entry
            entry.tasks[task_id] = t
    t.publish(op, **kw)


def finish(query_id: str) -> None:
    """Terminal-state hook: closes any open stall episode and stops the
    watcher from flagging this query."""
    entry = get(query_id)
    if entry is None:
        return
    now = time.time()
    with entry._lock:
        if entry._stall_since is not None and entry._stall_op:
            op = entry._stall_op[1]
            entry.stall_seconds[op] = (
                entry.stall_seconds.get(op, 0.0)
                + max(0.0, now - entry._stall_since))
        entry._stall_since = None
        entry._stall_op = None
        entry.finished = True
        for t in entry.tasks.values():
            t.finished = True


def merge_worker(node_id: str, doc: Dict[str, Any]) -> None:
    """Fold one worker heartbeat ``queryInflight`` doc (attempt query id
    -> task id -> task doc) into the registry. Idempotent per operator:
    an incoming op doc replaces the held one only when its seq is newer,
    so the in-process cluster (heartbeats re-reporting publishers that
    already live in the registry) never double-counts."""
    for attempt_id, tasks in (doc or {}).items():
        entry = get(attempt_id)
        if entry is None or not isinstance(tasks, dict):
            continue
        for task_id, tdoc in tasks.items():
            if not isinstance(tdoc, dict):
                continue
            with entry._lock:
                t = entry.tasks.get(task_id)
                if t is None:
                    t = TaskInflight(entry.query_id, task_id,
                                     fragment=tdoc.get("fragment", 0))
                    t._entry = entry
                    entry.tasks[task_id] = t
            moved = False
            for op, od in (tdoc.get("ops") or {}).items():
                if not isinstance(od, dict):
                    continue
                with t._lock:
                    held = t.ops.get(op)
                    if held is not None and int(held.get("seq", 0)) >= \
                            int(od.get("seq", 0)):
                        continue
                    merged = {k: v for k, v in od.items()
                              if k != "snapshots"}
                    merged["snapshots"] = deque(
                        od.get("snapshots") or [], maxlen=SNAPSHOT_DEPTH)
                    t.ops[op] = merged
                    moved = True
            if tdoc.get("finished"):
                t.finished = True
            if moved:
                entry._note_publish("", time.time())


# ---------------------------------------------------------------------------
# snapshots (the GET /v1/query/{id}/inflight doc)

def snapshot_doc(query_id: str) -> Optional[Dict[str, Any]]:
    """Merged per-fragment snapshot, or None when the query never
    registered (inflight off / unknown id)."""
    entry = get(query_id)
    if entry is None:
        return None
    with entry._lock:
        tasks = list(entry.tasks.values())
        publishes = entry.publishes
        last_ts = entry.last_publish_ts
        stalls = entry.stalls
        stall_seconds = dict(entry.stall_seconds)
        stragglers = list(entry.stragglers)
        finished = entry.finished
    tdocs = [t.doc() for t in tasks]
    frags: Dict[str, Dict[str, Any]] = {}
    for d in tdocs:
        f = frags.setdefault(str(d["fragment"]), {
            "windows": 0, "batches": 0, "rowsIn": 0, "rowsOut": 0,
            "tasks": 0, "repartitions": 0, "spillDepth": 0})
        f["tasks"] += 1
        for od in d["ops"].values():
            f["windows"] += int(od.get("windows", 0))
            f["batches"] += int(od.get("batches", 0))
            f["rowsIn"] += int(od.get("rowsIn", 0))
            f["rowsOut"] += int(od.get("rowsOut", 0))
            f["repartitions"] += int(od.get("repartitions", 0) or 0)
            f["spillDepth"] = max(f["spillDepth"],
                                  int(od.get("spillDepth", 0) or 0))
            if "laneUtil" in od:
                f["laneUtil"] = od["laneUtil"]
    doc: Dict[str, Any] = {
        "queryId": entry.query_id,
        "group": entry.group,
        "finished": finished,
        "publishes": publishes,
        "lastPublishTs": round(last_ts, 6) if last_ts else None,
        "stalls": stalls,
        "stallSeconds": {op: round(s, 6)
                         for op, s in stall_seconds.items()},
        "fragments": frags,
        "tasks": tdocs,
    }
    if stragglers:
        doc["stragglers"] = stragglers
    return doc


# ---------------------------------------------------------------------------
# metric families — armed-gated like the lifecycle plane: render on the
# scrape only once an inflight-on query has registered.

def metric_rows(labels: Dict[str, str]) -> List[tuple]:
    """Rows for server.metrics.render_metrics (call when armed)."""
    with _lock:
        active = sum(1 for e in _entries.values() if not e.finished)
    with _counter_lock:
        pubs, stalls, strag = (_publishes_total, _stalls_total,
                               _stragglers_total)
    lbl = dict(labels)
    return [
        ("presto_tpu_inflight_queries",
         "queries with a live inflight telemetry entry", active, lbl,
         "gauge"),
        ("presto_tpu_inflight_publishes_total",
         "operator window-boundary telemetry publishes", pubs, lbl,
         "counter"),
        ("presto_tpu_stalls_total",
         "stall episodes flagged by the inflight watcher", stalls, lbl,
         "counter"),
        ("presto_tpu_stragglers_total",
         "fragment sites flagged >factor behind their siblings", strag,
         lbl, "counter"),
    ]


# ---------------------------------------------------------------------------
# watcher: stall + straggler + drift detection

_watcher_lock = threading.Lock()
_watcher: Optional[threading.Thread] = None


def _ensure_watcher() -> None:
    global _watcher
    with _watcher_lock:
        if _watcher is not None and _watcher.is_alive():
            return
        _watcher = threading.Thread(target=_watch_loop,
                                    name="inflight-watcher", daemon=True)
        _watcher.start()


def _watch_loop() -> None:
    while True:
        with _lock:
            entries = [e for e in _entries.values() if not e.finished]
        # poll a few times per stall threshold so detection latency is a
        # fraction of the bound, bounded below to stay off the hot path
        thresholds = [e.stall_threshold_s for e in entries] or [2.0]
        interval = min(0.5, max(0.02, min(thresholds) / 5.0))
        time.sleep(interval)
        now = time.time()
        for e in entries:
            try:
                _check_stall(e, now)
                _check_stragglers(e, now)
                _check_drift(e)
            except Exception:
                # the watcher must never take down telemetry publishing
                pass


def _check_stall(e: QueryInflight, now: float) -> None:
    global _stalls_total
    with e._lock:
        if (e.finished or e._stall_since is not None
                or e.last_publish_ts is None
                or now - e.last_publish_ts <= e.stall_threshold_s):
            return
        last = e.last_publish_ts
        # the stalled operator is the last one to publish — it entered a
        # window it never came back from
        stuck_task, stuck_op, stuck_ts = None, None, -1.0
        for tid, t in e.tasks.items():
            with t._lock:
                for op, d in t.ops.items():
                    if d["ts"] > stuck_ts:
                        stuck_task, stuck_op, stuck_ts = tid, op, d["ts"]
        if stuck_op is None:
            return
        e._stall_since = last
        e._stall_op = (stuck_task, stuck_op)
        e.stalls += 1
    with _counter_lock:
        _stalls_total += 1
    _obs_events.EVENTS.emit(
        "stall_detected", query_id=e.query_id, group=e.group,
        operator=stuck_op, taskId=stuck_task,
        stalledS=round(now - last, 6),
        thresholdS=e.stall_threshold_s)
    _dump_forensics(e, stuck_op, stuck_task, now - last)


def _check_stragglers(e: QueryInflight, now: float) -> None:
    global _stragglers_total
    with e._lock:
        tasks = list(e.tasks.items())
        factor = e.straggler_factor
    frags: Dict[int, List[Tuple[str, int]]] = {}
    for tid, t in tasks:
        frags.setdefault(t.fragment, []).append(
            (tid, t.windows_watermark()))
    for frag, sites in frags.items():
        if len(sites) < 2:
            continue
        leader_id, leader = max(sites, key=lambda s: s[1])
        lag_id, lag = min(sites, key=lambda s: s[1])
        # minimum-progress floor: a 2-vs-0 start-of-run skew is noise
        if leader < max(2, factor) or leader < factor * max(1, lag):
            continue
        key = (frag, lag_id)
        with e._lock:
            if key in e._straggler_flagged:
                continue
            e._straggler_flagged.add(key)
            doc = {"fragment": frag, "taskId": lag_id,
                   "leaderTaskId": leader_id, "leaderWindows": leader,
                   "laggardWindows": lag, "factor": factor,
                   "ts": round(now, 6)}
            e.stragglers.append(doc)
        with _counter_lock:
            _stragglers_total += 1
        _obs_events.EVENTS.emit(
            "straggler_detected", query_id=e.query_id, group=e.group,
            **{k: v for k, v in doc.items() if k != "ts"})


def _check_drift(e: QueryInflight) -> None:
    """Throttled ``inflight_drift``: observed output rows crossed the
    next doubling of the HBO-predicted total."""
    from presto_tpu.obs import lifecycle as _lifecycle

    lc = _lifecycle.get(e.query_id)
    predicted = lc.predicted if lc is not None else None
    if not predicted:
        return
    p_sink = float(predicted.get("sink_rows", 0) or 0)
    if p_sink <= 0:
        return
    rows = e.total_rows_out()
    ratio = rows / p_sink
    with e._lock:
        if ratio < e._next_drift_ratio:
            return
        fired_at = e._next_drift_ratio
        while e._next_drift_ratio <= ratio:
            e._next_drift_ratio *= 2.0
    _obs_events.EVENTS.emit(
        "inflight_drift", query_id=e.query_id, group=e.group,
        observedRows=rows, predictedSinkRows=p_sink,
        ratio=round(ratio, 4), threshold=fired_at)


# ---------------------------------------------------------------------------
# forensics (the PR 11 OOM-forensics analog for stalls)

def _forensics_path() -> Optional[str]:
    base = _forensics_dir or os.environ.get("PRESTO_TPU_CACHE_DIR")
    if not base:
        return None
    return os.path.join(base, "inflight_forensics.jsonl")


def _dump_forensics(e: QueryInflight, op: str, task_id: Optional[str],
                    stalled_s: float) -> Optional[str]:
    path = _forensics_path()
    if path is None:
        return None
    ops: Dict[str, Any] = {}
    with e._lock:
        tasks = list(e.tasks.items())
    for tid, t in tasks:
        with t._lock:
            for name, d in t.ops.items():
                ops[f"{tid}/{name}"] = {
                    "task": tid, "fragment": t.fragment,
                    "snapshots": list(d["snapshots"])}
    rec = {
        "event": "stall_detected",
        "ts": round(time.time(), 6),
        "queryId": e.query_id,
        "group": e.group,
        "operator": op,
        "taskId": task_id,
        "stalledS": round(stalled_s, 6),
        "thresholdS": e.stall_threshold_s,
        "ops": ops,
    }
    if _pool_provider is not None:
        try:
            rec["pool"] = _pool_provider()
        except Exception:
            pass
    if _span_provider is not None:
        try:
            spans = _span_provider(e.query_id)
            if spans:
                rec["openSpans"] = spans
        except Exception:
            pass
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _obs_events._append_line(path, json.dumps(rec, default=str))
    except OSError:
        return None
    return path


# ---------------------------------------------------------------------------
# query doctor

def analyze(query_id: str, spans: Optional[list] = None,
            state: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Ranked bottleneck attribution for one query: stitch lifecycle
    segments, inflight watermarks, trace spans, HBO drift, and spill /
    cache / farm markers into causes scored by estimated fraction of
    wall. None when neither plane ever saw the query."""
    from presto_tpu.obs import lifecycle as _lifecycle

    lc = _lifecycle.get(query_id)
    entry = get(query_id)
    if lc is None and entry is None:
        return None
    now = time.time()
    segments = (lc.timeline.segments() if lc is not None
                else {s: 0.0 for s in ("queue_wait", "plan", "compile",
                                       "exec", "drain", "e2e")})
    wall = max(segments.get("e2e", 0.0), 1e-9)
    causes: List[Dict[str, Any]] = []

    def cause(kind: str, score: float, detail: str,
              where: Optional[str] = None, **extra) -> None:
        if score <= 0.0:
            return
        c = {"cause": kind, "score": round(min(1.0, score), 4),
             "detail": detail}
        if where:
            c["where"] = where
        c.update(extra)
        causes.append(c)

    # -- cache short-circuit dominates everything else
    cache = lc.cache_info if lc is not None else None
    if cache:
        cause("result_cache", 1.0,
              "full-query result-cache hit — wall is cache lookup + "
              "drain", key=cache.get("key"))

    # -- stalls: wall booked to the operator that stopped publishing
    stall_s = entry.stall_wall_s(now) if entry is not None else 0.0
    stall_share = min(1.0, stall_s / wall)
    if entry is not None and stall_s > 0:
        booked = dict(entry.stall_seconds)
        open_op = entry._stall_op[1] if entry._stall_op else None
        if open_op is not None:
            booked[open_op] = booked.get(open_op, 0.0) + max(
                0.0, now - (entry._stall_since or now))
        worst_op = max(booked, key=booked.get) if booked else "unknown"
        cause("stall", stall_share,
              f"row watermarks frozen {stall_s:.2f}s "
              f"(threshold {entry.stall_threshold_s}s)",
              where=f"operator {worst_op}", operator=worst_op)

    # -- stragglers: a site behind its siblings gates the fragment
    if entry is not None and entry.stragglers:
        worst = max(entry.stragglers,
                    key=lambda s: s["leaderWindows"]
                    - s["laggardWindows"])
        lagf = 1.0 - (worst["laggardWindows"]
                      / max(1, worst["leaderWindows"]))
        cause("straggler",
              (segments.get("exec", 0.0) / wall) * lagf,
              f"site {worst['laggardWindows']}/{worst['leaderWindows']} "
              f"windows behind leader",
              where=f"fragment {worst['fragment']} "
                    f"task {worst['taskId']}",
              operator=worst["taskId"])

    # -- exchange wait from closed spans: the span envelope covers the
    #    whole stream, so score the wait_s attr (true consumer-blocked
    #    seconds), residual after stall attribution — exchange wait
    #    downstream of a stalled operator is a symptom, not the cause
    exch_share = 0.0
    if spans:
        def _wait_s(s):
            a = getattr(s, "attrs", None) or {}
            w = a.get("wait_s")
            return float(w) if w is not None else s.duration_s

        waits = [s for s in spans
                 if getattr(s, "kind", None) == "exchange_wait"
                 and getattr(s, "end", None) is not None]
        total_wait = sum(_wait_s(s) for s in waits)
        exch_share = min(1.0, total_wait / wall)
        exch_residual = max(0.0, exch_share - stall_share)
        if waits and exch_residual >= 0.1:
            worst = max(waits, key=_wait_s)
            a = worst.attrs or {}
            util = a.get("util")
            detail = f"{total_wait:.3f}s blocked on exchange"
            if util is not None:
                detail += f"; lane util {util}"
            cause("exchange_wait", exch_residual, detail,
                  where=f"fragment {a.get('fragment', a.get('fid'))}")
        replays = sum(1 for s in spans
                      if getattr(s, "kind", None) == "overflow_replay")
        if replays:
            cause("overflow_replay", min(0.5, 0.15 * replays),
                  f"{replays} overflow replay wave(s) re-ran the "
                  f"breaker fragment")
        spills = sum(1 for s in spans
                     if getattr(s, "kind", None) == "spill_repartition")
        if spills:
            cause("spill", min(0.5, 0.1 * spills),
                  f"{spills} spill repartition(s) — build exceeded "
                  f"memory budget")

    # -- adaptive layer: what the in-run adaptation did, or what a missed
    #    action cost. Repeated replay waves with NO acted flip/presize/
    #    lane-resize attribute to the missing action — /doctor explains
    #    both why an action fired and why one didn't.
    try:
        from presto_tpu.exec import adaptive as _adaptive

        decs = _adaptive.recent_decisions(query_id)
        adaptive_mode = _adaptive.last_mode()
    except Exception:
        decs, adaptive_mode = [], None
    acted_decs = [d for d in decs if d.get("acted")]
    if acted_decs:
        kinds: Dict[str, int] = {}
        for d in acted_decs:
            kinds[d["kind"]] = kinds.get(d["kind"], 0) + 1
        cause("adaptive_action", 0.05,
              "in-run adaptation acted: " + ", ".join(
                  f"{k} x{n}" for k, n in sorted(kinds.items())),
              actions=kinds)
    replay_spans = sum(1 for s in (spans or ())
                       if getattr(s, "kind", None) == "overflow_replay")
    if replay_spans >= 2 and not any(
            d.get("acted") and d.get("kind") in
            ("engine_flip", "presize_grow", "lane_resize")
            for d in decs):
        if adaptive_mode == "observe":
            why = ("adaptive=observe logged what it would do without "
                   "acting — set adaptive=on")
        elif adaptive_mode == "on":
            why = ("adaptive=on but no decision point fired (replays "
                   "grew from a non-empty checkpoint or the site was "
                   "already pinned)")
        else:
            why = ("adaptive off — adaptive=on flips engines / presizes "
                   "between waves instead of replaying wider")
        cause("missed_adaptive_action", min(0.5, 0.15 * replay_spans),
              f"replayed the same configuration {replay_spans} time(s); "
              f"{why}")

    # -- lifecycle segment dominance (exec scored on its residual after
    #    stall/exchange attribution so a named operator outranks the
    #    generic segment)
    if lc is not None and not cache:
        for seg in ("queue_wait", "plan", "compile", "drain"):
            share = segments.get(seg, 0.0) / wall
            if seg in ("compile", "drain"):
                # distributed timelines book task execution into the
                # compile/drain envelope until the first/last root batch;
                # stall episodes overlapping it are the better-attributed
                # cause, so these segments score on their residual
                share = max(0.0, share - stall_share)
            if share >= 0.2:
                detail = f"{segments[seg]:.3f}s in {seg}"
                if seg == "compile" and lc.farm_info:
                    detail += " (farm attribution on record)"
                cause(seg, share, detail)
        exec_share = segments.get("exec", 0.0) / wall
        residual = max(0.0, exec_share - stall_share - exch_share)
        if residual >= 0.25:
            cause("exec", residual,
                  f"{segments['exec']:.3f}s executing — see devprof "
                  f"roofline for device vs dispatch split")

    # -- HBO drift: actual wall vs the pre-run prediction
    predicted = lc.predicted if lc is not None else None
    if predicted:
        p_wall = float(predicted.get("wall_s", 0) or 0)
        if p_wall > 0 and wall >= 2.0 * p_wall:
            cause("hbo_drift", min(1.0, (wall - p_wall) / wall),
                  f"est {wall / p_wall:.1f}x under actual "
                  f"(predicted {p_wall:.3f}s, actual {wall:.3f}s)")

    causes.sort(key=lambda c: c["score"], reverse=True)
    if causes:
        top = causes[0]
        verdict = f"{top['score'] * 100.0:.0f}% of wall in {top['cause']}"
        if top.get("where"):
            verdict += f" on {top['where']}"
        verdict += f"; {top['detail']}"
    else:
        verdict = "no dominant bottleneck attributed"
    doc: Dict[str, Any] = {
        "queryId": query_id,
        "state": state or (lc.timeline.terminal if lc else None)
        or "running",
        "wallS": round(wall, 6),
        "segments": {k: round(v, 6) for k, v in segments.items()},
        "verdict": verdict,
        "causes": causes,
    }
    if entry is not None:
        doc["inflight"] = {
            "publishes": entry.publishes,
            "stalls": entry.stalls,
            "stragglers": len(entry.stragglers),
        }
    if predicted:
        doc["predicted"] = {"rows": predicted.get("rows"),
                            "sinkRows": predicted.get("sink_rows"),
                            "wallS": predicted.get("wall_s")}
    try:
        from presto_tpu.obs import devprof as _devprof

        if _devprof.active():
            doc["devprof"] = _devprof.summary(wall_s=wall)
    except Exception:
        pass
    return doc


def slow_log_annotation(query_id: str) -> Optional[Dict[str, Any]]:
    """Extra fields for the slow-query JSONL record: the doctor verdict
    plus any straggler docs (merged with the lifecycle annotation by the
    coordinator's slow-log listener)."""
    entry = get(query_id)
    if entry is None:
        return None
    doc = analyze(query_id)
    extra: Dict[str, Any] = {}
    if doc is not None:
        extra["doctor"] = {"verdict": doc["verdict"],
                           "causes": doc["causes"][:3]}
    with entry._lock:
        if entry.stragglers:
            extra["stragglers"] = list(entry.stragglers)
        if entry.stalls:
            extra["stalls"] = entry.stalls
    return extra or None


# ---------------------------------------------------------------------------

def reset() -> None:
    """Test hook: drop all entries and counters, disarm. The watcher
    thread (if started) idles over an empty registry."""
    global _armed, _publishes_total, _stalls_total, _stragglers_total
    with _lock:
        _entries.clear()
        _aliases.clear()
    with _counter_lock:
        _publishes_total = 0
        _stalls_total = 0
        _stragglers_total = 0
        _armed = False
