"""Runtime statistics feedback plane (HBO: history-based optimization).

Every stats-driven decision the engine makes — breaker engine choice,
aggregate presize, exchange lane capacity, fragment window sizing — runs
on *static* estimates from plan/stats.py. This module closes the loop:
execution sites report what they actually saw (group counts the breakers
already hold, build-side live rows, per-lane exchange occupancy, scan
rows, overflow-replay waves, partition skew) keyed on the PR 5
structural fingerprint plus a catalog snapshot token, and the planner
consults that history on a repeat of the same structure.

Three exposure paths:

  * drift telemetry: every observation with a usable estimate feeds the
    ``presto_tpu_stats_drift_ratio`` log-bucket histogram (labels:
    plane, op, site) in obs/metrics.py, plus per-site counters for
    observations, corrections applied, and decisions-that-would-flip;
  * EXPLAIN ANALYZE: observing sites stamp ``node._runstats`` which
    plan_to_string renders as ``[est=… actual=… drift=…x]``, and
    history-corrected CBO verdicts carry an ``(hbo: observed)`` suffix;
  * the history store itself: process-wide, and JSONL-persisted under
    ``$PRESTO_TPU_CACHE_DIR/hbo_history.jsonl`` when that umbrella cache
    knob is set — one JSON object per line, ``{"fp": fingerprint,
    "site": site, "est": …, "actual": …, "n": …, …extras}``; the file is
    append-only and the last line for a (fp, site) pair wins on load.

Merge policy: ``actual`` and all numeric extras merge with max() — the
consumers are capacity decisions, where the high-water mark is the safe
correction; ``n`` counts observations. The store is behavior-neutral
unless the ``hbo`` session property / ExecConfig field asks for it:
``off`` disables even observation (strict no-op — the pre-HBO engine
bit-for-bit), ``observe`` (default) records and exposes drift, and
``correct`` additionally feeds observed values back into the CBO.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from presto_tpu.obs import metrics as _obs_metrics

_LOCK = threading.Lock()
_loaded = False
# byte offset into the JSONL file this process has loaded through
_load_offset = 0
# (fingerprint, site) -> {"est": float|None, "actual": float, "n": int, ...}
_history: Dict[Tuple[str, str], Dict[str, Any]] = {}
_observations: Dict[str, int] = {}
_would_flip: Dict[str, int] = {}
_corrections: Dict[str, int] = {}
# bumped on every history mutation: consumers that bake corrected values
# into traced programs (the mesh executor) mix this into their cache key
# so a fresh observation invalidates stale capacities
_generation = 0

_HISTORY_FILE = "hbo_history.jsonl"

# TTL / size bounds for the JSONL history (the file is append-only and
# last-line-wins, so it grows without these): entries older than the
# max age are dropped on load, the newest max-entries survive, and a
# badly bloated file (many superseded lines per live entry) is rewritten
# compacted in place. `python -m presto_tpu.obs.runstats --compact`
# forces the rewrite.
_MAX_AGE_S = float(os.environ.get("PRESTO_TPU_HBO_MAX_AGE_S",
                                  30 * 86400))
_MAX_ENTRIES = int(os.environ.get("PRESTO_TPU_HBO_MAX_ENTRIES", 10000))
# rewrite-on-load trigger: superseded lines per live entry
_COMPACT_BLOAT_RATIO = 4


def _flock(path: str, exclusive: bool):
    """Advisory cross-PROCESS lock on ``<path>.lock`` (fcntl.flock).
    _LOCK serializes threads within one process; this serializes the
    file against other engine processes sharing the cache dir. Returns
    an fd to pass to _funlock, or None where fcntl is unavailable
    (non-POSIX) — the in-process lock still holds there."""
    try:
        import fcntl
    except ImportError:  # pragma: no cover - POSIX-only container
        return None
    fd = None
    try:
        fd = os.open(path + ".lock", os.O_WRONLY | os.O_CREAT, 0o644)
        fcntl.flock(fd, fcntl.LOCK_EX if exclusive else fcntl.LOCK_SH)
        return fd
    except OSError:
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        return None


def _funlock(fd) -> None:
    if fd is None:
        return
    try:
        os.close(fd)  # closing the fd releases the flock
    except OSError:
        pass


def history_path() -> Optional[str]:
    d = os.environ.get("PRESTO_TPU_CACHE_DIR")
    if not d:
        return None
    return os.path.join(d, _HISTORY_FILE)


def catalog_token(catalog) -> str:
    """Cheap snapshot token for the catalog: connector names, their table
    lists, and per-table row counts. A history entry is only reusable
    while the data it was observed against is unchanged; this token is
    the best effort short of content hashing."""
    parts: List[str] = []
    try:
        for cname in sorted(getattr(catalog, "connectors", {}) or {}):
            if cname.startswith("_"):
                # engine-internal connectors (e.g. the result cache's
                # "_rc" splice tables) are derived state, not user data:
                # their churn must not invalidate history or cache keys
                continue
            conn = catalog.connectors[cname]
            try:
                names = sorted(conn.table_names())
            except Exception:
                names = []
            for t in names:
                rows = None
                try:
                    rows = conn.get_table(t).row_count
                except Exception:
                    pass
                parts.append(f"{cname}.{t}={rows}")
    except Exception:
        pass
    h = hashlib.sha256("|".join(parts).encode()).hexdigest()
    return h[:12]


def node_fingerprint(node, catalog) -> Optional[str]:  # fp: key(hbo-history) covers(plan-structure, catalog)
    """History key for a plan node: pure structural sha (reusing the
    compile plane's ``_program_ns`` stamp when present — its last 16 hex
    chars are the config fingerprint, which must NOT key history) plus
    the catalog snapshot token. Memoized on the node."""
    fp = node.__dict__.get("_hbo_fp")
    if fp is not None:
        return fp or None
    sha = None
    ns = node.__dict__.get("_program_ns")
    if isinstance(ns, str) and len(ns) > 16:
        sha = ns[:-16]
    if sha is None:
        try:
            from presto_tpu.exec.programs import structural_fingerprint
            sha = structural_fingerprint(node)
        except Exception:
            node.__dict__["_hbo_fp"] = ""
            return None
    fp = sha[:24] + "/" + catalog_token(catalog)
    node.__dict__["_hbo_fp"] = fp
    return fp


def _load_locked(max_age_s: Optional[float] = None,
                 max_entries: Optional[int] = None) -> None:
    global _loaded, _load_offset
    if _loaded:
        return
    _loaded = True
    _load_offset = 0
    path = history_path()
    if not path or not os.path.exists(path):
        return
    max_age_s = _MAX_AGE_S if max_age_s is None else max_age_s
    max_entries = _MAX_ENTRIES if max_entries is None else max_entries
    lines = 0
    now = time.time()
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
        # everything up to this offset has been seen (and possibly
        # deliberately TTL/cap-evicted) by THIS process; a compaction
        # rewrite treats only lines past it as foreign-process appends
        _load_offset = len(raw)
        for line in raw.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line:
                continue
            lines += 1
            try:
                rec = json.loads(line)
                fp, site = rec.pop("fp"), rec.pop("site")
            except Exception:
                continue
            # max-age compaction: stale observations (old data
            # distributions) must not correct tomorrow's queries;
            # ts-less records predate the TTL stamp — keep them
            ts = rec.get("ts")
            if max_age_s and isinstance(ts, (int, float)) \
                    and now - float(ts) > max_age_s:
                _history.pop((str(fp), str(site)), None)
                continue
            _history[(str(fp), str(site))] = rec
    except OSError:
        pass
    if max_entries and len(_history) > max_entries:
        # newest (by ts; ts-less sorts oldest) survive the entry cap
        keys = sorted(_history,
                      key=lambda k: float(_history[k].get("ts") or 0.0))
        for k in keys[:len(_history) - max_entries]:
            del _history[k]
    if lines > max(len(_history) * _COMPACT_BLOAT_RATIO, 1024):
        # the append-only file carries far more superseded lines than
        # live entries — rewrite it compacted while we hold the lock
        _rewrite_locked()


def _rewrite_locked() -> None:
    """Rewrite the JSONL file as exactly one line per live entry (atomic
    replace, same discipline as the connectors' atomic writes). Holds
    the exclusive cross-process flock for the whole read-merge-replace:
    appenders (shared flock) are quiesced, and lines appended past this
    process's load offset — foreign-process writes it never saw — are
    merged through rather than dropped by the os.replace. Lines BEFORE
    the offset were loaded (and possibly deliberately TTL/cap-evicted),
    so they are not resurrected."""
    global _load_offset
    path = history_path()
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        lk = _flock(path, exclusive=True)
        try:
            tail = b""
            try:
                with open(path, "rb") as fh:
                    fh.seek(_load_offset)
                    tail = fh.read()
            except OSError:
                pass
            foreign: Dict[Tuple[str, str], Dict[str, Any]] = {}
            for line in tail.decode("utf-8", "replace").splitlines():
                try:
                    rec = json.loads(line)
                    key = (str(rec.pop("fp")), str(rec.pop("site")))
                except Exception:
                    continue
                ent = _history.get(key)
                if ent is None:
                    foreign[key] = rec  # last line wins, as on load
                else:
                    # both processes hold the key (this process's own
                    # appends also land past the offset): the shipped
                    # max-merge policy applies, so replaying our own
                    # lines is a no-op and a foreign high-water wins
                    for k, v in rec.items():
                        if isinstance(v, (int, float)) \
                                and not isinstance(v, bool):
                            ent[k] = max(float(ent.get(k) or 0.0),
                                         float(v))
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                for (fp, site), ent in {**foreign, **_history}.items():
                    fh.write(json.dumps({"fp": fp, "site": site, **ent})
                             + "\n")
            os.replace(tmp, path)
            _load_offset = os.path.getsize(path)
        finally:
            _funlock(lk)
    except OSError:
        pass


def _persist_locked(fp: str, site: str, ent: Dict[str, Any]) -> None:
    path = history_path()
    if not path:
        return
    ent["ts"] = round(time.time(), 3)
    # one record = one os.write to an O_APPEND fd: POSIX appends are
    # atomic with respect to the file offset, so concurrent engine
    # processes interleave whole lines, never torn ones. The shared
    # flock additionally fences appends against a concurrent compaction
    # rewrite (whose os.replace would otherwise drop this record).
    data = (json.dumps({"fp": fp, "site": site, **ent}) + "\n").encode()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        lk = _flock(path, exclusive=False)
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
        finally:
            _funlock(lk)
    except OSError:
        pass


def observe(fp: Optional[str], site: str, op: str,
            est: Optional[float], actual: Optional[float],
            extra: Optional[Dict[str, Any]] = None,
            plane: str = "worker") -> Optional[Dict[str, Any]]:
    """Record one estimate-vs-actual observation. Updates the history
    store (max-merge), appends the merged entry to the JSONL file, and
    feeds the drift histogram when the estimate is usable."""
    if fp is None or actual is None:
        return None
    actual = float(actual)
    global _generation
    with _LOCK:
        _load_locked()
        _generation += 1
        key = (fp, site)
        ent = _history.get(key)
        if ent is None:
            ent = {"est": None, "actual": 0.0, "n": 0}
            _history[key] = ent
        if est is not None:
            ent["est"] = float(est)
        ent["actual"] = max(float(ent.get("actual") or 0.0), actual)
        ent["n"] = int(ent.get("n") or 0) + 1
        for k, v in (extra or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                ent[k] = max(float(ent.get(k) or 0.0), float(v))
            else:
                ent[k] = v
        _observations[site] = _observations.get(site, 0) + 1
        _persist_locked(fp, site, ent)
        out = dict(ent)
    if est is not None and est > 0:
        _obs_metrics.STATS_DRIFT.observe(
            actual / float(est), plane=plane, op=op, site=site)
    return out


def note(fp: Optional[str], site: str, **extras: Any) -> None:
    """Merge extras into an existing/new history entry without recording
    a drift observation (no estimate involved — e.g. fanout overflow
    rows discovered mid-probe)."""
    if fp is None or not extras:
        return
    global _generation
    with _LOCK:
        _load_locked()
        _generation += 1
        key = (fp, site)
        ent = _history.setdefault(key, {"est": None, "actual": 0.0, "n": 0})
        for k, v in extras.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                ent[k] = max(float(ent.get(k) or 0.0), float(v))
            else:
                ent[k] = v
        _persist_locked(fp, site, ent)


def generation() -> int:
    """History mutation counter — see the module-state comment."""
    with _LOCK:
        return _generation


def lookup(fp: Optional[str], site: str) -> Optional[Dict[str, Any]]:
    if fp is None:
        return None
    with _LOCK:
        _load_locked()
        ent = _history.get((fp, site))
        return dict(ent) if ent is not None else None


def lookup_node(node, catalog, site: str) -> Optional[Dict[str, Any]]:
    return lookup(node_fingerprint(node, catalog), site)


# Whole-query profile site recorded by obs/lifecycle.py on every FINISHED
# lifecycle-tracked query, keyed on the root plan-node fingerprint. Entries
# carry wall_s / rows / sink_rows (max-merged like every note()d numeric).
QUERY_SITE = "lifecycle/query"


def query_baseline(fp: Optional[str]) -> Optional[Dict[str, Any]]:
    """HBO baseline for a whole query: the lifecycle plane's live-progress
    denominator and latency-regression reference."""
    return lookup(fp, QUERY_SITE)


def record_flip(site: str) -> None:
    """A decision site, re-evaluated against freshly observed values,
    would have chosen differently than the static estimate did."""
    with _LOCK:
        _would_flip[site] = _would_flip.get(site, 0) + 1


def record_correction(site: str) -> None:
    """A decision site actually used an observed value in place of its
    static estimate (hbo=correct, warm history)."""
    with _LOCK:
        _corrections[site] = _corrections.get(site, 0) + 1


_HELP = {
    "presto_tpu_hbo_observations_total":
        "runtime estimate-vs-actual observations recorded, by decision site",
    "presto_tpu_hbo_would_flip_total":
        "decisions whose observed values would flip the static choice",
    "presto_tpu_hbo_corrections_total":
        "decisions that used history-observed values instead of estimates",
    "presto_tpu_hbo_history_entries":
        "distinct (fingerprint, site) entries in the HBO history store",
}


def metric_rows(labels: Dict[str, str]) -> List[tuple]:
    """Rows for server.metrics.render_metrics: per-site HBO counters plus
    a history-size gauge."""
    rows: List[tuple] = []
    with _LOCK:
        for name, per_site in (
                ("presto_tpu_hbo_observations_total", _observations),
                ("presto_tpu_hbo_would_flip_total", _would_flip),
                ("presto_tpu_hbo_corrections_total", _corrections)):
            for site in sorted(per_site):
                rows.append((name, _HELP[name], per_site[site],
                             {**labels, "site": site}, "counter"))
        rows.append(("presto_tpu_hbo_history_entries",
                     _HELP["presto_tpu_hbo_history_entries"],
                     len(_history), dict(labels), "gauge"))
    return rows


def snapshot() -> Dict[str, Any]:
    """Test/bench hook: a copy of the full in-memory state."""
    with _LOCK:
        return {
            "history": {f"{fp}|{site}": dict(ent)
                        for (fp, site), ent in _history.items()},
            "observations": dict(_observations),
            "would_flip": dict(_would_flip),
            "corrections": dict(_corrections),
        }


def reset() -> None:
    """Test hook: clear in-memory state and force a lazy reload from the
    JSONL file (if any) on the next lookup/observe."""
    global _loaded, _generation, _load_offset
    with _LOCK:
        _loaded = False
        _load_offset = 0
        _generation += 1
        _history.clear()
        _observations.clear()
        _would_flip.clear()
        _corrections.clear()


def compact(max_age_s: Optional[float] = None,
            max_entries: Optional[int] = None) -> Dict[str, Any]:
    """Force a TTL/size compaction of the JSONL history: reload with the
    given bounds (defaults: the module TTL knobs) and rewrite the file
    as one line per surviving entry. Returns what happened."""
    global _loaded, _generation
    path = history_path()
    lines_before = 0
    if path and os.path.exists(path):
        try:
            with open(path, "r") as fh:
                lines_before = sum(1 for ln in fh if ln.strip())
        except OSError:
            pass
    with _LOCK:
        _history.clear()
        _loaded = False
        _load_locked(max_age_s=max_age_s, max_entries=max_entries)
        _generation += 1
        _rewrite_locked()
        kept = len(_history)
    return {"path": path, "lines_before": lines_before, "entries": kept}


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m presto_tpu.obs.runstats --compact`` — operator-facing
    history maintenance (TTL expiry + file rewrite)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m presto_tpu.obs.runstats",
        description="HBO history store maintenance "
                    "($PRESTO_TPU_CACHE_DIR/hbo_history.jsonl)")
    ap.add_argument("--compact", action="store_true",
                    help="drop entries past the TTL/size bounds and "
                         "rewrite the JSONL one line per live entry")
    ap.add_argument("--max-age-s", type=float, default=None,
                    help=f"entry TTL in seconds (default {_MAX_AGE_S:g})")
    ap.add_argument("--max-entries", type=int, default=None,
                    help=f"entry cap, newest win (default {_MAX_ENTRIES})")
    args = ap.parse_args(argv)
    if not args.compact:
        ap.print_help()
        return 2
    if history_path() is None:
        print("no history: PRESTO_TPU_CACHE_DIR is not set")
        return 1
    res = compact(max_age_s=args.max_age_s, max_entries=args.max_entries)
    print(f"compacted {res['path']}: {res['lines_before']} lines -> "
          f"{res['entries']} entries")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
