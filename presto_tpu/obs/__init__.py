"""Observability plane: span tracing (trace), histogram metric families
(metrics), exposition-format lint (exposition), and query-event sinks
(events)."""

from presto_tpu.obs import trace  # noqa: F401
