from presto_tpu.sql.parser import parse_sql

__all__ = ["parse_sql"]
