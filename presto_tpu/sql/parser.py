"""SQL lexer + recursive-descent parser.

Analog of presto-parser (SqlBase.g4, 802-line ANTLR4 grammar +
parser/AstBuilder.java). Hand-written recursive descent over the query
subset the engine executes: SELECT .. FROM .. [JOIN ..] WHERE .. GROUP BY ..
HAVING .. ORDER BY .. LIMIT, WITH CTEs, subqueries (FROM / IN / EXISTS /
scalar), the TPC-H expression surface.

Operator precedence (low→high): OR, AND, NOT, comparison/IN/BETWEEN/LIKE/IS,
additive, multiplicative, unary.
"""

from __future__ import annotations

import re
from typing import List, Optional

from presto_tpu.sql import ast

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "escape", "is",
    "null", "true", "false", "case", "when", "then", "else", "end", "cast",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "distinct", "all", "asc", "desc", "nulls", "first", "last", "exists",
    "date", "interval", "day", "month", "year", "extract", "with", "union",
    "intersect", "except",
    "substring", "for", "over", "partition", "rows", "range", "unbounded",
    "preceding", "following", "current", "row",
    "create", "insert", "drop", "table", "into", "if",
}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|->|\|\||[-+*/%(),.<>=;\[\]?])
    """,
    re.VERBOSE | re.DOTALL,
)


class Token:
    __slots__ = ("kind", "value", "pos", "quoted")

    def __init__(self, kind, value, pos, quoted=False):
        self.kind = kind  # 'number' | 'string' | 'ident' | 'keyword' | 'op' | 'eof'
        self.value = value
        self.pos = pos
        # "was a double-quoted identifier": quoting forces identifier
        # interpretation (a quoted current_date is a column, never the
        # niladic function)
        self.quoted = quoted

    def __repr__(self):
        return f"Token({self.kind},{self.value!r})"


class ParseError(Exception):
    pass


def tokenize(sql: str) -> List[Token]:
    out = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise ParseError(f"unexpected character {sql[i]!r} at {i}")
        i = m.end()
        if m.lastgroup == "ws":
            continue
        v = m.group()
        if m.lastgroup == "ident":
            low = v.lower()
            if low in _KEYWORDS:
                out.append(Token("keyword", low, m.start()))
            else:
                out.append(Token("ident", low, m.start()))
        elif m.lastgroup == "qident":
            out.append(Token("ident", v[1:-1].replace('""', '"'), m.start(),
                             quoted=True))
        elif m.lastgroup == "string":
            out.append(Token("string", v[1:-1].replace("''", "'"), m.start()))
        elif m.lastgroup == "number":
            out.append(Token("number", v, m.start()))
        else:
            out.append(Token("op", v, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers ----------------------------------------------------

    def peek(self, ahead=0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws) -> Optional[str]:
        t = self.peek()
        if t.kind == "keyword" and t.value in kws:
            self.next()
            return t.value
        return None

    def expect_kw(self, kw):
        if not self.accept_kw(kw):
            raise ParseError(f"expected {kw.upper()}, got {self.peek()!r}")

    def accept_op(self, *ops) -> Optional[str]:
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            self.next()
            return t.value
        return None

    def expect_op(self, op):
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r}, got {self.peek()!r}")

    def ident(self) -> str:
        t = self.peek()
        # allow non-reserved keywords as identifiers where unambiguous
        if t.kind in ("ident",) or (t.kind == "keyword" and t.value in (
                "year", "month", "day", "date", "first", "last", "if",
                "table", "into", "view", "replace", "delete", "truncate",
                "values")):
            self.next()
            return t.value
        raise ParseError(f"expected identifier, got {t!r}")

    def accept_word(self, w: str) -> bool:
        """Match a NON-reserved statement word (ident or keyword token) —
        words like view/replace/delete/truncate stay usable as function
        and column names."""
        t = self.peek()
        if t.kind in ("ident", "keyword") and t.value == w:
            self.next()
            return True
        return False

    # -- entry ------------------------------------------------------------

    def parse_statement(self) -> ast.Node:
        t = self.peek()
        if t.kind == "keyword" and t.value == "create":
            q = self._parse_create()
        elif t.kind == "keyword" and t.value == "insert":
            q = self._parse_insert()
        elif t.kind == "keyword" and t.value == "drop":
            q = self._parse_drop()
        elif t.kind in ("keyword", "ident") and t.value == "delete":
            self.next()
            self.expect_kw("from")
            name = self._qualified_name()
            where = None
            if self.accept_kw("where"):
                where = self.parse_expr()
            q = ast.Delete(name, where)
        elif t.kind in ("keyword", "ident") and t.value == "truncate":
            self.next()
            self.expect_kw("table")
            q = ast.Truncate(self._qualified_name())
        else:
            q = self.parse_query()
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise ParseError(f"trailing tokens at {self.peek()!r}")
        return q

    def _qualified_name(self):
        parts = [self.ident()]
        while self.accept_op("."):
            parts.append(self.ident())
        return tuple(parts)

    def _parse_create(self) -> ast.Node:
        self.expect_kw("create")
        or_replace = False
        if self.accept_kw("or"):
            if not self.accept_word("replace"):
                raise ParseError("expected REPLACE after CREATE OR")
            or_replace = True
        if self.accept_word("view"):
            name = self._qualified_name()
            self.expect_kw("as")
            return ast.CreateView(name, self.parse_query(), or_replace)
        if or_replace:
            raise ParseError("CREATE OR REPLACE applies to views only")
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        name = self._qualified_name()
        if self.accept_op("("):
            # CREATE TABLE name (col type, ...)
            cols = []
            while True:
                cname = self.ident()
                tparts = [self.next().value]
                if self.accept_op("("):
                    targs = [self.next().value]
                    while self.accept_op(","):
                        targs.append(self.next().value)
                    self.expect_op(")")
                    tparts.append("(" + ",".join(targs) + ")")
                cols.append((cname, "".join(tparts)))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            props = self._parse_table_properties()
            return ast.CreateTable(name, cols, if_not_exists, props)
        props = self._parse_table_properties()
        self.expect_kw("as")
        q = self.parse_query()
        return ast.CreateTableAs(name, q, if_not_exists, props)

    def _parse_table_properties(self) -> dict:
        """WITH (key = <literal>, ...) — hive-style table properties;
        values are literals or ARRAY[<literals>]."""
        if not self.accept_kw("with"):
            return {}
        self.expect_op("(")
        props = {}

        def literal_value(e):
            if isinstance(e, ast.Literal):
                return e.value
            if (isinstance(e, ast.FunctionCall) and e.name == "array_ctor"
                    and all(isinstance(a, ast.Literal) for a in e.args)):
                return [a.value for a in e.args]
            raise ParseError(
                "table property values must be literals or arrays of "
                "literals")

        while True:
            key = self.ident()
            self.expect_op("=")
            props[key] = literal_value(self.parse_expr())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return props

    def _parse_insert(self) -> ast.Node:
        self.expect_kw("insert")
        self.expect_kw("into")
        name = self._qualified_name()
        q = self.parse_query()
        return ast.Insert(name, q)

    def _parse_drop(self) -> ast.Node:
        self.expect_kw("drop")
        if self.accept_word("view"):
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return ast.DropView(self._qualified_name(), if_exists)
        self.expect_kw("table")
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return ast.DropTable(self._qualified_name(), if_exists)

    def parse_query(self) -> ast.Query:
        ctes = []
        if self.accept_kw("with"):
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                sub = self.parse_query()
                self.expect_op(")")
                ctes.append((name, sub))
                if not self.accept_op(","):
                    break
        q = self.parse_set_expr()
        q.ctes = ctes
        return q

    def parse_set_expr(self):
        """queryTerm (UNION [ALL|DISTINCT] | EXCEPT) queryTerm — INTERSECT
        binds tighter (SqlBase.g4:802 precedence). A trailing ORDER BY/LIMIT
        parsed by the rightmost body applies to the whole set operation."""
        left = self.parse_intersect_term()
        while True:
            if self.accept_kw("union"):
                kind = "union"
            elif self.accept_kw("except"):
                kind = "except"
            else:
                break
            all_ = bool(self.accept_kw("all"))
            if not all_:
                self.accept_kw("distinct")
            right = self.parse_intersect_term()
            left = ast.SetOp(kind, all_, left, right)
        if isinstance(left, ast.SetOp):
            left.order_by, left.limit = self._steal_order_limit(left)
            # a parenthesized rightmost operand keeps its own clauses; a
            # trailing ORDER BY/LIMIT may still follow the set op itself
            if not left.order_by and self.accept_kw("order"):
                self.expect_kw("by")
                left.order_by.append(self.parse_order_item())
                while self.accept_op(","):
                    left.order_by.append(self.parse_order_item())
            if left.limit is None and self.accept_kw("limit"):
                t = self.next()
                if t.kind != "number":
                    raise ParseError("LIMIT expects a number")
                left.limit = int(t.value)
        return left

    def parse_intersect_term(self):
        left = self.parse_query_term()
        while self.accept_kw("intersect"):
            all_ = bool(self.accept_kw("all"))
            if not all_:
                self.accept_kw("distinct")
            right = self.parse_query_term()
            left = ast.SetOp("intersect", all_, left, right)
        return left

    def parse_query_term(self):
        if (self.peek().kind == "op" and self.peek().value == "("
                and self._peek2_is_query()):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            q._parenthesized = True  # its ORDER BY/LIMIT is its own
            return q
        return self.parse_query_body()

    def _peek2_is_query(self) -> bool:
        # skip any depth of opening parens: "((select ..." is a query term
        ahead = 1
        t = self.peek(ahead)
        while t.kind == "op" and t.value == "(":
            ahead += 1
            t = self.peek(ahead)
        return t.kind == "keyword" and t.value in ("select", "with")

    def _steal_order_limit(self, node):
        """Move the rightmost body's ORDER BY/LIMIT up to the set op (a
        trailing clause binds to the whole set expression — unless the body
        was parenthesized, in which case the clause is its own)."""
        right = node.right
        while isinstance(right, ast.SetOp):
            right = right.right
        if getattr(right, "_parenthesized", False):
            return [], None
        order, limit = right.order_by, right.limit
        right.order_by, right.limit = [], None
        return order, limit

    def parse_query_body(self) -> ast.Query:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        self.accept_kw("all")
        select = [self.parse_select_item()]
        while self.accept_op(","):
            select.append(self.parse_select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self.parse_relation()
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        group_by: List[ast.Node] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            gs = self._try_grouping_construct()
            if gs is not None:
                group_by.append(gs)
            else:
                group_by.append(self.parse_expr())
                while self.accept_op(","):
                    group_by.append(self.parse_expr())
        having = None
        if self.accept_kw("having"):
            having = self.parse_expr()
        order_by: List[ast.OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        limit = None
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind == "op" and t.value == "?":
                self._param_count = getattr(self, "_param_count", 0) + 1
                limit = ast.Parameter(self._param_count - 1)
            elif t.kind != "number":
                raise ParseError("LIMIT expects a number")
            else:
                limit = int(t.value)
        return ast.Query(
            select=select, distinct=distinct, from_=from_, where=where,
            group_by=group_by, having=having, order_by=order_by, limit=limit,
        )

    def _try_grouping_construct(self):
        """ROLLUP(...), CUBE(...), GROUPING SETS ((..), ..) — expanded to
        an explicit set list at parse time (SqlBase.g4 groupingElement;
        planner/GroupIdNode is redesigned as a UNION ALL of aggregates)."""
        t = self.peek()
        if t.kind != "ident" or t.value not in ("rollup", "cube", "grouping"):
            return None
        if t.value == "grouping":
            nt = self.peek(1)
            if not (nt.kind == "ident" and nt.value == "sets"):
                return None
            self.next()
            self.next()
            self.expect_op("(")
            sets = []
            while True:
                self.expect_op("(")
                one = []
                if not (self.peek().kind == "op" and self.peek().value == ")"):
                    one.append(self.parse_expr())
                    while self.accept_op(","):
                        one.append(self.parse_expr())
                self.expect_op(")")
                sets.append(one)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return ast.GroupingSets(sets)
        kind = t.value
        if not (self.peek(1).kind == "op" and self.peek(1).value == "("):
            return None
        self.next()
        self.expect_op("(")
        cols = [self.parse_expr()]
        while self.accept_op(","):
            cols.append(self.parse_expr())
        self.expect_op(")")
        if kind == "rollup":
            sets = [cols[:i] for i in range(len(cols), -1, -1)]
        else:  # cube: every subset, preserving column order
            sets = []
            n = len(cols)
            for mask in range((1 << n) - 1, -1, -1):
                sets.append([cols[i] for i in range(n) if mask & (1 << i)])
        return ast.GroupingSets(sets)

    def parse_select_item(self) -> ast.SelectItem:
        t = self.peek()
        if t.kind == "op" and t.value == "*":
            self.next()
            return ast.SelectItem(ast.Star(), None)
        # qualified star: ident '.' '*'
        if (
            t.kind == "ident"
            and self.peek(1).kind == "op" and self.peek(1).value == "."
            and self.peek(2).kind == "op" and self.peek(2).value == "*"
        ):
            self.next(); self.next(); self.next()
            return ast.SelectItem(ast.Star(qualifier=t.value), None)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.ident()
        return ast.SelectItem(e, alias)

    def parse_order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        else:
            self.accept_kw("asc")
        nulls_first = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return ast.OrderItem(e, asc, nulls_first)

    # -- relations --------------------------------------------------------

    def parse_relation(self) -> ast.Node:
        rel = self.parse_table_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.parse_table_primary()
                rel = ast.Join("cross", rel, right, None)
                continue
            kind = None
            if self.accept_kw("inner"):
                kind = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                kind = "full"
            if kind is not None:
                self.expect_kw("join")
            elif self.accept_kw("join"):
                kind = "inner"
            elif self.accept_op(","):
                right = self.parse_table_primary()
                rel = ast.Join("cross", rel, right, None)
                continue
            else:
                break
            right = self.parse_table_primary()
            self.expect_kw("on")
            cond = self.parse_expr()
            rel = ast.Join(kind, rel, right, cond)
        return rel

    def _parse_values(self) -> ast.Node:
        """VALUES (e, ...), (e, ...) → desugared UNION ALL of FROM-less
        SELECTs (planner/RelationPlanner.visitValues without a dedicated
        node — each row is a one-row projection)."""
        rows = []
        while True:
            if self.accept_op("("):
                row = [self.parse_expr()]
                while self.accept_op(","):
                    row.append(self.parse_expr())
                self.expect_op(")")
            else:
                row = [self.parse_expr()]  # VALUES 1, 2, 3 (single column)
            rows.append(row)
            if not self.accept_op(","):
                break
        arity = len(rows[0])
        for r in rows:
            if len(r) != arity:
                raise ParseError(
                    f"VALUES rows differ in arity ({arity} vs {len(r)})")

        def row_query(row):
            items = [ast.SelectItem(e, f"_col{i}")
                     for i, e in enumerate(row)]
            return ast.Query(select=items)

        node = row_query(rows[0])
        for r in rows[1:]:
            node = ast.SetOp("union", True, node, row_query(r))
        return node

    def parse_table_primary(self) -> ast.Node:
        if (self.peek().kind in ("keyword", "ident")
                and self.peek().value == "values"
                and self.peek(1).kind == "op"
                and self.peek(1).value in ("(",)):
            self.next()
            q = self._parse_values()
            alias = None
            if self.accept_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "ident":
                alias = self.ident()
            cols = None
            if alias is not None and self.accept_op("("):
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            return ast.ValuesRelation(q, alias or "values", cols)
        if (self.peek().kind == "ident" and self.peek().value == "unnest"
                and self.peek(1).kind == "op" and self.peek(1).value == "("):
            self.next()
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            ordinality = False
            if self.accept_kw("with"):
                word = self.ident()
                if word != "ordinality":
                    raise ParseError(f"expected ORDINALITY, got {word}")
                ordinality = True
            alias = cols = None
            if self.accept_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "ident":
                alias = self.ident()
            if alias is not None and self.accept_op("("):
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            return ast.UnnestRelation(exprs, ordinality, alias, cols)
        if self.accept_op("("):
            if (self.peek().kind in ("keyword", "ident")
                    and self.peek().value == "values"):
                self.next()
                q = self._parse_values()
                self.expect_op(")")
                alias = None
                if self.accept_kw("as"):
                    alias = self.ident()
                elif self.peek().kind == "ident":
                    alias = self.ident()
                cols = None
                if alias is not None and self.accept_op("("):
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                return ast.ValuesRelation(q, alias or "values", cols)
            if self.peek().kind == "keyword" and self.peek().value in ("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                self.accept_kw("as")
                alias = self.ident()
                return ast.SubqueryRelation(q, alias)
            rel = self.parse_relation()
            self.expect_op(")")
            return rel
        parts = [self.ident()]
        while self.accept_op("."):
            parts.append(self.ident())
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.ident()
        return ast.Table(tuple(parts), alias)

    # -- expressions ------------------------------------------------------

    def parse_expr(self) -> ast.Node:
        # lambda: `x -> body` or `(x, y) -> body` (valid only in function
        # argument position; the analyzer rejects stray lambdas)
        t = self.peek()
        if (t.kind == "ident" and self.peek(1).kind == "op"
                and self.peek(1).value == "->"):
            name = self.ident()
            self.next()  # ->
            return ast.Lambda([name], self.parse_expr())
        if (t.kind == "op" and t.value == "(" and self.peek(1).kind == "ident"
                and self.peek(2).kind == "op"
                and self.peek(2).value in (",", ")")):
            # lookahead for "(a, b) ->"
            save = self.i
            try:
                self.next()
                params = [self.ident()]
                while self.accept_op(","):
                    params.append(self.ident())
                if (self.accept_op(")")
                        and self.peek().kind == "op"
                        and self.peek().value == "->"):
                    self.next()
                    return ast.Lambda(params, self.parse_expr())
            except ParseError:
                pass
            self.i = save
        return self.parse_or()

    def parse_or(self) -> ast.Node:
        left = self.parse_and()
        while self.accept_kw("or"):
            left = ast.BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> ast.Node:
        left = self.parse_not()
        while self.accept_kw("and"):
            left = ast.BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> ast.Node:
        if self.accept_kw("not"):
            return ast.UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ast.Node:
        left = self.parse_additive()
        while True:
            negated = False
            save = self.i
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.peek().kind == "keyword" and self.peek().value in ("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, items, negated)
                continue
            if self.accept_kw("like"):
                pattern = self.parse_additive()
                escape = None
                if self.accept_kw("escape"):
                    escape = self.parse_additive()
                left = ast.Like(left, pattern, escape, negated)
                continue
            if negated:
                self.i = save
                break
            if self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                left = ast.IsNull(left, neg)
                continue
            op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
            if op:
                opmap = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt",
                         "<=": "le", ">": "gt", ">=": "ge"}
                right = self.parse_additive()
                left = ast.BinaryOp(opmap[op], left, right)
                continue
            break
        return left

    def parse_additive(self) -> ast.Node:
        left = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-", "||")
            if not op:
                break
            right = self.parse_multiplicative()
            left = ast.BinaryOp({"+": "add", "-": "sub", "||": "concat"}[op], left, right)
        return left

    def parse_multiplicative(self) -> ast.Node:
        left = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                break
            right = self.parse_unary()
            left = ast.BinaryOp({"*": "mul", "/": "div", "%": "mod"}[op], left, right)
        return left

    def parse_unary(self) -> ast.Node:
        if self.accept_op("-"):
            return ast.UnaryOp("-", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        e = self.parse_primary()
        while self.accept_op("["):
            idx = self.parse_expr()
            self.expect_op("]")
            e = ast.FunctionCall("subscript", [e, idx])
        return e

    def parse_primary(self) -> ast.Node:
        t = self.peek()
        if t.kind == "op" and t.value == "?":
            # prepared-statement parameter, bound at EXECUTE time
            self.next()
            self._param_count = getattr(self, "_param_count", 0) + 1
            return ast.Parameter(self._param_count - 1)
        # literals
        if t.kind == "number":
            self.next()
            txt = t.value
            if re.fullmatch(r"\d+", txt):
                return ast.Literal(int(txt), "integer", txt)
            if "e" in txt.lower():
                return ast.Literal(float(txt), "double", txt)
            return ast.Literal(float(txt), "decimal", txt)
        if t.kind == "string":
            self.next()
            return ast.Literal(t.value, "string", t.value)
        if t.kind == "keyword":
            kw = t.value
            if kw == "null":
                self.next()
                return ast.Literal(None, "null")
            if kw in ("true", "false"):
                self.next()
                return ast.Literal(kw == "true", "boolean")
            if kw == "date":
                # DATE 'yyyy-mm-dd'
                if self.peek(1).kind == "string":
                    self.next()
                    s = self.next().value
                    return ast.Literal(s, "date", s)
            if kw == "interval":
                self.next()
                v = self.next()
                if v.kind != "string":
                    raise ParseError("INTERVAL expects a quoted value")
                unit_tok = self.next()
                unit = unit_tok.value.lower().rstrip("s")
                if unit not in ("day", "month", "year"):
                    raise ParseError(f"unsupported interval unit {unit}")
                return ast.IntervalLiteral(int(v.value), unit)
            if kw == "case":
                return self.parse_case()
            if kw == "cast":
                self.next()
                return self._parse_cast_body()
            if kw == "extract":
                self.next()
                self.expect_op("(")
                field = self.next().value.lower()
                self.expect_kw("from")
                e = self.parse_expr()
                self.expect_op(")")
                return ast.Extract(field, e)
            if kw == "exists":
                self.next()
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                return ast.Exists(q)
            if kw == "substring":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                if self.accept_kw("from"):
                    start = self.parse_expr()
                    length = None
                    if self.accept_kw("for"):
                        length = self.parse_expr()
                else:
                    self.expect_op(",")
                    start = self.parse_expr()
                    length = None
                    if self.accept_op(","):
                        length = self.parse_expr()
                self.expect_op(")")
                args = [e, start] + ([length] if length is not None else [])
                return ast.FunctionCall("substr", args)
            if kw in ("year", "month", "day") and self.peek(1).kind == "op" and self.peek(1).value == "(":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_op(")")
                return ast.Extract(kw, e)
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.peek().kind == "keyword" and self.peek().value in ("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        # identifier or function call
        if t.kind in ("ident", "keyword"):
            was_quoted = t.quoted
            name = self.ident()
            if (name == "try_cast" and not was_quoted
                    and self.peek().kind == "op"
                    and self.peek().value == "("):
                # TRY_CAST(x AS t) ≡ CAST: device casts already yield
                # NULL on unparseable input (the engine's documented
                # row-level-error deviation), which IS try semantics
                return self._parse_cast_body()
            if (name == "timestamp" and not was_quoted
                    and self.peek().kind == "string"):
                # TIMESTAMP 'yyyy-mm-dd[ hh:mm:ss[.ffffff]]'
                s = self.next().value
                return ast.Literal(s, "timestamp", s)
            if (name == "time" and not was_quoted
                    and self.peek().kind == "string"):
                # TIME 'hh:mm:ss[.ffffff]'
                s = self.next().value
                return ast.Literal(s, "time", s)
            if name in ("current_date", "current_timestamp",
                        "localtimestamp") and not was_quoted and not (
                    self.peek().kind == "op"
                    and self.peek().value in ("(", ".")):
                # niladic datetime functions (standard SQL: no parens)
                return ast.FunctionCall(
                    "current_timestamp" if name == "localtimestamp"
                    else name, [])
            if name == "array" and self.peek().kind == "op" and self.peek().value == "[":
                # ARRAY[e1, .., eN] literal constructor
                self.next()
                items = []
                if not (self.peek().kind == "op" and self.peek().value == "]"):
                    items.append(self.parse_expr())
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                self.expect_op("]")
                return ast.FunctionCall("array_ctor", items)
            if self.peek().kind == "op" and self.peek().value == "(":
                self.next()
                if self.accept_op("*"):
                    self.expect_op(")")
                    fc = ast.FunctionCall(name, [], is_star=True)
                else:
                    distinct = bool(self.accept_kw("distinct"))
                    args = []
                    if not (self.peek().kind == "op" and self.peek().value == ")"):
                        args.append(self.parse_expr())
                        while self.accept_op(","):
                            args.append(self.parse_expr())
                    self.expect_op(")")
                    fc = ast.FunctionCall(name, args, distinct=distinct)
                if self.accept_kw("over"):
                    return self.parse_over(fc)
                return fc
            parts = [name]
            while self.accept_op("."):
                parts.append(self.ident())
            return ast.Identifier(tuple(parts))
        raise ParseError(f"unexpected token {t!r}")

    def parse_over(self, fc: ast.FunctionCall) -> ast.Node:
        """OVER (PARTITION BY ... ORDER BY ... [ROWS|RANGE frame])."""
        self.expect_op("(")
        partition_by = []
        order_by = []
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition_by.append(self.parse_expr())
            while self.accept_op(","):
                partition_by.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        if self.accept_kw("rows"):
            if self.accept_kw("between"):
                s = self._parse_frame_bound(is_start=True)
                self.expect_kw("and")
                e = self._parse_frame_bound(is_start=False)
            else:
                # shorthand: ROWS <bound> == BETWEEN <bound> AND CURRENT ROW
                s = self._parse_frame_bound(is_start=True)
                if s.startswith("f"):
                    raise ParseError(
                        "frame shorthand bound must be UNBOUNDED PRECEDING, "
                        "n PRECEDING or CURRENT ROW")
                e = "cur"
            frame = ("rows_unbounded_current" if (s, e) == ("up", "cur")
                     else f"rows:{s}:{e}")
        elif self.accept_kw("range"):
            if self.accept_kw("between"):
                s = self._parse_frame_bound(is_start=True)
                self.expect_kw("and")
                e = self._parse_frame_bound(is_start=False)
            else:
                s = self._parse_frame_bound(is_start=True)
                if s.startswith("f"):
                    raise ParseError(
                        "frame shorthand bound must be UNBOUNDED PRECEDING, "
                        "n PRECEDING or CURRENT ROW")
                e = "cur"
            # UNBOUNDED PRECEDING..CURRENT ROW is exactly the default
            # frame (peer-inclusive running aggregate) — leave frame unset
            frame = None if (s, e) == ("up", "cur") else f"range:{s}:{e}"
        self.expect_op(")")
        return ast.WindowFunction(
            fc.name, fc.args, partition_by, order_by, fc.is_star, frame
        )

    def _parse_frame_bound(self, is_start: bool) -> str:
        """UNBOUNDED PRECEDING|FOLLOWING, n PRECEDING|FOLLOWING,
        CURRENT ROW → the compact frame-bound token ('up','uf','cur',
        'pN','fN')."""
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                if not is_start:
                    raise ParseError("frame end cannot be UNBOUNDED PRECEDING")
                return "up"
            self.expect_kw("following")
            if is_start:
                raise ParseError("frame start cannot be UNBOUNDED FOLLOWING")
            return "uf"
        if self.accept_kw("current"):
            self.expect_kw("row")
            return "cur"
        t = self.next()
        if t.kind != "number" or not t.value.isdigit():
            raise ParseError(f"expected frame offset, got {t.value!r}")
        n = int(t.value)
        if self.accept_kw("preceding"):
            return f"p{n}"
        self.expect_kw("following")
        return f"f{n}"

    def _parse_cast_body(self) -> ast.Node:
        """`( expr AS typename )` — shared by CAST and TRY_CAST."""
        self.expect_op("(")
        e = self.parse_expr()
        self.expect_kw("as")
        # type name: ident or keyword ('date'), optional (p[,s])
        tt = self.next()
        type_name = tt.value
        if self.accept_op("("):
            args = [self.next().value]
            while self.accept_op(","):
                args.append(self.next().value)
            self.expect_op(")")
            type_name += "(" + ",".join(args) + ")"
        self.expect_op(")")
        return ast.Cast(e, type_name)

    def parse_case(self) -> ast.Node:
        self.expect_kw("case")
        operand = None
        if not (self.peek().kind == "keyword" and self.peek().value == "when"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            whens.append((cond, val))
        default = None
        if self.accept_kw("else"):
            default = self.parse_expr()
        self.expect_kw("end")
        return ast.Case(operand, whens, default)


def parse_sql(sql: str) -> ast.Query:
    """Parse a SQL query string into an AST (reference:
    presto-parser/.../SqlParser.java:91 createStatement)."""
    return Parser(sql).parse_statement()
