"""SQL abstract syntax tree.

Analog of presto-parser's tree package (164 node classes under
presto-parser/src/main/java/com/facebook/presto/sql/tree/) — reduced to the
query surface this engine executes. Untyped; the analyzer lowers AST
expressions into the typed IR (presto_tpu.expr.ir).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


# ---------------------------------------------------------------------------
# expressions


@dataclasses.dataclass
class Identifier(Node):
    parts: Tuple[str, ...]  # possibly qualified: (table, column) or (column,)

    def __str__(self):
        return ".".join(self.parts)


@dataclasses.dataclass
class Literal(Node):
    value: object  # int | float | str | bool | None
    kind: str  # 'integer' | 'decimal' | 'double' | 'string' | 'boolean' | 'null' | 'date'
    text: str = ""


@dataclasses.dataclass
class IntervalLiteral(Node):
    value: int
    unit: str  # 'day' | 'month' | 'year'


@dataclasses.dataclass
class UnaryOp(Node):
    op: str  # '-' | '+' | 'not'
    operand: Node


@dataclasses.dataclass
class BinaryOp(Node):
    op: str  # arithmetic / comparison / 'and' / 'or'
    left: Node
    right: Node


@dataclasses.dataclass
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclasses.dataclass
class InList(Node):
    value: Node
    items: List[Node]
    negated: bool = False


@dataclasses.dataclass
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclasses.dataclass
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass
class ScalarSubquery(Node):
    query: "Query"


@dataclasses.dataclass
class Like(Node):
    value: Node
    pattern: Node
    escape: Optional[Node] = None
    negated: bool = False


@dataclasses.dataclass
class IsNull(Node):
    value: Node
    negated: bool = False


@dataclasses.dataclass
class FunctionCall(Node):
    name: str
    args: List[Node]
    distinct: bool = False
    is_star: bool = False  # count(*)


@dataclasses.dataclass
class WindowFunction(Node):
    """fn(args) OVER (PARTITION BY ... ORDER BY ... [frame])."""

    name: str
    args: List[Node]
    partition_by: List[Node]
    order_by: List["OrderItem"]
    is_star: bool = False
    # frame: None = default (RANGE UNBOUNDED..CURRENT with ORDER BY, whole
    # partition otherwise); "rows_unbounded_current" = ROWS UNBOUNDED
    # PRECEDING..CURRENT ROW
    frame: object = None


@dataclasses.dataclass
class Parameter(Node):
    """`?` prepared-statement placeholder (bound before analysis by
    substitute_parameters; an unbound Parameter is an analysis error)."""

    index: int


def substitute_parameters(node, args: list):
    """Replace every ast.Parameter with its positional argument AST
    (generic dataclass walk — binding happens on the parse tree, never
    by text splicing). Returns (new_node, n_params_seen)."""
    seen = [0]

    def walk(x):
        if isinstance(x, Parameter):
            seen[0] = max(seen[0], x.index + 1)
            if x.index < len(args):
                return args[x.index]
            return x
        if isinstance(x, Node):
            changes = {}
            for f in dataclasses.fields(x):
                v = getattr(x, f.name)
                nv = walk(v)
                if nv is not v:
                    changes[f.name] = nv
            return dataclasses.replace(x, **changes) if changes else x
        if isinstance(x, list):
            out = [walk(v) for v in x]
            return out if any(a is not b for a, b in zip(out, x)) else x
        if isinstance(x, tuple):
            out = tuple(walk(v) for v in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        return x

    return walk(node), seen[0]


@dataclasses.dataclass
class Lambda(Node):
    """`x -> body` / `(a, b) -> body` — argument to higher-order array
    functions (SqlBase.g4 lambda; spi/function/LambdaDefinitionExpression)."""

    params: list
    body: Node


@dataclasses.dataclass
class Cast(Node):
    value: Node
    type_name: str


@dataclasses.dataclass
class Case(Node):
    operand: Optional[Node]  # simple CASE x WHEN ... vs searched CASE WHEN
    whens: List[Tuple[Node, Node]]
    default: Optional[Node]


@dataclasses.dataclass
class Extract(Node):
    field: str  # 'year' | 'month' | 'day'
    value: Node


@dataclasses.dataclass
class Star(Node):
    qualifier: Optional[str] = None


# ---------------------------------------------------------------------------
# relations


@dataclasses.dataclass
class Table(Node):
    name: Tuple[str, ...]
    alias: Optional[str] = None


@dataclasses.dataclass
class SubqueryRelation(Node):
    query: "Query"
    alias: str = ""


@dataclasses.dataclass
class Join(Node):
    kind: str  # 'inner' | 'left' | 'right' | 'cross'
    left: Node
    right: Node
    condition: Optional[Node] = None


@dataclasses.dataclass
class ValuesRelation(Node):
    """(VALUES ...) [AS alias (col, ...)] — `query` is the desugared
    UNION-ALL-of-one-row-SELECTs body (RelationPlanner.visitValues)."""

    query: Node  # Query | SetOp
    alias: str = "values"
    column_names: Optional[list] = None


@dataclasses.dataclass
class UnnestRelation(Node):
    """UNNEST(expr, ...) [WITH ORDINALITY] [AS alias (col, ...)].

    As the right side of CROSS JOIN it is lateral: the expressions may
    reference the left relation's columns (SqlBase.g4 unnest /
    planner/plan/UnnestNode)."""

    exprs: list
    ordinality: bool = False
    alias: Optional[str] = None
    column_names: Optional[list] = None


# ---------------------------------------------------------------------------
# query


@dataclasses.dataclass
class GroupingSets(Node):
    """GROUP BY GROUPING SETS / ROLLUP / CUBE, expanded to explicit key
    sets. Appears as the sole element of Query.group_by."""

    sets: list  # List[List[Node]]


@dataclasses.dataclass
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclasses.dataclass
class OrderItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = default (last for asc)


@dataclasses.dataclass
class Query(Node):
    select: List[SelectItem]
    distinct: bool = False
    from_: Optional[Node] = None
    where: Optional[Node] = None
    group_by: List[Node] = dataclasses.field(default_factory=list)
    having: Optional[Node] = None
    order_by: List[OrderItem] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    ctes: List[Tuple[str, "Query"]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class CreateTableAs(Node):
    """CREATE TABLE [IF NOT EXISTS] name [WITH (props)] AS query
    (reference: execution/CreateTableTask.java + the TableWriter chain;
    properties e.g. partitioned_by = array['c'] as in the hive
    connector's HiveTableProperties)."""

    name: Tuple[str, ...]
    query: Node  # Query | SetOp
    if_not_exists: bool = False
    properties: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Insert(Node):
    """INSERT INTO name query (reference: TableWriterOperator +
    TableFinishOperator row-count result)."""

    name: Tuple[str, ...]
    query: Node


@dataclasses.dataclass
class CreateTable(Node):
    """CREATE TABLE name (col type, ...) — empty table with an explicit
    schema (execution/CreateTableTask without the AS-query source)."""

    name: Tuple[str, ...]
    columns: list  # [(name, type_string)]
    if_not_exists: bool = False
    properties: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW name AS query — stored-query expansion at
    plan time (execution/CreateViewTask; views are engine-level here, not
    connector metadata)."""

    name: Tuple[str, ...]
    query: Node
    or_replace: bool = False


@dataclasses.dataclass
class DropView(Node):
    name: Tuple[str, ...]
    if_exists: bool = False


@dataclasses.dataclass
class Delete(Node):
    """DELETE FROM name [WHERE cond] — rewrite-based (kept rows are those
    where the predicate is not TRUE)."""

    name: Tuple[str, ...]
    where: Optional[Node] = None


@dataclasses.dataclass
class Truncate(Node):
    name: Tuple[str, ...]


@dataclasses.dataclass
class DropTable(Node):
    name: Tuple[str, ...]
    if_exists: bool = False


@dataclasses.dataclass
class SetOp(Node):
    """UNION [ALL] / INTERSECT / EXCEPT of two query bodies
    (SqlBase.g4:802 queryTerm; reference planner/plan/UnionNode,
    IntersectNode, ExceptNode). `order_by`/`limit` apply to the combined
    result; `ctes` from an enclosing WITH scope both sides."""

    kind: str  # 'union' | 'intersect' | 'except'
    all: bool
    left: Node  # Query | SetOp
    right: Node
    order_by: List[OrderItem] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    ctes: List[Tuple[str, "Query"]] = dataclasses.field(default_factory=list)
