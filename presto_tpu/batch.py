"""Fixed-capacity columnar batches — the device data model.

Reference data model: presto-spi/.../Page.java:34 + block/Block.java:23 (69
block classes, variable row counts, selection via DictionaryBlock wrapping /
positions lists).

TPU-native redesign: XLA wants static shapes, so a Batch is a set of
equal-capacity flat arrays plus a `live` row mask:

- capacity   : static (padded to a power-of-two bucket to bound recompiles)
- live       : bool[capacity]; padding rows and filtered-out rows are dead.
               A filter is just `live &= predicate` — no compaction, no
               selection vectors. Compaction happens only at materialization
               points (exchange, output, build side of joins).
- validity   : per-column bool[capacity] or None (all valid). SQL NULL is
               orthogonal to liveness.
- values     : one flat dtype array per column (strings are dict codes).

Batches are registered pytrees: (values/validity/live) are traced leaves;
(names, types, dicts) are static aux so jitted pipeline fragments cache on
schema. Dictionaries hash by identity — reuse the per-table-column Dictionary
object to avoid retraces.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.dictionary import Dictionary
from presto_tpu.types import Type


def round_up_capacity(n: int, minimum: int = 128) -> int:
    """Pad row counts into power-of-two buckets (compile-cache friendly)."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


class Column:
    """values + optional validity. A pytree node.

    `hi` is the optional high limb of a long-decimal column
    (DecimalType precision > 18): value = hi * 2^32 + values, with values
    (the low limb) kept canonical in [0, 2^32). None for all other types
    (reference: UnscaledDecimal128Arithmetic two-long layout).

    Structural columns (ArrayType / MapType — spi/block/ColumnarArray.java
    redesigned to a dense padded layout): `values` is a [capacity, W] plane
    of element values, `sizes` is int32[capacity] (row cardinalities,
    <= W), `evalid` an optional bool[capacity, W] element-validity plane
    (None = every in-size element valid), and for maps `keys` holds the
    aligned [capacity, W] key plane (map keys are non-null). `validity`
    stays the ROW-level null mask. All None for scalar columns."""

    __slots__ = ("values", "validity", "hi", "sizes", "evalid", "keys")

    def __init__(self, values, validity=None, hi=None, sizes=None,
                 evalid=None, keys=None):
        self.values = values
        self.validity = validity
        self.hi = hi
        self.sizes = sizes
        self.evalid = evalid
        self.keys = keys

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    @property
    def width(self):
        """Static element width W of a structural column (None for scalar)."""
        return self.values.shape[1] if self.values.ndim == 2 else None

    def valid_mask(self):
        if self.validity is None:
            return jnp.ones(self.values.shape[0], dtype=bool)
        return self.validity

    def gather(self, idx) -> "Column":
        """Row gather preserving validity, long-decimal limbs, and
        structural planes (2D values/evalid/keys gather by row)."""
        return Column(
            self.values[idx],
            None if self.validity is None else self.validity[idx],
            None if self.hi is None else self.hi[idx],
            None if self.sizes is None else self.sizes[idx],
            None if self.evalid is None else self.evalid[idx],
            None if self.keys is None else self.keys[idx],
        )

    def combined_f64(self):
        """Full value as float64 (exact below 2^53; the lossy escape hatch
        for arithmetic over long decimals)."""
        if self.hi is None:
            return self.values.astype(jnp.float64)
        return (self.hi.astype(jnp.float64) * float(1 << 32)
                + self.values.astype(jnp.float64))

    def __repr__(self):
        return f"Column({self.values!r}, validity={self.validity!r})"


def pad_plane_width(plane, w: int, fill=0):
    """Widen a [n, w0] structural plane to [n, w] with `fill` padding."""
    w0 = plane.shape[1]
    if w0 == w:
        return plane
    pad = jnp.full((plane.shape[0], w - w0), fill, plane.dtype)
    return jnp.concatenate([plane, pad], axis=1)


def concat_columns(cols: Sequence[Column], caps: Sequence[int]) -> Column:
    """Row-concatenate Columns preserving validity, long-decimal limbs and
    structural planes (2D value planes align on the max width). The single
    concatenation point for every accumulate/merge path — dropping a plane
    here is the Column.hi-through-joins bug class."""
    if any(c.values.ndim == 2 for c in cols):
        w = max(c.values.shape[1] for c in cols)
        vals = jnp.concatenate([pad_plane_width(c.values, w) for c in cols])
        sizes = jnp.concatenate([
            c.sizes if c.sizes is not None else jnp.zeros(cap, jnp.int32)
            for c, cap in zip(cols, caps)
        ])
        if any(c.evalid is not None for c in cols):
            evalid = jnp.concatenate([
                pad_plane_width(
                    c.evalid if c.evalid is not None
                    else jnp.ones((cap, c.values.shape[1]), bool),
                    w, False)
                for c, cap in zip(cols, caps)
            ])
        else:
            evalid = None
        if any(c.keys is not None for c in cols):
            kd = next(c.keys.dtype for c in cols if c.keys is not None)
            keys = jnp.concatenate([
                pad_plane_width(
                    c.keys if c.keys is not None
                    else jnp.zeros((cap, c.values.shape[1]), kd), w)
                for c, cap in zip(cols, caps)
            ])
        else:
            keys = None
    else:
        vals = jnp.concatenate([c.values for c in cols])
        sizes = evalid = keys = None
    if any(c.validity is not None for c in cols):
        valid = jnp.concatenate([
            c.validity if c.validity is not None else jnp.ones(cap, bool)
            for c, cap in zip(cols, caps)
        ])
    else:
        valid = None
    if any(c.hi is not None for c in cols):
        hi = jnp.concatenate([
            c.hi if c.hi is not None else jnp.zeros(cap, jnp.int64)
            for c, cap in zip(cols, caps)
        ])
    else:
        hi = None
    return Column(vals, valid, hi, sizes, evalid, keys)


def slice_column(c: Column, cap: int) -> Column:
    """First-cap-rows slice preserving every plane."""
    return Column(
        c.values[:cap],
        None if c.validity is None else c.validity[:cap],
        None if c.hi is None else c.hi[:cap],
        None if c.sizes is None else c.sizes[:cap],
        None if c.evalid is None else c.evalid[:cap],
        None if c.keys is None else c.keys[:cap],
    )


def _column_flatten(c: Column):
    return (c.values, c.validity, c.hi, c.sizes, c.evalid, c.keys), None


def _column_unflatten(aux, children):
    return Column(*children)


jax.tree_util.register_pytree_node(Column, _column_flatten, _column_unflatten)


class Batch:
    """A schema-carrying set of Columns with a shared live mask."""

    __slots__ = ("names", "types", "columns", "live", "dicts")

    def __init__(
        self,
        names: Sequence[str],
        types: Sequence[Type],
        columns: Sequence[Column],
        live,
        dicts: Optional[dict] = None,
    ):
        self.names = tuple(names)
        self.types = tuple(types)
        self.columns = tuple(columns)
        self.live = live
        self.dicts = dict(dicts or {})

    # -- construction -----------------------------------------------------

    @staticmethod
    def from_numpy(
        data: dict,
        types: dict,
        dicts: Optional[dict] = None,
        capacity: Optional[int] = None,
        device_put: bool = False,
    ) -> "Batch":
        """Build a batch from host numpy arrays, padding to capacity."""
        names = list(data.keys())
        n = len(next(iter(data.values()))) if names else 0
        cap = capacity or round_up_capacity(max(n, 1))
        cols = []
        for name in names:
            arr = np.asarray(data[name])
            t = types[name]
            vals = np.zeros(cap, dtype=t.dtype)
            vals[:n] = arr.astype(t.dtype)
            v = jnp.asarray(vals)
            cols.append(Column(v, None))
        live = np.zeros(cap, dtype=bool)
        live[:n] = True
        b = Batch(names, [types[k] for k in names], cols, jnp.asarray(live), dicts)
        if device_put:
            b = jax.device_put(b)
        return b

    # -- schema ops -------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.live.shape[0]

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    def type_of(self, name: str) -> Type:
        return self.types[self.names.index(name)]

    def dict_of(self, name: str) -> Optional[Dictionary]:
        return self.dicts.get(name)

    def select(self, names: Sequence[str]) -> "Batch":
        idx = [self.names.index(n) for n in names]
        dicts = {}
        for n in names:
            if n in self.dicts:
                dicts[n] = self.dicts[n]
            if n + "#keys" in self.dicts:  # map key-plane dictionary
                dicts[n + "#keys"] = self.dicts[n + "#keys"]
        return Batch(
            [self.names[i] for i in idx],
            [self.types[i] for i in idx],
            [self.columns[i] for i in idx],
            self.live,
            dicts,
        )

    def rename(self, names: Sequence[str]) -> "Batch":
        assert len(names) == len(self.names)
        dicts = {}
        for old, new in zip(self.names, names):
            if old in self.dicts:
                dicts[new] = self.dicts[old]
            if old + "#keys" in self.dicts:  # map key-plane dictionary
                dicts[new + "#keys"] = self.dicts[old + "#keys"]
        return Batch(names, self.types, self.columns, self.live, dicts)

    def with_column(self, name: str, typ: Type, col: Column, dictionary=None) -> "Batch":
        names = list(self.names)
        types = list(self.types)
        cols = list(self.columns)
        dicts = dict(self.dicts)
        if name in names:
            i = names.index(name)
            types[i] = typ
            cols[i] = col
            dicts.pop(name, None)
        else:
            names.append(name)
            types.append(typ)
            cols.append(col)
        if dictionary is not None:
            dicts[name] = dictionary
        return Batch(names, types, cols, self.live, dicts)

    def with_live(self, live) -> "Batch":
        return Batch(self.names, self.types, self.columns, live, self.dicts)

    # -- host-side materialization ---------------------------------------

    def num_live(self) -> int:
        return int(jnp.sum(self.live))

    def to_pydict(self, decode_strings: bool = True) -> dict:
        """Compact live rows to host numpy (test/output path, not hot)."""
        live = np.asarray(self.live)
        out = {}
        for name, t, c in zip(self.names, self.types, self.columns):
            if c.sizes is not None:
                out[name] = self._structural_to_py(name, t, c, live,
                                                   decode_strings)
                continue
            vals = np.asarray(c.values)[live]
            if c.hi is not None:
                # long decimal: exact int128 value from the two limbs
                his = np.asarray(c.hi)[live]
                vals = np.array(
                    [(int(h) << 32) + int(lo) for h, lo in zip(his, vals)],
                    dtype=object,
                )
            if c.validity is not None:
                valid = np.asarray(c.validity)[live]
            else:
                valid = None
            if t.is_string and decode_strings and name in self.dicts:
                arr = self.dicts[name].decode(
                    np.where(valid, vals, -1) if valid is not None else vals
                )
                if t.name == "varbinary":
                    # user-facing bytes back out of the latin-1 bijection
                    arr = np.array(
                        [None if v is None else str(v).encode("latin-1")
                         for v in arr], dtype=object)
                elif t.name in ("ipaddress", "ipprefix"):
                    # canonical-byte entries render as address text
                    from presto_tpu.expr import ip as _ip

                    fmt = (_ip.format_address if t.name == "ipaddress"
                           else _ip.format_prefix)
                    arr = np.array(
                        [None if v is None else fmt(str(v)) for v in arr],
                        dtype=object)
            else:
                from presto_tpu.types import DecimalType

                if isinstance(t, DecimalType) and decode_strings:
                    # user-facing: scale back to exact decimal.Decimal
                    import decimal as _dec

                    q = _dec.Decimal(1).scaleb(-t.scale)
                    arr = np.array(
                        [_dec.Decimal(int(v)).scaleb(-t.scale).quantize(q) for v in vals],
                        dtype=object,
                    )
                else:
                    arr = vals
                if valid is not None:
                    arr = arr.astype(object)
                    arr[~valid] = None
            out[name] = arr
        return out

    def _structural_to_py(self, name, t, c: Column, live, decode_strings):
        """ARRAY column → object array of python lists; MAP → dicts."""
        from presto_tpu.types import ArrayType, DecimalType, MapType

        vals = np.asarray(c.values)[live]
        sizes = np.asarray(c.sizes)[live]
        evalid = None if c.evalid is None else np.asarray(c.evalid)[live]
        rvalid = None if c.validity is None else np.asarray(c.validity)[live]
        keys = None if c.keys is None else np.asarray(c.keys)[live]

        def elem(et, x, edict):
            if et.is_string and decode_strings and edict is not None:
                return None if x < 0 else edict.values[x]
            if isinstance(et, DecimalType) and decode_strings:
                import decimal as _dec

                return _dec.Decimal(int(x)).scaleb(-et.scale)
            return x.item() if hasattr(x, "item") else x

        edict = self.dicts.get(name) if decode_strings else None
        kdict = self.dicts.get(name + "#keys") if decode_strings else None
        rows = np.empty(len(sizes), dtype=object)
        for i in range(len(sizes)):
            if rvalid is not None and not rvalid[i]:
                rows[i] = None
                continue
            s = int(sizes[i])
            if isinstance(t, MapType):
                rows[i] = {
                    elem(t.key, keys[i, j], kdict): (
                        elem(t.value, vals[i, j], edict)
                        if evalid is None or evalid[i, j] else None)
                    for j in range(s)
                }
            else:
                et = t.element if isinstance(t, ArrayType) else t
                rows[i] = [
                    elem(et, vals[i, j], edict)
                    if evalid is None or evalid[i, j] else None
                    for j in range(s)
                ]
        return rows

    def to_pandas(self, decode_strings: bool = True):
        import pandas as pd

        return pd.DataFrame(self.to_pydict(decode_strings))

    def __repr__(self):
        cols = ", ".join(f"{n}:{t}" for n, t in zip(self.names, self.types))
        return f"Batch[{cols}; capacity={self.capacity}]"


def _batch_flatten(b: Batch):
    aux = (b.names, b.types, tuple(sorted(b.dicts.items())))
    return (b.columns, b.live), aux


def _batch_unflatten(aux, children):
    names, types, dict_items = aux
    return Batch(names, types, children[0], children[1], dict(dict_items))


jax.tree_util.register_pytree_node(Batch, _batch_flatten, _batch_unflatten)
