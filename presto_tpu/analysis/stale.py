"""Stale-annotation reporter: suppressions must not outlive their bugs.

Every analysis plane lets code opt out of a rule with a trailing
annotation — ``# lint: allow(...)`` (kernel/concurrency),
``# fp: allow(...)`` (knob-flow), ``# shared: guarded-by(...)`` /
``# shared: requires(...)`` (concurrency guard registration). Each one
is a claim: *the rule fires here and the firing is intentional* (or,
for ``shared:``, *this state needs a guard contract*). When the code
under an annotation is refactored, the claim silently stops being
true and the annotation becomes a booby trap — it will hide the next
real bug introduced at that site.

This pass re-runs every analysis plane over the tree with all
annotations stripped (line numbers preserved) and flags each
annotation whose rule no longer fires at its site:

- ``allow(rule, ...)``: stale unless one of its rules fires at the
  annotated line (def-line annotations cover the def body, matching
  the suppression semantics).
- ``guarded-by(lock)`` / ``requires(lock)``: these are guard
  *registrations*, not suppressions — removing one changes the
  concurrency pass's inference rather than necessarily producing a
  finding, so strip-and-rerun is the wrong test. They go stale by
  becoming ORPHANED: the pass consumes ``guarded-by`` only on
  assignment lines (module-level names, ``self.attr`` in methods) and
  ``requires`` only on ``def`` header lines, so an annotation sitting
  on any other statement — the usual aftermath of a refactor that
  moved the code out from under its comment — registers nothing and is
  flagged.

Rule names that no pass knows are reported as ``unknown-rule``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from presto_tpu.analysis import astutil, concurrency, kernel_lint, knob_flow
from presto_tpu.analysis.findings import Finding

PLANE = "hygiene"

_ALLOW_ANN = re.compile(
    r"#\s*(lint|fp):\s*allow\(([a-z0-9_,\- ]+)\)")
_SHARED_ANN = re.compile(
    r"#\s*shared:\s*(guarded-by|requires)\(([^)]*)\)")
# only allow() suppressions are stripped for the rerun; shared: guard
# registrations stay in place (they feed inference, see module doc)
_STRIP_RES = (
    re.compile(r"#\s*(?:lint|fp):\s*allow\([a-z0-9_,\- ]+\).*"),
)

_KNOWN_RULES = (set(kernel_lint.RULES) | set(concurrency.RULES)
                | set(knob_flow.RULES))

_CONC_RULES = {"unguarded", "check-then-act"}

class _Annotation:
    def __init__(self, kind: str, line: int, rules: Set[str],
                 col: int = 0):
        self.kind = kind          # "allow" | "guarded-by" | "requires"
        self.line = line
        self.rules = rules
        self.col = col


def _string_spans(tree: ast.AST) -> List[Tuple[int, int, int, int]]:
    """(lineno, col, end_lineno, end_col) of every string literal —
    docstrings that MENTION the annotation syntax are not annotations."""
    out = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and n.end_lineno is not None:
            out.append((n.lineno, n.col_offset, n.end_lineno,
                        n.end_col_offset or 0))
    return out


def _in_string(line: int, col: int,
               spans: List[Tuple[int, int, int, int]]) -> bool:
    for lo, lc, hi, hc in spans:
        if (line, col) >= (lo, lc) and (line, col) < (hi, hc):
            return True
        if lo < line < hi:
            return True
    return False


def _collect_and_strip(source: str,
                       str_spans: List[Tuple[int, int, int, int]]
                       ) -> Tuple[str, List[_Annotation]]:
    anns: List[_Annotation] = []
    out_lines: List[str] = []
    for i, line in enumerate(source.splitlines(), start=1):
        m = _ALLOW_ANN.search(line)
        if m and not _in_string(i, m.start(), str_spans):
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            anns.append(_Annotation("allow", i, rules, m.start()))
        m = _SHARED_ANN.search(line)
        if m and not _in_string(i, m.start(), str_spans):
            anns.append(_Annotation(m.group(1), i, set(), m.start()))
        stripped = line
        if not _in_string(i, 0, str_spans) \
                and not _in_string(i, max(0, len(line) - 1), str_spans):
            for pat in _STRIP_RES:
                stripped = pat.sub("", stripped)
        out_lines.append(stripped.rstrip())
    return "\n".join(out_lines) + "\n", anns


def _consumable_lines(tree: ast.AST) -> Tuple[Set[int], Set[int]]:
    """(guard_lines, def_lines): the statement lines where the
    concurrency pass actually reads a guarded-by / requires annotation —
    assignments to plain names or ``self.attr``, and def headers."""
    guards: Set[int] = set()
    defs: Set[int] = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.add(n.lineno)
        elif isinstance(n, (ast.Assign, ast.AnnAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            if getattr(n, "value", None) is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name) or (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    guards.add(n.lineno)
    return guards, defs


def _def_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    return [(n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _line_of(f: Finding) -> int:
    try:
        return int(f.loc.rsplit(":", 1)[1])
    except (IndexError, ValueError):
        return 0


def analyze_paths(paths: Sequence[str],
                  lint_paths: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    """Flag annotations in `paths` whose rule no longer fires.

    `lint_paths` bounds the kernel-lint plane to its usual scope (ops/
    plus the jit runtime modules); concurrency and knob-flow scan
    everything, matching the real CLI passes.
    """
    files = astutil.iter_py_files(paths)
    lint_scope = set(astutil.iter_py_files(lint_paths)) \
        if lint_paths is not None else set(files)

    stripped: Dict[str, str] = {}
    annotations: Dict[str, List[_Annotation]] = {}
    triples: List[Tuple[str, str, ast.AST]] = []
    spans: Dict[str, List[Tuple[int, int]]] = {}
    consumable: Dict[str, Tuple[Set[int], Set[int]]] = {}
    out: List[Finding] = []
    for p in files:
        try:
            src, orig_tree = astutil.load_file(p)
        except (OSError, SyntaxError):
            continue
        s_src, anns = _collect_and_strip(src, _string_spans(orig_tree))
        try:
            # annotation-free files still parse into the module set: the
            # concurrency/knob-flow fixpoints are interprocedural
            tree = astutil.parse(s_src, p)
        except SyntaxError:
            continue
        stripped[p] = s_src
        annotations[p] = anns
        triples.append((s_src, p, tree))
        spans[p] = _def_spans(tree)
        consumable[p] = _consumable_lines(orig_tree)

    # one stripped-tree run per plane; merged per-file finding index
    by_file: Dict[str, List[Finding]] = {p: [] for p in stripped}
    for src, p, tree in triples:
        if p in lint_scope:
            for f in kernel_lint.lint_source(src, p, kernel_lint.RULES,
                                             tree=tree):
                by_file[p].append(f)
    for f in concurrency.analyze_modules(triples, concurrency.RULES):
        if f.loc.rsplit(":", 1)[0] in by_file:
            by_file[f.loc.rsplit(":", 1)[0]].append(f)
    for f in knob_flow.analyze_modules(triples, knob_flow.RULES):
        if f.loc.rsplit(":", 1)[0] in by_file:
            by_file[f.loc.rsplit(":", 1)[0]].append(f)

    for p, anns in annotations.items():
        found = by_file.get(p, [])
        for ann in anns:
            out.extend(_judge(p, ann, found, spans.get(p, []),
                              consumable.get(p, (set(), set()))))
    return sorted(out, key=lambda f: f.loc)


def _covering_span(line: int,
                   spans: List[Tuple[int, int]]) -> Tuple[int, int]:
    """The innermost def whose header starts at/just below the
    annotation line; else the line itself."""
    best = None
    for lo, hi in spans:
        if lo <= line + 1 and line <= hi and line >= lo - 1:
            if lo in (line, line + 1) or lo <= line <= hi:
                if best is None or lo > best[0]:
                    best = (lo, hi)
    if best is not None and best[0] in (line, line + 1):
        return best  # def-line annotation covers the body
    return (line, line)


def _judge(path: str, ann: _Annotation, found: List[Finding],
           spans: List[Tuple[int, int]],
           consumable: Tuple[Set[int], Set[int]]) -> List[Finding]:
    guard_lines, def_lines = consumable
    out: List[Finding] = []
    if ann.kind == "allow":
        unknown = ann.rules - _KNOWN_RULES
        for r in sorted(unknown):
            out.append(Finding(
                "unknown-rule", f"{path}:{ann.line}",
                f"allow({r}) names a rule no analysis pass defines",
                PLANE))
        rules = ann.rules & _KNOWN_RULES
        if not rules:
            return out
        lo, hi = _covering_span(ann.line, spans)
        live = any(f.rule in rules and lo <= _line_of(f) <= hi
                   for f in found)
        if not live:
            out.append(Finding(
                "stale-suppression", f"{path}:{ann.line}",
                f"allow({', '.join(sorted(rules))}) suppresses nothing: "
                f"no listed rule fires here when the annotation is "
                f"removed — delete it so it cannot mask a future bug",
                PLANE))
    elif ann.kind == "guarded-by":
        if ann.line not in guard_lines:
            out.append(Finding(
                "stale-suppression", f"{path}:{ann.line}",
                "guarded-by(...) is orphaned: the concurrency pass "
                "reads it only on an assignment to a name or "
                "`self.attr`, and this line has none — the state it "
                "once registered moved out from under the annotation",
                PLANE))
    elif ann.kind == "requires":
        if ann.line not in def_lines:
            out.append(Finding(
                "stale-suppression", f"{path}:{ann.line}",
                "requires(...) is orphaned: the concurrency pass reads "
                "it only on a `def` header line, and this line is not "
                "one — the contract binds nothing", PLANE))
    return out
